"""Device-memory accountant: the ONE host→HBM placement seam.

The canonical accelerator failure mode — the HBM allocator returning
RESOURCE_EXHAUSTED — used to be fatal here: the error was unclassified
by the retry machinery and nothing tracked how much device memory was
actually live.  This module makes device bytes a *governed resource*
the way locks (transaction/locks.py) and admission slots (wlm/) are:

* **DeviceMemoryAccountant** — ONE per data_dir (sessions sharing a
  data_dir share the device), a measured ledger of live per-device
  bytes.  Every placement in the tree flows through :meth:`place`
  (graftlint's ``raw-device-placement`` rule rejects bypasses), which
  charges the ledger, intercepts allocator RESOURCE_EXHAUSTED and
  re-raises it as the classified :class:`DeviceMemoryExhausted`, and
  hangs a ``weakref.finalize`` off the returned array so the charge is
  released the moment the device buffer is garbage — the ledger is
  *measured* live bytes, not an estimate.  Static plan intermediates
  (join/shuffle/grid buffers, which XLA allocates inside the compiled
  program where Python cannot see them) charge through :meth:`lease`
  for the duration of each execution, using the same worst-buffer
  estimate the ``max_plan_buffer_bytes`` guard trusts.

* **MemSim** — the CrashSim pattern at this seam: an armed per-device
  byte budget (and/or a deterministic fail-at-allocation-N trigger)
  raises synthetic RESOURCE_EXHAUSTED so the OOM torture harness
  (tests/test_oom_torture.py) can sweep every allocation index of a
  workload on hardware that never really OOMs.  Releases credit the
  simulated allocator too, so the degradation ladder's evictions
  genuinely create headroom under an armed budget.

Charge categories:

* ``feed``        — transient resident-path table feeds (statement-scoped)
* ``cache``       — feed-cache-resident arrays (evictable on demand: the
                    OOM ladder's first rung frees them, so they do not
                    count against admission pressure)
* ``stream``      — in-flight stream/multipass batch arrays
* ``prefetch``    — pipelined-scan buffers placed AHEAD of consumption
                    (executor/scanpipe.py): wire payloads awaiting
                    on-device decode and feed columns still in the
                    prefetch queue.  Sheddable first: an OOM during a
                    pipelined feed drains the pipeline and the feed
                    retries eagerly, so these bytes never pin a
                    statement.  The charge graduates to its final
                    category (``recharge``) when the consumer adopts
                    the array into the feed.
* ``plan``        — leased static plan-buffer estimate of an executing
                    program
* ``other``       — anything else routed through the seam

`jax` ``device.memory_stats()`` is cross-checked where the backend
exposes it (TPU does; CPU test meshes return None) and surfaced via
``citus_stat_memory()``.
"""

from __future__ import annotations

import contextlib
import os
import threading
import weakref

from ..errors import DeviceMemoryExhausted

CATEGORIES = ("feed", "cache", "stream", "prefetch", "plan", "other")

# substring the XLA allocator (and MemSim, deliberately) puts in every
# device-OOM message — the classification key
_OOM_TOKEN = "RESOURCE_EXHAUSTED"


def is_resource_exhausted(exc: BaseException) -> bool:
    """Does this exception report a device-allocator OOM?  Matches the
    XLA RESOURCE_EXHAUSTED status string (jaxlib raises it as
    XlaRuntimeError with the status name embedded in the message)."""
    return _OOM_TOKEN in str(exc)


class MemSim:
    """One simulated HBM lifetime: arm with ``budget`` (per-device
    bytes; a charge that would exceed it OOMs) and/or ``fail_at=N``
    (the N-th charge through the seam OOMs once, 1-based).  Journals
    every charge so the torture harness can size its sweep."""

    def __init__(self, budget: int | None = None,
                 fail_at: int | None = None):
        self.budget = budget
        self.fail_at = fail_at
        self.allocs = 0
        self.oom_raised = 0
        self.journal: list[tuple[int, str, int]] = []


class DeviceMemoryAccountant:
    """Measured live device bytes for one data_dir's mesh (per-device
    accounting: sharded arrays divide across devices, replicated ones
    occupy their full size on every device)."""

    def __init__(self, data_dir: str):
        self.data_dir = data_dir
        # REENTRANT: _release runs from weakref finalizers, which the
        # interpreter may fire at ANY allocation point — including gc
        # triggered inside a _charge that already holds the lock.  A
        # plain Lock would self-deadlock there; with an RLock the
        # nested _release interleaves safely (it touches only its own
        # handle's entry)
        self._mu = threading.RLock()
        self._next_handle = 0
        # handle → (category, per-device bytes, applied per-device
        # vector) — the vector is what _release subtracts, so a charge
        # recorded under one mesh size releases exactly what it added
        self._live: dict[int, tuple[str, int, tuple[int, ...]]] = {}
        self._live_total = 0
        self._live_by_cat: dict[str, int] = {c: 0 for c in CATEGORIES}
        # measured live bytes PER DEVICE index: uniform charges (whole
        # sharded/replicated arrays, leases) apply their per-device
        # figure to every device of the last-seen mesh; the slice seam
        # (place_sharded_slices) applies each device's actual slice
        # bytes.  Budget enforcement is against the HOTTEST device —
        # one hot device OOMs regardless of cluster-wide headroom.
        self._live_by_dev: list[int] = [0]
        self._n_dev = 1
        self.peak_bytes = 0
        self.charges_total = 0
        self.releases_total = 0
        self.oom_total = 0
        self._sim: MemSim | None = None
        self._backend_budget: int | None = None  # memoized bytes_limit
        # weak registry of evictable device caches (each session's
        # FeedCache): the device is shared, so the OOM ladder's
        # eviction rung must be able to reclaim EVERY session's
        # cache-resident bytes, not just the OOMing session's own
        self._evictables: list = []

    # -- the seam ----------------------------------------------------------
    def place(self, mesh, arr, sharded: bool, category: str = "feed"):
        """Place one host array on the mesh through the accounted seam.
        Returns the device array; raises DeviceMemoryExhausted when the
        allocator (real or simulated) refuses."""
        out, _handle = self.place_tracked(mesh, arr, sharded, category)
        return out

    def place_tracked(self, mesh, arr, sharded: bool,
                      category: str = "feed"):
        """`place` returning ``(array, charge_handle)`` — the pipelined
        scan path (executor/scanpipe.py) places columns under the
        sheddable ``prefetch`` category while they sit in the prefetch
        queue and graduates the charge via :meth:`recharge` when the
        consumer adopts them into the feed."""
        from ..distributed.mesh import put_replicated, put_sharded
        from ..utils.faultinjection import fault_point

        # named seam: a host→HBM transfer that dies here (device OOM,
        # remote-attached link drop) must surface as a classified
        # statement error, never a partially placed feed
        fault_point("executor.hbm_exhausted")
        n_dev = mesh.devices.size
        self._note_mesh(n_dev)
        nbytes = (int(arr.nbytes) if not sharded or n_dev <= 0
                  else -(-int(arr.nbytes) // n_dev))
        handle = self._charge(category, nbytes)
        try:
            out = (put_sharded if sharded else put_replicated)(mesh, arr)
        except Exception as e:
            self._release(handle)
            if is_resource_exhausted(e):
                self._count_oom()
                err = DeviceMemoryExhausted(
                    f"device allocator OOM placing {nbytes} bytes/device "
                    f"(category {category!r}): {e}")
                err.nbytes = nbytes  # bounds the eviction rung's target
                raise err from e
            raise
        weakref.finalize(out, self._release, handle)
        return out, handle

    def place_sharded_slices(self, mesh, slices,
                             category: str = "feed"):
        """Place per-device host slices as ONE mesh-sharded array
        (distributed/mesh.py put_sharded_slices) — the device-owned
        feed seam: each device's transfer dispatches independently and
        the ledger charges each device its OWN slice bytes, so a
        skew-placed table (every shard on one node of a grown mesh)
        shows up as the hot-device pressure it really is."""
        out, _handle = self.place_sharded_slices_tracked(mesh, slices,
                                                         category)
        return out

    def place_sharded_slices_tracked(self, mesh, slices,
                                     category: str = "feed"):
        from ..distributed.mesh import put_sharded_slices
        from ..utils.faultinjection import fault_point

        # same named seam/classification contract as place_tracked
        fault_point("executor.hbm_exhausted")
        self._note_mesh(mesh.devices.size)
        per_dev = tuple(int(s.nbytes) for s in slices)
        nbytes = max(per_dev) if per_dev else 0
        handle = self._charge(category, nbytes, per_dev=per_dev)
        try:
            out = put_sharded_slices(mesh, slices)
        except Exception as e:
            self._release(handle)
            if is_resource_exhausted(e):
                self._count_oom()
                err = DeviceMemoryExhausted(
                    f"device allocator OOM placing {nbytes} bytes on "
                    f"the hottest device (category {category!r}): {e}")
                err.nbytes = nbytes
                raise err from e
            raise
        weakref.finalize(out, self._release, handle)
        return out, handle

    def _note_mesh(self, n_dev: int) -> None:
        """Learn the mesh width so uniform charges span every device."""
        n = max(1, int(n_dev))
        with self._mu:
            if n > self._n_dev:
                self._n_dev = n
            if n > len(self._live_by_dev):
                self._live_by_dev.extend(
                    [0] * (n - len(self._live_by_dev)))

    def resize_mesh(self, n_dev: int) -> None:
        """Re-size the per-device budget axis after an elastic shrink
        or device-loss failover (Executor.adopt_mesh): hot-device
        enforcement and the per-device ledger now span the SURVIVING
        width, so a MemSim/hbm budget is judged against the mesh that
        actually executes.  The ledger vector keeps its old tail so
        charges recorded under the wider mesh still release exactly
        what they added; _note_mesh grows it again if the mesh ever
        widens back."""
        n = max(1, int(n_dev))
        with self._mu:
            self._n_dev = n
            if n > len(self._live_by_dev):
                self._live_by_dev.extend(
                    [0] * (n - len(self._live_by_dev)))

    def recharge(self, handle: int, category: str) -> None:
        """Move a live charge to another category (pipelined feed
        columns graduate prefetch → feed/cache on adoption).  A handle
        whose charge already released (array died mid-pipeline) is a
        no-op."""
        if category not in CATEGORIES:
            category = "other"
        with self._mu:
            entry = self._live.get(handle)
            if entry is None:
                return
            old_cat, nbytes, per_dev = entry
            if old_cat == category:
                return
            self._live[handle] = (category, nbytes, per_dev)
            self._live_by_cat[old_cat] -= nbytes
            self._live_by_cat[category] += nbytes

    def adopt(self, arr, sharded: bool, n_dev: int,
              category: str = "feed") -> None:
        """Charge a device array the seam did NOT place — the output of
        an on-device decode (a compiled expansion of a wire payload,
        allocated by XLA where `place` cannot see it).  The charge is
        measured (released by the array's finalizer) so decoded feeds
        stay visible to the ledger, the WLM gate and MemSim exactly
        like host-placed ones."""
        nbytes = (int(arr.nbytes) if not sharded or n_dev <= 0
                  else -(-int(arr.nbytes) // n_dev))
        handle = self._charge(category, nbytes)
        weakref.finalize(arr, self._release, handle)

    @contextlib.contextmanager
    def lease(self, category: str, nbytes: int):
        """Charge `nbytes`/device for the duration of the block — the
        static-plan-buffer accounting around each compiled execution
        (XLA allocates those inside the program; the lease makes them
        visible to the ledger, the WLM gate and MemSim)."""
        handle = self._charge(category, max(0, int(nbytes)))
        try:
            yield
        finally:
            self._release(handle)

    # -- ledger ------------------------------------------------------------
    def _charge(self, category: str, nbytes: int,
                per_dev: tuple[int, ...] | None = None) -> int:
        if category not in CATEGORIES:
            category = "other"
        with self._mu:
            # the applied per-device vector: uniform charges put their
            # per-device figure on every device of the known mesh
            applied = (per_dev if per_dev is not None
                       else (nbytes,) * self._n_dev)
            if len(applied) > len(self._live_by_dev):
                self._live_by_dev.extend(
                    [0] * (len(applied) - len(self._live_by_dev)))
            sim = self._sim
            if sim is not None:
                sim.allocs += 1
                sim.journal.append((sim.allocs, category, nbytes))
                fail = (sim.fail_at is not None
                        and sim.allocs == sim.fail_at)
                # per-device enforcement: the budget is a PER-DEVICE
                # ceiling, so the check is against the hottest device's
                # prospective load — cluster-wide headroom does not
                # save a device whose own slice no longer fits
                hot = max(self._live_by_dev[d] + b
                          for d, b in enumerate(applied)) \
                    if applied else nbytes
                over = (sim.budget is not None and hot > sim.budget)
                if fail or over:
                    sim.oom_raised += 1
                    self.oom_total += 1
                    why = (f"armed at allocation {sim.fail_at}" if fail
                           else f"budget {sim.budget} bytes/device, "
                                f"hottest device would reach {hot}")
                    err = DeviceMemoryExhausted(
                        f"{_OOM_TOKEN} (MemSim): allocation "
                        f"{sim.allocs} of {nbytes} bytes/device "
                        f"(category {category!r}) refused — {why}")
                    err.nbytes = nbytes
                    raise err
            self._next_handle += 1
            handle = self._next_handle
            self._live[handle] = (category, nbytes, applied)
            self._live_total += nbytes
            self._live_by_cat[category] += nbytes
            for d, b in enumerate(applied):
                self._live_by_dev[d] += b
            self.charges_total += 1
            if self._live_total > self.peak_bytes:
                self.peak_bytes = self._live_total
            return handle

    def _release(self, handle: int) -> None:
        with self._mu:
            entry = self._live.pop(handle, None)
            if entry is None:
                return
            category, nbytes, applied = entry
            self._live_total -= nbytes
            self._live_by_cat[category] -= nbytes
            for d, b in enumerate(applied):
                self._live_by_dev[d] -= b
            self.releases_total += 1

    def _count_oom(self) -> None:
        with self._mu:
            self.oom_total += 1

    def note_oom(self) -> None:
        """Fold an allocator OOM observed OUTSIDE place()/lease() (a
        compiled program's internal allocation) into the totals."""
        self._count_oom()

    # -- reads -------------------------------------------------------------
    def live_bytes(self, category: str | None = None) -> int:
        with self._mu:
            return (self._live_total if category is None
                    else self._live_by_cat.get(category, 0))

    def live_bytes_by_device(self) -> list[int]:
        """Measured live bytes per mesh-device index (uniform charges
        span every device; slice placements charge each device its own
        slice) — the hot-device view citus_stat_mesh() surfaces."""
        with self._mu:
            return list(self._live_by_dev[:self._n_dev])

    def transient_bytes(self) -> int:
        """Live bytes that should return to zero between statements —
        everything but the deliberately resident feed cache.  The OOM
        torture harness asserts this is 0 after every statement (no
        accountant leaks)."""
        with self._mu:
            return self._live_total - self._live_by_cat["cache"]

    def pressure_bytes(self) -> int:
        """Live bytes that genuinely constrain a new admission: cache
        bytes are excluded because they are reclaimable on demand (the
        degradation ladder's first rung evicts them)."""
        return self.transient_bytes()

    def budget_bytes(self, settings=None) -> int:
        """The per-device byte ceiling the accountant can enforce
        against: an armed MemSim budget, else the `hbm_budget_bytes`
        config var, else the backend's reported bytes_limit where
        available.  0 = unknown/unbounded."""
        with self._mu:
            if self._sim is not None and self._sim.budget is not None:
                return self._sim.budget
        if settings is not None:
            cfg = settings.get("hbm_budget_bytes")
            if cfg:
                return int(cfg)
        if self._backend_budget is None:
            # computed once: device limits are fixed for the process,
            # and memory_stats() can be a backend RPC
            stats = self.device_memory_stats()
            limits = [s.get("bytes_limit", 0) for s in stats]
            self._backend_budget = (min(limits)
                                    if limits and all(limits) else 0)
        return self._backend_budget

    @staticmethod
    def device_memory_stats() -> list[dict]:
        """Per-device allocator stats where the backend exposes them
        (TPU/GPU do; CPU returns None) — the measured cross-check the
        ledger is validated against in citus_stat_memory()."""
        import jax

        out = []
        for d in jax.devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats:
                out.append({"device": str(d),
                            "bytes_in_use": int(
                                stats.get("bytes_in_use", 0)),
                            "peak_bytes_in_use": int(
                                stats.get("peak_bytes_in_use", 0)),
                            "bytes_limit": int(
                                stats.get("bytes_limit", 0))})
        return out

    def snapshot(self) -> dict:
        """citus_stat_memory() source."""
        with self._mu:
            by_cat = dict(self._live_by_cat)
            sim = self._sim
            snap = {
                "live_bytes": self._live_total,
                "live_bytes_hot_device": max(
                    self._live_by_dev[:self._n_dev], default=0),
                "peak_bytes": self.peak_bytes,
                "charges_total": self.charges_total,
                "releases_total": self.releases_total,
                "oom_total": self.oom_total,
                "memsim_armed": sim is not None,
                "memsim_budget": (sim.budget if sim is not None
                                  else None),
                "memsim_allocs": sim.allocs if sim is not None else 0,
            }
        for c in CATEGORIES:
            snap[f"live_{c}_bytes"] = by_cat[c]
        return snap

    # -- eviction registry -------------------------------------------------
    def register_evictable(self, cache) -> None:
        """Register a cache exposing evict_coldest(target_bytes) —
        called once per Executor for its FeedCache; weakly held so a
        closed session's cache does not pin."""
        with self._mu:
            self._evictables = [r for r in self._evictables
                                if r() is not None]
            self._evictables.append(weakref.ref(cache))

    def evict_evictable(self, target_bytes: int | None = None) -> int:
        """Evict cache-resident device arrays across EVERY registered
        cache, coldest-first within each, until `target_bytes` have
        been requested freed (None = everything).  Returns entries
        evicted.  Runs outside the accountant lock: evicting acquires
        each cache's own lock, and the dropped arrays' finalizers
        re-enter _release (lock order: cache lock → accountant lock,
        never the reverse)."""
        with self._mu:
            refs = list(self._evictables)
        evicted = 0
        remaining = target_bytes
        for ref in refs:
            cache = ref()
            if cache is None:
                continue
            before = cache.total_bytes
            evicted += cache.evict_coldest(remaining)
            if remaining is not None:
                remaining -= max(0, before - cache.total_bytes)
                if remaining <= 0:
                    break
        return evicted

    # -- simulation --------------------------------------------------------
    def install_sim(self, sim: MemSim | None) -> None:
        with self._mu:
            self._sim = sim


# process-wide registry: sessions sharing a data_dir share the device,
# so they share ONE ledger (the lock-manager/WLM pattern)
_registry: dict[str, DeviceMemoryAccountant] = {}
_registry_mu = threading.Lock()


def accountant_for(data_dir: str) -> DeviceMemoryAccountant:
    key = os.path.realpath(data_dir)
    with _registry_mu:
        if key not in _registry:
            _registry[key] = DeviceMemoryAccountant(key)
        return _registry[key]


class oom_budget:
    """``with oom_budget(accountant, budget=..., fail_at=...) as sim:``
    — arm a MemSim for the duration of the block.  ``budget=None,
    fail_at=None`` counts allocations without failing (the rehearsal
    run that sizes the torture sweep)."""

    def __init__(self, accountant: DeviceMemoryAccountant,
                 budget: int | None = None, fail_at: int | None = None):
        self.accountant = accountant
        self.sim = MemSim(budget, fail_at)

    def __enter__(self) -> MemSim:
        self.accountant.install_sim(self.sim)
        return self.sim

    def __exit__(self, *exc) -> bool:
        self.accountant.install_sim(None)
        return False
