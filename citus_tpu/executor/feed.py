"""Host→device data feed: shard stripes → padded mesh-sharded arrays.

Replaces the reference's per-tuple worker scan + COPY result streaming with
bulk columnar placement: each device's rows are the concatenation of its
shards' stripes (colocation-preserving), padded to a common static
capacity, laid out as [n_devices, capacity] and device_put with a
NamedSharding over the 'shards' mesh axis.  Reference tables feed as
replicated [capacity] arrays.

Shard pruning (ScanNode.pruned_shards) skips entire shards at feed time —
the PruneShards analogue executed host-side.
"""

from __future__ import annotations

import math

import numpy as np
from jax.sharding import Mesh

from ..catalog import Catalog, DistributionMethod
from ..errors import ExecutionError
from ..planner.plan import (
    AggregateNode,
    JoinNode,
    PlanNode,
    ProjectNode,
    QueryPlan,
    ScanNode,
    WindowNode,
)
from ..storage import TableStore
from .compiler import FeedSpec, _round_cap


def walk_plan(node: PlanNode):
    yield node
    if isinstance(node, JoinNode):
        yield from walk_plan(node.left)
        yield from walk_plan(node.right)
    elif isinstance(node, (AggregateNode, ProjectNode, WindowNode)):
        yield from walk_plan(node.input)


def build_feeds(plan: QueryPlan, catalog: Catalog, store: TableStore,
                mesh: Mesh, compute_dtype=np.float32,
                cache=None, counters=None, accountant=None,
                no_cache_nodes=frozenset(), stats=None
                ) -> dict[int, FeedSpec]:
    """`no_cache_nodes`: node ids whose feeds bypass the device cache —
    the multipass driver's per-pass split feeds must NOT pin every
    pass's partition resident at once (that would defeat the pass)."""
    feeds: dict[int, FeedSpec] = {}
    for node in walk_plan(plan.root):
        if isinstance(node, ScanNode):
            node_cache = None if id(node) in no_cache_nodes else cache
            feeds[id(node)] = _feed_scan_cached(node, catalog, store, mesh,
                                                plan.n_devices, compute_dtype,
                                                node_cache, counters,
                                                accountant, stats)
    return feeds


def skippable_tests(filter_expr) -> tuple:
    """Canonical (col, op, value) skip tests from a scan filter — also the
    feed-cache key component (feeds built under different chunk filters
    hold different rows and must not share a cache slot)."""
    from ..planner import expr as ir

    if filter_expr is None:
        return ()
    _FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
    # BParam carries its bound value: chunk skipping is host-side per
    # execution, so generic plans keep min/max pruning (and the feed
    # cache keys on the VALUE, as it must — different values read
    # different chunks)
    const_types = (ir.BConst, ir.BParam)
    tests: list[tuple[str, str, object]] = []
    for c in ir.split_conjuncts(filter_expr):
        if isinstance(c, ir.BCmp) and c.op in _FLIP:
            if isinstance(c.left, ir.BCol) \
                    and isinstance(c.right, const_types) \
                    and c.right.value is not None:
                tests.append((c.left.cid.split(".", 1)[1], c.op,
                              c.right.value))
            elif isinstance(c.right, ir.BCol) and \
                    isinstance(c.left, const_types) \
                    and c.left.value is not None:
                tests.append((c.right.cid.split(".", 1)[1], _FLIP[c.op],
                              c.left.value))
        elif isinstance(c, ir.BInConst) and not c.negated and \
                isinstance(c.operand, ir.BCol) and c.values:
            tests.append((c.operand.cid.split(".", 1)[1], "in",
                          tuple(c.values)))
    return tuple(sorted(tests, key=repr))


def make_chunk_filter(filter_expr, counters=None, storage_name=None):
    """ScanNode filter → per-chunk min/max skip predicate.

    The chunk-granularity PruneShards analogue (reference:
    columnar_reader.c:323 chunk-group filtering over ColumnChunkSkipNode
    min/max).  Handles AND-ed `col <op> const` comparisons and positive
    IN-lists (string predicates arrive as dictionary-code IN-lists from
    the binder); any unsatisfiable conjunct skips the whole chunk.
    Returns None when the filter has no skippable shape.

    `storage_name` maps current → on-disk column names: stripe stats are
    keyed by storage names, which diverge after ALTER TABLE RENAME.
    """
    tests = skippable_tests(filter_expr)
    if not tests:
        return None
    if storage_name:
        tests = tuple((storage_name.get(col, col), op, val)
                      for col, op, val in tests)

    def chunk_filter(stats: dict) -> bool:
        for col, op, val in tests:
            s = stats.get(col)
            if s is None:
                continue
            mn, mx, _nulls = s
            if mn is None:
                # no stats for this column (e.g. dictionary-coded strings
                # in older stripes) — cannot conclude anything
                continue
            ok = ((op == "<" and mn < val) or (op == "<=" and mn <= val)
                  or (op == ">" and mx > val) or (op == ">=" and mx >= val)
                  or (op == "=" and mn <= val <= mx)
                  or (op == "in" and any(mn <= v <= mx for v in val)))
            if not ok:
                _count_skip(counters)
                return False
        return True

    return chunk_filter


def _count_skip(counters) -> None:
    if counters is not None:
        from ..stats.counters import CHUNKS_SKIPPED

        counters.increment(CHUNKS_SKIPPED)


def _overlay_touches(store: TableStore, table: str) -> bool:
    ov = store.overlay
    if ov is None:
        return False
    return (any(t == table for t, _ in ov.records)
            or any(t == table for t, _, _ in ov.deletes))


def _feed_scan_cached(node: ScanNode, catalog: Catalog, store: TableStore,
                      mesh: Mesh, n_dev: int, compute_dtype,
                      cache, counters=None, accountant=None,
                      stats=None) -> FeedSpec:
    """Device-feed cache wrapper: HBM-resident table arrays keyed on
    (table, columns, pruning, placement, data version) — see
    executor/cache.py.  Open-transaction overlays bypass the cache (their
    visibility is session-private and changes mid-transaction)."""
    table = node.rel.table
    if cache is None or _overlay_touches(store, table):
        return _feed_scan(node, catalog, store, mesh, n_dev, compute_dtype,
                          counters, accountant, category="feed",
                          stats=stats)
    shards = catalog.table_shards(table)
    placement_sig = tuple(
        (s.shard_id, catalog.active_placement(s.shard_id).node_id)
        for s in shards)
    # skip-filter fingerprint under STORAGE column names — the names the
    # chunk filter actually tests stripe stats against.  Keying on the
    # current names would let two filters that alias through a rename
    # share one skip-pruned (possibly prefetched) feed; the mapped
    # fingerprint makes cacheability a function of what was READ
    skip_fp = tuple(
        (store.storage_column_name(table, col), op, val)
        for col, op, val in skippable_tests(node.filter))
    key = (table, store.data_version(table), tuple(node.columns),
           None if node.pruned_shards is None else tuple(node.pruned_shards),
           n_dev, str(np.dtype(compute_dtype)), placement_sig,
           skip_fp)
    entry = cache.get(key)
    if entry is None:
        # superseded versions of this table can never hit again — free
        # their HBM before resident-caching the fresh feed
        cache.invalidate_table(table, keep_version=key[1])
        # accounted as "cache" from the start: the arrays become
        # cache-resident below, and cache bytes are the evictable
        # class the ladder/admission pressure treats as reclaimable
        spec = _feed_scan(node, catalog, store, mesh, n_dev, compute_dtype,
                          counters, accountant, category="cache",
                          stats=stats)
        from .cache import CachedFeed

        nbytes = sum(int(np.dtype(a.dtype).itemsize * a.size)
                     for a in list(spec.arrays.values())
                     + list(spec.nulls.values()) + [spec.valid])
        entry = CachedFeed(sharded=spec.sharded, arrays=spec.arrays,
                           nulls=spec.nulls, valid=spec.valid,
                           capacity=spec.capacity, nbytes=nbytes,
                           dev_rows=spec.dev_rows)
        cache.put(key, entry)
        return spec
    return FeedSpec(node=node, sharded=entry.sharded, arrays=entry.arrays,
                    nulls=entry.nulls, valid=entry.valid,
                    capacity=entry.capacity, dev_rows=entry.dev_rows)


def _feed_scan(node: ScanNode, catalog: Catalog, store: TableStore,
               mesh: Mesh, n_dev: int, compute_dtype,
               counters=None, accountant=None,
               category: str = "feed", stats=None) -> FeedSpec:
    # pipelined path first (executor/scanpipe.py): prefetch + decode on
    # a producer thread overlapped with accounted placement, optional
    # on-device decode.  None ⇒ ineligible (scan_pipeline off, tiny
    # table under 'auto', open overlay) or shed after a prefetch OOM —
    # the eager path below is both the fallback and the reference
    # semantics the fuzzer parity slice pins the pipeline to.
    from .scanpipe import maybe_pipelined_feed

    pipelined = maybe_pipelined_feed(node, catalog, store, mesh, n_dev,
                                     compute_dtype, counters, accountant,
                                     category, stats)
    if pipelined is not None:
        return pipelined
    rel = node.rel
    meta = catalog.table(rel.table)
    colnames = [cid.split(".", 1)[1] for cid in node.columns]
    shards = catalog.table_shards(rel.table)
    chunk_filter = None
    if node.filter is not None:
        name_map = {c.name: store.storage_column_name(rel.table, c.name)
                    for c in meta.schema.columns}
        chunk_filter = make_chunk_filter(node.filter, counters, name_map)

    if meta.method == DistributionMethod.HASH:
        # device-owned assembly: each device's slice is built from ONLY
        # the shards the catalog's node↔device map assigns it, as an
        # independent [cap] buffer — never one [n_dev, cap] host concat.
        # Placement below transfers the slices individually, so an
        # N-device mesh absorbs N dispatches in parallel.
        per_dev_vals: list[dict[str, list[np.ndarray]]] = [
            {c: [] for c in colnames} for _ in range(n_dev)]
        per_dev_mask: list[dict[str, list[np.ndarray]]] = [
            {c: [] for c in colnames} for _ in range(n_dev)]
        per_dev_rows = [0] * n_dev
        from ..planner.plan import table_placement

        placement = table_placement(catalog, rel.table, n_dev)
        for s, dev in zip(shards, placement):
            if node.pruned_shards is not None and \
                    s.shard_index not in node.pruned_shards:
                continue
            vals, mask, n = store.read_shard(rel.table, s.shard_id, colnames,
                                             chunk_filter)
            if n == 0:
                continue
            per_dev_rows[dev] += n
            for c in colnames:
                per_dev_vals[dev][c].append(vals[c])
                per_dev_mask[dev][c].append(mask[c])
        cap = _round_cap(max(per_dev_rows) if any(per_dev_rows) else 1)
        arrays, nulls = {}, {}
        for cid, cname in zip(node.columns, colnames):
            dtype = rel.schema.column(cname).dtype.numpy_dtype
            if dtype == np.float64 and compute_dtype is not None:
                dtype = np.dtype(compute_dtype)
            slices = []
            nslices = []
            has_nulls = False
            for d in range(n_dev):
                sl = np.zeros(cap, dtype=dtype)
                nsl = np.zeros(cap, dtype=bool)
                if per_dev_vals[d][cname]:
                    v = np.concatenate(per_dev_vals[d][cname]).astype(dtype)
                    m = np.concatenate(per_dev_mask[d][cname])
                    sl[:len(v)] = v
                    if not m.all():
                        has_nulls = True
                        nsl[:len(m)] = ~m
                slices.append(sl)
                nslices.append(nsl)
            arrays[cid] = slices
            if has_nulls:
                nulls[cid] = nslices
        valid = []
        for d in range(n_dev):
            vsl = np.zeros(cap, dtype=bool)
            vsl[:per_dev_rows[d]] = True
            valid.append(vsl)
        feed = FeedSpec(node=node, sharded=True, arrays=arrays, nulls=nulls,
                        valid=valid, capacity=cap,
                        dev_rows=list(per_dev_rows))
    else:
        # reference/local: single shard replicated to every device
        if len(shards) != 1:
            raise ExecutionError(
                f"table {rel.table}: expected single shard")
        vals, mask, n = store.read_shard(rel.table, shards[0].shard_id,
                                         colnames, chunk_filter)
        cap = _round_cap(max(n, 1))
        arrays, nulls = {}, {}
        for cid, cname in zip(node.columns, colnames):
            dtype = rel.schema.column(cname).dtype.numpy_dtype
            if dtype == np.float64 and compute_dtype is not None:
                dtype = np.dtype(compute_dtype)
            buf = np.zeros(cap, dtype=dtype)
            if n:
                buf[:n] = vals[cname].astype(dtype)
                if not mask[cname].all():
                    nbuf = np.zeros(cap, dtype=bool)
                    nbuf[:n] = ~mask[cname]
                    nulls[cid] = nbuf
            arrays[cid] = buf
        valid = np.zeros(cap, dtype=bool)
        valid[:n] = True
        feed = FeedSpec(node=node, sharded=False, arrays=arrays, nulls=nulls,
                        valid=valid, capacity=cap)

    # place on the mesh through the ONE accounted seam (executor/hbm.py)
    from ..utils.faultinjection import fault_point
    from .hbm import accountant_for

    # named seam: a host→HBM transfer failure (device OOM, remote-
    # attached link drop) must surface as a retryable statement error,
    # never a partially placed feed
    fault_point("executor.device_put")
    acc = accountant_for(store.data_dir) if accountant is None \
        else accountant

    def put(a):
        # sharded feeds arrive as per-device slice lists (device-owned
        # path: independent per-device transfers through the slice
        # seam, charged per device); replicated feeds as one host array
        if feed.sharded:
            return acc.place_sharded_slices(mesh, a, category)
        return acc.place(mesh, a, False, category)

    feed.arrays = {c: put(a) for c, a in feed.arrays.items()}
    feed.nulls = {c: put(a) for c, a in feed.nulls.items()}
    feed.valid = put(feed.valid)
    return feed
