"""Streamed execution: tables larger than HBM feed in stripe batches.

The reference never holds a whole table in memory — the columnar reader
iterates stripe-by-stripe (columnar/columnar_reader.c:323) and the adaptive
executor streams task results.  The resident-feed executor here does the
opposite (whole padded table in HBM, executor/feed.py), which caps table
size at device memory.  This module restores the streaming property the
TPU-native way:

* the LARGEST sharded scan of the plan is picked as the *stream* node;
* its stripes are assembled into fixed-shape [n_dev, batch_cap] batches
  (same capacity every batch ⇒ ONE compiled program, reused);
* a background thread prefetches + device_puts batch i+1 while the mesh
  executes batch i (the double-buffered stripe→HBM pipeline of SURVEY §7
  step 4);
* per-batch device outputs merge on the host: group rows re-aggregate
  (count/sum/min/max are distributive; avg is already split into
  sum+count by the planner), plain row outputs concatenate.

Eligibility is a plan-shape property (`_stream_path`): every join between
the stream scan and the root must see the full other side per batch and
emit each output row in exactly one batch — inner joins anywhere, outer
joins only when the streamed side is the preserved side.  Aggregates are
allowed only at the root (distributive merge); windows never (a window
partition must see all its rows at once).
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..planner.plan import (
    AggregateNode,
    JoinNode,
    PlanNode,
    ProjectNode,
    QueryPlan,
    ScanNode,
    WindowNode,
    table_placement,
)
from ..catalog import DistributionMethod
from .cache import feeds_signature, node_fingerprint
from .compiler import FeedSpec, _round_cap, unpack_outputs
from .feed import _feed_scan_cached, walk_plan


# ---------------------------------------------------------------------------
# eligibility + sizing

def _scan_width_bytes(node: ScanNode, catalog, compute_dtype) -> int:
    """Per-row feed bytes for one scan: column widths (after the f64→
    compute-dtype policy) + a null byte per column + the validity byte."""
    meta = catalog.table(node.rel.table)
    w = 1
    for cid in node.columns:
        cname = cid.split(".", 1)[1]
        dt = meta.schema.column(cname).dtype.numpy_dtype
        if dt == np.float64 and compute_dtype is not None:
            dt = np.dtype(compute_dtype)
        w += np.dtype(dt).itemsize + 1
    return w


def _scan_dev_rows(node: ScanNode, catalog, store, n_dev: int) -> int:
    """Max rows any device would hold for this scan (pre-padding)."""
    meta = catalog.table(node.rel.table)
    shards = catalog.table_shards(node.rel.table)
    if meta.method != DistributionMethod.HASH:
        return store.table_row_count(node.rel.table)
    placement = table_placement(catalog, node.rel.table, n_dev)
    per_dev = [0] * n_dev
    for s, dev in zip(shards, placement):
        if node.pruned_shards is not None and \
                s.shard_index not in node.pruned_shards:
            continue
        per_dev[dev] += store.shard_row_count(node.rel.table, s.shard_id)
    return max(per_dev) if per_dev else 0


def _stream_path(plan: QueryPlan, stream_id: int) -> bool:
    """Is batching the scan `stream_id` semantics-preserving?

    Path constraints (root → stream scan):
    * JoinNode: inner always; LEFT only when the stream side is the left
      (preserved/probe) subtree; RIGHT only when it is the right.  FULL
      never (both sides preserved — unmatched flags need global state).
    * AggregateNode: only as the plan ROOT (its distributive partials
      merge host-side); a nested aggregate (DISTINCT rewrite) would
      dedupe per batch only.
    * WindowNode: never on the path.
    """

    def path_to(node: PlanNode) -> list[PlanNode] | None:
        if id(node) == stream_id:
            return [node]
        kids = []
        if isinstance(node, JoinNode):
            kids = [node.left, node.right]
        elif isinstance(node, (AggregateNode, ProjectNode, WindowNode)):
            kids = [node.input]
        for k in kids:
            p = path_to(k)
            if p is not None:
                return [node] + p
        return None

    path = path_to(plan.root)
    if path is None:
        return False
    for i, node in enumerate(path[:-1]):
        if isinstance(node, JoinNode):
            on_left = path[i + 1] is node.left
            if node.join_type == "inner":
                continue
            if node.join_type in ("left", "semi", "anti") and on_left:
                # semi/anti distribute over probe batches when the build
                # side is fully resident (each batch sees every match)
                continue
            if node.join_type == "right" and not on_left:
                continue
            return False
        if isinstance(node, WindowNode):
            return False
        if isinstance(node, AggregateNode):
            if i != 0:
                return False
            if not _mergeable_aggregate(node):
                return False
    return True


def _mergeable_aggregate(node: AggregateNode) -> bool:
    for a, _cid in node.aggs:
        if getattr(a, "distinct", False):
            return False
        if a.kind not in ("count", "count_star", "sum", "min", "max"):
            return False
    return True


def stream_candidates(plan: QueryPlan, catalog) -> list[ScanNode]:
    """Hash-distributed scans on a semantics-preserving stream path —
    the eligibility half of pick_stream_node, shared with the OOM
    degradation ladder (can a forced-stream rung help this plan?)."""
    return [s for s in walk_plan(plan.root) if isinstance(s, ScanNode)
            and catalog.table(s.rel.table).method ==
            DistributionMethod.HASH and _stream_path(plan, id(s))]


def pick_stream_node(plan: QueryPlan, catalog, store, n_dev: int,
                     compute_dtype, budget: int, forced_rows: int = 0,
                     shrink: int = 1, force: bool = False,
                     prefetch_depth: int = 1):
    """(stream ScanNode, batch_cap) or None.

    Streams only when the combined per-device feed bytes exceed `budget`
    and the largest sharded scan is on a semantics-preserving path.  A
    non-zero `forced_rows` (test/tuning knob) overrides batch sizing.

    `shrink`/`force` are the OOM degradation ladder's inputs
    (executor.Executor.degrade_for_oom): `shrink` divides the computed
    batch_cap (each level is one recompile, memoized via the plan
    fingerprint), `force` streams even when the feeds fit the
    configured budget — a real allocator OOM proved the effective
    ceiling lower than the configured one.

    `prefetch_depth` is the bounded batch-queue depth
    (scan_prefetch_depth): depth+1 batches can be device-resident at
    once, so the per-batch budget divisor scales with it — a deeper
    queue must mean smaller batches, never more resident bytes than
    the budget the streaming path exists to honor."""
    scans = [n for n in walk_plan(plan.root) if isinstance(n, ScanNode)]
    sizes = {}
    for s in scans:
        rows = _scan_dev_rows(s, catalog, store, n_dev)
        sizes[id(s)] = _round_cap(max(rows, 1)) * \
            _scan_width_bytes(s, catalog, compute_dtype)
    total = sum(sizes.values())
    if total <= budget and not force:
        return None
    candidates = [s for s in scans
                  if catalog.table(s.rel.table).method ==
                  DistributionMethod.HASH and _stream_path(plan, id(s))]
    if not candidates:
        return None
    stream = max(candidates, key=lambda s: sizes[id(s)])
    width = _scan_width_bytes(stream, catalog, compute_dtype)
    stream_rows = max(1, sizes[id(stream)] // width)
    if forced_rows:
        return stream, _round_cap(max(1, forced_rows // max(1, shrink)))
    other = total - sizes[id(stream)]
    # resident batches (depth queued + 1 consumed) + downstream join/
    # shuffle intermediates sized off the batch: budget each batch at
    # 1/(depth+5) of what remains (depth 1 keeps the historic 1/6)
    div = max(1, int(prefetch_depth)) + 5
    avail = budget - other
    if avail < div * width * 4096 and not force:
        return None  # other feeds leave no useful room — fall through
    batch_cap = int(max(avail, div * width * 1024) // (div * width))
    if force:
        # a forced stream must actually batch: at least 2 batches even
        # when the sizing math says everything fits — and the usual
        # 1024-row floor must not re-inflate a small table's halved
        # cap back into one full-table batch (128 is the _round_cap
        # floor; shrink may push small tables' batches below 1024 by
        # design — that is exactly what the rung is for)
        batch_cap = min(batch_cap, -(-stream_rows // 2))
    floor = 128 if force else 1024
    batch_cap = _round_cap(max(floor, batch_cap // max(1, shrink)))
    if not force and batch_cap * 1.05 >= stream_rows:
        return None  # would be a single batch anyway
    return stream, batch_cap


# ---------------------------------------------------------------------------
# batched stream feeds

class StreamBatcher:
    """Assemble one scan's stripes into fixed-shape [n_dev, batch_cap]
    feed batches, reading lazily (at most one open stripe per device)."""

    def __init__(self, node: ScanNode, catalog, store, mesh, n_dev: int,
                 compute_dtype, batch_cap: int, accountant=None,
                 stats=None):
        from .hbm import accountant_for

        self.stats = stats
        self.node = node
        self.catalog = catalog
        self.store = store
        self.mesh = mesh
        self.n_dev = n_dev
        self.compute_dtype = compute_dtype
        self.batch_cap = batch_cap
        self.accountant = (accountant_for(store.data_dir)
                           if accountant is None else accountant)
        table = node.rel.table
        shards = catalog.table_shards(table)
        placement = table_placement(catalog, table, n_dev)
        self.colnames = [cid.split(".", 1)[1] for cid in node.columns]
        # same storage-name-mapped chunk-group skip filter the resident
        # feed path applies (min/max pruning must not vanish just
        # because the table streams)
        self._chunk_filter = None
        if node.filter is not None:
            from .feed import make_chunk_filter

            meta0 = catalog.table(table)
            name_map = {c.name: store.storage_column_name(table, c.name)
                        for c in meta0.schema.columns}
            self._chunk_filter = make_chunk_filter(node.filter, None,
                                                   name_map)
        self._dev_shards: list[list[int]] = [[] for _ in range(n_dev)]
        self._dev_rows = [0] * n_dev
        for s, dev in zip(shards, placement):
            if node.pruned_shards is not None and \
                    s.shard_index not in node.pruned_shards:
                continue
            self._dev_shards[dev].append(s.shard_id)
            self._dev_rows[dev] += store.shard_row_count(table, s.shard_id)
        self.n_batches = max(
            1, max(-(-r // batch_cap) for r in self._dev_rows))
        # per-device pull state: a stripe iterator + carryover remainder
        self._iters = [self._stripes(d) for d in range(n_dev)]
        self._carry: list[tuple[dict, dict, int] | None] = [None] * n_dev
        # Which columns carry a nulls plane is decided ONCE, from
        # manifest stripe stats, so every batch presents the same pytree
        # structure to the compiled program (a per-batch decision would
        # crash the cached executable when NULL presence differs across
        # stripes).  Missing stats are treated as "may have NULLs".
        null_cols: set[str] = set()
        storage_of = {c: store.storage_column_name(table, c)
                      for c in self.colnames}
        recs = [r for sids in self._dev_shards for sid in sids
                for r in store.shard_stripe_records(table, sid)]
        for cname in self.colnames:
            s_name = storage_of[cname]
            for r in recs:
                stats = r.get("stats") or {}
                s = stats.get(s_name)
                if s is None or len(s) < 3 or s[2]:
                    # stats missing / pre-null-count manifest / has NULLs
                    null_cols.add(cname)
                    break
        self._null_cols = null_cols

    def _stripes(self, dev: int):
        for sid in self._dev_shards[dev]:
            yield from self.store.iter_shard_stripes(
                self.node.rel.table, sid, self.colnames,
                self._chunk_filter)

    def _pull(self, dev: int, want: int):
        """Up to `want` rows from device dev's stripe stream."""
        vals: list[dict] = []
        got = 0
        while got < want:
            if self._carry[dev] is not None:
                v, m, n = self._carry[dev]
                self._carry[dev] = None
            else:
                try:
                    v, m, n = next(self._iters[dev])
                except StopIteration:
                    break
                if n == 0:
                    continue
            take = min(n, want - got)
            if take < n:
                self._carry[dev] = (
                    {c: a[take:] for c, a in v.items()},
                    {c: a[take:] for c, a in m.items()}, n - take)
                v = {c: a[:take] for c, a in v.items()}
                m = {c: a[:take] for c, a in m.items()}
            vals.append((v, m, take))
            got += take
        return vals, got

    def feed(self, batch_index: int) -> FeedSpec | None:
        """Build the next batch (sequential; called once per index).
        Returns None when the stream is exhausted — checked BEFORE any
        buffer allocation or device transfer, so exhaustion costs
        nothing.  Batch 0 always materializes (empty-table queries still
        need one execution)."""
        from ..stats.tracing import trace_span

        node, rel = self.node, self.node.rel
        cap, n_dev = self.batch_cap, self.n_dev
        t_pull = time.perf_counter()
        with trace_span("stream.decode"):
            per_dev = [self._pull(d, cap) for d in range(n_dev)]
        if self.stats is not None:
            self.stats.add(
                stream_decode_seconds=time.perf_counter() - t_pull)
        self.last_rows = sum(got for _v, got in per_dev)
        if batch_index > 0 and self.last_rows == 0:
            return None
        arrays, nulls = {}, {}
        for cid, cname in zip(node.columns, self.colnames):
            dtype = rel.schema.column(cname).dtype.numpy_dtype
            if dtype == np.float64 and self.compute_dtype is not None:
                dtype = np.dtype(self.compute_dtype)
            buf = np.zeros((n_dev, cap), dtype=dtype)
            with_nulls = cname in self._null_cols
            nbuf = np.zeros((n_dev, cap), dtype=bool) if with_nulls \
                else None
            for d in range(n_dev):
                pos = 0
                for v, m, take in per_dev[d][0]:
                    buf[d, pos:pos + take] = v[cname].astype(dtype)
                    if with_nulls:
                        nbuf[d, pos:pos + take] = ~m[cname]
                    pos += take
            arrays[cid] = buf
            if with_nulls:
                nulls[cid] = nbuf
        valid = np.zeros((n_dev, cap), dtype=bool)
        for d in range(n_dev):
            valid[d, :per_dev[d][1]] = True
        feed = FeedSpec(node=node, sharded=True, arrays=arrays,
                        nulls=nulls, valid=valid, capacity=cap,
                        dev_rows=[per_dev[d][1] for d in range(n_dev)])
        # accounted placement (executor/hbm.py): a batch that does not
        # fit raises the classified DeviceMemoryExhausted through the
        # consumer queue, and its charge releases with the batch arrays
        acc = self.accountant

        def put(a):
            # device-owned slice seam: each device's batch rows (built
            # from only its own shards' stripes) transfer independently
            # and charge per device (executor/hbm.py)
            return acc.place_sharded_slices(
                self.mesh, [a[d] for d in range(self.n_dev)], "stream")

        t_put = time.perf_counter()
        with trace_span("stream.transfer"):
            feed.arrays = {c: put(a) for c, a in feed.arrays.items()}
            feed.nulls = {c: put(a) for c, a in feed.nulls.items()}
            feed.valid = put(feed.valid)
        if self.stats is not None:
            self.stats.add(
                stream_transfer_seconds=time.perf_counter() - t_put)
        return feed


# ---------------------------------------------------------------------------
# host merge

def _flatten_batch(cols, nulls, valid):
    v = np.asarray(valid).reshape(-1)
    fc, fn = {}, {}
    for cid in cols:
        fc[cid] = np.asarray(cols[cid]).reshape(-1)[v]
        fn[cid] = np.asarray(nulls[cid]).reshape(-1)[v]
    return fc, fn


_BIG = {"min": lambda dt: (np.inf if np.issubdtype(dt, np.floating)
                           else np.iinfo(dt).max),
        "max": lambda dt: (-np.inf if np.issubdtype(dt, np.floating)
                           else np.iinfo(dt).min)}


def merge_aggregate_parts(node: AggregateNode, parts):
    """Re-aggregate per-batch group rows host-side (the coordinator
    combine over per-batch partials — same split the reference's logical
    optimizer plans, planner/multi_logical_optimizer.c:1419)."""
    cids = ([cid for _g, cid in node.group_keys]
            + [cid for _a, cid in node.aggs])
    cat, catn = {}, {}
    for cid in cids:
        cat[cid] = np.concatenate([p[0][cid] for p in parts])
        catn[cid] = np.concatenate([p[1][cid] for p in parts])
    n = len(next(iter(cat.values()))) if cids else 0
    if n == 0:
        return cat, catn  # typed empties straight through

    key_cols = []
    for _g, cid in node.group_keys:
        v = cat[cid]
        if np.issubdtype(v.dtype, np.floating):
            v = (v.astype(np.float32).view(np.int32)
                 if v.dtype == np.float32 else v.view(np.int64))
        nm = catn[cid]
        key_cols.append(np.where(nm, 0, v.astype(np.int64)))
        key_cols.append(nm.astype(np.int64))
    if key_cols:
        mat = np.stack(key_cols, axis=1)
        _, first, inv = np.unique(mat, axis=0, return_index=True,
                                  return_inverse=True)
        inv = inv.reshape(-1)
        m = len(first)
    else:
        first = np.zeros(1, dtype=np.int64)
        inv = np.zeros(n, dtype=np.int64)
        m = 1

    out_c, out_n = {}, {}
    for _g, cid in node.group_keys:
        out_c[cid] = cat[cid][first]
        out_n[cid] = catn[cid][first]
    for a, cid in node.aggs:
        v, nm = cat[cid], catn[cid]
        if a.kind in ("count", "count_star"):
            acc = np.zeros(m, dtype=v.dtype)
            np.add.at(acc, inv, v)
            out_c[cid] = acc
            out_n[cid] = np.zeros(m, dtype=bool)
            continue
        contrib = ~nm
        if a.kind == "sum":
            acc = np.zeros(m, dtype=v.dtype)
            np.add.at(acc, inv[contrib], v[contrib])
        elif a.kind == "min":
            acc = np.full(m, _BIG["min"](v.dtype), dtype=v.dtype)
            np.minimum.at(acc, inv[contrib], v[contrib])
        else:  # max
            acc = np.full(m, _BIG["max"](v.dtype), dtype=v.dtype)
            np.maximum.at(acc, inv[contrib], v[contrib])
        cnt = np.zeros(m, dtype=np.int64)
        np.add.at(cnt, inv, contrib.astype(np.int64))
        out_c[cid] = acc
        out_n[cid] = cnt == 0
    return out_c, out_n


# ---------------------------------------------------------------------------
# driver

def try_execute_streamed(executor, plan: QueryPlan, raw: bool,
                         return_parts: bool = False,
                         no_cache_nodes=frozenset()):
    """Streamed execution when the plan's feeds exceed the HBM budget;
    None ⇒ caller proceeds on the resident-feed path.

    `return_parts=True` (the multipass driver's mode) skips the final
    host combine and returns (parts, rows_scanned, retries, batches,
    caps) — flattened per-batch column/null dicts the caller merges
    across its own passes before ONE host combine."""
    settings = executor.settings
    budget = settings.get("max_feed_bytes_per_device")
    if budget <= 0:
        return None
    # the accountant may know a REAL ceiling below the configured one
    # (armed MemSim, hbm_budget_bytes, backend bytes_limit): size the
    # stream against it so the statement streams at the true budget
    # up front instead of discovering it through an OOM round-trip
    hw = executor.accountant.budget_bytes(settings)
    if hw:
        budget = min(budget, hw)
    compute_dtype = np.dtype(settings.get("compute_dtype"))
    n_dev = plan.n_devices
    oom = executor.oom
    picked = pick_stream_node(plan, executor.catalog, executor.store,
                              n_dev, compute_dtype, budget,
                              settings.get("stream_batch_rows"),
                              shrink=oom.batch_shrink,
                              force=oom.force_stream,
                              prefetch_depth=settings.get(
                                  "scan_prefetch_depth"))
    if picked is None:
        return None
    stream_node, batch_cap = picked

    # scale cardinality estimates along the stream path: downstream
    # buffers size per batch, not per table
    total_rows = sum(
        executor.store.shard_row_count(stream_node.rel.table, s.shard_id)
        for s in executor.catalog.table_shards(stream_node.rel.table))
    frac = min(1.0, (batch_cap * n_dev) / max(1, total_rows))
    _scale_path_estimates(plan, id(stream_node), frac)

    batcher = StreamBatcher(stream_node, executor.catalog, executor.store,
                            executor.mesh, n_dev, compute_dtype, batch_cap,
                            accountant=executor.accountant,
                            stats=executor.scan_stats)
    feeds: dict[int, FeedSpec] = {}
    for node in walk_plan(plan.root):
        if isinstance(node, ScanNode) and node is not stream_node:
            cache = (None if id(node) in no_cache_nodes
                     else executor.feed_cache)
            feeds[id(node)] = _feed_scan_cached(
                node, executor.catalog, executor.store, executor.mesh,
                n_dev, compute_dtype, cache,
                executor.counters, executor.accountant,
                executor.scan_stats)

    # prefetch thread: builds + device_puts the next batch while the mesh
    # chews the current one (scan_prefetch_depth batches in flight —
    # the same knob that bounds the pipelined scan's column prefetch).
    # stop_evt lets a failing consumer unblock the producer's bounded
    # put (a plain put would pin the thread and a device-resident batch
    # forever).
    fetched: queue.Queue = queue.Queue(
        maxsize=max(1, settings.get("scan_prefetch_depth")))
    stop_evt = threading.Event()

    def _put(item) -> bool:
        while not stop_evt.is_set():
            try:
                fetched.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    from ..stats.tracing import adopt_context, capture_context

    trace_ctx = capture_context()

    def producer():
        from ..utils.faultinjection import fault_point

        # the batch producer adopts the statement's trace context so
        # its stream.decode/stream.transfer spans land on their own
        # track of the statement trace (leak-proof: adopt_context
        # force-closes anything left open)
        with adopt_context(trace_ctx):
            try:
                i = 0
                while not stop_evt.is_set():
                    # named seam: a prefetch-thread death mid-stream
                    # must surface as a query error, never a hang or
                    # partial result (VERDICT r3 weak #6)
                    fault_point("stream.prefetch")
                    feed = batcher.feed(i)
                    if feed is None:
                        break
                    if not _put(("ok", feed)):
                        return
                    i += 1
                _put(("done", None))
            except BaseException as e:  # graftlint: ignore[swallowed-base-exception] — not swallowed: forwarded over the queue and re-raised on the consumer thread
                _put(("err", e))

    t = threading.Thread(target=producer, daemon=True)
    t.start()

    topk_sig = (plan.device_topk, tuple(
        (repr(e), d, nf) for e, d, nf in plan.host_order_by)
        if plan.device_topk is not None else ())
    caps = None
    fingerprint = None
    fn = out_meta = None
    parts = []
    rows_scanned = 0
    retries_total = 0
    agg_root = (plan.root if isinstance(plan.root, AggregateNode)
                else None)
    n_consumed = 0
    from ..utils.cancellation import check_cancel

    try:
        while True:
            # batch boundaries are the streaming path's cancellation
            # seams: a statement_timeout_ms deadline or Session.cancel()
            # stops between batches (the finally below unwinds the
            # prefetch thread cleanly).  The bounded get keeps the
            # deadline live even when the producer is wedged.
            check_cancel()
            try:
                kind, payload = fetched.get(timeout=0.25)
            except queue.Empty:
                continue
            if kind == "err":
                raise payload
            if kind == "done":
                break
            n_consumed += 1
            feeds[id(stream_node)] = payload
            if caps is None:
                fingerprint = ("stream", batch_cap,
                               node_fingerprint(plan.root), n_dev,
                               str(compute_dtype),
                               feeds_signature(plan, feeds), topk_sig,
                               executor.settings.get("group_by_kernel"))
                memo = executor._caps_memo.get(fingerprint)
                caps = (executor._caps_from_order(plan, memo)
                        if memo is not None
                        else executor._initial_capacities(plan, feeds))
            # no feedback tightening mid-stream: batches share one
            # compiled program, and per-batch actuals vary — tightening
            # on batch 1 would risk a recompile-overflow-regrow cycle
            # on a later, fuller batch
            from ..stats.tracing import trace_span

            with trace_span("stream.batch", batch=n_consumed - 1):
                packed, out_meta, caps, r = executor.run_with_retry(
                    plan, feeds, caps, fingerprint, compute_dtype,
                    allow_tighten=False)
                retries_total += r
                cols, nulls, valid = unpack_outputs(packed, out_meta)
                rows_scanned += int(np.asarray(valid).size)
                parts.append(_flatten_batch(cols, nulls, valid))
    finally:
        stop_evt.set()
        while True:  # drain so a blocked put wakes immediately
            try:
                fetched.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=5.0)

    if return_parts:
        return parts, rows_scanned, retries_total, n_consumed, caps
    if agg_root is not None:
        merged_c, merged_n = merge_aggregate_parts(agg_root, parts)
    else:
        merged_c = {cid: np.concatenate([p[0][cid] for p in parts])
                    for cid in parts[0][0]} if parts else {}
        merged_n = {cid: np.concatenate([p[1][cid] for p in parts])
                    for cid in parts[0][1]} if parts else {}
    n = len(next(iter(merged_c.values()))) if merged_c else 0
    valid = np.ones((1, n), dtype=bool)
    cols = {cid: a.reshape(1, n) for cid, a in merged_c.items()}
    nulls = {cid: a.reshape(1, n) for cid, a in merged_n.items()}
    result = executor._host_combine(plan, cols, nulls, valid, raw)
    result.retries = retries_total
    result.device_rows_scanned = rows_scanned
    result.streamed_batches = n_consumed
    from .runner import feed_device_rows

    rows_in = feed_device_rows(
        {k: v for k, v in feeds.items() if k != id(stream_node)}, n_dev)
    totals = rows_in if rows_in is not None else [0] * n_dev
    for d, r in enumerate(batcher._dev_rows):
        totals[d] += int(r)
    result.device_rows_in = totals
    if executor.counters is not None:
        from ..stats.counters import QUERIES_STREAMED

        executor.counters.increment(QUERIES_STREAMED)
    if caps is not None:
        # once per STATEMENT, after the batch loop (run_with_retry runs
        # per batch and must not inflate the statement-level counter)
        executor.count_groupby_bucketed(plan, caps)
    return result


def _scale_path_estimates(plan: QueryPlan, stream_id: int,
                          frac: float) -> None:
    """Scale est_rows along root→stream-scan (output cardinality of every
    node containing the streamed batch scales with the batch fraction)."""

    def rec(node: PlanNode) -> bool:
        here = id(node) == stream_id
        kids = []
        if isinstance(node, JoinNode):
            kids = [node.left, node.right]
        elif isinstance(node, (AggregateNode, ProjectNode, WindowNode)):
            kids = [node.input]
        on_path = here or any(rec(k) for k in kids)
        if on_path and getattr(node, "est_rows", None):
            node.est_rows = max(1, int(node.est_rows * frac))
        return on_path

    rec(plan.root)
