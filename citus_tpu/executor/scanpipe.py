"""Pipelined columnar scan: overlapped prefetch/decode/transfer with
optional on-device decode of compressed column payloads.

The eager feed path (executor/feed.py `_feed_scan`) is three strictly
serial phases: read+decode EVERY stripe, assemble padded [n_dev, cap]
buffers for EVERY column, then device_put them one after another.  On a
remote-attached chip the transfer leg dominates that wall (BENCH_r05:
5.7 s of a 6.1 s cold scan), with the host decoder idle the whole time.
This module restores the overlap the reference's stripe reader gets for
free from its row-at-a-time pull loop (columnar_reader.c:323), done the
TPU-native way — fixed-shape feeds, one producer thread, a bounded
queue:

* **prefetch + decode** (producer thread): columns are read one at a
  time across all visible stripes through the native threaded codec,
  with the chunk-group skip set computed ONCE per stripe over the full
  projection's stats (skipped chunks are never fetched) and pinned for
  every column so rows stay aligned.  The producer runs
  `scan_prefetch_depth` columns ahead of the consumer.
* **double-buffered async transfer**: the producer also *places* each
  assembled column through the ONE accounted seam
  (`DeviceMemoryAccountant.place`, category ``prefetch``) — so column
  i+1 decodes and column i+2's stripes stream off disk while column
  i's bytes are still in flight to the device.  Prefetch charges
  graduate to their final category when the consumer adopts them; an
  allocator OOM while prefetching sheds the pipeline (the bounded
  queue drains, every prefetch charge releases) and the feed retries
  eagerly — pipelined feeds stay OOM-governed and cost the ladder
  nothing.
* **on-device decode** (``scan_pipeline=device``): instead of decoded
  float32/int64, *compressed* payloads cross the wire — integer/date/
  dictionary-code columns frame-of-reference-packed to the narrowest
  unsigned width, low-NDV float columns as dictionary codes plus a
  tiny value LUT, validity planes bit-packed 8:1 and the valid prefix
  as one row-count per device — and expand on the mesh (Pallas
  bit-unpack / dictionary-gather kernels on a single-device TPU, XLA
  formulations elsewhere).  `bytes_on_wire` < `bytes_decoded` by the
  packing ratio, which on a tunnel-attached chip is the whole game.

`scan_pipeline` picks the mode (off | host | device, 'auto' resolves
by backend), `scan_prefetch_depth` bounds the queue.  Overlay-touching
tables (open-transaction visibility) fall back to the eager path.
"""

from __future__ import annotations

import functools
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import DeviceMemoryExhausted

# below this many table rows 'auto' keeps the eager path: a producer
# thread + per-column reads cost more than they hide on tiny feeds
AUTO_MIN_ROWS = 4096

# dictionary encoding applies up to this many distinct values (uint16
# codes); the NDV probe samples this many rows before paying a full
# np.unique over the column
_DICT_MAX_NDV = 65536
_NDV_SAMPLE = 65536


class ScanPhaseStats:
    """Per-executor accumulator for the scan pipeline's phase walls and
    wire/decoded byte totals — the bench drivers read (and reset) this
    to stamp per-phase timers into the BENCH artifact."""

    FIELDS = ("prefetch_seconds", "decode_seconds", "transfer_seconds",
              "device_decode_seconds", "bytes_on_wire", "bytes_decoded",
              "prefetch_stalls", "chunks_prefetched", "feeds_pipelined",
              "stream_decode_seconds", "stream_transfer_seconds")

    def __init__(self):
        self._mu = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._mu:
            for f in self.FIELDS:
                setattr(self, f, 0.0 if "seconds" in f else 0)
            # wire bytes placed per mesh-device index (the device-owned
            # slice seam charges each device its own slice) — the
            # multichip bench stamps the hot device's share to prove
            # per-device feed bytes shrink ≈1/N with mesh width
            self.wire_by_device: dict[int, int] = {}

    def add(self, **kw) -> None:
        with self._mu:
            for k, v in kw.items():
                setattr(self, k, getattr(self, k) + v)

    def add_device_bytes(self, per_dev) -> None:
        with self._mu:
            for d, b in enumerate(per_dev):
                self.wire_by_device[d] = \
                    self.wire_by_device.get(d, 0) + int(b)

    def snapshot(self) -> dict:
        with self._mu:
            out = {f: (round(getattr(self, f), 4)
                       if "seconds" in f else int(getattr(self, f)))
                   for f in self.FIELDS}
            n = max(self.wire_by_device, default=-1) + 1
            out["wire_bytes_by_device"] = [
                self.wire_by_device.get(d, 0) for d in range(n)]
            return out

    def merge(self, other: "ScanPhaseStats") -> None:
        """Fold another accumulator in (a completed pipeline's local
        tallies graduate into the executor-wide stats — discarded
        attempts never fold, so the published phase walls describe
        only builds whose feeds were actually used)."""
        with other._mu:
            vals = {f: getattr(other, f) for f in self.FIELDS}
            per_dev_items = list(other.wire_by_device.items())
        self.add(**vals)
        with self._mu:
            for d, b in per_dev_items:
                self.wire_by_device[d] = \
                    self.wire_by_device.get(d, 0) + b


def resolve_scan_mode(settings) -> str:
    """The scan_pipeline mode this session would run: 'off', 'host' or
    'device' ('auto' resolves by backend — device decode pays off when
    a wire separates host and chip, not on a CPU test mesh)."""
    if settings is None:
        return "off"
    raw = settings.get("scan_pipeline")
    if raw != "auto":
        return raw
    import jax

    return "device" if jax.default_backend() != "cpu" else "host"


class _Shed(Exception):
    """Internal: an OOM while prefetching — drain and retry eagerly."""


# ---------------------------------------------------------------------------
# wire encodings (host side)

def _encode_for(buf: np.ndarray):
    """Frame-of-reference pack an integer buffer to the narrowest
    unsigned width; None when no narrower width exists."""
    if buf.size == 0:
        return None
    mn = int(buf.min())
    span = int(buf.max()) - mn
    for limit, wdt in ((1 << 8, np.uint8), (1 << 16, np.uint16),
                       (1 << 32, np.uint32)):
        if span < limit:
            if np.dtype(wdt).itemsize >= buf.dtype.itemsize:
                return None
            wire = (buf.astype(np.int64) - mn).astype(wdt)
            return wire, np.asarray(mn, dtype=buf.dtype)
    return None


def _encode_dict(buf: np.ndarray):
    """Dictionary-code a low-NDV float buffer (codes + LUT); None when
    the column is too distinct (or carries NaN) to pay for itself."""
    if buf.size == 0 or np.isnan(buf).any():
        return None
    flat = buf.reshape(-1)
    if flat.size > 4 * _NDV_SAMPLE:
        step = max(1, flat.size // _NDV_SAMPLE)
        if len(np.unique(flat[::step])) > _DICT_MAX_NDV // 4:
            return None  # sample already too distinct: skip the full sort
    lut = np.unique(flat)
    if len(lut) > _DICT_MAX_NDV:
        return None
    wdt = np.uint8 if len(lut) <= 256 else np.uint16
    codes = np.searchsorted(lut, buf).astype(wdt)
    if codes.nbytes + lut.nbytes >= buf.nbytes:
        return None
    return codes, lut.astype(buf.dtype)


def encode_column(buf: np.ndarray):
    """(kind, wire, extra) for one assembled feed buffer: 'for' (wire =
    offsets, extra = base scalar), 'dict' (wire = codes, extra = LUT)
    or 'plain' (wire = buf)."""
    if np.issubdtype(buf.dtype, np.integer) and \
            buf.dtype.itemsize > 1:
        packed = _encode_for(buf)
        if packed is not None:
            return "for", packed[0], packed[1]
    if np.issubdtype(buf.dtype, np.floating):
        packed = _encode_dict(buf)
        if packed is not None:
            return "dict", packed[0], packed[1]
    return "plain", buf, None


# ---------------------------------------------------------------------------
# on-device decode (XLA formulations; Pallas on a single-device TPU)

@jax.jit
def _for_expand(wire, base):
    return wire.astype(base.dtype) + base


@jax.jit
def _dict_expand(codes, lut):
    return jnp.take(lut, codes.astype(jnp.int32), axis=0)


@functools.partial(jax.jit, static_argnames=("cap",))
def _bits_expand(packed, cap):
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(packed.shape[:-1] + (cap,)).astype(bool)


@functools.partial(jax.jit, static_argnames=("cap",))
def _valid_expand(rows, cap):
    return jnp.arange(cap, dtype=jnp.int32)[None, :] < rows


@functools.lru_cache(maxsize=1)
def _use_pallas() -> bool:
    import jax

    from ..ops.pallas_kernels import pallas_available

    return jax.default_backend() == "tpu" and pallas_available()


def _expand_bits(packed, cap: int, n_dev: int):
    # Pallas on a single-device TPU only: calling a pallas kernel on a
    # multi-device global array outside shard_map would gather it — the
    # XLA formulation partitions under GSPMD for free
    if n_dev == 1 and _use_pallas():
        from ..ops.pallas_kernels import bit_unpack_pallas

        if packed.ndim == 1:
            return bit_unpack_pallas(packed.reshape(1, -1), cap)[0]
        return bit_unpack_pallas(packed, cap)
    return _bits_expand(packed, cap)


def _expand_dict(codes, lut, n_dev: int):
    if n_dev == 1 and _use_pallas():
        from ..ops.pallas_kernels import dict_decode_pallas

        if codes.ndim == 1:
            return dict_decode_pallas(codes.reshape(1, -1), lut)[0]
        return dict_decode_pallas(codes, lut)
    return _dict_expand(codes, lut)


# ---------------------------------------------------------------------------
# the pipeline

def maybe_pipelined_feed(node, catalog, store, mesh, n_dev: int,
                         compute_dtype, counters=None, accountant=None,
                         category: str = "feed", stats=None):
    """Build `node`'s feed through the pipelined path, or return None
    (caller proceeds on the eager path): scan_pipeline off / too small
    under 'auto' / open-transaction overlay on the table / the
    pipeline shed itself after a prefetch OOM."""
    from .feed import _overlay_touches

    settings = store.settings
    mode = resolve_scan_mode(settings)
    if mode == "off":
        return None
    table = node.rel.table
    if _overlay_touches(store, table):
        return None  # session-private visibility: eager reads it exactly
    if settings.get("scan_pipeline") == "auto" and \
            store.table_row_count(table) < AUTO_MIN_ROWS:
        return None
    from .hbm import accountant_for

    acc = accountant_for(store.data_dir) if accountant is None \
        else accountant
    pipe = _ScanPipeline(node, catalog, store, mesh, n_dev,
                         compute_dtype, mode, counters, acc, category,
                         stats, settings.get("scan_prefetch_depth"))
    try:
        return pipe.run()
    except _Shed:
        # prefetch OOM: the pipeline drained (every prefetch charge
        # released) — the eager retry is the cheapest rung of all
        return None


class _ScanPipeline:
    def __init__(self, node, catalog, store, mesh, n_dev, compute_dtype,
                 mode, counters, accountant, category, stats, depth):
        from ..catalog import DistributionMethod
        from .feed import make_chunk_filter

        self.node = node
        self.store = store
        self.mesh = mesh
        self.n_dev = n_dev
        self.mode = mode
        self.counters = counters
        self.acc = accountant
        self.category = category
        # tallies accumulate LOCALLY and fold into the executor-wide
        # accumulator only when the pipeline completes — a shed/failed
        # build's phase walls must not skew the published stats
        self.stats_out = stats
        self.stats = ScanPhaseStats() if stats is not None else None
        # producer-side tallies, folded into `counters` on the
        # STATEMENT thread when the pipeline finishes: incrementing
        # StatCounters from the short-lived producer thread would
        # append one never-reclaimed thread-local slot per feed build
        # (the same reason StreamBatcher passes its chunk filter no
        # counters)
        self.chunks_prefetched = 0
        self.chunks_skipped = 0
        self.table = node.rel.table
        meta = catalog.table(self.table)
        self.sharded = meta.method == DistributionMethod.HASH
        self.colnames = [cid.split(".", 1)[1] for cid in node.columns]
        self.dtypes = []
        for cname in self.colnames:
            dt = meta.schema.column(cname).dtype.numpy_dtype
            if dt == np.float64 and compute_dtype is not None:
                dt = np.dtype(compute_dtype)
            self.dtypes.append(np.dtype(dt))
        self.storage_of = {c: store.storage_column_name(self.table, c)
                           for c in self.colnames}
        name_map = {c.name: store.storage_column_name(self.table, c.name)
                    for c in meta.schema.columns}
        # counters=None: the filter runs on the producer thread; skips
        # are tallied from the selection result and folded later
        self.chunk_filter = (make_chunk_filter(node.filter, None,
                                               name_map)
                             if node.filter is not None else None)
        # read units: (dev, shard_id, record) in shard order — the same
        # order the eager path concatenates, so rows land identically
        self.tasks: list[list] = []
        shards = catalog.table_shards(self.table)
        if self.sharded:
            from ..planner.plan import table_placement

            placement = table_placement(catalog, self.table, n_dev)
            for s, dev in zip(shards, placement):
                if node.pruned_shards is not None and \
                        s.shard_index not in node.pruned_shards:
                    continue
                for rec in store.shard_stripe_records(self.table,
                                                      s.shard_id):
                    self.tasks.append([dev, s.shard_id, rec])
        else:
            if len(shards) != 1:
                from ..errors import ExecutionError

                raise ExecutionError(
                    f"table {self.table}: expected single shard")
            for rec in store.shard_stripe_records(self.table,
                                                  shards[0].shard_id):
                self.tasks.append([0, shards[0].shard_id, rec])
        # per-task layout, filled by the first column pass:
        # [dest_offset, n_rows, selected_chunks|None, keep_mask|None,
        #  n_chunks]
        self.layout: list[list] = [[0, 0, None, None, 0]
                                   for _ in self.tasks]
        self.dev_rows = [0] * (n_dev if self.sharded else 1)
        self.cap = 0
        self._readers: dict[str, object] = {}
        self.q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        self.stop_evt = threading.Event()

    # -- producer ----------------------------------------------------------
    def _verified(self, sid: int, fname: str, fn):
        """verified_read with the eager path's failover contract: the
        `store.read_shard` seam fires per stripe read, and a failed
        read carries (table, shard_id) so the statement retry loop can
        mark the placement suspect and route the next attempt to a
        surviving replica (read_shard tags eager reads the same way —
        without this, a dead copy would fail every retry while a
        healthy replica sat idle)."""
        from ..errors import StorageError
        from ..utils.faultinjection import fault_point

        try:
            fault_point("store.read_shard")
            return self.store.verified_read(self.table, sid, fname, fn)
        except Exception as e:
            if isinstance(e, (StorageError, OSError)) or \
                    getattr(e, "injected_fault", False):
                e.table = self.table
                e.shard_id = sid
            raise

    def _reader(self, path: str):
        r = self._readers.get(path)
        if r is None:
            from ..storage.format import StripeReader

            r = StripeReader(path, verify=self.store._verify_enabled())
            self._readers[path] = r
        return r

    def _read_stripe_column(self, ti: int, cname: str, first: bool):
        """One (stripe, column) read through the replica-failover seam.
        Returns (values, validity, n) AFTER delete-mask filtering; the
        first column's pass records the chunk selection + keep mask the
        later columns are pinned to."""
        dev, sid, rec = self.tasks[ti]
        lay = self.layout[ti]
        storage = self.storage_of[cname]
        dmask = (self.store.effective_delete_mask(self.table, sid, rec)
                 if first else None)

        def read_one(path):
            reader = self._reader(path)
            present_all = [self.storage_of[c] for c in self.colnames
                           if self.storage_of[c] in reader._by_name]
            if first:
                # chunk selection over the FULL projection's stats,
                # computed once and pinned for every column; stripes
                # with deletions read whole (positions must align with
                # the bitmap), trading chunk skipping for correctness
                if dmask is None and self.chunk_filter is not None \
                        and present_all:
                    lay[2] = reader.selected_chunks(present_all,
                                                    self.chunk_filter)
                lay[3] = None if dmask is None or not dmask.any() \
                    else ~dmask
                # stash the total only: the tally happens once per
                # stripe AFTER verified_read returns — this closure
                # re-runs on a replica-failover retry and would
                # double-count (idempotent slot write, not an append)
                lay[4] = reader.n_chunks
            sel = lay[2]
            n_sel = (reader.row_count if sel is None
                     else sum(reader.footer["chunk_rows"][i]
                              for i in sel))
            if storage not in reader._by_name:
                # column added by ALTER TABLE after this stripe was
                # written: reads as all-NULL (eager-path contract)
                dt = self.dtypes[self.colnames.index(cname)]
                return (np.zeros(n_sel, dtype=dt),
                        np.zeros(n_sel, dtype=np.bool_), n_sel)
            rv, rm, rn = reader.read([storage], chunks=sel)
            return rv[storage], rm[storage], rn

        v, m, n = self._verified(sid, rec["file"], read_one)
        if first:
            n_ch = len(lay[2]) if lay[2] is not None else lay[4]
            self.chunks_prefetched += n_ch
            self.chunks_skipped += lay[4] - n_ch
            self._stat(chunks_prefetched=n_ch)
        keep = lay[3]
        if keep is not None:
            v, m = v[keep], m[keep]
            n = int(keep.sum())
        return dev if self.sharded else 0, v, m, n

    def _assemble(self, ci: int, pieces=None):
        """[n_dev, cap] (or [cap]) buffer + nulls plane for column ci —
        from the first pass's saved pieces, or by re-reading at the
        recorded offsets."""
        from ..utils.faultinjection import fault_point

        cname = self.colnames[ci]
        dtype = self.dtypes[ci]
        shape = ((len(self.dev_rows), self.cap) if self.sharded
                 else (self.cap,))
        buf = np.zeros(shape, dtype=dtype)
        nbuf = None
        for ti in range(len(self.tasks)):
            if pieces is not None:
                dev, v, m, n = pieces[ti]
            else:
                fault_point("executor.scan_prefetch")
                dev, v, m, n = self._read_stripe_column(ti, cname,
                                                        first=False)
            off = self.layout[ti][0]
            if n == 0:
                continue
            dst = buf[dev] if self.sharded else buf
            dst[off:off + n] = v.astype(dtype)
            if not m.all():
                if nbuf is None:
                    nbuf = np.zeros(shape, dtype=bool)
                ndst = nbuf[dev] if self.sharded else nbuf
                ndst[off:off + n] = ~m
        return buf, nbuf

    def _first_pass(self):
        """Read column 0 across every stripe, recording the layout
        (offsets, chunk selections, keep masks) every later column is
        pinned to.  A zero-column projection (bare count(*)) needs only
        row counts: footers + delete masks, no chunk decode at all —
        cheaper than the eager path, which reads every column to count
        rows."""
        from ..utils.faultinjection import fault_point

        pieces = []
        for ti in range(len(self.tasks)):
            # named seam: a prefetch death must drain the pipeline into
            # a clean statement error, never a hang or a leaked charge
            fault_point("executor.scan_prefetch")
            if self.colnames:
                dev, v, m, n = self._read_stripe_column(
                    ti, self.colnames[0], first=True)
                pieces.append((dev, v, m, n))
            else:
                dev, sid, rec = self.tasks[ti]
                dev = dev if self.sharded else 0
                dmask = self.store.effective_delete_mask(
                    self.table, sid, rec)
                n = self._verified(
                    sid, rec["file"],
                    lambda p: self._reader(p).row_count)
                if dmask is not None and dmask.any():
                    n = int((~dmask).sum())
            lay = self.layout[ti]
            lay[0] = self.dev_rows[dev]
            lay[1] = n
            self.dev_rows[dev] += n
        from .compiler import _round_cap

        self.cap = _round_cap(max(self.dev_rows)
                              if any(self.dev_rows) else 1)
        return pieces

    def _place(self, arr, category=None):
        """Accounted placement from the producer thread — the transfer
        is in flight while the next column decodes.  Sharded buffers go
        through the device-owned slice seam: each device's row slice
        (built from only the shards it owns) dispatches as its own
        transfer and charges its own per-device bytes."""
        cat = self.category if category is None else category
        if self.sharded:
            slices = [arr[d] for d in range(arr.shape[0])]
            out = self.acc.place_sharded_slices_tracked(
                self.mesh, slices, cat)
            if self.stats is not None:
                self.stats.add_device_bytes([s.nbytes for s in slices])
            return out
        return self.acc.place_tracked(self.mesh, arr, False, cat)

    def _encode_and_place(self, ci: int, buf, nbuf):
        """Wire-encode (device mode) + place one column; returns the
        queue payload the consumer finishes."""
        from ..stats.tracing import trace_span

        t0 = time.perf_counter()
        if self.mode != "device":
            with trace_span("scan.transfer"):
                arr, h = self._place(buf, "prefetch")
                payload = {"kind": "plain", "arr": arr, "handle": h,
                           "wire": buf.nbytes, "decoded": buf.nbytes}
                if nbuf is not None:
                    narr, nh = self._place(nbuf, "prefetch")
                    payload.update(
                        nulls=narr, nulls_handle=nh,
                        wire=payload["wire"] + nbuf.nbytes,
                        decoded=payload["decoded"] + nbuf.nbytes)
            self._stat(transfer_seconds=time.perf_counter() - t0)
            return payload
        with trace_span("scan.wire_encode"):
            kind, wire, extra = encode_column(buf)
        t1 = time.perf_counter()
        with trace_span("scan.transfer"):
            arr, h = self._place(wire, "prefetch")
            payload = {"kind": kind, "arr": arr, "handle": h,
                       "dtype": buf.dtype, "wire": wire.nbytes,
                       "decoded": buf.nbytes}
            if kind == "for":
                payload["base"] = extra
            elif kind == "dict":
                lut, lh = self.acc.place_tracked(self.mesh, extra,
                                                 False, "prefetch")
                payload.update(lut=lut, lut_handle=lh,
                               wire=payload["wire"] + extra.nbytes)
            if nbuf is not None:
                packed = np.packbits(nbuf, axis=-1)
                narr, nh = self._place(packed, "prefetch")
                payload.update(nulls=narr, nulls_handle=nh,
                               nulls_packed=True,
                               wire=payload["wire"] + packed.nbytes,
                               decoded=payload["decoded"] + nbuf.nbytes)
        self._stat(decode_seconds=t1 - t0,
                   transfer_seconds=time.perf_counter() - t1)
        return payload

    def _valid_payload(self):
        from ..stats.tracing import trace_span

        t0 = time.perf_counter()
        with trace_span("scan.transfer"):
            if self.mode == "device" and self.sharded:
                rows = np.asarray(self.dev_rows,
                                  dtype=np.int32).reshape(-1, 1)
                arr, h = self._place(rows, "prefetch")
                payload = {"kind": "rows", "arr": arr, "handle": h,
                           "wire": rows.nbytes,
                           "decoded": len(self.dev_rows) * self.cap}
            else:
                if self.sharded:
                    valid = np.zeros((len(self.dev_rows), self.cap),
                                     dtype=bool)
                    for d, r in enumerate(self.dev_rows):
                        valid[d, :r] = True
                else:
                    valid = np.zeros(self.cap, dtype=bool)
                    valid[:self.dev_rows[0]] = True
                arr, h = self._place(valid, "prefetch")
                payload = {"kind": "plain", "arr": arr, "handle": h,
                           "wire": valid.nbytes,
                           "decoded": valid.nbytes}
        self._stat(transfer_seconds=time.perf_counter() - t0)
        return payload

    def _stat(self, **kw):
        if self.stats is not None:
            self.stats.add(**kw)

    def _put(self, item) -> bool:
        while not self.stop_evt.is_set():
            try:
                self.q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self):
        from ..stats.tracing import adopt_context, trace_span
        from ..utils.faultinjection import fault_point

        # the producer adopts the statement's trace context: its
        # prefetch/encode/transfer spans nest under the span that was
        # open when run() captured the token (the feed build), on the
        # producer's own track — any span this thread leaves open is
        # force-closed and counted by adopt_context on the way out
        with adopt_context(self._trace_ctx):
            try:
                t0 = time.perf_counter()
                # classification parity with the eager path: the
                # feed-level placement seam fires here too, before any
                # transfer starts
                fault_point("executor.device_put")
                with trace_span("scan.prefetch"):
                    pieces = self._first_pass()
                self._stat(prefetch_seconds=time.perf_counter() - t0)
                if self.colnames:
                    buf, nbuf = self._assemble(0, pieces)
                    del pieces
                    if not self._put(("col", self.node.columns[0],
                                      self._encode_and_place(0, buf,
                                                             nbuf))):
                        return
                    del buf, nbuf
                for ci in range(1, len(self.colnames)):
                    t0 = time.perf_counter()
                    with trace_span("scan.prefetch"):
                        buf, nbuf = self._assemble(ci)
                    self._stat(
                        prefetch_seconds=time.perf_counter() - t0)
                    if not self._put(("col", self.node.columns[ci],
                                      self._encode_and_place(ci, buf,
                                                             nbuf))):
                        return
                    del buf, nbuf
                if not self._put(("valid", None,
                                  self._valid_payload())):
                    return
                self._put(("done", None, None))
            except DeviceMemoryExhausted as e:
                self._put(("shed", None, e))
            except BaseException as e:  # graftlint: ignore[swallowed-base-exception] — not swallowed: forwarded over the queue and re-raised on the consumer thread
                self._put(("err", None, e))

    # -- consumer ----------------------------------------------------------
    def _finish_col(self, payload, category=None):
        """Adopt one placed column on the statement thread: recharge a
        plain placement to its final category, or expand a wire payload
        on-device and adopt the decoded output."""
        from ..utils.faultinjection import fault_point

        cat = self.category if category is None else category
        self._stat(bytes_on_wire=payload["wire"],
                   bytes_decoded=payload["decoded"])
        kind = payload["kind"]
        decoded_nulls = None
        from ..stats.tracing import trace_span

        if payload.get("nulls") is not None:
            if payload.get("nulls_packed"):
                fault_point("executor.device_decode")
                t0 = time.perf_counter()
                with trace_span("scan.device_decode"):
                    decoded_nulls = _expand_bits(payload["nulls"],
                                                 self.cap, self.n_dev)
                    self.acc.adopt(decoded_nulls, self.sharded,
                                   self.n_dev, cat)
                self._stat(
                    device_decode_seconds=time.perf_counter() - t0)
                self._count_decoded(decoded_nulls)
            else:
                self.acc.recharge(payload["nulls_handle"], cat)
                decoded_nulls = payload["nulls"]
        if kind == "plain":
            self.acc.recharge(payload["handle"], cat)
            return payload["arr"], decoded_nulls
        # named seam: a failure while expanding a wire payload must
        # surface as a clean statement error with the charge released
        fault_point("executor.device_decode")
        t0 = time.perf_counter()
        with trace_span("scan.device_decode"):
            if kind == "for":
                decoded = _for_expand(payload["arr"], payload["base"])
            elif kind == "dict":
                decoded = _expand_dict(payload["arr"], payload["lut"],
                                       self.n_dev)
            else:  # rows → valid prefix
                decoded = _valid_expand(payload["arr"], self.cap)
            self.acc.adopt(decoded, self.sharded, self.n_dev, cat)
        self._stat(device_decode_seconds=time.perf_counter() - t0)
        self._count_decoded(decoded)
        return decoded, decoded_nulls

    def _count_decoded(self, arr) -> None:
        if self.counters is not None:
            from ..stats.counters import DEVICE_DECODED_BYTES_TOTAL

            self.counters.increment(DEVICE_DECODED_BYTES_TOTAL,
                                    int(arr.nbytes))

    def run(self):
        from ..stats.tracing import capture_context
        from ..utils.cancellation import check_cancel
        from .compiler import FeedSpec

        # hand the statement's trace context to the producer thread
        # (None when nothing is being traced — adoption then no-ops)
        self._trace_ctx = capture_context()
        t = threading.Thread(target=self._produce, daemon=True,
                             name="scan-prefetch")
        t.start()
        arrays: dict = {}
        nulls: dict = {}
        valid = None
        waiting = False
        got_first = False
        try:
            while True:
                # queue pops are the consumer's cancellation seams (the
                # finally below unwinds the producer cleanly)
                check_cancel()
                try:
                    kind, cid, payload = self.q.get(timeout=0.25)
                except queue.Empty:
                    # the initial fill is not an underrun: the first
                    # column's full read can never be hidden behind a
                    # previous one, so counting it would stamp one
                    # noise stall on every feed regardless of depth
                    if not waiting and got_first:
                        waiting = True
                        self._stat(prefetch_stalls=1)
                        if self.counters is not None:
                            from ..stats.counters import (
                                PREFETCH_STALLS_TOTAL,
                            )

                            self.counters.increment(
                                PREFETCH_STALLS_TOTAL)
                    continue
                waiting = False
                got_first = True
                if kind == "err":
                    raise payload
                if kind == "shed":
                    # the SAME statement attempt redoes this feed
                    # eagerly (its chunk filter counts skips afresh):
                    # folding the discarded build's tallies too would
                    # double-report the statement's chunk accounting
                    self.chunks_prefetched = self.chunks_skipped = 0
                    raise _Shed()
                if kind == "done":
                    break
                if kind == "col":
                    a, nb = self._finish_col(payload)
                    arrays[cid] = a
                    if nb is not None:
                        nulls[cid] = nb
                else:  # valid
                    valid, _ = self._finish_col(payload)
        finally:
            self.stop_evt.set()
            while True:  # drain so a blocked put wakes immediately
                try:
                    self.q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)
            # fold producer tallies on THIS (statement) thread — a
            # per-producer-thread increment would leak counter slots
            if self.counters is not None:
                from ..stats.counters import (
                    CHUNKS_PREFETCHED_TOTAL,
                    CHUNKS_SKIPPED,
                )

                if self.chunks_prefetched:
                    self.counters.increment(CHUNKS_PREFETCHED_TOTAL,
                                            self.chunks_prefetched)
                if self.chunks_skipped:
                    self.counters.increment(CHUNKS_SKIPPED,
                                            self.chunks_skipped)
        self._stat(feeds_pipelined=1)
        if self.stats_out is not None:
            self.stats_out.merge(self.stats)
        return FeedSpec(node=self.node, sharded=self.sharded,
                        arrays=arrays, nulls=nulls, valid=valid,
                        capacity=self.cap,
                        dev_rows=(list(self.dev_rows) if self.sharded
                                  else None))
