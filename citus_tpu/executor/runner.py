"""Distributed execution driver: capacities, retry loop, host combine.

The coordinator-side finish: gathers device outputs, evaluates the combine
phase (host_select / HAVING / ORDER BY / LIMIT — the combine_query of
planner/combine_query_planner.c), decodes dictionary strings, and returns a
ResultSet.  Overflowed static buffers trigger recompile-with-doubled-caps
(bounded retries), the executor's answer to data-dependent cardinalities.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass

import numpy as np
from jax.sharding import Mesh

from ..catalog import Catalog
from ..config import Settings
from ..errors import (
    CapacityOverflowError,
    DeviceMemoryExhausted,
    ExecutionError,
    PlanningError,
)
from ..planner import expr as ir
from ..planner.plan import (
    AggregateNode,
    JoinNode,
    ProjectNode,
    QueryPlan,
    ScanNode,
    WindowNode,
)
from ..storage import TableStore
from ..storage.dictionary import resolve_decode
from ..types import DataType, days_to_date
from .cache import (
    FeedCache,
    PlanCache,
    caps_signature,
    feeds_signature,
    node_fingerprint,
)
from .compiler import (
    Capacities,
    PlanCompiler,
    _round_cap,
    flatten_feed_arrays,
    unpack_outputs,
)
from .exprs import ColumnSource, evaluate, predicate_mask
from .feed import build_feeds, walk_plan

MAX_RETRIES = 4

# degradation ladder bounds: each batch-shrink rung halves the stream
# batch (one memoized recompile per level); beyond this the rung is
# spent and the ladder moves on
MAX_BATCH_SHRINK = 64


@dataclass
class OomState:
    """Sticky (per-executor) outcome of the OOM degradation ladder —
    memoized so a statement that needed rungs does not re-discover
    them (and re-pay the OOM + recompile) on every execution.

    * ``batch_shrink`` — divisor applied to the stream batch_cap;
    * ``force_stream`` — stream even when the feeds fit the configured
      budget (a real OOM proved the effective ceiling lower);
    * ``multipass_k`` — split the build side into K host-resident
      passes (executor/multipass.py)."""

    batch_shrink: int = 1
    force_stream: bool = False
    multipass_k: int = 1


@dataclass
class ResultSet:
    column_names: list[str]
    columns: dict[str, np.ndarray | list]
    row_count: int
    # output SQL types by column name (None where a producer has no type
    # info, e.g. UDF results); lets consumers round-trip DATE values that
    # the combine phase formatted to ISO strings
    dtypes: dict[str, DataType] | None = None
    # execution metadata (EXPLAIN ANALYZE / stats counters read these)
    retries: int = 0
    device_rows_scanned: int = 0
    # rows each mesh device fed INTO the program (per-device sums over
    # the sharded scan feeds; None when unknown) — the Mesh: line's
    # rows-in column
    device_rows_in: list[int] | None = None
    fast_path: bool = False   # executed host-side via the fast-path router
    streamed_batches: int = 0  # >0 ⇒ executed via the stream pipeline
    spill_passes: int = 0     # >0 ⇒ executed via multi-pass partitioning
    # per-column NULL masks (raw mode keeps typed arrays + mask instead of
    # objectified None entries); None when columns carry None directly
    null_masks: dict[str, np.ndarray] | None = None
    # raw mode: STRING columns hold dictionary codes for this source
    # (output name → (table, column) whose dictionary decodes them)
    decode_map: dict[str, tuple[str, str]] | None = None
    # raw mode: surviving-row count per device, rows in device-major
    # order — lets a colocated INSERT..SELECT slice per-device blocks
    # without re-hashing.  None when HAVING/ORDER/LIMIT disturbed the
    # device order.
    device_rows: list[int] | None = None

    def rows(self) -> list[tuple]:
        cols = [self.columns[n] for n in self.column_names]
        return [tuple(c[i] for c in cols) for i in range(self.row_count)]

    def __len__(self):
        return self.row_count


class Executor:
    def __init__(self, catalog: Catalog, store: TableStore,
                 settings: Settings, mesh: Mesh, counters=None):
        self.catalog = catalog
        self.store = store
        self.settings = settings
        self.mesh = mesh
        self.counters = counters
        self.plan_cache = PlanCache(
            settings.get("max_cached_plans"))
        self.feed_cache = FeedCache(
            settings.get("max_cached_feed_bytes"))
        # fingerprint → plan-walk-order-keyed capacities that last
        # succeeded: a query whose first run needed overflow/dense
        # retries starts warm runs from the converged sizes instead of
        # re-paying the retry executions.  Keyed by walk INDEX, not node
        # id — every execution builds a fresh QueryPlan instance.
        # Persisted under the data dir: a NEW session starts from the
        # converged/tightened sizes instead of re-paying the feedback
        # recompile (a stale entry self-heals via overflow-retry)
        self._caps_memo: dict = self._load_caps_memo()
        # fingerprints already tightened by capacity feedback: tighten at
        # most ONCE per plan shape, or generic (prepared) plans would
        # recompile on every parameter value's slightly different actuals
        self._tightened_fps: set = set()
        # caps-memo persistence debounce state: under a compile storm
        # every memoization used to rewrite the whole memo file
        # (O(N²) bytes) — writes now coalesce and flush_persistent()
        # drains the remainder at session close
        self._memo_dirty = 0
        self._memo_last_write = 0.0
        self._memo_writes = 0  # rewrite count (regression-tested)
        # concurrent execute() threads share this executor: the memo
        # dict is iterated while being written (_memoize_caps), which
        # CPython turns into "dict changed size during iteration"
        self._caps_lock = threading.Lock()
        # device-memory accountant: ONE per data_dir (sessions share
        # the device) — every placement this executor makes flows
        # through it, and the OOM degradation ladder consults its
        # measured ledger (executor/hbm.py)
        from .hbm import accountant_for

        self.accountant = accountant_for(store.data_dir)
        self.accountant.register_evictable(self.feed_cache)
        # persistent executable cache + single-flight compile gate:
        # ONE per data_dir (sessions share the device and the disk) —
        # a restart loads serialized executables instead of recompiling
        # and N sessions racing a cold shape produce ONE compile
        # (executor/execcache.py; gated by `exec_cache_enabled`)
        from .execcache import exec_cache_for

        self.exec_cache = exec_cache_for(store.data_dir)
        # scan-pipeline phase accounting (executor/scanpipe.py): the
        # bench drivers reset + read this to stamp prefetch/decode/
        # transfer walls and the bytes-on-wire ratio into the artifact
        from .scanpipe import ScanPhaseStats

        self.scan_stats = ScanPhaseStats()
        self.oom = OomState()
        # per-thread plan of the in-flight statement: the degradation
        # ladder peeks at it to skip rungs that cannot help this shape
        self._oom_tls = threading.local()

    # ------------------------------------------------------------------
    def execute_plan(self, plan: QueryPlan, raw: bool = False) -> ResultSet:
        from .fastpath import try_execute_fast_path

        # cross-session read-committed visibility: another session over
        # this data_dir may have committed since our manifest was cached
        # (one stat() per scanned table; writers refresh under the DML
        # lock, this is the readers' counterpart)
        for node in walk_plan(plan.root):
            if isinstance(node, ScanNode):
                self.store.refresh_if_stale(node.rel.table)

        # the degradation ladder peeks at the in-flight plan to decide
        # which rungs can help this statement's shape
        self._oom_tls.plan = plan
        fast = try_execute_fast_path(self, plan, raw)
        if fast is not None:
            return fast
        if self.oom.multipass_k > 1:
            from .multipass import try_execute_multipass

            mp = try_execute_multipass(self, plan, raw,
                                       self.oom.multipass_k)
            if mp is not None:
                return mp
        from .stream import try_execute_streamed

        streamed = try_execute_streamed(self, plan, raw)
        if streamed is not None:
            return streamed
        compute_dtype = np.dtype(self.settings.get("compute_dtype"))
        packed, out_meta, caps, retries, feeds = self._run_resident(
            plan, compute_dtype)
        self.count_groupby_bucketed(plan, caps)
        from ..stats.tracing import trace_span

        with trace_span("combine"):
            cols, nulls, valid = unpack_outputs(packed, out_meta)
            result = self._host_combine(plan, cols, nulls, valid, raw)
        result.retries = retries
        # result-transfer volume in row slots (n_dev·cap, or n_dev·k under
        # device top-k pushdown) — EXPLAIN ANALYZE / stats surface this
        result.device_rows_scanned = int(np.asarray(valid).size)
        result.device_rows_in = feed_device_rows(feeds, plan.n_devices)
        return result

    # ------------------------------------------------------------------
    def _run_resident(self, plan: QueryPlan, compute_dtype,
                      no_cache_nodes=frozenset()):
        """Resident-feed execution core: build feeds, resolve the
        capacity memo, run the overflow-retry loop.  Shared by
        execute_plan and the multipass pass driver."""
        from ..stats.tracing import trace_span

        with trace_span("feed"):
            feeds = build_feeds(plan, self.catalog, self.store,
                                self.mesh, compute_dtype,
                                cache=self.feed_cache,
                                counters=self.counters,
                                accountant=self.accountant,
                                no_cache_nodes=no_cache_nodes,
                                stats=self.scan_stats)
        # device_topk + its ORDER BY keys are traced into the program
        topk_sig = (plan.device_topk, tuple(
            (repr(e), d, nf) for e, d, nf in plan.host_order_by)
            if plan.device_topk is not None else ())
        orp = plan.output_repart
        orp_sig = (None if orp is None
                   else (orp[0], orp[1], orp[2], repr(orp[3])))
        # group_by_kernel changes which CAPACITY TABLES exist
        # (agg_bucket vs sort-path buffers), so converged sizes memoized
        # under one mode must not be replayed under another — it joins
        # the fingerprint, unlike join_probe_kernel which only swaps the
        # inner formulation at unchanged shapes
        fingerprint = (node_fingerprint(plan.root), plan.n_devices,
                       str(compute_dtype), feeds_signature(plan, feeds),
                       topk_sig, orp_sig,
                       self.settings.get("group_by_kernel"))
        with self._caps_lock:
            memo = self._caps_memo.get(fingerprint)
        caps = (self._caps_from_order(plan, memo) if memo is not None
                else self._initial_capacities(plan, feeds))
        packed, out_meta, caps, retries = self.run_with_retry(
            plan, feeds, caps, fingerprint, compute_dtype)
        return packed, out_meta, caps, retries, feeds

    # ------------------------------------------------------------------
    def execute_pass(self, plan: QueryPlan, split_nid: int):
        """One multipass pass (executor/multipass.py): run the pruned
        plan via the stream pipeline when it still exceeds the budget,
        else resident, and return its flattened pre-combine parts as
        (parts, rows_scanned, retries, streamed_batches).  The split
        scan's per-pass feed bypasses the device cache — resident-
        caching every pass's partition would defeat the pass."""
        from .stream import _flatten_batch, try_execute_streamed

        streamed = try_execute_streamed(self, plan, raw=True,
                                        return_parts=True,
                                        no_cache_nodes=frozenset(
                                            {split_nid}))
        if streamed is not None:
            parts, scanned, retries, batches, caps = streamed
            if caps is not None:
                self.count_groupby_bucketed(plan, caps)
            return parts, scanned, retries, batches
        compute_dtype = np.dtype(self.settings.get("compute_dtype"))
        packed, out_meta, caps, retries, _feeds = self._run_resident(
            plan, compute_dtype, no_cache_nodes=frozenset({split_nid}))
        self.count_groupby_bucketed(plan, caps)
        cols, nulls, valid = unpack_outputs(packed, out_meta)
        scanned = int(np.asarray(valid).size)
        return [_flatten_batch(cols, nulls, valid)], scanned, retries, 0

    # ------------------------------------------------------------------
    def run_with_retry(self, plan: QueryPlan, feeds, caps: Capacities,
                       fingerprint, compute_dtype, allow_tighten=True):
        """Compile (or fetch cached) + execute + overflow-retry loop.

        Shared by the resident-feed path and the streamed (batched)
        path.  Returns (packed, out_meta, converged_caps, retries);
        converged capacities are memoized under `fingerprint` whenever a
        retry occurred so later executions start warm.

        Capacity feedback (the adaptive-executor move,
        adaptive_executor.c:962, done the static-shape way): a clean
        execution whose recorded stage actuals sit far below their
        buffers tightens the capacities to actual×slack, recompiles
        once, and memoizes — warm executions then run with near-actual
        buffers even where the planner's estimate was 10× off (join
        selectivities over correlated columns are statically
        unestimable).  An over-tightened buffer (data changed) simply
        overflows and regrows through the normal retry path."""
        from ..utils.cancellation import check_cancel

        limit = self.settings.get("max_plan_buffer_bytes")
        retries = 0
        tightened = False
        while True:
            check_cancel()  # overflow-retry iterations are cancel seams
            if limit:
                est = _plan_buffer_bytes(plan, caps)
                if est > limit:
                    if self._plan_degradable(plan):
                        # eligible over-limit plans route into the OOM
                        # degradation ladder (stream / multi-pass)
                        # instead of erroring — the guard becomes a
                        # pre-allocation OOM signal
                        raise DeviceMemoryExhausted(
                            f"RESOURCE_EXHAUSTED (guard): plan needs "
                            f"~{est / 1e9:.1f} GB of device buffers "
                            f"(max_plan_buffer_bytes = "
                            f"{limit / 1e9:.1f} GB) — degrading")
                    raise PlanningError(
                        f"plan needs ~{est / 1e9:.1f} GB of device "
                        f"buffers (max_plan_buffer_bytes = "
                        f"{limit / 1e9:.1f} GB) — usually a cartesian "
                        "or extreme-fanout join; rewrite the query or "
                        "raise the limit")
            probe_kernel = self.settings.get("join_probe_kernel")
            # group_by_kernel already rides in `fingerprint` (it shapes
            # the capacity tables); probe_kernel only swaps the inner
            # formulation so it joins the key here
            group_kernel = self.settings.get("group_by_kernel")
            from ..stats.tracing import trace_span

            key = fingerprint + (caps_signature(plan, caps), probe_kernel)
            entry = self.plan_cache.get(key)
            if entry is None:
                from ..utils.faultinjection import fault_point

                # named seam: a failure while tracing/compiling must
                # leave the plan cache without a half-built entry
                fault_point("executor.plan_cache_fill")
                entry = self._compile_or_load(plan, feeds, caps,
                                              compute_dtype,
                                              probe_kernel, group_kernel,
                                              key)
                self.plan_cache.put(key, entry)
                fn, out_meta, stage_keys, shuffle_bytes = entry
                feed_arrays = flatten_feed_arrays(plan, feeds,
                                                  compute_dtype)
            else:
                fn, out_meta, stage_keys, shuffle_bytes = entry
                with trace_span("compile", cache="hit"):
                    feed_arrays = flatten_feed_arrays(plan, feeds,
                                                      compute_dtype)
            # two device→host transfers total: the bit-packed output block
            # and the overflow counters (each transfer pays a full round
            # trip on remote-attached TPUs)
            import jax

            from ..distributed.mesh import (
                is_device_loss,
                mesh_device_check,
                mesh_device_ids,
            )
            from ..errors import DeviceLostError
            from .hbm import is_resource_exhausted

            # XLA allocates the program's static intermediates where
            # Python cannot see them — the lease makes the estimate
            # visible to the measured ledger (and to an armed MemSim)
            # for exactly the execution window
            est_per_dev = _plan_buffer_bytes(plan, caps) \
                // max(1, plan.n_devices)

            def _dispatch():
                # mesh seams: a device dying mid-collective kills the
                # dispatch; a device dying between dispatch and the
                # device→host pull poisons the fetch.  Both are named
                # fault points AND MeshSim checkpoints, so the whole
                # kill-mid-query failover path is drivable on a CPU
                # test mesh (distributed/mesh.py)
                dev_ids = mesh_device_ids(self.mesh)
                with trace_span("mesh.dispatch"):
                    fault_point("mesh.collective")
                    mesh_device_check("mesh.collective", dev_ids)
                    out = fn(*feed_arrays)
                with trace_span("mesh.fetch"):
                    fault_point("mesh.fetch")
                    mesh_device_check("mesh.fetch", dev_ids)
                    return jax.device_get(out)

            from ..utils.faultinjection import fault_point

            try:
                with self.accountant.lease("plan", est_per_dev):
                    packed, overflow = _dispatch()
            except jax.errors.JaxRuntimeError as e:
                if is_resource_exhausted(e):
                    # the canonical accelerator failure: classify it so
                    # the session retry envelope degrades-then-retries
                    # instead of dying (errors.DeviceMemoryExhausted)
                    self.accountant.note_oom()
                    raise DeviceMemoryExhausted(
                        f"device allocator OOM executing plan "
                        f"(~{est_per_dev} intermediate bytes/device): "
                        f"{e}") from e
                if is_device_loss(e):
                    # a device (or its ICI link) died under the
                    # compiled program: classify it so the session
                    # retry envelope shrinks the mesh and fails over
                    # instead of dying (errors.DeviceLostError; the
                    # session's probe pass identifies WHICH device)
                    raise DeviceLostError(
                        f"device loss executing plan: {e}",
                        seam="mesh.collective") from e
                # remote-attached compile services flake transiently on
                # long compilations (connection drops mid-response); one
                # clean retry re-issues the compile.  Anything else, or a
                # second failure, propagates.
                if "remote_compile" not in str(e):
                    raise
                with self.accountant.lease("plan", est_per_dev):
                    packed, overflow = _dispatch()
            ov = np.asarray(overflow).reshape(-1, 2 + len(stage_keys))
            cap_overflow = int(ov[:, 0].sum())
            dense_oob = int(ov[:, 1].sum())
            if cap_overflow == 0 and dense_oob == 0:
                first_tighten = False
                if allow_tighten and not tightened and \
                        self.settings.get("enable_capacity_feedback"):
                    with self._caps_lock:
                        if fingerprint not in self._tightened_fps:
                            if len(self._tightened_fps) > 512:
                                self._tightened_fps.clear()
                            self._tightened_fps.add(fingerprint)
                            first_tighten = True
                if first_tighten:
                    tight = self._tighten_caps(
                        plan, caps, stage_keys,
                        ov[:, 2:].max(axis=0) if len(stage_keys) else [])
                    if tight is not None:
                        caps = tight
                        tightened = True
                        self._memoize_caps(fingerprint, plan, caps)
                        continue  # recompile tight + re-execute
                if retries or tightened:
                    self._memoize_caps(fingerprint, plan, caps)
                if self.counters is not None and shuffle_bytes:
                    # TRACED all_to_all volume of the converged
                    # execution (PlanCompiler counts the exchange
                    # stages that actually exist — the psum-directory
                    # pushdown compiles shuffles away; stream paths
                    # pass here per batch, so the counter scales with
                    # what actually crossed the mesh)
                    from ..stats import counters as sc

                    self.counters.increment(sc.SHUFFLE_BYTES_TOTAL,
                                            shuffle_bytes)
                return packed, out_meta, caps, retries
            retries += 1
            from ..utils.faultinjection import fault_point

            # named seam: a failure while growing capacities must leave
            # the plan cache / capacity memo consistent (the retry loop
            # is the count-then-emit recovery path)
            fault_point("executor.overflow_retry")
            if retries >= MAX_RETRIES:
                raise CapacityOverflowError(
                    f"buffer overflow persisted after {retries} retries "
                    f"({cap_overflow + dense_oob} rows dropped)",
                    cap_overflow + dense_oob, 0)
            if dense_oob:
                # statistics-planned dense structures (join directories,
                # dense agg grids) saw out-of-range keys: stats were
                # stale — recompile on the general paths.  Merge with the
                # current capacities so growth from earlier overflow
                # retries isn't thrown away (each wasted cycle would
                # burn one of MAX_RETRIES)
                fresh = self._initial_capacities(plan, feeds,
                                                 dense_off=True)
                caps = Capacities(
                    {k: max(v, caps.repartition.get(k, 0))
                     for k, v in fresh.repartition.items()},
                    {k: max(v, caps.join_out.get(k, 0))
                     for k, v in fresh.join_out.items()},
                    {k: max(v, caps.agg_out.get(k, 0))
                     for k, v in fresh.agg_out.items()},
                    dense_off=True,
                    scan_out={k: max(v, caps.scan_out.get(k, 0))
                              for k, v in fresh.scan_out.items()},
                    output_repart=max(fresh.output_repart or 0,
                                      caps.output_repart or 0) or None,
                    bucket_probe={k: max(v, caps.bucket_probe.get(k, 0))
                                  for k, v in fresh.bucket_probe.items()},
                    agg_bucket={k: max(v, caps.agg_bucket.get(k, 0))
                                for k, v in fresh.agg_bucket.items()})
            if cap_overflow:
                caps = caps.grown(cap_overflow)
            # overflow-regrow bounded by the accountant: a regrow whose
            # buffers can no longer fit the remaining device budget
            # would retry straight into a guaranteed OOM — degrade
            # (stream / multi-pass) instead of burning the retries
            budget = self.accountant.budget_bytes(self.settings)
            if budget:
                need = _plan_buffer_bytes(plan, caps) \
                    // max(1, plan.n_devices)
                room = budget - self.accountant.pressure_bytes()
                if need > room and self._plan_degradable(plan):
                    raise DeviceMemoryExhausted(
                        f"RESOURCE_EXHAUSTED (regrow guard): capacity "
                        f"regrow needs ~{need} bytes/device but only "
                        f"~{room} remain of the {budget}-byte device "
                        "budget — degrading instead of retrying into "
                        "a guaranteed OOM")

    # ------------------------------------------------------------------
    def _compile_or_load(self, plan: QueryPlan, feeds, caps: Capacities,
                         compute_dtype, probe_kernel, group_kernel,
                         key) -> tuple:
        """Plan-cache miss resolution, restart-survivable.  The whole
        resolve — disk load AND compile — runs single-flight through
        the per-data_dir gate: N sessions hitting a cold shape produce
        ONE deserialization (the PystachIO one-load-per-replica move)
        or, when the disk has nothing, ONE compile; followers wait
        under their own deadline/cancel budget and adopt the leader's
        executable.  Inside the flight the order is:

        1. the persistent executable cache (``exec_cache_enabled``):
           load-don't-compile — a deserialized AOT executable replaces
           trace + XLA compile (corrupt/skewed entries are detected
           and fall through);
        2. the compile itself, AOT (lower + compile, so the finished
           executable is serializable), persisted through the io seam.

        Returns the plan-cache entry ``(fn, out_meta, stage_keys,
        shuffle_bytes)``."""
        from ..stats import counters as sc
        from ..stats.tracing import trace_span

        use_cache = self.settings.get("exec_cache_enabled")
        ec = self.exec_cache

        def compile_fn():
            with trace_span("compile", cache="miss"):
                compiler = PlanCompiler(plan, self.mesh, feeds,
                                        caps, compute_dtype,
                                        probe_kernel=probe_kernel,
                                        group_kernel=group_kernel)
                fn, feed_arrays, out_meta, stage_keys = \
                    compiler.build()
                # AOT: compile NOW (not lazily at first dispatch) so
                # the executable exists to serialize and to hand to
                # deduped followers
                fn = fn.lower(*feed_arrays).compile()
            ec.note_compile()  # actual-compile ledger (dedup asserts)
            entry = (fn, out_meta, stage_keys, compiler.shuffle_bytes)
            if use_cache:
                ec.store(key, self.mesh, *entry)
            return entry

        if not use_cache:
            return compile_fn()

        def resolve_fn():
            with trace_span("compile.cache_load"):
                entry, status = ec.load(key, self.mesh)
            if self.counters is not None:
                if status == "hit":
                    self.counters.increment(sc.EXEC_CACHE_HITS_TOTAL)
                elif status == "reject":
                    # detected rot/skew: recorded, then recompiled
                    self.counters.increment(sc.EXEC_CACHE_REJECTS_TOTAL)
                else:
                    self.counters.increment(sc.EXEC_CACHE_MISSES_TOTAL)
            if entry is not None:
                return entry
            return compile_fn()

        entry, deduped = ec.gate.run(key, resolve_fn)
        if deduped and self.counters is not None:
            self.counters.increment(sc.COMPILES_DEDUPED_TOTAL)
        return entry

    # ------------------------------------------------------------------
    def warmup_from_cache(self, deadline: float, top_n: int,
                          stop=None) -> int:
        """Warm-before-admit: pre-adopt the persisted cache's hottest
        executables into this executor's plan cache before the WLM
        admits non-exempt traffic (Session starts this on a warmup
        thread; the admission hold auto-expires at `deadline`).  Runs
        until the entries or the monotonic `deadline` run out —
        overrun or a fault degrades gracefully to lazy loading, never
        blocks admission forever.  Returns executables adopted."""
        import time as _time

        from ..stats import counters as sc
        from ..stats.tracing import trace_span
        from ..utils.faultinjection import fault_point

        loaded = 0
        for h in self.exec_cache.top_hashes(max(0, top_n)):
            if _time.monotonic() >= deadline or \
                    (stop is not None and stop.is_set()):
                # budget spent or the owning session is closing (the
                # admission hold must not outlive it): lazy from here
                break
            try:
                fault_point("wlm.warmup")
                with trace_span("wlm.warmup"):
                    key, entry = self.exec_cache.load_hash(h, self.mesh)
            except Exception:  # graftlint: ignore[swallowed-fault-seam] — not swallowed into silence: a warmup failure (injected or real) degrades to lazy compile by design; the admission hold releases in the caller's finally
                break
            if entry is None:
                continue  # skewed/corrupt entry: lazy path rejects too
            self.plan_cache.put(key, entry)
            loaded += 1
            if self.counters is not None:
                self.counters.increment(sc.WARMUP_COMPILES_TOTAL)
        return loaded

    # ------------------------------------------------------------------
    def adopt_mesh(self, mesh: Mesh) -> None:
        """Swap in a (usually shrunken) mesh after device loss or an
        elastic resize — the session's mesh-degrade path calls this
        after rebuilding the mesh from survivors.  Compiled executables
        and cache-resident feeds reference the dead device's buffers,
        so both caches drop wholesale (plans re-key on the new
        n_devices anyway; the caps memo keys on n_devices too, so
        converged sizes for other widths stay warm).  Statements
        already in flight on the old mesh object finish there — fake
        and surviving real devices keep answering for them — and their
        next retry re-plans onto this mesh."""
        self.mesh = mesh
        self.plan_cache.clear()
        self.feed_cache.clear()
        self.accountant.resize_mesh(mesh.devices.size)

    # ------------------------------------------------------------------
    def _plan_degradable(self, plan: QueryPlan) -> bool:
        """Can the degradation ladder shrink this plan's footprint?
        (executor/multipass.py owns the shape rules; windows and
        cartesian blowups stay clean immediate rejects.)"""
        from .multipass import ladder_degradable

        return ladder_degradable(
            plan, self.catalog, self.store, plan.n_devices,
            np.dtype(self.settings.get("compute_dtype")))

    # ------------------------------------------------------------------
    def degrade_for_oom(self, step: int, nbytes: int | None = None
                        ) -> str | None:
        """Apply the next rung of the OOM degradation ladder; returns
        the rung name, or None when no rung can help (the session then
        surfaces a clean ResourceExhausted).  `step` is the statement's
        1-based OOM count — monotone, so repeated OOMs walk DOWN the
        ladder instead of cycling on one rung; `nbytes` is the failed
        allocation's size when known (bounds the eviction target).

        Rungs, cheapest first:
          1. evict feed/result caches coldest-first (free HBM, nothing
             recompiles);
          2. halve the stream batch_cap (one memoized recompile);
          3. force the stream path even under the resident ceiling;
          4+. multi-pass partitioned execution, K doubling per rung.
        EVERY rung re-runs the eviction first — a retry re-fills the
        device cache, and stale cached feeds riding into a shrunk/
        streamed re-run would eat exactly the headroom the rung just
        created.  Batch-shrink/force/multipass state is sticky on the
        executor — memoized, so later statements start from the
        converged shape."""
        evicted = self._evict_for_oom(nbytes)
        if step <= 1:
            if evicted:
                return "evict_caches"
            step = 2  # nothing to evict: spend the escalation rung now
        plan = getattr(self._oom_tls, "plan", None)
        can_stream = False
        can_multipass = False
        if plan is not None:
            from .multipass import multipass_candidate
            from .stream import stream_candidates

            can_stream = bool(stream_candidates(plan, self.catalog))
            can_multipass = multipass_candidate(
                plan, self.catalog, self.store, plan.n_devices,
                np.dtype(self.settings.get("compute_dtype"))) is not None
        max_passes = self.settings.get("oom_max_spill_passes")
        i = step - 2  # escalation ladder position (0-based)
        while True:
            if i == 0:
                if can_stream and self.oom.batch_shrink < MAX_BATCH_SHRINK:
                    self.oom.batch_shrink *= 2
                    if self.counters is not None:
                        from ..stats import counters as sc

                        self.counters.increment(
                            sc.STREAM_BATCH_SHRINKS_TOTAL)
                    return "shrink_stream_batch"
            elif i == 1:
                if can_stream and not self.oom.force_stream:
                    self.oom.force_stream = True
                    return "force_stream"
            else:
                if can_multipass and self.oom.multipass_k < max_passes:
                    self.oom.multipass_k = min(
                        max_passes, max(2, self.oom.multipass_k * 2))
                    return "multipass"
                return None
            i += 1

    def _evict_for_oom(self, nbytes: int | None = None) -> int:
        """Rung 1: drop cache-resident device arrays coldest-first —
        across EVERY session's FeedCache on this data_dir (the device
        is shared; another session's cache pins HBM just the same).
        Frees at least 4× the failed allocation when its size is known
        (headroom for the retry's sibling feeds), everything
        otherwise.  Returns DEVICE cache entries evicted — only those
        mark the rung successful (a retry is pointless unless HBM was
        actually freed)."""
        # err.nbytes is PER-DEVICE; CachedFeed.nbytes (what eviction
        # counts down) is the host array total across all devices —
        # scale the target or sharded feeds under-evict by n_devices
        n_dev = max(1, self.mesh.devices.size)
        target = nbytes * 4 * n_dev if nbytes else None
        evicted = self.accountant.evict_evictable(target)
        if evicted and self.counters is not None:
            from ..stats import counters as sc

            self.counters.increment(sc.CACHE_EVICTIONS_TOTAL, evicted)
        # best-effort: finished result sets are host bytes, but a
        # memory-pressured data_dir should not keep serving caches
        # warm either; never resurrects a released registry entry and
        # never counts toward the rung's success
        from ..serving.result_cache import peek_result_cache

        rcache = peek_result_cache(self.store.data_dir)
        if rcache is not None and len(rcache):
            rcache.clear()
        return evicted

    # ------------------------------------------------------------------
    def count_groupby_bucketed(self, plan: QueryPlan,
                               caps: Capacities) -> None:
        """groupby_bucketed_total: bump once per executed STATEMENT
        whose converged plan ran the bucketed dense-grid group-by —
        callers invoke this after their retry loop settles (the
        streamed path calls it once after the batch loop, not per
        batch), and a dense_oob fallback onto the sort path
        (caps.dense_off) correctly counts nothing."""
        if self.counters is None:
            return
        from ..stats import counters as sc

        group_kernel = self.settings.get("group_by_kernel")
        nbk = sum(1 for nd in walk_plan(plan.root)
                  if isinstance(nd, AggregateNode)
                  and PlanCompiler.agg_bucket_shape(
                      nd, group_kernel, caps.dense_off))
        if nbk:
            self.counters.increment(sc.GROUPBY_BUCKETED_TOTAL, nbk)

    # ------------------------------------------------------------------
    CAPS_MEMO_VERSION = 6  # bump when capacity semantics change

    def _memo_path(self) -> str:
        import os

        return os.path.join(self.store.data_dir, "caps_memo.json")

    # the memo is plain tuples/dicts of ints, strings, bools and Nones —
    # JSON round-trips it (lists→tuples, int keys re-parsed) without the
    # arbitrary-code-execution hazard pickle.load would add to a SHARED
    # data_dir (every other persisted artifact here is JSON for the same
    # reason).  ONE codec, shared with the executable cache's key
    # encoding (executor/execcache.py) — the two used to be copies and
    # diverged on numpy-scalar coercion, which made memo persistence
    # silently fail (TypeError swallowed below) for fingerprints
    # carrying np.int64 key extents.
    @staticmethod
    def _memo_to_json(obj):
        from .execcache import key_to_json

        return key_to_json(obj)

    @staticmethod
    def _memo_from_json(obj):
        from .execcache import key_from_json

        return key_from_json(obj)

    def _load_caps_memo(self) -> dict:
        import json as _json

        try:
            with open(self._memo_path()) as f:
                obj = _json.load(f)
            if obj.get("version") == self.CAPS_MEMO_VERSION:
                return {self._memo_from_json(k): self._memo_from_json(v)
                        for k, v in obj["memo"]}
        except (OSError, ValueError, KeyError, TypeError,
                AttributeError):
            # unreadable/corrupt memo file (incl. valid JSON that is
            # not an object — obj.get raises AttributeError): start cold
            pass
        return {}

    # memo bounds + rewrite debounce: overflow evicts the OLDEST HALF
    # (a full clear() forgot every converged shape at once — a
    # self-inflicted cold start), and the whole-file rewrite coalesces
    # under a compile storm (every memoization used to rewrite O(N)
    # bytes — O(N²) across a storm).  A lone memoization past the idle
    # window still writes immediately; close() drains the remainder
    # via flush_persistent().
    CAPS_MEMO_MAX = 512
    CAPS_MEMO_FLUSH_EVERY = 8
    CAPS_MEMO_FLUSH_IDLE_S = 0.25

    def _memoize_caps(self, fingerprint, plan: QueryPlan,
                      caps: Capacities) -> None:
        self._caps_memo_insert(fingerprint,
                               self._caps_to_order(plan, caps))

    def _caps_memo_insert(self, fingerprint, ordered) -> None:
        import time as _time

        with self._caps_lock:
            if fingerprint not in self._caps_memo and \
                    len(self._caps_memo) >= self.CAPS_MEMO_MAX:
                # evict the oldest half (dict insertion order): the
                # newest converged shapes — the live working set under
                # a storm — stay warm
                for k in list(self._caps_memo)[
                        :len(self._caps_memo) // 2]:
                    del self._caps_memo[k]
            # LRU, not insertion-order: a re-memoized hot shape must
            # move to the young end or the overflow above would evict
            # it as "oldest" despite being actively refreshed
            self._caps_memo.pop(fingerprint, None)
            self._caps_memo[fingerprint] = ordered
            self._memo_dirty += 1
            now = _time.monotonic()
            if self._memo_dirty < self.CAPS_MEMO_FLUSH_EVERY and \
                    now - self._memo_last_write < \
                    self.CAPS_MEMO_FLUSH_IDLE_S:
                return  # coalesce: a later insert or close() flushes
        self._flush_caps_memo()

    def _flush_caps_memo(self) -> None:
        import contextlib
        import os
        import time as _time

        from ..utils.io import atomic_write_json

        # snapshot under the lock (concurrent statements memoize while
        # this thread serializes the items), write the file outside it
        with self._caps_lock:
            if not self._memo_dirty:
                return
            self._memo_dirty = 0
            self._memo_last_write = _time.monotonic()
            payload = [[self._memo_to_json(k), self._memo_to_json(v)]
                       for k, v in self._caps_memo.items()]
        try:
            atomic_write_json(
                self._memo_path(),
                {"version": self.CAPS_MEMO_VERSION,
                 "memo": payload})
            self._memo_writes += 1
            # complete the pkl→json migration: the pickle predecessor
            # must not linger in a shared data_dir
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(self.store.data_dir,
                                       "caps_memo.pkl"))
        except (OSError, TypeError, ValueError):
            pass  # persistence is best-effort; in-memory memo suffices

    def flush_persistent(self) -> None:
        """Drain debounced persistence (caps memo, exec-cache hotness
        index) — Session.close() calls this so a clean shutdown leaves
        the warm-start state current on disk."""
        self._flush_caps_memo()
        self.exec_cache.flush_index()

    # ------------------------------------------------------------------
    # feedback sizing: actual×slack, with headroom so equal-sized reruns
    # never re-overflow; only shrink when the win is material (a
    # recompile costs real time on remote-attached chips).  The
    # threshold is PER KIND: repartition/agg_out are pure buffer sizes
    # (tightening is free — smaller shuffles and slices), but
    # scan_out/join_out tightening can INTRODUCE a compaction pass that
    # costs ~(n_cols+1) output-sized gathers — and TPU gathers run at
    # ~80M elem/s (bench_kernels), so a 60M→42M "win" measured 2.5 s
    # SLOWER on Q3 SF10.  Compaction must shrink ≥3× to pay for itself.
    TIGHTEN_SLACK = 1.3
    # agg_grid = the bucketed grid's live-group count: it shares the
    # agg_out capacity table but shrinking it INSTALLS a compaction
    # pass over the slot grid, so it pays the compaction economics
    TIGHTEN_THRESHOLD = {"repartition": 0.85, "agg_out": 0.85,
                         "bucket_probe": 0.85, "agg_bucket": 0.85,
                         "scan_out": 1.0 / 3.0, "join_out": 1.0 / 3.0,
                         "agg_grid": 1.0 / 3.0}

    def _tighten_caps(self, plan: QueryPlan, caps: Capacities,
                      stage_keys, actuals) -> Capacities | None:
        """Shrink buffers whose recorded actual row counts sit far below
        their current size.  stage_keys entries are (walk_index, kind,
        width); actuals is the per-stage max over devices.  Returns the
        tightened Capacities, or None when nothing material changed."""
        from .cache import plan_order

        rev = {i: nid for nid, i in plan_order(plan).items()}
        new = {"repartition": dict(caps.repartition),
               "join_out": dict(caps.join_out),
               "agg_out": dict(caps.agg_out),
               "scan_out": dict(caps.scan_out),
               "bucket_probe": dict(caps.bucket_probe),
               "agg_bucket": dict(caps.agg_bucket)}
        changed = False
        for (widx, kind, width), actual in zip(stage_keys, actuals):
            nid = rev.get(widx)
            if nid is None:
                continue
            table = new["agg_out" if kind == "agg_grid" else kind]
            cur = table.get(nid, width)
            t = _round_cap(int(int(actual) * self.TIGHTEN_SLACK) + 128)
            if t < cur * self.TIGHTEN_THRESHOLD[kind]:
                table[nid] = t
                changed = True
        if not changed:
            return None
        return Capacities(new["repartition"], new["join_out"],
                          new["agg_out"], caps.dense_off,
                          new["scan_out"], caps.output_repart,
                          new["bucket_probe"], new["agg_bucket"])

    # ------------------------------------------------------------------
    @staticmethod
    def _caps_to_order(plan: QueryPlan, caps: Capacities) -> tuple:
        """id(node)-keyed Capacities → plan-walk-index-keyed tuple
        (node ids are per-plan-instance; walk order is structural)."""
        from .cache import plan_order

        order = plan_order(plan)
        return ({order[k]: v for k, v in caps.repartition.items()},
                {order[k]: v for k, v in caps.join_out.items()},
                {order[k]: v for k, v in caps.agg_out.items()},
                caps.dense_off,
                {order[k]: v for k, v in caps.scan_out.items()},
                caps.output_repart,
                {order[k]: v for k, v in caps.bucket_probe.items()},
                {order[k]: v for k, v in caps.agg_bucket.items()})

    @staticmethod
    def _caps_from_order(plan: QueryPlan, memo: tuple) -> Capacities:
        from .cache import plan_order

        rev = {i: nid for nid, i in plan_order(plan).items()}
        return Capacities({rev[i]: v for i, v in memo[0].items()},
                          {rev[i]: v for i, v in memo[1].items()},
                          {rev[i]: v for i, v in memo[2].items()},
                          memo[3],
                          {rev[i]: v for i, v in memo[4].items()},
                          memo[5] if len(memo) > 5 else None,
                          {rev[i]: v for i, v in memo[6].items()}
                          if len(memo) > 6 else None,
                          {rev[i]: v for i, v in memo[7].items()}
                          if len(memo) > 7 else None)

    def _initial_capacities(self, plan: QueryPlan, feeds,
                            dense_off: bool = False) -> Capacities:
        """Propagate static per-device capacities bottom-up."""
        repart_factor = self.settings.get("repartition_capacity_factor")
        join_factor = self.settings.get("join_output_capacity_factor")
        group_factor = self.settings.get("agg_group_capacity_factor")
        bucket_factor = self.settings.get("join_probe_bucket_factor")
        agg_bucket_factor = self.settings.get("agg_bucket_capacity_factor")
        group_kernel = self.settings.get("group_by_kernel")
        n_dev = plan.n_devices
        repart: dict[int, int] = {}
        join_out: dict[int, int] = {}
        agg_out: dict[int, int] = {}
        scan_out: dict[int, int] = {}
        bucket_probe: dict[int, int] = {}
        agg_bucket: dict[int, int] = {}

        def cap_of(node, skip_emit: bool = False) -> int:
            """skip_emit: the node's OWN output buffer is never
            allocated (aggregate pushdown consumes the join without pair
            emission) — register child + repartition capacities only."""
            if isinstance(node, ScanNode):
                base = feeds[id(node)].capacity
                if node.filter is None:
                    return base
                # selective scans compact survivors so downstream buffers
                # size by the filtered estimate, not the table (1.5×
                # slack over the uniform-assumption estimate; an
                # under-estimate overflows and retries doubled, and the
                # converged sizes are memoized per plan fingerprint).
                # Compaction pays ~(n_cols+1) output-sized gathers at
                # ~80M elem/s — only a ≥3× shrink is worth the pass
                est = max(1, node.est_rows)
                per_dev = (est if not feeds[id(node)].sharded
                           else -(-est // n_dev))
                k = _round_cap(int(per_dev * 1.5) + 512)
                if k * 3 < base:
                    scan_out[id(node)] = k
                    return k
                return base
            if isinstance(node, ProjectNode):
                return cap_of(node.input)
            if isinstance(node, JoinNode):
                lcap = cap_of(node.left)
                rcap = cap_of(node.right)
                if node.strategy == "repart_right":
                    repart[id(node)] = _round_cap(int(rcap * repart_factor))
                    rcap = n_dev * repart[id(node)]
                elif node.strategy == "repart_left":
                    repart[id(node)] = _round_cap(int(lcap * repart_factor))
                    lcap = n_dev * repart[id(node)]
                elif node.strategy == "repart_both":
                    repart[id(node)] = _round_cap(
                        int(max(lcap, rcap) * repart_factor))
                    lcap = n_dev * repart[id(node)]
                    rcap = n_dev * repart[id(node)]
                if node.join_type in ("semi", "anti"):
                    # output rows ARE probe rows (no emission buffer);
                    # only a cross-side residual needs a candidate-pair
                    # expansion buffer
                    if node.residual is not None:
                        join_out[id(node)] = _round_cap(int(
                            lcap * join_factor
                            * max(1.0, node.est_expansion)) + 128)
                    return lcap
                if skip_emit:
                    # aggregate pushdown consumes the join through
                    # _bounds (no fused lookup, no pair emission): no
                    # emission OR bucket-probe buffer exists
                    return max(lcap, rcap)
                if getattr(node, "fuse_lookup", False) and not dense_off \
                        and node.left_keys:
                    # fused PK lookup: one output slot per probe row; a
                    # selective build side (FK match fraction < 1)
                    # additionally compacts the output so downstream
                    # aggregates/joins size by the join estimate
                    out = (rcap if node.join_type == "inner"
                           and node.build_side == "left" else lcap)
                    if getattr(node, "probe_bucketed", False):
                        # bucketed probe: per-bucket slots at the
                        # uniform-hash expectation × skew headroom;
                        # a hot bucket overflows and regrows through
                        # the normal retry path, feedback tightens
                        ext = (node.left_key_extents
                               if node.build_side == "left"
                               else node.right_key_extents)
                        if ext and ext[0] is not None:
                            from ..ops.join import probe_bucket_count

                            nb = probe_bucket_count(int(ext[0][1]))
                            bucket_probe[id(node)] = _round_cap(
                                int(out / nb * bucket_factor))
                    if node.join_type == "inner" and node.residual is None:
                        est = max(1, node.est_rows)
                        k = _round_cap(int(-(-est // n_dev) * 1.5) + 512)
                        if k * 3 < out:  # same ≥3× compaction economics
                            out = k
                    join_out[id(node)] = out
                    return out
                if not node.left_keys:
                    # cartesian: output is the full product (the gathered
                    # build side is n_dev shards wide)
                    if node.strategy == "cartesian_gather":
                        rcap = rcap * n_dev
                    out = _round_cap(lcap * rcap)
                else:
                    # probe side is the left/outer side; est_expansion
                    # scales for many-to-many fan-out
                    out = _round_cap(int(
                        lcap * join_factor
                        * max(1.0, node.est_expansion)) + 128)
                    if node.join_type in ("left", "full"):
                        # unmatched probe rows add up to lcap extra slots
                        out = _round_cap(out + lcap)
                join_out[id(node)] = out
                if node.join_type in ("right", "full"):
                    # the unmatched-build segment appends rcap fixed slots
                    out = out + rcap
                return out
            if isinstance(node, WindowNode):
                in_cap = cap_of(node.input)
                if node.combine != "repartition":
                    return in_cap
                if node.partition_by:
                    repart[id(node)] = _round_cap(
                        int(in_cap * repart_factor))
                else:
                    # one global partition: every row on one device
                    repart[id(node)] = _round_cap(
                        int(in_cap * n_dev * repart_factor))
                return n_dev * repart[id(node)]
            if isinstance(node, AggregateNode):
                if node.combine == "global" and \
                        isinstance(node.input, JoinNode) and \
                        PlanCompiler.agg_pushdown_shape(node):
                    cap_of(node.input, skip_emit=True)
                    return 1
                in_cap = cap_of(node.input)
                if node.combine == "global":
                    return 1
                if node.dense_keys is not None and not dense_off and \
                        node.combine in ("local", "repartition"):
                    return node.dense_total  # fixed dense-grid output
                if PlanCompiler.agg_bucket_shape(node, group_kernel,
                                                 dense_off):
                    # bucketed dense grid: the packed input buffer is
                    # [n_buckets, cap] at the uniform expectation ×
                    # skew headroom (a hot bucket overflows and
                    # regrows; feedback tightens converged sizes), and
                    # the [bucket_total] output grid compacts to the
                    # estimated group count under the same ≥3×
                    # economics as every compaction pass
                    from ..ops.groupby import group_bucket_count

                    nb = group_bucket_count(node.bucket_total)
                    agg_bucket[id(node)] = _round_cap(
                        int(-(-in_cap // nb) * agg_bucket_factor) + 128)
                    out = node.bucket_total
                    est_g = node.est_groups
                    if est_g:
                        k = _round_cap(
                            min(out, int(est_g * group_factor) + 16))
                        if k * 3 < out:
                            agg_out[id(node)] = k
                            out = k
                    return out
                est_g = node.est_groups
                if est_g:
                    # group-count estimate bounds every aggregate buffer:
                    # a 4-group Q1 stops shipping input-sized arrays
                    # through the shuffle and back to the host
                    agg_cap = _round_cap(
                        min(in_cap, int(est_g * group_factor) + 16))
                    agg_out[id(node)] = agg_cap
                    if node.combine == "repartition":
                        # worst case: every group hashes to one target
                        repart[id(node)] = agg_cap
                    return agg_cap
                if node.combine == "repartition":
                    repart[id(node)] = _round_cap(int(in_cap * repart_factor))
                    return n_dev * repart[id(node)]
                return in_cap
            raise ExecutionError(f"unknown node {type(node).__name__}")

        root_cap = cap_of(plan.root)
        out_rp = None
        if plan.output_repart is not None:
            # balanced-hash expectation with headroom; skew overflows
            # and regrows through the normal retry path
            out_rp = _round_cap(
                int(-(-root_cap // n_dev) * repart_factor) + 256)
        return Capacities(repart, join_out, agg_out, dense_off, scan_out,
                          out_rp, bucket_probe, agg_bucket)

    # ------------------------------------------------------------------
    def _host_combine(self, plan: QueryPlan, cols, nulls, valid,
                      raw: bool = False) -> ResultSet:
        valid_2d = np.asarray(valid)
        device_rows = (valid_2d.sum(axis=1).astype(int).tolist()
                       if valid_2d.ndim == 2 else None)
        valid_np = valid_2d.reshape(-1)
        flat_cols: dict[str, np.ndarray] = {}
        flat_nulls: dict[str, np.ndarray] = {}
        for cid in cols:
            arr = np.asarray(cols[cid]).reshape(-1)
            flat_cols[cid] = arr[valid_np]
            nmask = np.asarray(nulls[cid]).reshape(-1)
            flat_nulls[cid] = nmask[valid_np]
        src = ColumnSource(flat_cols, flat_nulls)
        n = int(valid_np.sum())

        # HAVING
        if plan.host_having is not None:
            mask = np.broadcast_to(np.asarray(
                predicate_mask(plan.host_having, src, np)), (n,))
            flat_cols = {c: a[mask] for c, a in flat_cols.items()}
            flat_nulls = {c: a[mask] for c, a in flat_nulls.items()}
            src = ColumnSource(flat_cols, flat_nulls)
            n = int(mask.sum())
            device_rows = None  # filtered: per-device counts are stale

        # select outputs
        out_cols: dict[str, object] = {}
        out_nulls: dict[str, np.ndarray] = {}
        out_dtypes: dict[str, DataType] = {}
        decode_map: dict[str, tuple[str, str]] = {}
        names: list[str] = []
        for e, name in plan.host_select:
            v, nmask = evaluate(e, src, np)
            v = np.broadcast_to(np.asarray(v), (n,)).copy()
            nmask = (np.zeros(n, dtype=bool) if nmask is None
                     else np.broadcast_to(np.asarray(nmask), (n,)).copy())
            out_name = self._unique_name(name, names)
            names.append(out_name)
            out_cols[out_name] = v
            out_nulls[out_name] = nmask
            out_dtypes[out_name] = e.dtype
            # decode dictionary strings / format dates (vectorized —
            # result sets can be SF100-sized); raw mode keeps codes/day
            # numbers typed so bulk consumers (INSERT..SELECT) skip the
            # decode→re-encode round trip
            if raw:
                if isinstance(e, ir.BCol) and e.cid in plan.decode:
                    decode_map[out_name] = plan.decode[e.cid]
            elif isinstance(e, ir.BCol) and e.cid in plan.decode:
                d = resolve_decode(self.store, plan.decode[e.cid])
                out_cols[out_name] = _decode_strings(d, v, nmask)
            elif e.dtype == DataType.DATE:
                out_cols[out_name] = _format_dates(v, nmask)

        # ORDER BY (host): exact multi-key sort via factorize + lexsort.
        # Values factorize through np.unique (ascending codes — exact for
        # any dtype incl. decoded strings); DESC negates codes; NULL
        # placement follows PG defaults (NULLS LAST for ASC, FIRST for DESC)
        if plan.host_order_by and n > 0:
            device_rows = None  # re-sorted: device-major order destroyed
            order_src = ColumnSource(flat_cols, flat_nulls)
            lex_keys = []  # built primary-first, reversed for np.lexsort
            for e, desc, nulls_first in plan.host_order_by:
                v, nmask = evaluate(e, order_src, np)
                v = np.broadcast_to(np.asarray(v), (n,))
                nmask = (np.zeros(n, dtype=bool) if nmask is None
                         else np.broadcast_to(np.asarray(nmask), (n,)))
                if isinstance(e, ir.BCol) and e.cid in plan.decode:
                    d = resolve_decode(self.store, plan.decode[e.cid])
                    lut = np.asarray(d.values + [""], dtype=object)
                    codes = np.asarray(v).astype(np.int64)
                    oob = (codes < 0) | (codes >= len(d))
                    v = lut[np.where(oob, len(d), codes)].astype(str)
                _, codes = np.unique(v, return_inverse=True)
                codes = codes.astype(np.int64)
                if desc:
                    codes = -codes
                nulls_last = (not nulls_first if nulls_first is not None
                              else not desc)
                null_key = nmask if nulls_last else ~nmask
                # per item: null placement outranks the value code
                lex_keys.append(null_key.astype(np.int8))
                lex_keys.append(codes)
            order = np.lexsort(tuple(reversed(lex_keys)))
            for c in names:
                out_cols[c] = out_cols[c][order]
                out_nulls[c] = out_nulls[c][order]
        # OFFSET / LIMIT
        lo = plan.offset or 0
        hi = n if plan.limit is None else min(n, lo + plan.limit)
        if lo or hi < n:
            for c in names:
                out_cols[c] = out_cols[c][lo:hi]
                out_nulls[c] = out_nulls[c][lo:hi]
            device_rows = None  # sliced: per-device counts are stale
        final_n = max(0, hi - lo)

        if raw:
            return ResultSet(names, out_cols, final_n, dtypes=out_dtypes,
                             null_masks=out_nulls, decode_map=decode_map,
                             device_rows=device_rows)
        # surface NULLs as None in object columns
        for c in names:
            if out_nulls[c].any():
                col = np.asarray(out_cols[c], dtype=object)
                col[out_nulls[c]] = None
                out_cols[c] = col
        return ResultSet(names, out_cols, final_n, dtypes=out_dtypes,
                         device_rows=device_rows)

    @staticmethod
    def _unique_name(name: str, taken: list[str]) -> str:
        if name not in taken:
            return name
        i = 1
        while f"{name}_{i}" in taken:
            i += 1
        return f"{name}_{i}"


def feed_device_rows(feeds, n_dev: int) -> list[int] | None:
    """Per-device rows-in across the sharded scan feeds (the Mesh:
    line's input column); None when no feed carries per-device counts
    (pure reference-table plans)."""
    totals = [0] * n_dev
    seen = False
    for f in feeds.values():
        dr = getattr(f, "dev_rows", None)
        if dr is None:
            continue
        seen = True
        for d, r in enumerate(dr[:n_dev]):
            totals[d] += int(r)
    return totals if seen else None


def _plan_buffer_bytes(plan: QueryPlan, caps: Capacities) -> int:
    """Worst single-buffer estimate for a capacity assignment: each
    join/repartition/aggregate buffer holds its node's output columns at
    the static capacity, per device.  Guards against executing plans
    whose shapes could never fit (a 2G-slot cartesian output would
    otherwise OOM — or segfault — the backend allocator)."""
    nodes = {id(n): n for n in walk_plan(plan.root)}
    worst = 0
    for table, factor in ((caps.join_out, 1), (caps.repartition,
                                               plan.n_devices),
                          (caps.agg_out, 1), (caps.scan_out, 1)):
        for nid, cap in table.items():
            node = nodes.get(nid)
            ncols = len(node.out_columns) if node is not None else 4
            worst = max(worst,
                        cap * factor * (ncols + 2) * 8 * plan.n_devices)
    for nid, cap in caps.bucket_probe.items():
        # bucketed-probe pack: [n_buckets, cap] int32 × (local, pos,
        # gathered output) per device.  A hot-bucket overflow retry
        # regrows the PER-BUCKET cap, so this is the buffer that can
        # explode under skew — it must be visible to the guard.
        node = nodes.get(nid)
        ext = (() if node is None else
               (node.left_key_extents if node.build_side == "left"
                else node.right_key_extents))
        if ext and ext[0] is not None:
            from ..ops.join import probe_bucket_count

            nb = probe_bucket_count(int(ext[0][1]))
            worst = max(worst, cap * nb * 3 * 4 * plan.n_devices)
    for nid, cap in caps.agg_bucket.items():
        # bucketed group-by: the [n_buckets, cap] pack per value column
        # (int64-worst, per device — the hot-bucket regrow path, same
        # skew-explosion exposure as the probe pack above) AND the
        # [bucket_total]-slot result grid (results + companions + key
        # reconstruction), which at the 2^24 slot cap is the largest
        # buffer this path allocates when no agg_out compaction applies
        node = nodes.get(nid)
        total = getattr(node, "bucket_total", 0) if node is not None else 0
        if total:
            from ..ops.groupby import group_bucket_count

            nb = group_bucket_count(total)
            ncols = len(node.out_columns) if node is not None else 4
            worst = max(worst,
                        cap * nb * (ncols + 2) * 8 * plan.n_devices,
                        total * (ncols + 2) * 8 * plan.n_devices)
    return worst


def _decode_strings(d, codes, nmask) -> np.ndarray:
    """Vectorized dictionary decode: codes → object array (None = NULL)."""
    lut = np.asarray(d.values + [None], dtype=object)
    codes = np.asarray(codes).astype(np.int64)
    codes = np.where(nmask | (codes < 0) | (codes >= len(d)), len(d), codes)
    return lut[codes]


def _format_dates(days, nmask) -> np.ndarray:
    """Vectorized day-number → ISO date string (None = NULL)."""
    days = np.asarray(days).astype("int64")
    iso = (days.astype("datetime64[D]")).astype(str).astype(object)
    iso[np.asarray(nmask)] = None
    return iso


