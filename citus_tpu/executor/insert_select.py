"""INSERT .. SELECT execution modes.

The reference plans INSERT..SELECT as pushdown / repartition /
pull-to-coordinator (/root/reference/src/backend/distributed/planner/
insert_select_planner.c:1-60, executor/repartition_executor.c:1-40,
README throughput: ~100M / ~10M / ~1M rows/s respectively).  Here the
source SELECT always runs as one device program; the modes differ in
how results reach the target shards:

* colocated — the source plan's output distribution already matches the
  target's sharding on the inserted distribution column: the raw result
  is sliced per device and appended straight to that device's shard —
  no hashing, no routing masks (the pushdown mode, where the write
  never crosses workers).
* repartition, device-routed — when the target has one shard per device
  and an integer distribution key, the plan gains an OUTPUT shuffle
  (QueryPlan.output_repart → pack_by_target + all_to_all, the
  worker_partition_query_result analogue,
  partitioned_intermediate_results.c:108): rows arrive pre-partitioned
  and the write slices per device like the colocated path.
* repartition, host-routed — fallback (streamed sources, string
  distribution keys, shard_count ≠ n_devices): a vectorized numpy
  hash-route over the raw result arrays.

The reference's third mode (pull-to-coordinator) has no analogue: every
result already materializes at the single controller, so "pull" and
"repartition, host-routed" are the same path here.

All modes use the executor's raw results: STRING columns stay
dictionary codes (translated dictionary→dictionary by a vectorized LUT)
and DATE columns stay day numbers — no decode→parse round trip.
"""

from __future__ import annotations

import numpy as np

from ..catalog import DistributionMethod
from ..catalog.distribution import hash_token, shard_index_for_token_ranges
from ..errors import IngestError, PlanningError
from ..planner import expr as ir
from ..planner.plan import QueryPlan
from ..storage.dictionary import NULL_CODE
from ..types import DataType


def choose_mode(session, plan: QueryPlan, meta,
                columns: list[str]) -> str:
    """colocated | repartition — pushdown applies when the source root is
    hash-distributed with the target's shard map and the select item
    feeding the target's distribution column is a bare column of the
    source's partition equivalence set."""
    if meta.method != DistributionMethod.HASH:
        return "repartition"  # single-shard target: routing is trivial
    root = plan.root
    if root.dist.kind != "hash":
        return "repartition"
    from ..planner.plan import table_placement

    shards = session.catalog.table_shards(meta.name)
    placement = table_placement(session.catalog, meta.name,
                                session.n_devices)
    bounds = tuple(session.catalog.shard_mins(meta.name))
    if root.dist.shard_count != len(shards) or \
            root.dist.placement != placement or \
            (root.dist.bounds and tuple(root.dist.bounds) != bounds):
        return "repartition"
    try:
        di = columns.index(meta.distribution_column)
    except ValueError:
        return "repartition"
    if di >= len(plan.host_select):
        return "repartition"
    e, _name = plan.host_select[di]
    # resolve projection outputs back to their source expressions (the
    # host_select references ProjectNode cids like "p0", while dist.cids
    # carry relation cids like "0.k")
    from ..planner.plan import ProjectNode

    node = root
    while isinstance(e, ir.BCol):
        if e.cid in node.dist.cids:
            return "colocated"
        if isinstance(node, ProjectNode):
            src = next((se for se, cid in node.exprs if cid == e.cid),
                       None)
            if src is None:
                break
            e = src
            node = node.input
            continue
        break
    return "repartition"


def execute_insert_select(session, stmt):
    """Array-path INSERT..SELECT; returns (ResultSet, mode)."""
    from .runner import ResultSet

    meta = session.catalog.table(stmt.table)
    columns = list(stmt.columns or meta.schema.names)
    plan, cleanup = session._plan_select(stmt.query)
    try:
        if len(plan.host_select) != len(columns):
            raise PlanningError(
                f"INSERT..SELECT arity mismatch: {len(columns)} target "
                f"columns, {len(plan.host_select)} select items")
        mode = choose_mode(session, plan, meta, columns)
        if mode == "repartition":
            rp = _plan_output_repart(session, plan, meta, columns)
            if rp is not None:
                plan.output_repart = rp
        result = session.executor.execute_plan(plan, raw=True)
        if plan.output_repart is not None and result.device_rows is None:
            # source streamed (or order disturbed): rows were not
            # device-partitioned end-to-end — host routing below
            plan.output_repart = None
        n = _write_result(session, meta, columns, result, mode,
                          device_routed=plan.output_repart is not None,
                          plan_catalog_version=plan.catalog_version)
        stats = getattr(session, "stats", None)
        if stats is not None:
            from ..stats import counters as sc

            stats.counters.increment(
                sc.INSERT_SELECT_PUSHDOWN if mode == "colocated"
                else sc.INSERT_SELECT_REPARTITION)
            stats.counters.increment(sc.ROWS_INGESTED, n)
        return ResultSet(["inserted"], {"inserted": [n]}, 1), mode
    finally:
        for t in cleanup:
            session._drop_temp(t)


def _target_arrays(session, meta, columns, result):
    """Raw result columns → typed target arrays + validity, dictionary
    codes translated source→target."""
    n = result.row_count
    typed: dict[str, np.ndarray] = {}
    validity: dict[str, np.ndarray] = {}
    for tgt_col, out_name in zip(columns, result.column_names):
        cdef = meta.schema.column(tgt_col)
        arr = np.asarray(result.columns[out_name])
        nmask = result.null_masks.get(out_name)
        nmask = (np.zeros(n, dtype=bool) if nmask is None
                 else np.asarray(nmask, dtype=bool))
        if not cdef.nullable and nmask.any():
            raise IngestError(
                f"NULL in non-nullable column {tgt_col!r} of {meta.name!r}")
        if cdef.dtype == DataType.STRING:
            src = (result.decode_map or {}).get(out_name)
            if src is None:
                if arr.dtype == object or arr.dtype.kind in ("U", "S"):
                    # string values materialized host-side (e.g. literals)
                    d = session.store.dictionary(meta.name, tgt_col)
                    codes = d.intern_array(
                        [None if nm else str(v)
                         for v, nm in zip(arr, nmask)])
                    typed[tgt_col] = codes
                else:
                    raise PlanningError(
                        f"cannot infer dictionary for string column "
                        f"{tgt_col!r}")
            else:
                from ..storage.dictionary import resolve_decode

                src_d = resolve_decode(session.store, src)
                tgt_d = session.store.dictionary(meta.name, tgt_col)
                if src == (meta.name, tgt_col):
                    codes = arr.astype(np.int32)
                elif len(src_d) == 0:
                    codes = np.zeros(n, dtype=np.int32)
                else:
                    # translate only the codes actually present — interning
                    # the whole source dictionary would permanently bloat
                    # the target's (dictionaries persist at commit)
                    safe = np.clip(arr.astype(np.int64), 0, len(src_d) - 1)
                    present = np.unique(safe[~nmask]) if (~nmask).any() \
                        else np.empty(0, dtype=np.int64)
                    lut = np.zeros(len(src_d), dtype=np.int32)
                    src_vals = src_d.values
                    for c in present:
                        lut[c] = tgt_d.intern(src_vals[int(c)])
                    codes = lut[safe]
                codes = np.where(nmask, np.int32(NULL_CODE),
                                 codes.astype(np.int32))
                typed[tgt_col] = codes
        else:
            dt = cdef.dtype.numpy_dtype
            if arr.dtype == object:
                arr = np.array([0 if (v is None or nm) else v
                                for v, nm in zip(arr, nmask)])
            vals = arr.astype(dt)
            if nmask.any():
                vals = np.where(nmask, np.zeros((), dtype=dt), vals)
            typed[tgt_col] = vals
        validity[tgt_col] = ~nmask
    # unspecified target columns become NULL
    for c in meta.schema.names:
        if c not in typed:
            cdef = meta.schema.column(c)
            if not cdef.nullable:
                raise IngestError(
                    f"non-nullable column {c!r} missing from INSERT")
            typed[c] = np.zeros(n, dtype=(np.int32 if cdef.dtype ==
                                          DataType.STRING
                                          else cdef.dtype.numpy_dtype))
            if cdef.dtype == DataType.STRING:
                typed[c] = np.full(n, NULL_CODE, dtype=np.int32)
            validity[c] = np.zeros(n, dtype=bool)
    return typed, validity


def _plan_output_repart(session, plan: QueryPlan, meta, columns):
    """(shard_count, placement, bounds, key_expr) when the repartition
    write can route ON DEVICE: hash-distributed (non-streamed) source,
    one target shard per device, and a non-string distribution key whose
    source expression the device program outputs.  None → host route."""
    from ..catalog import DistributionMethod as DM

    if meta.method != DM.HASH or plan.root.dist.kind != "hash":
        return None
    if _device_shard_map(session, meta) is None:
        return None
    if meta.schema.column(meta.distribution_column).dtype == \
            DataType.STRING:
        # device blocks hold per-source dictionary codes; the ingest
        # token hash needs the string bytes — host route
        return None
    try:
        di = columns.index(meta.distribution_column)
    except ValueError:
        return None
    key_expr, _name = plan.host_select[di]
    # the key must be computable from the device block alone
    for n_ in ir.walk(key_expr):
        if isinstance(n_, ir.BAgg):
            return None
    from ..planner.plan import table_placement

    placement = table_placement(session.catalog, meta.name,
                                session.n_devices)
    bounds = tuple(session.catalog.shard_mins(meta.name))
    shards = session.catalog.table_shards(meta.name)
    return (len(shards), placement, bounds, key_expr)


def _device_shard_map(session, meta):
    """device → shard_id when each device holds EXACTLY one shard of the
    target (the 1:1 layout where colocated writes need no hashing at
    all); None otherwise."""
    from ..planner.plan import table_placement

    shards = session.catalog.table_shards(meta.name)
    placement = table_placement(session.catalog, meta.name,
                                session.n_devices)
    if len(shards) != session.n_devices or \
            sorted(placement) != list(range(session.n_devices)):
        return None
    return {dev: shards[i].shard_id for i, dev in enumerate(placement)}


def _write_result(session, meta, columns, result, mode="repartition",
                  device_routed: bool = False,
                  plan_catalog_version: int | None = None) -> int:
    n = result.row_count
    if n == 0:
        return 0
    typed, validity = _target_arrays(session, meta, columns, result)
    # Every write happens under the DML shard locks (the shard split
    # holds them while it flips the catalog), with _dml_locks' reload
    # loop adopting the committed catalog before we route — otherwise a
    # split committing between routing and append sends rows into the
    # dropped parent shard (lost).  Device-pre-partitioned writes
    # (colocated slices, device-routed repartition) additionally trust
    # routing DERIVED AT PLAN TIME: if the catalog moved since planning,
    # demote to host hash-routing — per-row re-hash against the CURRENT
    # shard map is correct under any split.
    table = meta.name
    with session._dml_locks(
            table, lambda: session.catalog.table_shards(table)):
        if (device_routed or mode == "colocated") and \
                plan_catalog_version is not None and \
                session.catalog.version != plan_catalog_version:
            mode, device_routed = "repartition", False
        return _route_and_write(session, meta, columns, typed, validity,
                                result, mode, device_routed)


def _route_and_write(session, meta, columns, typed, validity, result,
                     mode, device_routed) -> int:
    from ..utils.faultinjection import fault_point

    # named seam: a failure while shuffling INSERT..SELECT rows to their
    # target shards must leak no invisible stripes (the discard_pending
    # cleanup below is the recovery path under test)
    fault_point("executor.repartition_shuffle")
    n = result.row_count
    codec = session.settings.get("columnar_compression")
    level = session.settings.get("columnar_compression_level")
    chunk_rows = session.settings.get("columnar_chunk_group_row_limit")
    pending: list[tuple[int, dict]] = []
    table = meta.name
    try:
        dev_map = (_device_shard_map(session, meta)
                   if (mode == "colocated" or device_routed)
                   and result.device_rows
                   else None)
        if dev_map is not None:
            # COLOCATED fast path: rows are already partitioned exactly
            # like the target (choose_mode verified shard map + bounds)
            # and each device holds one target shard — slice the
            # device-major result per device and write each block
            # directly, no hash, no routing masks (the pushdown mode of
            # insert_select_planner.c:1-60, where the write never
            # crosses workers)
            dist_col = meta.distribution_column
            if not validity[dist_col].all():
                raise IngestError(
                    f"NULL distribution column value in {table!r}")
            off = 0
            for dev, cnt in enumerate(result.device_rows):
                if cnt == 0:
                    continue
                sl = slice(off, off + cnt)
                off += cnt
                rec = session.store.append_stripe(
                    table, dev_map[dev],
                    {c: typed[c][sl] for c in typed},
                    {c: validity[c][sl] for c in validity},
                    codec=codec, level=level, chunk_rows=chunk_rows,
                    commit=False)
                pending.append((dev_map[dev], rec))
        elif meta.method == DistributionMethod.HASH:
            dist_col = meta.distribution_column
            if not validity[dist_col].all():
                raise IngestError(
                    f"NULL distribution column value in {table!r}")
            dt = meta.schema.column(dist_col).dtype
            if dt == DataType.STRING:
                d = session.store.dictionary(table, dist_col)
                tokens = d.hash_tokens()[typed[dist_col]]
            else:
                tokens = hash_token(typed[dist_col])
            shards = session.catalog.table_shards(table)
            shard_idx = shard_index_for_token_ranges(
                tokens, session.catalog.shard_mins(table))
            for i, s in enumerate(shards):
                mask = shard_idx == i
                if not mask.any():
                    continue
                sub = {c: typed[c][mask] for c in typed}
                subv = {c: validity[c][mask] for c in validity}
                rec = session.store.append_stripe(
                    table, s.shard_id, sub, subv, codec=codec,
                    level=level, chunk_rows=chunk_rows, commit=False)
                pending.append((s.shard_id, rec))
        else:
            shard = session.catalog.table_shards(table)[0]
            rec = session.store.append_stripe(
                table, shard.shard_id, typed, validity, codec=codec,
                level=level, chunk_rows=chunk_rows, commit=False)
            pending.append((shard.shard_id, rec))
    except Exception:
        session.store.discard_pending(table, pending)
        raise
    session._apply_dml(table, {}, pending)
    return n
