"""Fast-path router execution: single-shard queries skip the mesh.

The reference plans `distcol = const` queries straight to one shard and
bypasses the whole distributed machinery
(/root/reference/src/backend/distributed/planner/fast_path_router_planner.c:530,
distributed_planner.c:719 PlanFastPathDistributedStmt).  The TPU analogue:
when every hash-distributed scan prunes to at most ONE shard and the
surviving rows are small, dispatching a [n_dev, cap] shard_map program
(plus two device round trips) costs orders of magnitude more than the
query itself.  This module executes the SAME bound plan tree host-side
with numpy — exact sizes, no capacities, no device round trip — and
reuses the executor's host-combine phase (HAVING / ORDER BY / LIMIT /
decode) unchanged.

Scope: Scan / Project / inner+left Join plans.  Aggregates and
right/full joins fall back to the device path (still correct, just not
point-lookup-latency).  The row threshold keeps the host from scanning
big shards a devious filter failed to prune.
"""

from __future__ import annotations

import numpy as np

from ..catalog import DistributionMethod
from ..planner import expr as ir
from ..planner.plan import JoinNode, ProjectNode, QueryPlan, ScanNode
from ..types import DataType
from .exprs import ColumnSource, evaluate, predicate_mask
from .feed import make_chunk_filter, walk_plan


def _conjuncts(e):
    if isinstance(e, ir.BBool) and e.op == "AND":
        return [c for a in e.args for c in _conjuncts(a)]
    return [e]


def point_lookup_const(node: ScanNode, catalog, settings=None):
    """STRUCTURAL point-index eligibility: the distribution-column
    equality constant when the plan shape qualifies for the persistent
    point-lookup index (storage/pkindex.py — the btree/hash-index
    analogue, columnar/README.md:176); else None.  Shared by the
    executor and EXPLAIN so the plan display cannot drift from the
    runtime's matcher; the executor's index_probe adds the
    instant-dependent overlay check on top."""
    if settings is not None and \
            not settings.get("enable_point_lookup_index"):
        return None
    if node.filter is None or node.pruned_shards is None or \
            len(node.pruned_shards) != 1:
        return None
    meta = catalog.table(node.rel.table)
    if meta.method != DistributionMethod.HASH:
        return None
    dcol = meta.distribution_column
    if meta.schema.column(dcol).dtype not in (
            DataType.INT32, DataType.INT64, DataType.DATE):
        return None
    for c in _conjuncts(node.filter):
        if isinstance(c, ir.BCmp) and c.op == "=":
            col, const = c.left, c.right
            if not isinstance(col, ir.BCol):
                col, const = c.right, c.left
            if isinstance(col, ir.BCol) and isinstance(const, ir.BConst) \
                    and col.column == dcol \
                    and col.table == node.rel.table \
                    and isinstance(const.value, (int, np.integer)):
                return int(const.value)
    return None


def index_probe(executor, node: ScanNode):
    """point_lookup_const + this session's transaction state: staged
    overlay rows are invisible to the index, so report ineligible here
    and the row-ceiling gate counts the shard instead of assuming an
    indexed answer."""
    value = point_lookup_const(node, executor.catalog, executor.settings)
    if value is None:
        return None
    store = executor.store
    if store.overlay is not None and (
            any(t == node.rel.table for (t, _s) in store.overlay.records)):
        return None
    return value


def fast_path_shape(plan: QueryPlan, catalog) -> bool:
    """Structural eligibility: Scan/Project/inner+left-Join plans whose
    hash-distributed scans all prune to at most one shard.  Shared by
    the executor and EXPLAIN (the executor adds the GUC + row-count
    checks on top)."""
    pruned_any = False
    for node in walk_plan(plan.root):
        if isinstance(node, ProjectNode):
            continue
        if isinstance(node, JoinNode):
            if node.join_type not in ("inner", "left"):
                return False
            # same restriction the device compiler enforces — float keys
            # must raise PlanningError there, not silently truncate here
            for e in (*node.left_keys, *node.right_keys):
                if e.dtype.value in ("float32", "float64"):
                    return False
        elif isinstance(node, ScanNode):
            meta = catalog.table(node.rel.table)
            if meta.method == DistributionMethod.HASH:
                if node.pruned_shards is None or \
                        len(node.pruned_shards) > 1:
                    return False
                pruned_any = True
        else:
            return False  # aggregates take the device path
    return pruned_any


def try_execute_fast_path(executor, plan: QueryPlan, raw: bool):
    """Host-side execution, or None when the plan doesn't qualify."""
    if not executor.settings.get("enable_fast_path_router"):
        return None
    if not fast_path_shape(plan, executor.catalog):
        return None
    max_rows = executor.settings.get("fast_path_max_rows")
    total = 0
    for node in walk_plan(plan.root):
        if not isinstance(node, ScanNode):
            continue
        if index_probe(executor, node) is not None:
            continue  # answered by the point index: O(matches), not O(shard)
        meta = executor.catalog.table(node.rel.table)
        shards = executor.catalog.table_shards(node.rel.table)
        if meta.method == DistributionMethod.HASH:
            for idx in node.pruned_shards:
                total += executor.store.shard_row_count(
                    node.rel.table, shards[idx].shard_id)
        else:
            total += executor.store.shard_row_count(
                node.rel.table, shards[0].shard_id)
        if total > max_rows:
            return None
    from ..stats.tracing import trace_span

    with trace_span("fastpath"):
        cols, nulls, valid = _exec_host(executor, plan.root)
        # host-combine expects a null mask per column (the device path
        # always materializes them)
        for cid, arr in cols.items():
            if cid not in nulls:
                nulls[cid] = np.zeros(arr.shape[0], dtype=bool)
        result = executor._host_combine(plan, cols, nulls, valid, raw)
    result.fast_path = True
    result.device_rows_scanned = 0
    return result


def _exec_host(executor, node):
    """Mirror of PlanCompiler._exec with numpy + exact row counts."""
    if isinstance(node, ScanNode):
        return _scan_host(executor, node)
    if isinstance(node, ProjectNode):
        cols, nulls, valid = _exec_host(executor, node.input)
        src = ColumnSource(cols, nulls)
        out_cols, out_nulls = {}, {}
        n = valid.shape[0]
        for e, cid in node.exprs:
            v, nm = evaluate(e, src, np)
            out_cols[cid] = np.broadcast_to(np.asarray(v), (n,))
            if nm is not None:
                out_nulls[cid] = np.broadcast_to(np.asarray(nm), (n,))
        return out_cols, out_nulls, valid
    if isinstance(node, JoinNode):
        return _join_host(executor, node)
    raise AssertionError(f"fast path: unexpected {type(node).__name__}")


def _scan_host(executor, node: ScanNode):
    meta = executor.catalog.table(node.rel.table)
    shards = executor.catalog.table_shards(node.rel.table)
    if meta.method == DistributionMethod.HASH:
        wanted = [shards[i] for i in (node.pruned_shards or [])]
    else:
        wanted = [shards[0]]
    colnames = [cid.split(".", 1)[1] for cid in node.columns]

    value = index_probe(executor, node)
    if value is not None and len(wanted) == 1:
        got = _index_rows(executor, node.rel.table, wanted[0].shard_id,
                          meta.distribution_column, value, colnames)
        if got is not None:
            vals, mask, n = got
            cols = {cid: vals[cname]
                    for cid, cname in zip(node.columns, colnames)}
            nulls = {cid: ~mask[cname]
                     for cid, cname in zip(node.columns, colnames)
                     if not mask[cname].all()}
            valid = np.ones(n, dtype=bool)
            if n:  # the remaining (non-key) conjuncts still apply
                valid = valid & np.broadcast_to(np.asarray(predicate_mask(
                    node.filter, ColumnSource(cols, nulls), np)), (n,))
            return _compress(cols, nulls, valid)
    chunk_filter = None
    if node.filter is not None:
        name_map = {c.name: executor.store.storage_column_name(
            node.rel.table, c.name) for c in meta.schema.columns}
        chunk_filter = make_chunk_filter(node.filter, executor.counters,
                                         name_map)
    parts_v = {c: [] for c in colnames}
    parts_m = {c: [] for c in colnames}
    n = 0
    for s in wanted:
        vals, mask, cnt = executor.store.read_shard(
            node.rel.table, s.shard_id, colnames, chunk_filter)
        if cnt == 0:
            continue
        n += cnt
        for c in colnames:
            parts_v[c].append(vals[c])
            parts_m[c].append(mask[c])
    cols, nulls = {}, {}
    for cid, cname in zip(node.columns, colnames):
        if parts_v[cname]:
            cols[cid] = np.concatenate(parts_v[cname])
            m = np.concatenate(parts_m[cname])
            if not m.all():
                nulls[cid] = ~m
        else:
            dtype = node.rel.schema.column(cname).dtype.numpy_dtype
            cols[cid] = np.zeros(0, dtype=dtype)
    valid = np.ones(n, dtype=bool)
    if node.filter is not None and n:
        valid = valid & np.broadcast_to(np.asarray(
            predicate_mask(node.filter, ColumnSource(cols, nulls), np)),
            (n,))
    return _compress(cols, nulls, valid)


def _index_rows(executor, table: str, shard_id: int, column: str,
                value: int, colnames):
    """Point-index rows for one key — through the cross-session
    micro-batcher (serving/batcher.py) when the serving layer is on,
    solo otherwise.  None ⇒ the index cannot answer (overlay appeared):
    the caller falls back to the ordinary scan path."""
    from ..storage import pkindex

    store = executor.store
    if executor.settings.get("serving_enabled") \
            and store.overlay is None \
            and executor.settings.get("storage_verify_checksums"):
        # only overlay-free sessions batch: an open transaction's staged
        # state (records AND delete masks) is session-private, resolved
        # against this session's own store — it must neither be missed
        # by another session's probe store (read-your-writes: a staged
        # DELETE stays visible through the records-only index guard)
        # nor answer other sessions (dirty read of uncommitted deletes).
        # And only verify-on sessions batch: the coalesced probe reads
        # through ONE member's store, so a verify-off session leading
        # the group would hand unverified bytes to sessions that never
        # opted out of the PR 7 integrity invariant
        batcher = getattr(store, "_serving_batcher", None)
        if batcher is None:
            # resolve the per-data_dir batcher once per store (the
            # registry realpath-walks the path on every call)
            from ..serving.batcher import batcher_for

            batcher = store._serving_batcher = batcher_for(store.data_dir)
        res = batcher.lookup(
            store, table, shard_id, column, value, colnames,
            max_batch=executor.settings.get("serving_max_batch"),
            window_s=executor.settings.get(
                "serving_batch_window_ms") / 1000.0)
        if res.fallback:
            return None
        if executor.counters is not None:
            from ..stats import counters as sc

            executor.counters.increment(sc.POINT_INDEX_LOOKUPS)
            # requester-side fold: this session's lookup rode a batch;
            # the leader additionally owns the dispatches it drove
            executor.counters.increment(sc.SERVING_BATCHED_LOOKUPS_TOTAL)
            if res.dispatches_led:
                executor.counters.increment(
                    sc.SERVING_BATCH_DISPATCH_TOTAL, res.dispatches_led)
        return res.vals, res.mask, res.n
    hits = pkindex.lookup(store, table, shard_id, column, value)
    if hits is None:
        return None
    if executor.counters is not None:
        from ..stats import counters as sc

        executor.counters.increment(sc.POINT_INDEX_LOOKUPS)
    return pkindex.read_rows(store, table, shard_id, colnames, hits)


def _compress(cols, nulls, valid):
    if valid.all():
        return cols, nulls, valid
    return ({c: a[valid] for c, a in cols.items()},
            {c: a[valid] for c, a in nulls.items()},
            np.ones(int(valid.sum()), dtype=bool))


def _eval_keys_host(keys, cols, nulls, n):
    src = ColumnSource(cols, nulls)
    arrays = []
    matchable = np.ones(n, dtype=bool)
    for e in keys:
        v, nm = evaluate(e, src, np)
        arrays.append(np.broadcast_to(np.asarray(v), (n,)).astype(np.int64))
        if nm is not None:
            matchable &= ~np.broadcast_to(np.asarray(nm), (n,))
    return arrays, matchable


def _join_host(executor, node: JoinNode):
    lcols, lnulls, lvalid = _exec_host(executor, node.left)
    rcols, rnulls, rvalid = _exec_host(executor, node.right)
    ln, rn = lvalid.shape[0], rvalid.shape[0]
    if node.left_keys:
        lkeys, lmatch = _eval_keys_host(node.left_keys, lcols, lnulls, ln)
        rkeys, rmatch = _eval_keys_host(node.right_keys, rcols, rnulls, rn)
    else:  # keyless product against a replicated side
        lkeys, lmatch = [np.zeros(ln, np.int64)], np.ones(ln, bool)
        rkeys, rmatch = [np.zeros(rn, np.int64)], np.ones(rn, bool)
    src_l = ColumnSource(lcols, lnulls)
    src_r = ColumnSource(rcols, rnulls)
    if node.left_match_filter is not None:
        lmatch &= np.broadcast_to(np.asarray(predicate_mask(
            node.left_match_filter, src_l, np)), (ln,))
    if node.right_match_filter is not None:
        rmatch &= np.broadcast_to(np.asarray(predicate_mask(
            node.right_match_filter, src_r, np)), (rn,))

    # sorted build + run expansion, exact sizes via np.repeat
    bkey = np.stack(rkeys, axis=0)[:, rmatch] if rn else \
        np.zeros((len(rkeys), 0), np.int64)
    border = np.nonzero(rmatch)[0]
    order = np.lexsort(bkey[::-1]) if border.size else np.zeros(0, np.int64)
    border = border[order]
    skey = bkey[:, order]
    pk = np.stack(lkeys, axis=0)
    # lexicographic lower/upper bounds via structured view trick: encode
    # multi-key as tuples through successive searchsorted refinement is
    # fiddly — keys here are int64; pack pairs via 128-bit is overkill at
    # fast-path sizes, so compare via np.searchsorted per composite string
    if skey.shape[0] == 1:
        lo = np.searchsorted(skey[0], pk[0], side="left")
        hi = np.searchsorted(skey[0], pk[0], side="right")
    else:
        void_b = np.ascontiguousarray(skey.T).view(
            [("", np.int64)] * skey.shape[0]).reshape(-1)
        void_p = np.ascontiguousarray(pk.T).view(
            [("", np.int64)] * pk.shape[0]).reshape(-1)
        lo = np.searchsorted(void_b, void_p, side="left")
        hi = np.searchsorted(void_b, void_p, side="right")
    counts = np.where(lmatch, hi - lo, 0)

    probe_outer = node.join_type == "left"
    emit = np.where(lvalid & (counts == 0), 1, counts) if probe_outer \
        else counts
    probe_idx = np.repeat(np.arange(ln), emit)
    offs = np.arange(int(emit.sum())) - np.repeat(
        np.cumsum(emit) - emit, emit)
    matched = np.repeat(counts > 0, emit)
    sorted_pos = np.minimum(np.repeat(lo, emit) + offs,
                            max(border.size - 1, 0))
    build_idx = np.where(matched, border[sorted_pos] if border.size
                         else 0, 0)

    cols, nulls = {}, {}
    for cid, arr in lcols.items():
        cols[cid] = arr[probe_idx]
    for cid, nm in lnulls.items():
        nulls[cid] = nm[probe_idx]
    for cid, arr in rcols.items():
        cols[cid] = arr[build_idx] if arr.size else \
            np.zeros(probe_idx.shape[0], arr.dtype)
        nm = rnulls.get(cid)
        gathered = nm[build_idx] if (nm is not None and arr.size) else None
        if probe_outer:
            missing = ~matched
            nulls[cid] = missing if gathered is None else \
                (gathered | missing)
        elif gathered is not None:
            nulls[cid] = gathered
    valid = np.ones(probe_idx.shape[0], dtype=bool)
    if node.residual is not None and valid.size:
        valid &= np.broadcast_to(np.asarray(predicate_mask(
            node.residual, ColumnSource(cols, nulls), np)),
            valid.shape)
    return _compress(cols, nulls, valid)
