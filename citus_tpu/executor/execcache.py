"""Persistent compiled-executable cache + single-flight compile dedup.

The serving layer made the steady state fast, but every fresh process
re-paid parse → plan → XLA compile per plan shape: a deploy/restart
under live traffic was a compile storm.  The inference-serving move
(PystachIO, PAPERS.md) treats compiled artifacts as durable, versioned
state that is *loaded* — not recomputed — on startup:

* **ExecutableCache** — one per data_dir (the lock_manager_for /
  workload_manager_for pattern): serialized AOT executables
  (``jax.experimental.serialize_executable``) written through the PR-7
  durable-io seam into ``<data_dir>/exec_cache/``.  Each entry is a
  checksummed meta JSON (``atomic_write_json_checked`` — version, env
  stamp, the full plan-cache key, unpack metadata, payload CRC) plus a
  framed binary payload; the payload write lands FIRST, the meta write
  is the commit point, so a power cut between the two leaves an
  invisible orphan, never a torn entry.  Corrupt, torn, truncated or
  version/backend-skewed entries are *detected* (CRC + stamp check) and
  fall back to a clean recompile — never a crash, never a wrong or
  stale executable.

* **CompileGate** — single-flight compile dedup: one in-flight compile
  per cache key per data_dir.  N sessions hitting a cold shape produce
  ONE compile; followers wait in cancellation-aware slices under their
  own ``statement_timeout_ms`` budget.  The serving batcher's ledger
  invariant holds: every follower resolves answered XOR cleanly
  errored XOR promoted (a leader dying on a BaseException or its own
  cancel hands leadership to a waiting follower — no stranded
  waiters).

Trust model: the executable payload is deserialized via jax's pjrt
unpickler (there is no JSON encoding of a compiled binary), so the
cache directory sits in the same trust domain as the data files beside
it — the CRC/stamp checks defend against *rot and skew*, not a
malicious writer with filesystem access (who could corrupt the stripes
directly).  Everything else persisted here stays JSON.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib

from ..errors import StorageError

EXEC_CACHE_VERSION = 1
EXEC_CACHE_DIR = "exec_cache"
# on-disk entry bound per data_dir: retry/tightening intermediates and
# dead shapes age out coldest-first (hits, then insertion sequence)
EXEC_CACHE_MAX_ENTRIES = 512
# coalesce index rewrites: the hit/seq index is advisory (warmup
# ordering) — rebuildable from entry mtimes — so it flushes debounced
INDEX_FLUSH_EVERY = 16

_MAGIC = b"CTEX1\n"


# -- key / metadata serialization -------------------------------------------
# The plan-cache key is a nested tuple of strings, ints, floats, bools
# and Nones (plan fingerprint, n_devices, dtype, feed signature, caps
# signature, probe kernel) — the same JSON-safe shape as the caps memo,
# encoded the same way (tuples tagged so they round-trip).
def key_to_json(obj):
    if isinstance(obj, tuple):
        return {"t": [key_to_json(x) for x in obj]}
    if isinstance(obj, dict):
        return {"d": [[key_to_json(k), key_to_json(v)]
                      for k, v in obj.items()]}
    # numpy scalars ride in some fingerprints (key extents, repart
    # caps): coerce to python scalars — hash/equality agree, so a key
    # reconstructed from JSON still hits the in-memory plan cache
    if isinstance(obj, bool) or obj is None or \
            isinstance(obj, (int, float, str)):
        return obj
    import numpy as _np

    if isinstance(obj, _np.bool_):
        return bool(obj)
    if isinstance(obj, _np.integer):
        return int(obj)
    if isinstance(obj, _np.floating):
        return float(obj)
    return obj


def key_from_json(obj):
    if isinstance(obj, dict) and "t" in obj:
        return tuple(key_from_json(x) for x in obj["t"])
    if isinstance(obj, dict) and "d" in obj:
        return {key_from_json(k): key_from_json(v) for k, v in obj["d"]}
    return obj


def env_stamp(mesh) -> dict:
    """The environment a serialized executable is only valid in: cache
    format version, jax version, backend platform + device kind, and
    the exact mesh device ids (a shrunken post-failover mesh compiles
    different programs than the full one).  Part of the entry hash —
    a skewed entry is never even looked up — AND re-verified from the
    meta on load (defense in depth against hand-moved files)."""
    import jax

    devs = list(mesh.devices.flat)
    return {
        "cache_version": EXEC_CACHE_VERSION,
        "jax": jax.__version__,
        "platform": devs[0].platform,
        "device_kind": getattr(devs[0], "device_kind", ""),
        "devices": [d.id for d in devs],
    }


def _canonical(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


def entry_hash(key, stamp: dict) -> str:
    h = hashlib.sha256()
    h.update(_canonical(key_to_json(key)))
    h.update(b"\0")
    h.update(_canonical(stamp))
    return h.hexdigest()[:40]


def _frame(blobs: list[bytes]) -> bytes:
    out = [_MAGIC]
    for b in blobs:
        out.append(len(b).to_bytes(8, "little"))
        out.append(b)
    return b"".join(out)


def _unframe(data: bytes, n: int) -> list[bytes]:
    if not data.startswith(_MAGIC):
        raise ValueError("exec-cache payload: bad magic")
    off = len(_MAGIC)
    blobs = []
    for _ in range(n):
        if off + 8 > len(data):
            raise ValueError("exec-cache payload: truncated length")
        ln = int.from_bytes(data[off:off + 8], "little")
        off += 8
        if off + ln > len(data):
            raise ValueError("exec-cache payload: truncated blob")
        blobs.append(data[off:off + ln])
        off += ln
    return blobs


def _clone_error(e: Exception) -> Exception:
    """Per-follower copy of a leader's compile failure (sharing one
    exception object across raising threads would share tracebacks);
    classifier markers ride along so each session's retry envelope
    treats it exactly like a solo failure (the serving batcher's
    pattern)."""
    try:
        clone = type(e)(*e.args)
    except Exception:
        clone = StorageError(f"deduped compile failed: {e}")
    for attr in ("injected_fault", "fault_point", "post_visibility"):
        if hasattr(e, attr):
            try:
                setattr(clone, attr, getattr(e, attr))
            except Exception:  # graftlint: ignore[silent-exception] — best-effort marker copy: a clone type refusing ONE attr must not drop the remaining markers or the error itself
                continue
    return clone


class _Flight:
    __slots__ = ("evt", "entry", "error", "promote")

    def __init__(self):
        self.evt = threading.Event()
        self.entry = None
        self.error: Exception | None = None
        self.promote = False


class CompileGate:
    """Single-flight compile dedup: one in-flight compile per key.

    ``run(key, compile_fn)`` either leads (runs ``compile_fn`` and
    publishes the entry to every waiter) or follows (waits, in
    cancellation-aware slices, for the leader's entry).  Ledger: every
    caller resolves answered XOR cleanly errored XOR promoted —
    a leader that dies on a BaseException (power cut, interpreter
    teardown) or on its own cancel/timeout hands leadership to a
    self-promoting follower instead of erroring innocents."""

    def __init__(self):
        self._mu = threading.Lock()
        self._flights: dict = {}
        # shared-layer totals (bench cold_start + the fan-in test read
        # these; per-session counters fold requester-side).  A flight
        # is one gated RESOLVE (disk load or compile — the owning
        # ExecutableCache counts actual compiles separately)
        self.flights_led_total = 0
        self.deduped_total = 0
        self.promoted_total = 0
        self.errored_followers_total = 0

    def run(self, key, compile_fn):
        """Returns ``(entry, deduped)``; raises the compile failure
        (leaders raise their own, followers a per-waiter clone)."""
        from ..errors import QueryCanceled, StatementTimeout
        from ..utils.cancellation import check_cancel

        while True:
            with self._mu:
                fl = self._flights.get(key)
                lead = fl is None
                if lead:
                    fl = self._flights[key] = _Flight()
            if lead:
                try:
                    entry = compile_fn()
                except BaseException as e:
                    with self._mu:
                        self._flights.pop(key, None)
                        if isinstance(e, Exception) and \
                                not isinstance(e, (QueryCanceled,
                                                   StatementTimeout)):
                            # a real compile failure: followers raise a
                            # clone and their own envelopes classify it
                            fl.error = e
                        else:
                            # leader death / leader-local cancel:
                            # innocent followers self-promote instead
                            # of inheriting a failure they never caused
                            fl.promote = True
                    fl.evt.set()
                    raise
                with self._mu:
                    fl.entry = entry
                    self._flights.pop(key, None)
                    self.flights_led_total += 1
                fl.evt.set()
                return entry, False
            from ..stats.tracing import trace_span

            with trace_span("compile.single_flight_wait"):
                while not fl.evt.wait(0.005):
                    check_cancel()  # deadline / Session.cancel() seam
            if fl.promote:
                with self._mu:
                    self.promoted_total += 1
                continue  # self-promote: next loop may lead
            if fl.error is not None:
                with self._mu:
                    self.errored_followers_total += 1
                raise _clone_error(fl.error)
            with self._mu:
                self.deduped_total += 1
            return fl.entry, True

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "in_flight": len(self._flights),
                "flights_led_total": self.flights_led_total,
                "deduped_total": self.deduped_total,
                "promoted_total": self.promoted_total,
                "errored_followers_total": self.errored_followers_total,
            }


class ExecutableCache:
    """Per-data_dir on-disk cache of serialized compiled executables."""

    def __init__(self, data_dir: str):
        self.dir = os.path.join(data_dir, EXEC_CACHE_DIR)
        self.gate = CompileGate()
        self._mu = threading.Lock()
        # hash → {"hits": n, "seq": m}: the warmup ordering source.
        # Advisory — corrupt/absent index rebuilds from entry mtimes
        self._index: dict[str, dict] = {}
        self._seq = 0
        self._index_loaded = False
        self._index_dirty = 0
        # shared-layer totals (citus_stat-style; per-session counters
        # fold requester-side in the runner).  compiles_total counts
        # ACTUAL PlanCompiler builds (the runner bumps it inside its
        # gated compile_fn) — the fan-in/storm "zero redundant
        # compiles" assertions read this, not flight counts
        self.hits_total = 0
        self.misses_total = 0
        self.rejects_total = 0
        self.stores_total = 0
        self.compiles_total = 0

    def note_compile(self) -> None:
        with self._mu:
            self.compiles_total += 1

    # -- paths ---------------------------------------------------------------
    def _meta_path(self, h: str) -> str:
        return os.path.join(self.dir, f"{h}.meta.json")

    def _bin_path(self, h: str) -> str:
        return os.path.join(self.dir, f"{h}.bin")

    def _index_path(self) -> str:
        return os.path.join(self.dir, "index.json")

    def has_entries(self) -> bool:
        try:
            return any(f.endswith(".meta.json")
                       for f in os.listdir(self.dir))
        except OSError:
            return False

    # -- load ----------------------------------------------------------------
    def load(self, key, mesh):
        """Resolve `key` from disk.  Returns ``(entry, status)`` where
        entry is the plan-cache tuple ``(compiled_fn, out_meta,
        stage_keys, shuffle_bytes)`` or None, and status is
        ``'hit' | 'miss' | 'reject'``.  Every failure mode — torn or
        bit-flipped payload, corrupt meta, version/backend/mesh skew,
        an unloadable executable — is *detected* and reported as a
        reject so the caller compiles cleanly; nothing here raises
        except an armed fault/cancel (cooperative seams)."""
        stamp = env_stamp(mesh)
        h = entry_hash(key, stamp)
        meta_path = self._meta_path(h)
        if not os.path.exists(meta_path):
            with self._mu:
                self.misses_total += 1
            return None, "miss"
        from ..utils.faultinjection import fault_point

        from ..errors import QueryCanceled, StatementTimeout

        try:
            # named seam INSIDE the guard: injected rot/IO failure
            # while adopting a persisted executable must end in a
            # counted reject + clean recompile, exactly like real rot
            fault_point("executor.exec_cache_load")
            entry = self._load_verified(h, meta_path, stamp)
        except (QueryCanceled, StatementTimeout):
            raise  # the statement's own deadline/cancel, not rot
        except Exception as e:  # graftlint: ignore[swallowed-fault-seam] — not swallowed into silence: THE contract of this seam is that rot (injected or real) downgrades to a counted reject + clean recompile, never a crash or a stale executable
            with self._mu:
                self.rejects_total += 1
            if self._is_verified_rot(e):
                # only VERIFIED rot (CRC/magic/skew/torn commit)
                # deletes the entry; a transient EMFILE/EIO must not
                # destroy a payload that is actually intact
                self._drop_entry(h)
            return None, "reject"
        self._touch(h)
        with self._mu:
            self.hits_total += 1
        return entry, "hit"

    def load_hash(self, h: str, mesh):
        """Warmup path: adopt entry `h` by its hash, returning
        ``(key, entry)`` — or ``(None, None)`` when the entry is
        missing, skewed or corrupt (warmup skips it; the lazy path
        would reject it the same way)."""
        stamp = env_stamp(mesh)
        meta_path = self._meta_path(h)
        if not os.path.exists(meta_path):
            # pruned/dropped since top_hashes ranked it: not rot — the
            # rejects counter must only ever report DETECTED corruption
            return None, None
        try:
            meta = self._read_meta(meta_path, stamp)
            key = key_from_json(meta["key"])
            if entry_hash(key, stamp) != h:
                raise ValueError("exec-cache entry hash mismatch")
            entry = self._load_verified(h, meta_path, stamp, meta=meta)
        except Exception:
            with self._mu:
                self.rejects_total += 1
            return None, None
        self._touch(h)
        with self._mu:
            self.hits_total += 1
        return key, entry

    @staticmethod
    def _is_verified_rot(e: Exception) -> bool:
        """True when the load failure PROVES the entry is bad (corrupt
        meta/payload, version or environment skew, a bin file missing
        under a present meta = torn commit, malformed fields) rather
        than a transient IO condition."""
        from ..errors import CorruptStripe

        return isinstance(e, (CorruptStripe, ValueError, KeyError,
                              TypeError, FileNotFoundError,
                              EOFError))

    def _read_meta(self, meta_path: str, stamp: dict) -> dict:
        from ..utils.io import read_json_checked

        meta = read_json_checked(meta_path)  # raises CorruptStripe on rot
        if meta.get("version") != EXEC_CACHE_VERSION:
            raise ValueError("exec-cache entry version skew")
        if meta.get("stamp") != stamp:
            # backend / jax-version / mesh-shape skew: a stale
            # executable must never be served across an upgrade
            raise ValueError("exec-cache entry environment skew")
        return meta

    def _load_verified(self, h: str, meta_path: str, stamp: dict,
                       meta: dict | None = None):
        import pickle

        import numpy as np
        from jax.experimental import serialize_executable as _se

        if meta is None:
            meta = self._read_meta(meta_path, stamp)
        with open(self._bin_path(h), "rb") as f:
            data = f.read()
        if zlib.crc32(data) != meta["payload_crc32"]:
            raise ValueError("exec-cache payload checksum mismatch")
        exe, it, ot = _unframe(data, 3)
        compiled = _se.deserialize_and_load(
            exe, pickle.loads(it), pickle.loads(ot))
        out_meta = [(kind, cid, np.dtype(dt))
                    for kind, cid, dt in meta["out_meta"]]
        stage_keys = [tuple(sk) for sk in meta["stage_keys"]]
        return (compiled, out_meta, stage_keys,
                int(meta["shuffle_bytes"]))

    # -- store ---------------------------------------------------------------
    def store(self, key, mesh, compiled, out_meta, stage_keys,
              shuffle_bytes: int) -> bool:
        """Persist one compiled entry.  Best-effort for REAL IO errors
        (the in-memory entry still answers the statement; persistence
        is a warm-start optimization, like the caps memo) — but the
        named fault seam fires before the catch, so an injected fault
        propagates and the session retry envelope exercises the
        recompile path.  Returns True when the entry landed."""
        import pickle

        from ..utils.faultinjection import fault_point
        from ..utils.io import (
            atomic_write_bytes,
            atomic_write_json_checked,
        )

        fault_point("executor.exec_cache_store")
        stamp = env_stamp(mesh)
        h = entry_hash(key, stamp)
        try:
            from jax.experimental import serialize_executable as _se

            exe, in_tree, out_tree = _se.serialize(compiled)
            data = _frame([bytes(exe), pickle.dumps(in_tree),
                           pickle.dumps(out_tree)])
            os.makedirs(self.dir, exist_ok=True)
            # payload first, checksummed meta LAST (the commit point):
            # a power cut between the two leaves an invisible orphan
            # the next store simply overwrites
            atomic_write_bytes(self._bin_path(h), data)
            atomic_write_json_checked(self._meta_path(h), {
                "version": EXEC_CACHE_VERSION,
                "stamp": stamp,
                "key": key_to_json(key),
                "out_meta": [[kind, cid, str(dt)]
                             for kind, cid, dt in out_meta],
                "stage_keys": [list(sk) for sk in stage_keys],
                "shuffle_bytes": int(shuffle_bytes),
                "payload_crc32": zlib.crc32(data),
                "payload_bytes": len(data),
            })
        except Exception:  # graftlint: ignore[silent-exception] — best-effort by contract: a backend whose executables don't serialize (XlaRuntimeError UNIMPLEMENTED), unpicklable treedefs, or a full/read-only disk must NOT fail the statement — it already holds its in-memory executable; warm restarts just stay cold.  The named fault seam fired BEFORE this try, so injected faults still propagate.
            return False
        with self._mu:
            self.stores_total += 1
        self._touch(h)
        self._prune()
        return True

    # -- hotness index / warmup ordering -------------------------------------
    def _load_index_locked(self) -> None:
        if self._index_loaded:
            return
        self._index_loaded = True
        from ..utils.io import read_json_checked

        try:
            obj = read_json_checked(self._index_path())
            idx = {h: {"hits": int(v["hits"]), "seq": int(v["seq"])}
                   for h, v in obj["entries"].items()}
        except Exception:
            # absent/corrupt index: rebuild advisory ordering from
            # entry mtimes (the entries themselves stay verified)
            idx = {}
            try:
                metas = [f for f in os.listdir(self.dir)
                         if f.endswith(".meta.json")]
            except OSError:
                metas = []
            stats = []
            for f in metas:
                try:
                    stats.append((os.stat(
                        os.path.join(self.dir, f)).st_mtime, f))
                except OSError:
                    continue
            for i, (_, f) in enumerate(sorted(stats)):
                idx[f[:-len(".meta.json")]] = {"hits": 0, "seq": i}
        self._index = idx
        self._seq = max((v["seq"] for v in idx.values()), default=-1) + 1

    def _touch(self, h: str) -> None:
        flush = False
        with self._mu:
            self._load_index_locked()
            ent = self._index.get(h)
            if ent is None:
                ent = self._index[h] = {"hits": 0, "seq": 0}
            ent["hits"] += 1
            ent["seq"] = self._seq
            self._seq += 1
            self._index_dirty += 1
            if self._index_dirty >= INDEX_FLUSH_EVERY:
                self._index_dirty = 0
                flush = True
        if flush:
            self.flush_index()

    def flush_index(self) -> None:
        from ..utils.io import atomic_write_json_checked

        with self._mu:
            self._load_index_locked()
            payload = {"entries": dict(self._index)}
            self._index_dirty = 0
        try:
            os.makedirs(self.dir, exist_ok=True)
            atomic_write_json_checked(self._index_path(), payload)
        except OSError:
            pass  # advisory: warmup ordering degrades to mtimes

    def top_hashes(self, limit: int) -> list[str]:
        """Entry hashes hottest-first (hits desc, then recency desc) —
        the warmup phase's work list."""
        with self._mu:
            self._load_index_locked()
            ranked = sorted(self._index.items(),
                            key=lambda kv: (-kv[1]["hits"],
                                            -kv[1]["seq"]))
        out = []
        for h, _ in ranked:
            if os.path.exists(self._meta_path(h)):
                out.append(h)
            if len(out) >= max(0, limit):
                break
        return out

    # -- hygiene -------------------------------------------------------------
    def _drop_entry(self, h: str) -> None:
        for p in (self._meta_path(h), self._bin_path(h)):
            try:
                os.unlink(p)
            except OSError:
                pass
        with self._mu:
            self._load_index_locked()
            self._index.pop(h, None)

    def _prune(self) -> None:
        """Age out coldest entries beyond EXEC_CACHE_MAX_ENTRIES."""
        with self._mu:
            self._load_index_locked()
            if len(self._index) <= EXEC_CACHE_MAX_ENTRIES:
                return
            ranked = sorted(self._index.items(),
                            key=lambda kv: (kv[1]["hits"], kv[1]["seq"]))
            doomed = [h for h, _ in
                      ranked[:len(self._index) - EXEC_CACHE_MAX_ENTRIES]]
        for h in doomed:
            self._drop_entry(h)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "hits_total": self.hits_total,
                "misses_total": self.misses_total,
                "rejects_total": self.rejects_total,
                "stores_total": self.stores_total,
                "compiles_total": self.compiles_total,
                "entries": len(self._index) if self._index_loaded
                else None,
                **{f"gate_{k}": v for k, v in
                   self.gate.snapshot().items()},
            }


# process-wide registry: sessions sharing a data_dir share the cache
# AND the compile gate (the lock_manager_for pattern)
_registry: dict[str, ExecutableCache] = {}
_registry_mu = threading.Lock()


def exec_cache_for(data_dir: str) -> ExecutableCache:
    key = os.path.realpath(data_dir)
    with _registry_mu:
        if key not in _registry:
            _registry[key] = ExecutableCache(key)
        return _registry[key]
