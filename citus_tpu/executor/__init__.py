from .batch import Block, block_from_numpy, block_to_numpy, compact_to_numpy
from .runner import Executor, ResultSet

__all__ = ["Block", "block_from_numpy", "block_to_numpy",
           "compact_to_numpy", "Executor", "ResultSet"]
