"""Distributed DML: UPDATE / DELETE / MERGE over sharded columnar tables.

The reference plans UPDATE/DELETE through the router planner
(/root/reference/src/backend/distributed/planner/multi_router_planner.c:214
CreateModifyPlan: prune by the distribution column, then run the rewritten
statement per shard placement) and MERGE through its own 3-mode planner
(planner/merge_planner.c:1245, requiring the ON clause to match the
distribution column for the pushable form).

TPU-native shape: tables are immutable columnar stripes in the host store,
so modification is a *functional* operation — DELETE writes per-stripe
deletion bitmaps, UPDATE appends rewritten rows and tombstones the old
positions, and both flip visibility with one atomic manifest write
(storage.table_store.TableStore.apply_dml).  Shard pruning reuses the
planner's PruneShards analogue, so a dist-col-constrained DML touches one
shard exactly like the reference's fast-path router.
"""

from __future__ import annotations

import numpy as np

from ..catalog import DistributionMethod
from ..catalog.distribution import hash_token, shard_index_for_token_ranges
from ..errors import ExecutionError, PlanningError, UnsupportedQueryError
from ..planner import expr as ir
from ..planner.bind import Binder
from ..sql import ast
from ..types import DataType
from . import host_eval
from .exprs import ColumnSource, evaluate, predicate_mask


def _result(count: int, tag: str):
    from .runner import ResultSet

    return ResultSet([tag], {tag: [count]}, 1)


def _bind_single_table(session, table: str, alias: str | None,
                       where: ast.Expr | None,
                       item_exprs: tuple[ast.Expr, ...] = ()):
    """Bind a one-table pseudo-SELECT; returns (BoundQuery, BoundRel)."""
    from ..session import _StoreDicts

    meta = session.catalog.table(table)
    items = tuple(ast.SelectItem(e) for e in item_exprs) or (
        ast.SelectItem(ast.ColumnRef(meta.schema.names[0])),)
    sel = ast.Select(items=items,
                     from_items=(ast.TableRef(table, alias),),
                     where=where)
    binder = Binder(session.catalog, _StoreDicts(session.store))
    bound = binder.bind_select(sel)
    return bound, bound.rels[0]


def _target_shards(session, table: str, rel, conjuncts):
    """All shards, narrowed by distribution-column pruning when possible."""
    from ..planner.plan import DistributedPlanner
    from ..session import _StoreDicts, _StoreStats

    shards = session.catalog.table_shards(table)
    planner = DistributedPlanner(session.catalog,
                                 _StoreStats(session.store),
                                 session.n_devices, True,
                                 dicts=_StoreDicts(session.store))
    pruned = planner._prune_shards(rel, conjuncts)
    if pruned is not None:
        keep = set(pruned)
        shards = [s for s in shards if s.shard_index in keep]
    return shards


def _stripe_source(rel, vals, valid):
    cols = {rel.cid(c): v for c, v in vals.items()}
    nulls = {rel.cid(c): ~m for c, m in valid.items() if not m.all()}
    return ColumnSource(cols, nulls)


def _match_mask(bound, rel, vals, valid, n, dmask):
    """Rows (physical stripe positions) the WHERE clause selects and that
    are still alive."""
    mask = np.ones(n, dtype=bool)
    if bound.conjuncts:
        src = _stripe_source(rel, vals, valid)
        for c in bound.conjuncts:
            m = predicate_mask(c, src, np)
            mask &= np.broadcast_to(np.asarray(m, dtype=bool), (n,))
    if dmask is not None:
        mask &= ~dmask
    return mask


def _pred_columns(bound, rel) -> list[str]:
    prefix = f"{rel.rel_index}."
    out: set[str] = set()
    for c in bound.conjuncts:
        for node in ir.walk(c):
            if isinstance(node, ir.BCol) and node.cid.startswith(prefix):
                out.add(node.cid[len(prefix):])
    return sorted(out) or [rel.schema.names[0]]


def execute_delete(session, stmt: ast.Delete):
    bound, rel = _bind_single_table(session, stmt.table, stmt.alias,
                                    stmt.where)
    cols = _pred_columns(bound, rel)
    deletes: dict[int, dict[str, np.ndarray]] = {}
    count = 0
    with session._dml_locks(
            stmt.table,
            lambda: _target_shards(session, stmt.table, rel,
                                   bound.conjuncts)) as shards:
        for shard in shards:
            for rec in session.store.shard_stripe_records(stmt.table,
                                                          shard.shard_id):
                vals, valid, n, dmask = session.store.read_stripe_raw(
                    stmt.table, shard.shard_id, rec["file"], cols, rec)
                mask = _match_mask(bound, rel, vals, valid, n, dmask)
                hits = int(mask.sum())
                if hits:
                    deletes.setdefault(shard.shard_id, {})[rec["file"]] = mask
                    count += hits
        if deletes:
            session._apply_dml(stmt.table, deletes, [])
    return _result(count, "DELETE")


def _split_assignments(session, table: str, meta, assignments):
    """→ (direct, exprs): direct = {col: (value_array_fn)} for STRING/NULL
    literals handled outside the binder; exprs = [(col, ast expr)] bound
    through the pseudo-SELECT."""
    seen = set()
    direct: list[tuple[str, object]] = []
    bindable: list[tuple[str, ast.Expr]] = []
    for a in assignments:
        if a.column in seen:
            raise PlanningError(
                f"multiple assignments to column {a.column!r}")
        seen.add(a.column)
        col = meta.schema.column(a.column)  # raises on unknown column
        if (meta.method == DistributionMethod.HASH
                and a.column == meta.distribution_column):
            # reference errors identically: modifying the partition value
            # is not allowed (multi_router_planner.c)
            raise UnsupportedQueryError(
                "modifying the distribution column is not supported")
        is_null_lit = isinstance(a.value, ast.Literal) and a.value.value is None
        if col.dtype == DataType.STRING:
            if not isinstance(a.value, ast.Literal) or not (
                    is_null_lit or isinstance(a.value.value, str)):
                raise UnsupportedQueryError(
                    "string column assignment must be a literal")
            code = (None if is_null_lit else
                    int(session.store.dictionary(table, a.column)
                        .intern_array([a.value.value])[0]))
            direct.append((a.column, code))
        elif is_null_lit:
            direct.append((a.column, None))
        else:
            bindable.append((a.column, a.value))
    return direct, bindable


def execute_update(session, stmt: ast.Update):
    meta = session.catalog.table(stmt.table)
    direct, bindable = _split_assignments(session, stmt.table, meta,
                                          stmt.assignments)
    bound, rel = _bind_single_table(
        session, stmt.table, stmt.alias, stmt.where,
        tuple(e for _, e in bindable))
    if bindable:
        for bexpr, _name in bound.select[:len(bindable)]:
            for node in ir.walk(bexpr):
                if isinstance(node, ir.BAgg):
                    raise PlanningError(
                        "aggregates are not allowed in UPDATE SET")
    bound_assign = list(zip((c for c, _ in bindable),
                            (e for e, _ in bound.select[:len(bindable)])))

    deletes: dict[int, dict[str, np.ndarray]] = {}
    pending: list[tuple[int, dict]] = []
    count = 0
    codec = session.settings.get("columnar_compression")
    level = session.settings.get("columnar_compression_level")
    chunk_rows = session.settings.get("columnar_chunk_group_row_limit")
    with session._dml_locks(
            stmt.table,
            lambda: _target_shards(session, stmt.table, rel,
                                   bound.conjuncts)) as shards:
        try:
            count = _update_shards(session, stmt, meta, bound, rel,
                                   bound_assign, direct, deletes, pending,
                                   codec, level, chunk_rows, shards)
        except Exception:
            session.store.discard_pending(stmt.table, pending)
            raise
        if deletes or pending:
            session._apply_dml(stmt.table, deletes, pending)
    return _result(count, "UPDATE")


def _update_shards(session, stmt, meta, bound, rel, bound_assign, direct,
                   deletes, pending, codec, level, chunk_rows, shards) -> int:
    count = 0
    for shard in shards:
        new_vals: dict[str, list[np.ndarray]] = {c: [] for c in
                                                 meta.schema.names}
        new_valid: dict[str, list[np.ndarray]] = {c: [] for c in
                                                  meta.schema.names}
        shard_rows = 0
        for rec in session.store.shard_stripe_records(stmt.table,
                                                      shard.shard_id):
            if bound.conjuncts:
                # cheap pass: predicate columns only; decompress the full
                # stripe only when something actually matches
                pv, pm, n, dmask = session.store.read_stripe_raw(
                    stmt.table, shard.shard_id, rec["file"],
                    _pred_columns(bound, rel), rec)
                mask = _match_mask(bound, rel, pv, pm, n, dmask)
                if not mask.any():
                    continue
                vals, valid, _n, _dm = session.store.read_stripe_raw(
                    stmt.table, shard.shard_id, rec["file"], record=rec)
            else:
                vals, valid, n, dmask = session.store.read_stripe_raw(
                    stmt.table, shard.shard_id, rec["file"], record=rec)
                mask = _match_mask(bound, rel, vals, valid, n, dmask)
            hits = int(mask.sum())
            if not hits:
                continue
            deletes.setdefault(shard.shard_id, {})[rec["file"]] = mask
            count += hits
            shard_rows += hits
            idx = np.nonzero(mask)[0]
            sub_vals = {c: vals[c][idx] for c in vals}
            sub_valid = {c: valid[c][idx] for c in valid}
            src = _stripe_source(rel, sub_vals, sub_valid)
            assigned: dict[str, tuple[np.ndarray, np.ndarray]] = {}
            for colname, bexpr in bound_assign:
                dt = meta.schema.column(colname).dtype.numpy_dtype
                v, nm = evaluate(bexpr, src, np)
                v = np.broadcast_to(np.asarray(v).astype(dt), (hits,)).copy()
                ok = (np.ones(hits, dtype=bool) if nm is None
                      else ~np.broadcast_to(nm, (hits,)))
                assigned[colname] = (v, ok.copy())
            for colname, code in direct:
                dt = meta.schema.column(colname).dtype.numpy_dtype
                if code is None:
                    assigned[colname] = (np.zeros(hits, dtype=dt),
                                         np.zeros(hits, dtype=bool))
                else:
                    assigned[colname] = (np.full(hits, code, dtype=dt),
                                         np.ones(hits, dtype=bool))
            for c in meta.schema.names:
                if c in assigned:
                    v, ok = assigned[c]
                    if not meta.schema.column(c).nullable and not ok.all():
                        raise ExecutionError(
                            f"NULL in non-nullable column {c!r}")
                else:
                    v, ok = sub_vals[c], sub_valid[c]
                new_vals[c].append(v)
                new_valid[c].append(ok)
        if shard_rows:
            cols = {c: np.concatenate(new_vals[c]) for c in new_vals}
            validity = {c: np.concatenate(new_valid[c]) for c in new_valid}
            rec = session.store.append_stripe(
                stmt.table, shard.shard_id, cols, validity,
                codec=codec, level=level, chunk_rows=chunk_rows,
                commit=False)
            pending.append((shard.shard_id, rec))
    return count


# ---------------------------------------------------------------------------
# MERGE
# ---------------------------------------------------------------------------

def _decode_columns(store, table, schema, vals, valid):
    """Stored arrays → decoded (strings as objects) + null masks."""
    out = {}
    for name in schema.names:
        dtype = schema.column(name).dtype
        v = vals[name]
        nulls = ~valid[name]
        if dtype == DataType.STRING:
            d = store.dictionary(table, name)
            v = np.asarray(d.decode_array(v), dtype=object)
        out[name] = (v, nulls if nulls.any() else None)
    return out


def _merge_source(session, source: ast.FromItem):
    """→ (alias, {col: (values, nulls)}, n_rows)."""
    if isinstance(source, ast.TableRef):
        meta = session.catalog.table(source.name)
        parts: list[dict] = []
        total = 0
        for shard in session.catalog.table_shards(source.name):
            vals, valid, n = session.store.read_shard(source.name,
                                                      shard.shard_id)
            if n:
                parts.append((vals, valid, n))
                total += n
        merged_v = {c: np.concatenate([p[0][c] for p in parts])
                    if parts else np.empty(
                        0, dtype=meta.schema.column(c).dtype.numpy_dtype)
                    for c in meta.schema.names}
        merged_m = {c: np.concatenate([p[1][c] for p in parts])
                    if parts else np.empty(0, dtype=bool)
                    for c in meta.schema.names}
        cols = _decode_columns(session.store, source.name, meta.schema,
                               merged_v, merged_m)
        return source.alias or source.name, cols, total
    if isinstance(source, ast.SubqueryRef):
        res = session._execute_subselect(source.query)
        cols = {}
        for name in res.column_names:
            data = res.columns[name]
            dt = (res.dtypes or {}).get(name)
            if dt == DataType.DATE:
                from ..types import date_to_days

                arr = np.array([None if x is None else date_to_days(str(x))
                                for x in data], dtype=object)
                nulls = np.array([x is None for x in data], dtype=bool)
                vals = np.array([0 if x is None else x for x in arr],
                                dtype=np.int32)
            else:
                lst = list(data)
                nulls = np.array([x is None for x in lst], dtype=bool)
                if any(isinstance(x, str) for x in lst):
                    vals = np.asarray(lst, dtype=object)
                else:
                    vals = np.array([0 if x is None else x for x in lst])
            cols[name] = (vals, nulls if nulls.any() else None)
        return source.alias, cols, res.row_count
    raise UnsupportedQueryError("MERGE source must be a table or subquery")


def _classify_on(on: ast.Expr, target_names: set[str],
                 target_quals: set[str], source_names: set[str],
                 source_qual: str):
    """ON conjuncts → ([(target_col, source_col)], residual conjuncts)."""

    def side_of(ref: ast.ColumnRef) -> str:
        if ref.table:
            if ref.table in target_quals:
                return "t"
            if ref.table == source_qual:
                return "s"
            raise PlanningError(f"unknown qualifier {ref.table!r} in MERGE ON")
        in_t, in_s = ref.name in target_names, ref.name in source_names
        if in_t and in_s:
            raise PlanningError(
                f"ambiguous column {ref.name!r} in MERGE ON")
        if in_t:
            return "t"
        if in_s:
            return "s"
        raise PlanningError(f"unknown column {ref.name!r} in MERGE ON")

    pairs: list[tuple[str, str]] = []
    residual: list[ast.Expr] = []
    for c in host_eval.split_conjuncts(on):
        if (isinstance(c, ast.BinaryOp) and c.op == "="
                and isinstance(c.left, ast.ColumnRef)
                and isinstance(c.right, ast.ColumnRef)):
            ls, rs = side_of(c.left), side_of(c.right)
            if ls == "t" and rs == "s":
                pairs.append((c.left.name, c.right.name))
                continue
            if ls == "s" and rs == "t":
                pairs.append((c.right.name, c.left.name))
                continue
        residual.append(c)
    return pairs, residual


def execute_merge(session, stmt: ast.Merge):
    meta = session.catalog.table(stmt.target)
    target_alias = stmt.target_alias or stmt.target
    src_alias, src_cols, src_n = _merge_source(session, stmt.source)
    source_names = set(src_cols.keys())
    pairs, residual = _classify_on(
        stmt.on, set(meta.schema.names), {target_alias, stmt.target},
        source_names, src_alias)
    if not pairs:
        raise UnsupportedQueryError(
            "MERGE ON must contain at least one target = source equality")

    if meta.method == DistributionMethod.HASH:
        dist_pairs = [p for p in pairs if p[0] == meta.distribution_column]
        if not dist_pairs:
            # reference requirement: MERGE ON must join on the distribution
            # column (merge_planner.c)
            raise UnsupportedQueryError(
                "MERGE ON must include the target distribution column")
        dist_src = dist_pairs[0][1]
        dv, dn = src_cols[dist_src]
        dt = meta.schema.column(meta.distribution_column).dtype
        if dt == DataType.STRING:
            from ..storage.dictionary import string_hash_tokens

            tokens = string_hash_tokens(
                ["" if x is None else str(x) for x in dv])
        else:
            tokens = hash_token(np.asarray(
                [0 if x is None else x for x in dv], dtype=dt.numpy_dtype))

        def _route():
            # shard INDEXES come from the catalog — derived under the
            # DML locks so a concurrent split can't strand source rows
            src_shard = np.asarray(
                shard_index_for_token_ranges(
                    tokens, session.catalog.shard_mins(stmt.target)),
                dtype=np.int64)
            if dn is not None:
                # NULL join keys never match; those source rows go
                # straight to WHEN NOT MATCHED (PostgreSQL semantics)
                src_shard = np.where(dn, np.int64(-1), src_shard)
            return src_shard
    else:
        def _route():
            return np.zeros(src_n, dtype=np.int64)

    codec = session.settings.get("columnar_compression")
    level = session.settings.get("columnar_compression_level")
    chunk_rows = session.settings.get("columnar_chunk_group_row_limit")
    all_deletes: dict[int, dict[str, np.ndarray]] = {}
    all_pending: list[tuple[int, dict]] = []

    with session._dml_locks(
            stmt.target,
            lambda: session.catalog.table_shards(stmt.target)) as shards:
        src_shard = _route()
        try:
            n_updated, n_deleted, n_inserted, insert_cols, insert_rows_acc = \
                _merge_shards(session, stmt, meta, shards, src_shard,
                              src_cols, src_alias, target_alias, pairs,
                              residual, all_deletes, all_pending,
                              codec, level, chunk_rows)
            if insert_rows_acc:
                # inserts join the same manifest flip as updates/deletes —
                # the whole MERGE becomes visible atomically or not at all
                from ..ingest.copy_from import prepare_rows

                _n, ins_pending = prepare_rows(
                    session, stmt.target, list(insert_cols),
                    [list(r) for r in insert_rows_acc], commit=False)
                all_pending.extend(ins_pending)
        except Exception:
            session.store.discard_pending(stmt.target, all_pending)
            raise

        if all_deletes or all_pending:
            session._apply_dml(stmt.target, all_deletes, all_pending)
    return _result(n_updated + n_deleted + n_inserted, "MERGE")


def _merge_shards(session, stmt, meta, shards, src_shard, src_cols,
                  src_alias, target_alias, pairs, residual,
                  all_deletes, all_pending, codec, level, chunk_rows):
    n_updated = n_deleted = n_inserted = 0
    insert_rows_acc: list[list] = []
    insert_cols: list[str] | None = None

    def handle_not_matched(srow: int) -> None:
        nonlocal insert_cols, n_inserted
        action = _first_action(stmt.not_matched, {}, src_cols, target_alias,
                               stmt.target, src_alias, [], srow,
                               source_only=True)
        if action is None or action.kind == "nothing":
            return
        cols = list(action.insert_columns or meta.schema.names)
        if len(cols) != len(action.insert_values):
            raise PlanningError("MERGE INSERT arity mismatch")
        scope = _pair_scope({}, src_cols, target_alias, stmt.target,
                            src_alias, None, srow)
        row = []
        for e in action.insert_values:
            v, nm = host_eval.eval_expr(e, scope)
            isnull = nm is not None and bool(np.asarray(nm).any())
            row.append(None if isnull else _to_py(np.asarray(v)[()]))
        if insert_cols is None:
            insert_cols = cols
        elif insert_cols != cols:
            raise UnsupportedQueryError(
                "MERGE INSERT column lists must agree across rows")
        insert_rows_acc.append(row)
        n_inserted += 1

    # source rows whose join key is NULL match nothing anywhere
    for srow in np.nonzero(src_shard < 0)[0]:
        handle_not_matched(int(srow))

    for si, shard in enumerate(shards):
        rows_here = np.nonzero(src_shard == si)[0]
        if len(rows_here) == 0:
            continue
        # materialize the target shard with per-stripe position tracking
        stripes = []  # (fname, start, nrows, dmask)
        tv: dict[str, list[np.ndarray]] = {c: [] for c in meta.schema.names}
        tm: dict[str, list[np.ndarray]] = {c: [] for c in meta.schema.names}
        start = 0
        for rec in session.store.shard_stripe_records(stmt.target,
                                                      shard.shard_id):
            vals, valid, n, dmask = session.store.read_stripe_raw(
                stmt.target, shard.shard_id, rec["file"], record=rec)
            stripes.append((rec["file"], start, n, dmask))
            start += n
            for c in meta.schema.names:
                tv[c].append(vals[c])
                tm[c].append(valid[c])
        total = start
        tvals = {c: (np.concatenate(tv[c]) if tv[c] else np.empty(
            0, dtype=meta.schema.column(c).dtype.numpy_dtype))
            for c in meta.schema.names}
        tvalid = {c: (np.concatenate(tm[c]) if tm[c]
                      else np.empty(0, dtype=bool))
                  for c in meta.schema.names}
        alive = np.ones(total, dtype=bool)
        for _f, s0, n, dmask in stripes:
            if dmask is not None:
                alive[s0:s0 + n] &= ~dmask
        tcols = _decode_columns(session.store, stmt.target, meta.schema,
                                tvals, tvalid)

        # hash index on the target join keys (alive rows only)
        index: dict[tuple, list[int]] = {}
        key_arrays = []
        for tcol, _scol in pairs:
            v, nm = tcols[tcol]
            key_arrays.append((v, nm))
        for pos in np.nonzero(alive)[0]:
            key = tuple(
                None if (nm is not None and nm[pos]) else v[pos]
                for v, nm in key_arrays)
            if None in key:
                continue
            index.setdefault(key, []).append(int(pos))

        touched: set[int] = set()
        del_mask = np.zeros(total, dtype=bool)
        upd_rows: list[dict] = []   # {col: (value, is_null)}

        for srow in rows_here:
            key = tuple(
                None if (nm is not None and nm[srow]) else v[srow]
                for (_t, scol) in pairs
                for v, nm in [src_cols[scol]])
            matches = index.get(key, []) if None not in key else []
            if matches and residual:
                matches = [p for p in matches
                           if _pair_truthy(residual, tcols, src_cols,
                                           target_alias, stmt.target,
                                           src_alias, p, srow)]
            if matches:
                # WHEN MATCHED conditions are per (target, source) pair:
                # each matching target row picks its own first-passing
                # clause (PostgreSQL MERGE semantics)
                for p in matches:
                    action = _first_action(stmt.matched, tcols, src_cols,
                                           target_alias, stmt.target,
                                           src_alias, [p], srow)
                    if action is None or action.kind == "nothing":
                        continue
                    if p in touched:
                        raise ExecutionError(
                            "MERGE command cannot affect row a second time")
                    touched.add(p)
                    del_mask[p] = True
                    if action.kind == "delete":
                        n_deleted += 1
                        continue
                    # update = tombstone + rewritten row
                    n_updated += 1
                    row = {}
                    scope = _pair_scope(tcols, src_cols, target_alias,
                                        stmt.target, src_alias, p, srow)
                    assigned = {}
                    for a in action.assignments:
                        meta.schema.column(a.column)  # validates existence
                        if (meta.method == DistributionMethod.HASH and
                                a.column == meta.distribution_column):
                            raise UnsupportedQueryError(
                                "modifying the distribution column is not "
                                "supported")
                        v, nm = host_eval.eval_expr(a.value, scope)
                        isnull = bool(np.asarray(nm).any()) if nm is not None \
                            else False
                        assigned[a.column] = (None if isnull
                                              else np.asarray(v)[()], isnull)
                    for c in meta.schema.names:
                        if c in assigned:
                            row[c] = assigned[c]
                        else:
                            v, nm = tcols[c]
                            isnull = nm is not None and bool(nm[p])
                            row[c] = (None if isnull else v[p], isnull)
                    upd_rows.append(row)
            else:
                handle_not_matched(int(srow))

        # accumulate this shard's tombstones + rewrites; applied for ALL
        # shards in one manifest flip after the statement fully evaluates
        for fname, s0, n, _dm in stripes:
            sub = del_mask[s0:s0 + n]
            if sub.any():
                all_deletes.setdefault(shard.shard_id, {})[fname] = sub.copy()
        if upd_rows:
            cols_arr: dict[str, np.ndarray] = {}
            valid_arr: dict[str, np.ndarray] = {}
            for c in meta.schema.names:
                cdef = meta.schema.column(c)
                nulls = np.array([r[c][1] for r in upd_rows], dtype=bool)
                if cdef.dtype == DataType.STRING:
                    d = session.store.dictionary(stmt.target, c)
                    codes = d.intern_array(
                        [None if isnull else _as_str(v, tcols, c)
                         for (v, isnull) in (r[c] for r in upd_rows)])
                    cols_arr[c] = codes
                else:
                    cols_arr[c] = np.array(
                        [0 if r[c][1] else r[c][0] for r in upd_rows],
                        dtype=cdef.dtype.numpy_dtype)
                if not cdef.nullable and nulls.any():
                    raise ExecutionError(
                        f"NULL in non-nullable column {c!r}")
                valid_arr[c] = ~nulls
            rec = session.store.append_stripe(
                stmt.target, shard.shard_id, cols_arr, valid_arr,
                codec=codec, level=level, chunk_rows=chunk_rows,
                commit=False)
            all_pending.append((shard.shard_id, rec))

    return n_updated, n_deleted, n_inserted, insert_cols, insert_rows_acc


def _as_str(v, tcols, c):
    return None if v is None else str(v)


def _to_py(v):
    if isinstance(v, np.generic):
        return v.item()
    return v


def _pair_scope(tcols, src_cols, target_alias, target_name, src_alias,
                tpos: int | None, spos: int) -> host_eval.Scope:
    scope = host_eval.Scope()
    if tpos is not None:
        for c, (v, nm) in tcols.items():
            val = np.asarray(v[tpos]) if v.dtype != object else \
                np.asarray(v[tpos], dtype=object)
            nul = (np.asarray(True) if (nm is not None and nm[tpos])
                   else None)
            scope.add(target_alias, c, val, nul)
            if target_alias != target_name:
                scope.add(target_name, c, val, nul)
    for c, (v, nm) in src_cols.items():
        val = np.asarray(v[spos]) if v.dtype != object else \
            np.asarray(v[spos], dtype=object)
        nul = np.asarray(True) if (nm is not None and nm[spos]) else None
        scope.add(src_alias, c, val, nul)
    return scope


def _pair_truthy(conjuncts, tcols, src_cols, target_alias, target_name,
                 src_alias, tpos, spos) -> bool:
    scope = _pair_scope(tcols, src_cols, target_alias, target_name,
                        src_alias, tpos, spos)
    for c in conjuncts:
        v, nm = host_eval.eval_expr(c, scope)
        if nm is not None and bool(np.asarray(nm).any()):
            return False
        if not bool(np.asarray(v).all()):
            return False
    return True


def _first_action(actions, tcols, src_cols, target_alias, target_name,
                  src_alias, matches, srow, source_only: bool = False):
    for action in actions:
        if action.condition is None:
            return action
        tpos = None if source_only or not matches else matches[0]
        if _pair_truthy([action.condition], tcols, src_cols, target_alias,
                        target_name, src_alias, tpos, srow):
            return action
    return None
