"""Host-side AST expression evaluator over decoded numpy columns.

Used where evaluation must cross table boundaries with raw (dictionary-
decoded) values — MERGE join/action conditions and assignment expressions
(the reference plans MERGE with the insert-select machinery,
/root/reference/src/backend/distributed/planner/merge_planner.c:1245) —
and by test oracles.  Unlike executor.exprs (which runs over bound IR with
per-table dictionary codes), strings here are numpy object arrays compared
by value, so `target.name = source.name` is correct across tables with
different dictionaries.

Values are (values, null_mask | None) pairs, numpy only; WHERE-style
consumers use `truthy()` (NULL → false).
"""

from __future__ import annotations

import numpy as np

from ..errors import ExecutionError
from ..sql import ast
from ..types import date_to_days


class Scope:
    """Column name resolution: qualified 'alias.col' and bare 'col'.

    Bare names that exist under several qualifiers are ambiguous and
    rejected at lookup time (PostgreSQL raises the same way).
    """

    def __init__(self):
        self._cols: dict[str, tuple] = {}
        self._bare: dict[str, object] = {}

    _AMBIGUOUS = object()

    def add(self, qualifier: str, name: str, values, nulls=None):
        self._cols[f"{qualifier}.{name}"] = (values, nulls)
        if name in self._bare and self._bare[name] != f"{qualifier}.{name}":
            self._bare[name] = self._AMBIGUOUS
        else:
            self._bare[name] = f"{qualifier}.{name}"

    def resolve(self, ref: ast.ColumnRef):
        if ref.table:
            key = f"{ref.table}.{ref.name}"
            if key not in self._cols:
                raise ExecutionError(f"column {key} does not exist")
            return self._cols[key]
        slot = self._bare.get(ref.name)
        if slot is None:
            raise ExecutionError(f"column {ref.name!r} does not exist")
        if slot is self._AMBIGUOUS:
            raise ExecutionError(f"column reference {ref.name!r} is ambiguous")
        return self._cols[slot]


import contextlib
import threading

_guard_state = threading.local()


@contextlib.contextmanager
def _guarded():
    """Marks evaluation of a CASE branch result: per-row guards may
    exclude the rows whose divisors are zero, so raising is wrong."""
    prev = getattr(_guard_state, "depth", 0)
    _guard_state.depth = prev + 1
    try:
        yield
    finally:
        _guard_state.depth = prev


def _check_divisor(rv, rn, ln=None) -> None:
    """PostgreSQL raises division_by_zero for any non-NULL zero divisor
    (a NULL on EITHER side short-circuits the strict operator to NULL).
    Suppressed inside CASE branches (see _guarded) where the old
    masked-NaN behavior applies."""
    if getattr(_guard_state, "depth", 0):
        return
    rv = np.asarray(rv)
    zero = rv == 0
    if rn is not None:
        zero = zero & ~np.broadcast_to(rn, rv.shape)
    if ln is not None:
        zero = zero & ~np.broadcast_to(ln, zero.shape)
    if np.any(zero):
        raise ExecutionError("division by zero")


def _null_or(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a | b


def eval_expr(e: ast.Expr, scope: Scope):
    """→ (values, null_mask | None); values is a numpy array or scalar."""
    if isinstance(e, ast.Literal):
        if e.value is None:
            return np.zeros((), dtype=np.int32), np.ones((), dtype=bool)
        if e.type_hint == "date":
            return np.asarray(date_to_days(str(e.value))), None
        if isinstance(e.value, str):
            return np.asarray(e.value, dtype=object), None
        return np.asarray(e.value), None
    if isinstance(e, ast.ColumnRef):
        return scope.resolve(e)
    if isinstance(e, ast.UnaryOp):
        v, nm = eval_expr(e.operand, scope)
        if e.op == "-":
            return -v, nm
        if e.op.upper() == "NOT":
            # NOT NULL is NULL (null mask passes through)
            return ~np.asarray(v, dtype=bool), nm
        raise ExecutionError(f"bad unary op {e.op}")
    if isinstance(e, ast.BinaryOp):
        lv, ln = eval_expr(e.left, scope)
        rv, rn = eval_expr(e.right, scope)
        op = e.op.upper() if e.op.isalpha() else e.op
        if op in ("AND", "OR"):
            # full Kleene 3VL: NULL AND false = false, NULL AND true = NULL,
            # NULL OR true = true, NULL OR false = NULL — so NOT above a
            # composite still treats NULL correctly
            lb, rb = np.asarray(lv, dtype=bool), np.asarray(rv, dtype=bool)
            any_null = _null_or(ln, rn)
            if op == "AND":
                out = lb & rb
                if any_null is None:
                    return out, None
                lfalse = ~lb if ln is None else (~lb & ~ln)
                rfalse = ~rb if rn is None else (~rb & ~rn)
                definite_false = lfalse | rfalse
                return out, np.broadcast_to(any_null, np.shape(
                    definite_false)) & ~definite_false
            out = lb | rb
            if any_null is None:
                return out, None
            ltrue = lb if ln is None else (lb & ~ln)
            rtrue = rb if rn is None else (rb & ~rn)
            definite_true = ltrue | rtrue
            return out, np.broadcast_to(any_null, np.shape(
                definite_true)) & ~definite_true
        if op in ("=", "<>", "<", "<=", ">", ">="):
            out = _compare(op, lv, rv)
            return out, _null_or(ln, rn)
        if op == "||":
            ls = np.char.array(lv.astype(str) if hasattr(lv, "astype") else lv)
            rs = np.char.array(rv.astype(str) if hasattr(rv, "astype") else rv)
            return np.asarray(ls + rs, dtype=object), _null_or(ln, rn)
        if op in ("+", "-", "*", "/", "%"):
            lv = np.asarray(lv)
            rv = np.asarray(rv)
            if op == "+":
                out = lv + rv
            elif op == "-":
                out = lv - rv
            elif op == "*":
                out = lv * rv
            elif op == "/":
                _check_divisor(rv, rn, ln)
                if np.issubdtype(np.result_type(lv, rv), np.integer):
                    rv_safe = np.where(rv == 0, 1, rv)
                    q = lv // rv_safe
                    r = lv - q * rv_safe
                    out = q + ((r != 0) & ((lv < 0) != (rv_safe < 0)))
                else:
                    out = lv / np.where(rv == 0, np.nan, rv)
            else:
                _check_divisor(rv, rn, ln)
                out = np.fmod(lv, np.where(rv == 0, 1, rv))
            return out, _null_or(ln, rn)
        raise ExecutionError(f"bad binary op {e.op}")
    if isinstance(e, ast.IsNull):
        v, nm = eval_expr(e.operand, scope)
        isnull = (np.zeros(np.shape(v), dtype=bool) if nm is None
                  else np.broadcast_to(nm, np.shape(v)))
        return (~isnull if e.negated else isnull.copy()), None
    if isinstance(e, ast.Between):
        v, nm = eval_expr(e.operand, scope)
        lo, ln = eval_expr(e.low, scope)
        hi, hn = eval_expr(e.high, scope)
        out = (v >= lo) & (v <= hi)
        if e.negated:
            out = ~out
        return out, _null_or(nm, _null_or(ln, hn))
    if isinstance(e, ast.InList):
        v, nm = eval_expr(e.operand, scope)
        vals = []
        has_null_item = False
        for item in e.items:
            iv, inull = eval_expr(item, scope)
            if inull is not None and bool(np.asarray(inull).any()):
                has_null_item = True
                continue
            vals.append(iv[()] if np.ndim(iv) == 0 else iv)
        out = np.zeros(np.shape(v), dtype=bool)
        for x in vals:
            out = out | (v == x)
        # SQL: x IN (..., NULL) is TRUE when matched, else NULL;
        # x NOT IN (..., NULL) is FALSE when matched, else NULL
        null_out = nm
        if has_null_item:
            unmatched_null = ~out
            null_out = unmatched_null if null_out is None else (
                null_out | unmatched_null)
        if e.negated:
            out = ~out
        return out, null_out
    if isinstance(e, ast.CaseWhen):
        # branch results evaluate vectorized over ALL rows, so a zero
        # divisor in a branch the guard excludes must not raise — PG
        # guarantees CASE short-circuits per row (_check_divisor defers)
        if e.else_result is not None:
            with _guarded():
                out, nm = eval_expr(e.else_result, scope)
            out = np.asarray(out)
        else:
            out, nm = np.zeros((), dtype=np.int64), np.ones((), dtype=bool)
        for cond, res in reversed(e.whens):
            with _guarded():
                cv, cn = eval_expr(cond, scope)
            take = np.asarray(cv, dtype=bool)
            if cn is not None:
                take = take & ~cn
            with _guarded():
                rv, rn = eval_expr(res, scope)
            out = np.where(take, rv, out)
            new_null = (np.zeros(np.shape(rv), dtype=bool) if rn is None
                        else rn)
            old_null = np.zeros((), dtype=bool) if nm is None else nm
            nm = np.where(take, new_null, old_null)
        return out, nm
    if isinstance(e, ast.Cast):
        v, nm = eval_expr(e.operand, scope)
        return v, nm
    raise ExecutionError(
        f"host evaluator: unsupported expression {type(e).__name__}")


def _compare(op, lv, rv):
    if op == "=":
        return lv == rv
    if op == "<>":
        return lv != rv
    if op == "<":
        return lv < rv
    if op == "<=":
        return lv <= rv
    if op == ">":
        return lv > rv
    return lv >= rv


def truthy(e: ast.Expr, scope: Scope, n: int) -> np.ndarray:
    """Evaluate as a WHERE predicate over n rows: NULL → false."""
    v, nm = eval_expr(e, scope)
    out = np.broadcast_to(np.asarray(v, dtype=bool), (n,)).copy()
    if nm is not None:
        out &= ~np.broadcast_to(nm, (n,))
    return out


def split_conjuncts(e: ast.Expr | None) -> list[ast.Expr]:
    if e is None:
        return []
    if isinstance(e, ast.BinaryOp) and e.op.upper() == "AND":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]
