"""Bound-expression evaluator: one implementation, two backends.

Evaluates planner IR (citus_tpu.planner.expr) over a Block's column dict
with jax.numpy on device and over plain numpy dicts on the host (final
HAVING / combine step) — the same split as the reference's worker vs
coordinator qual evaluation.  NULL semantics: every node returns
(values, null_mask | None); comparisons yield NULL if either side is NULL;
AND/OR use Kleene logic; WHERE treats NULL as false (callers apply
`predicate_mask`).
"""

from __future__ import annotations

import numpy as np

from ..errors import ExecutionError
from ..planner import expr as ir
from ..types import DataType

_NP_DTYPE = {
    DataType.INT32: "int32", DataType.INT64: "int64",
    DataType.FLOAT32: "float32", DataType.FLOAT64: "float64",
    DataType.BOOL: "bool_", DataType.DATE: "int32",
    DataType.STRING: "int32",
}

# device float policy: SQL double precision evaluates in the session's
# compute dtype on device (f64 is emulated on TPU — slow, and 64-bit
# bitcasts don't compile); the host backend keeps exact float64.  The
# compiler sets this at trace time (PlanCompiler.build) — thread-local so
# sessions tracing concurrently with different compute dtypes don't race.
import threading

_device_float = threading.local()


def set_device_float64(dtype) -> None:
    _device_float.dtype = np.dtype(dtype)


# prepared-statement parameters at trace time: the compiler binds traced
# scalars per param index before tracing the plan body, so BParam nodes
# lower to program INPUTS (generic plans — one executable, any values).
# Host-side evaluation leaves this unset and falls back to the bound
# value carried on the node.
_device_params = threading.local()


def set_device_params(params: dict | None) -> None:
    _device_params.values = params


def _dt(e_dtype: DataType, xp):
    name = _NP_DTYPE[e_dtype]
    if name == "float64" and xp is not np:
        return getattr(_device_float, "dtype", np.dtype(np.float64))
    return getattr(np, name)


class ColumnSource:
    """What the evaluator reads: column arrays + null masks by cid."""

    def __init__(self, columns: dict, nulls: dict | None = None):
        self.columns = columns
        self.nulls = nulls or {}

    def get(self, cid: str):
        if cid not in self.columns:
            raise ExecutionError(f"executor: missing column {cid!r}")
        return self.columns[cid], self.nulls.get(cid)


def evaluate(e: ir.BExpr, src: ColumnSource, xp):
    """→ (values, null_mask | None). xp = jax.numpy or numpy."""
    if isinstance(e, ir.BCol):
        return src.get(e.cid)
    if isinstance(e, ir.BConst):
        if isinstance(e.value, tuple):
            raise ExecutionError("unfolded interval constant reached executor")
        if e.value is None:
            # typed NULL: zeros + all-null mask (broadcast by consumers)
            return (xp.zeros((), dtype=_dt(e.dtype, xp)),
                    xp.ones((), dtype=bool))
        return (xp.asarray(e.value, dtype=_dt(e.dtype, xp)),
                None)
    if isinstance(e, ir.BParam):
        traced = getattr(_device_params, "values", None)
        if traced is not None and e.idx in traced:
            return traced[e.idx].astype(_dt(e.dtype, xp)), None
        return (xp.asarray(e.value, dtype=_dt(e.dtype, xp)), None)
    if isinstance(e, ir.BArith):
        lv, ln = evaluate(e.left, src, xp)
        rv, rn = evaluate(e.right, src, xp)
        dt = _dt(e.dtype, xp)
        lv = lv.astype(dt)
        rv = rv.astype(dt)
        if e.op == "+":
            out = lv + rv
        elif e.op == "-":
            out = lv - rv
        elif e.op == "*":
            out = lv * rv
        elif e.op == "/":
            out = _safe_div(lv, rv, xp)
        elif e.op == "%":
            out = _safe_mod(lv, rv, xp)
        else:
            raise ExecutionError(f"bad arith op {e.op}")
        return out, _or_null(ln, rn, xp)
    if isinstance(e, ir.BCmp):
        lv, ln = evaluate(e.left, src, xp)
        rv, rn = evaluate(e.right, src, xp)
        if e.op == "=":
            out = lv == rv
        elif e.op == "<>":
            out = lv != rv
        elif e.op == "<":
            out = lv < rv
        elif e.op == "<=":
            out = lv <= rv
        elif e.op == ">":
            out = lv > rv
        elif e.op == ">=":
            out = lv >= rv
        else:
            raise ExecutionError(f"bad cmp op {e.op}")
        return out, _or_null(ln, rn, xp)
    if isinstance(e, ir.BBool):
        if e.op == "NOT":
            v, nmask = evaluate(e.args[0], src, xp)
            return ~v, nmask
        vals, nulls = [], []
        for a in e.args:
            v, nmask = evaluate(a, src, xp)
            vals.append(v)
            nulls.append(nmask)
        if e.op == "AND":
            out = vals[0]
            for v in vals[1:]:
                out = out & v
            # Kleene: NULL AND false = false; NULL if no operand is false
            any_null = _any_null(nulls, xp)
            if any_null is None:
                return out, None
            definite_false = _definite(vals, nulls, False, xp)
            return out, any_null & ~definite_false
        if e.op == "OR":
            out = vals[0]
            for v in vals[1:]:
                out = out | v
            any_null = _any_null(nulls, xp)
            if any_null is None:
                return out, None
            definite_true = _definite(vals, nulls, True, xp)
            return out, any_null & ~definite_true
        raise ExecutionError(f"bad bool op {e.op}")
    if isinstance(e, ir.BIsNull):
        v, nmask = evaluate(e.operand, src, xp)
        isnull = (xp.zeros(getattr(v, "shape", ()), dtype=bool)
                  if nmask is None else nmask)
        return (~isnull if e.negated else isnull), None
    if isinstance(e, ir.BInConst):
        v, nmask = evaluate(e.operand, src, xp)
        if len(e.values) == 0:
            out = xp.zeros(getattr(v, "shape", ()), dtype=bool)
        else:
            out = xp.isin(v, xp.asarray(list(e.values), dtype=v.dtype))
        if e.negated:
            out = ~out
        return out, nmask
    if isinstance(e, ir.BCase):
        dt = _dt(e.dtype, xp)
        if e.else_result is not None:
            out, nmask = evaluate(e.else_result, src, xp)
            out = xp.asarray(out, dtype=dt)
        else:
            out = xp.zeros((), dtype=dt)
            nmask = xp.ones((), dtype=bool)
        # apply WHENs in reverse so earlier branches win
        for cond, res in reversed(e.whens):
            cv, cn = evaluate(cond, src, xp)
            take = cv if cn is None else (cv & ~cn)
            rv, rn = evaluate(res, src, xp)
            out = xp.where(take, xp.asarray(rv, dtype=dt), out)
            new_null = (xp.zeros(getattr(rv, "shape", ()), dtype=bool)
                        if rn is None else rn)
            old_null = (xp.zeros((), dtype=bool) if nmask is None else nmask)
            nmask = xp.where(take, new_null, old_null)
        return out, nmask
    if isinstance(e, ir.BMath):
        v, nmask = evaluate(e.operand, src, xp)
        v = v.astype(_dt(e.dtype, xp))
        if e.op == "exp2neg":
            return xp.exp2(-v), nmask
        if e.op == "ln":
            return xp.log(v), nmask
        raise ExecutionError(f"bad math op {e.op}")
    if isinstance(e, ir.BDDBucket):
        from ..ops.sketches import dd_bucket

        v, nmask = evaluate(e.operand, src, xp)
        return dd_bucket(v.astype(_dt(DataType.FLOAT64, xp)), xp), nmask
    if isinstance(e, (ir.BHllBucket, ir.BHllRho)):
        v, nmask = evaluate(e.operand, src, xp)
        h = _hash32(v, xp)
        if isinstance(e, ir.BHllBucket):
            out = (h >> np.uint32(32 - e.p)).astype(np.int32)
            return out, nmask
        w = (h << np.uint32(e.p)).astype(np.uint32)
        rho = _clz32(w, xp) + 1
        cap = 32 - e.p + 1
        return xp.minimum(rho, cap).astype(np.int32), nmask
    if isinstance(e, ir.BStrRemap):
        v, nmask = evaluate(e.operand, src, xp)
        m = len(e.lut)
        if m == 0:
            # empty dictionary (all-NULL / empty column): codes are all
            # NULL_CODE — pass them through, nothing to remap
            return v, nmask
        lut = xp.asarray(list(e.lut), dtype=np.int32)
        # codes outside [0, m) are NULL_CODE or post-bind interned values
        # (stale plan — the fingerprint includes the lut, but guard the
        # gather anyway); map them to themselves → treated as NULL below
        safe = xp.clip(v, 0, m - 1)
        return xp.where((v >= 0) & (v < m), lut[safe], v), nmask
    if isinstance(e, ir.BCast):
        v, nmask = evaluate(e.operand, src, xp)
        return v.astype(_dt(e.dtype, xp)), nmask
    if isinstance(e, ir.BExtract):
        v, nmask = evaluate(e.operand, src, xp)
        return _extract_date_part(v, e.part, xp), nmask
    if isinstance(e, ir.BAgg):
        raise ExecutionError(
            "aggregate reached the scalar evaluator (planner bug)")
    raise ExecutionError(f"unsupported expression node {type(e).__name__}")


def _hash32(v, xp):
    """32-bit murmur-finalizer hash of an int/code column (the HLL input;
    same fmix32 as shard routing, both backends bit-identical)."""
    if xp is np:
        from ..catalog.distribution import hash_token

        return hash_token(np.asarray(v)).view(np.uint32)
    from ..ops.hashing import hash_token_jax

    return hash_token_jax(v).view(xp.uint32)


def _clz32(w, xp):
    """Count leading zeros of uint32 (clz(0) = 32)."""
    if xp is np:
        w64 = w.astype(np.uint64)
        # bit_length via exact float64 log2 (exact for < 2^53)
        bitlen = np.ceil(np.log2(w64.astype(np.float64) + 1.0))
        return (32 - bitlen).astype(np.int32)
    import jax

    return jax.lax.clz(w.astype(xp.uint32)).astype(xp.int32)


def predicate_mask(e: ir.BExpr, src: ColumnSource, xp):
    """WHERE semantics: NULL → false."""
    v, nmask = evaluate(e, src, xp)
    if nmask is None:
        return v
    return v & ~nmask


def _or_null(a, b, xp):
    if a is None:
        return b
    if b is None:
        return a
    return a | b


def _any_null(nulls, xp):
    out = None
    for nmask in nulls:
        out = _or_null(out, nmask, xp)
    return out


def _definite(vals, nulls, truth: bool, xp):
    """Rows where some operand is definitely `truth` (not NULL)."""
    out = None
    for v, nmask in zip(vals, nulls):
        vv = v if truth else ~v
        if nmask is not None:
            vv = vv & ~nmask
        out = vv if out is None else (out | vv)
    return out


def _safe_div(lv, rv, xp):
    if np.issubdtype(np.asarray(rv).dtype if xp is np else rv.dtype,
                     np.integer):
        rv_safe = xp.where(rv == 0, xp.ones((), dtype=rv.dtype), rv)
        # SQL integer division truncates toward zero; // floors — bump the
        # quotient when signs differ and the division is inexact
        q = lv // rv_safe
        r = lv - q * rv_safe
        return q + ((r != 0) & ((lv < 0) != (rv_safe < 0))).astype(q.dtype)
    return lv / xp.where(rv == 0, xp.asarray(np.nan, dtype=rv.dtype), rv)


def _safe_mod(lv, rv, xp):
    # fmod semantics (sign of the dividend) — SQL/PG modulo truncates,
    # Python/numpy % floors; (-7) % 2 must be -1, not 1
    rv_safe = xp.where(rv == 0, xp.ones((), dtype=rv.dtype), rv)
    return xp.fmod(lv, rv_safe)


# Gregorian civil-date decomposition from days-since-epoch, branch-free
# (Howard Hinnant's civil_from_days algorithm) — runs on VPU as int math.
def _extract_date_part(days, part: str, xp):
    z = days.astype("int64") + 719468
    era = xp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = xp.where(mp < 10, mp + 3, mp - 9)
    y = xp.where(m <= 2, y + 1, y)
    if part == "year":
        return y.astype("int32")
    if part == "month":
        return m.astype("int32")
    if part == "day":
        return d.astype("int32")
    raise ExecutionError(f"bad extract part {part}")
