"""Compile a QueryPlan into ONE shard_map program + host glue.

This is the structural replacement for the reference's adaptive executor +
repartition-join machinery (executor/adaptive_executor.c:962,
repartition_join_execution.c:59, intermediate_results.c): where Citus runs
a Job DAG of SQL tasks over libpq connections with intermediate-result
files, the whole distributed query here traces into a single XLA program
executed over the mesh:

    map task  (worker_partition_query_result)  → pack_by_target
    fetch task (fetch_intermediate_results)    → jax.lax.all_to_all
    merge/join task                            → expand_join per device
    worker partial agg / coordinator combine   → segment_aggregate + psum /
                                                 all_to_all final aggregate

Static capacities replace dynamic result sizes; each stage reports an
overflow count, and `execute_with_retry` doubles capacities and recompiles
when any stage overflowed (count-then-emit at host granularity,
SURVEY §7 hard part #1).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(body, mesh, in_specs, out_specs, check_vma=False):
    """Version-compat shim: the replication-check kwarg was renamed
    check_rep → check_vma across jax releases."""
    try:
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma)
    except TypeError:
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)

from ..catalog.distribution import HASH_TOKEN_COUNT, INT32_MIN
from ..errors import ExecutionError, PlanningError
from ..ops import pack_by_target, segment_aggregate
from ..ops.join import expand_join_outer, expand_join_pairs
from ..ops.hashing import hash_token_jax
from ..planner.plan import (
    AggregateNode,
    JoinNode,
    PlanNode,
    ProjectNode,
    QueryPlan,
    ScanNode,
    WindowNode,
)
from ..distributed.mesh import SHARD_AXIS
from .batch import Block
from .exprs import ColumnSource, evaluate, predicate_mask

NULL_PREFIX = "__null__"


def _round_cap(n: int) -> int:
    return max(128, int(math.ceil(n / 128.0)) * 128)


def _scan_ids(plan: QueryPlan) -> list[int]:
    from .feed import walk_plan

    return [id(n) for n in walk_plan(plan.root) if isinstance(n, ScanNode)]


def collect_device_params(plan: QueryPlan) -> list:
    """BParam nodes reachable by the traced program, sorted by index.

    Walks every expression the device program evaluates (scan filters,
    projections, join keys/residuals, window specs, aggregates, and the
    device-topk ORDER BY keys).  Host-only expressions (host_select,
    HAVING) evaluate from the bound values and need no program input."""
    from ..planner import expr as ir

    from .feed import walk_plan

    found: dict[int, object] = {}

    def visit(e):
        if e is None:
            return
        for n in ir.walk(e):
            if isinstance(n, ir.BParam):
                found[n.idx] = n

    for node in walk_plan(plan.root):
        if isinstance(node, ScanNode):
            visit(node.filter)
        elif isinstance(node, ProjectNode):
            for e, _cid in node.exprs:
                visit(e)
        elif isinstance(node, JoinNode):
            for e in list(node.left_keys) + list(node.right_keys):
                visit(e)
            visit(node.residual)
            visit(node.left_match_filter)
            visit(node.right_match_filter)
        elif isinstance(node, WindowNode):
            for w, _cid in node.functions:
                visit(w)
            for p in node.partition_by:
                visit(p)
        elif isinstance(node, AggregateNode):
            for g, _cid in node.group_keys:
                visit(g)
            for a, _cid in node.aggs:
                visit(a)
    if plan.device_topk is not None:
        for e, _d, _nf in plan.host_order_by:
            visit(e)
    return [found[i] for i in sorted(found)]


def param_feed_arrays(plan: QueryPlan, compute_dtype) -> list:
    """One [1] host array per device param, in collect order (appended
    after the scan feeds; replicated across the mesh)."""
    out = []
    for p in collect_device_params(plan):
        dt = np.dtype(p.dtype.numpy_dtype)
        if dt == np.float64 and compute_dtype is not None:
            dt = np.dtype(compute_dtype)
        out.append(np.asarray([p.value], dtype=dt))
    return out


def flatten_feed_arrays(plan: QueryPlan, feeds, compute_dtype=None) -> list:
    """Feed arrays in the exact order PlanCompiler.build consumes them —
    lets a plan-cache hit skip rebuilding the compiler entirely."""
    out = []
    for node_id in _scan_ids(plan):
        feed = feeds[node_id]
        for cid in sorted(feed.arrays):
            out.append(feed.arrays[cid])
        for cid in sorted(feed.nulls):
            out.append(feed.nulls[cid])
        out.append(feed.valid)
    out.extend(param_feed_arrays(plan, compute_dtype))
    return out


def _to_bits64(a):
    """Lossless device-side widening to int64 for the packed transfer.

    64-bit bitcasts are not implemented by the TPU X64 rewriter, so f64
    splits into two 32-bit bitcast words recombined arithmetically."""
    if a.dtype == jnp.float64:
        parts = jax.lax.bitcast_convert_type(a, jnp.uint32)  # [..., 2]
        lo = parts[..., 0].astype(jnp.uint64)
        hi = parts[..., 1].astype(jnp.uint64)
        return ((hi << jnp.uint64(32)) | lo).astype(jnp.int64)
    if a.dtype == jnp.float32:
        # sign-extended int32 bits; host truncation recovers them exactly
        return jax.lax.bitcast_convert_type(a, jnp.int32).astype(jnp.int64)
    return a.astype(jnp.int64)


def _from_bits64(arr: np.ndarray, dtype: np.dtype) -> np.ndarray:
    if dtype == np.float64:
        return arr.view(np.float64)
    if dtype == np.float32:
        return arr.astype(np.int32).view(np.float32)
    if dtype == np.bool_:
        return arr != 0
    return arr.astype(dtype)


def unpack_outputs(packed: np.ndarray, out_meta):
    """Packed [n_out, n_dev, cap] int64 → (cols, nulls, valid) numpy."""
    cols: dict[str, np.ndarray] = {}
    nulls: dict[str, np.ndarray] = {}
    valid = None
    for i, (kind, cid, dt) in enumerate(out_meta):
        arr = _from_bits64(packed[i], dt)
        if kind == "col":
            cols[cid] = arr
        elif kind == "null":
            nulls[cid] = arr
        else:
            valid = arr
    return cols, nulls, valid


@dataclass
class FeedSpec:
    """Host-side data feed for one scan: arrays indexed like the plan."""

    node: ScanNode
    sharded: bool               # False ⇒ replicated (reference table)
    arrays: dict[str, np.ndarray]       # cid → [n_dev, cap] or [cap]
    nulls: dict[str, np.ndarray]
    valid: np.ndarray                   # [n_dev, cap] or [cap]
    capacity: int
    # rows each device owns (pre-padding; None for replicated feeds) —
    # the EXPLAIN ANALYZE Mesh: line's per-device rows-in source
    dev_rows: list[int] | None = None


@dataclass
class Capacities:
    """Per-node static buffer sizes (trace-time constants)."""

    repartition: dict[int, int]
    join_out: dict[int, int]
    # aggregate output slots (present only when the planner estimated the
    # group count); segment_aggregate outputs slice down to this, shrinking
    # shuffle buffers AND device→host result transfer
    agg_out: dict[int, int] = None
    # True after a dense_oob retry: statistics-planned dense structures
    # (join key directories, dense aggregation grids) proved stale at
    # runtime; recompile on the general sort/search paths
    dense_off: bool = False
    # post-filter compaction slots per selective scan: surviving rows
    # pack into this many slots so downstream joins/aggregates size by
    # the filtered estimate, not the full table
    scan_out: dict[int, int] = None
    # per-(source, target) bucket slots for the INSERT..SELECT output
    # shuffle (QueryPlan.output_repart); None when the plan has none
    output_repart: int | None = None
    # per-bucket probe slots for bucketed fused lookups (JoinNode.
    # probe_bucketed): the packed probe buffer is [n_buckets, this];
    # skew overflows and regrows through the normal retry path
    bucket_probe: dict[int, int] = None
    # per-bucket row slots for bucketed dense-grid aggregation
    # (AggregateNode.bucket_keys): the packed input buffer is
    # [n_buckets, this]; a hot bucket overflows and regrows through
    # the normal retry path, feedback tightens at 0.85
    agg_bucket: dict[int, int] = None

    def __post_init__(self):
        if self.agg_out is None:
            self.agg_out = {}
        if self.scan_out is None:
            self.scan_out = {}
        if self.bucket_probe is None:
            self.bucket_probe = {}
        if self.agg_bucket is None:
            self.agg_bucket = {}

    def grown(self, overflow: int) -> "Capacities":
        """Retry sizing: at least double, and at least enough for the
        observed overflow (expand_join reports exact total-minus-capacity,
        so one retry usually suffices even for 100× join fan-out)."""

        def g(v: int) -> int:
            return _round_cap(max(v * 2, v + int(overflow)))

        return Capacities({k: g(v) for k, v in self.repartition.items()},
                          {k: g(v) for k, v in self.join_out.items()},
                          {k: g(v) for k, v in self.agg_out.items()},
                          self.dense_off,
                          {k: g(v) for k, v in self.scan_out.items()},
                          g(self.output_repart)
                          if self.output_repart else None,
                          {k: g(v) for k, v in self.bucket_probe.items()},
                          {k: g(v) for k, v in self.agg_bucket.items()})


class PlanCompiler:
    """One instance per (plan, feeds, capacities) — produces a jitted fn."""

    def __init__(self, plan: QueryPlan, mesh: Mesh,
                 feeds: dict[int, FeedSpec], caps: Capacities,
                 compute_dtype=np.float32, probe_kernel: str = "xla",
                 group_kernel: str = "auto"):
        self.plan = plan
        self.mesh = mesh
        self.feeds = feeds
        self.caps = caps
        self.n_dev = plan.n_devices
        self.compute_dtype = compute_dtype
        # bucketed-probe inner formulation ('xla' | 'pallas'): a
        # hardware-measured choice (bench_kernels.bench_probe), part of
        # the plan-cache key in the runner
        self.probe_kernel = probe_kernel
        # group-by path pick ('auto' | 'sort' | 'bucketed' |
        # 'bucketed_pallas'): auto defers to the planner's TPU-gated
        # group_bucketed annotation; the rest override it where the
        # plan is structurally eligible (bench_kernels.py groupby is
        # the measurement behind the default).  Part of the plan-cache
        # key in the runner, like probe_kernel.
        self.group_kernel = group_kernel

    # ------------------------------------------------------------------
    def build(self):
        """Returns (jitted_fn, ordered_feed_arrays, out_meta).

        Feeds flatten in deterministic plan-walk order (NOT id() order) so
        a cached executable can be re-fed by flatten_feed_arrays for a
        structurally identical plan compiled in another execution.

        The jitted fn returns (packed, overflow): every output column /
        null mask / validity bitcast to int64 and stacked into ONE
        [n_out, n_dev, cap] array, so fetching results costs two
        device→host transfers total instead of one per column — on
        remote-attached TPUs each transfer pays a full round trip.
        out_meta describes how to unpack (see unpack_outputs)."""
        from .cache import plan_order

        # adaptive-capacity feedback (the static-shape answer to the
        # reference's adaptive executor streaming ACTUAL result sizes,
        # adaptive_executor.c:962): every capacity-consuming stage
        # records its true row count into the overflow transfer, and the
        # host tightens over-estimated buffers + recompiles once, so
        # warm executions run at near-actual sizes even when the
        # planner's estimate was 10× off (e.g. Q3's correlated
        # date-range join selectivity, statically unestimable)
        self._walk_order = plan_order(self.plan)
        self._stage_actual = {}
        self._stage_width = {}
        self.stage_keys = []

        feed_arrays = []
        in_specs = []
        feed_index = {}
        for node_id in _scan_ids(self.plan):
            feed = self.feeds[node_id]
            names = []
            for cid in sorted(feed.arrays):
                feed_arrays.append(feed.arrays[cid])
                in_specs.append(P(SHARD_AXIS) if feed.sharded else P())
                names.append(("col", cid))
            for cid in sorted(feed.nulls):
                feed_arrays.append(feed.nulls[cid])
                in_specs.append(P(SHARD_AXIS) if feed.sharded else P())
                names.append(("null", cid))
            feed_arrays.append(feed.valid)
            in_specs.append(P(SHARD_AXIS) if feed.sharded else P())
            names.append(("valid", ""))
            feed_index[node_id] = names
        self._feed_index = feed_index
        self._feed_sharded = {nid: self.feeds[nid].sharded
                              for nid in feed_index}
        # prepared-statement params ride as replicated [1] inputs AFTER
        # the feeds: the executable is generic over their values (see
        # planner/expr.py BParam)
        self._param_idx = [p.idx for p in collect_device_params(self.plan)]
        n_params = len(self._param_idx)
        feed_arrays.extend(param_feed_arrays(self.plan, self.compute_dtype))
        in_specs.extend([P()] * n_params)

        out_cids = sorted(self.plan.root.out_columns)
        out_specs = ({c: P(SHARD_AXIS) for c in out_cids},
                     {c: P(SHARD_AXIS) for c in out_cids},
                     P(SHARD_AXIS), P(SHARD_AXIS))

        def body(*flat_feeds):
            # trace-time device float policy: SQL float64 evaluates in the
            # session compute dtype on device (thread-local — tracing runs
            # on the calling thread)
            from .exprs import set_device_float64, set_device_params

            set_device_float64(self.compute_dtype)
            if n_params:
                param_args = flat_feeds[-n_params:]
                flat_feeds = flat_feeds[:-n_params]
                set_device_params({idx: arr[0] for idx, arr in
                                   zip(self._param_idx, param_args)})
            try:
                blocks = self._unpack_feeds(flat_feeds)
                self._overflow = jnp.zeros((), dtype=jnp.int64)
                self._dense_oob = jnp.zeros((), dtype=jnp.int64)
                self._stage_actual = {}
                # static all_to_all volume this program moves across
                # the mesh — assigned (not accumulated across traces:
                # eval_shape and the jit both trace this body) and
                # published as PlanCompiler.shuffle_bytes after build
                self._shuffle_bytes = 0
                out = self._exec(self.plan.root, blocks)
                if self.plan.output_repart is not None:
                    # INSERT..SELECT device routing: shuffle the final
                    # block to the TARGET table's sharding so the host
                    # writes per-device slices without re-hashing
                    shard_count, placement, bounds, key_expr = \
                        self.plan.output_repart
                    out = self._repartition(
                        out, [key_expr], shard_count, placement,
                        self.caps.output_repart,
                        keep_null_rows=True,  # host raises on NULL dist
                        bounds=bounds or None)
                if self.plan.root.dist.kind == "replicated":
                    # every device holds identical rows; emit from
                    # device 0 only
                    out = out.with_filter(
                        jnp.broadcast_to(
                            jax.lax.axis_index(SHARD_AXIS) == 0,
                            out.valid.shape))
                topk = self.plan.device_topk
                if topk is not None and out.valid.shape[0] > topk:
                    out = self._device_topk(out, topk)
            finally:
                # traced scalars must not leak into host-side evaluation
                # on this thread after the trace completes
                set_device_params(None)
            cols = {cid: jnp.broadcast_to(out.columns[cid],
                                          out.valid.shape)[None, :]
                    for cid in out_cids}
            nulls = {cid: jnp.broadcast_to(out.null_mask(cid),
                                           out.valid.shape)[None, :]
                     for cid in out_cids}
            # overflow block per device: [capacity_overflow, dense_oob,
            # *stage_actuals] — the host grows buffers for the first,
            # drops stale dense structures for the second, and tightens
            # over-sized buffers from the rest (feedback)
            skeys = sorted(self._stage_actual,
                           key=lambda k: (self._walk_order.get(
                               k[0], 1 << 30), k[1]))
            self.stage_keys = [
                (self._walk_order.get(nid, -1), kind,
                 self._stage_width[(nid, kind)]) for nid, kind in skeys]
            return (cols, nulls, out.valid[None, :],
                    jnp.stack([self._overflow, self._dense_oob]
                              + [self._stage_actual[k] for k in skeys]))

        mapped = shard_map(body, mesh=self.mesh,
                           in_specs=tuple(in_specs), out_specs=out_specs,
                           check_vma=False)
        # abstract-eval to learn output dtypes, then build the pack plan
        shapes = jax.eval_shape(mapped, *feed_arrays)
        # traced, not estimated: the repartition stages that actually
        # exist in this program (the psum-directory pushdown compiles
        # shuffles away entirely — a caps-table estimate would lie)
        self.shuffle_bytes = int(self._shuffle_bytes)
        s_cols, s_nulls, s_valid, _ = shapes
        out_meta = []
        for cid in out_cids:
            out_meta.append(("col", cid, np.dtype(s_cols[cid].dtype)))
        for cid in out_cids:
            out_meta.append(("null", cid, np.dtype(s_nulls[cid].dtype)))
        out_meta.append(("valid", "", np.dtype(s_valid.dtype)))

        def packed_fn(*flat_feeds):
            cols, nulls, valid, overflow = mapped(*flat_feeds)
            rows = []
            for kind, cid, _dt in out_meta:
                arr = (cols[cid] if kind == "col"
                       else nulls[cid] if kind == "null" else valid)
                rows.append(_to_bits64(arr))
            return jnp.stack(rows), overflow

        # the cached executable closes over this compiler (via body); drop
        # the FeedSpec device arrays so the plan cache pins only code +
        # metadata, not every input table's HBM buffers
        self.feeds = None
        # stage_keys was populated by the eval_shape trace above; entries
        # are (walk_index, kind, width) — walk indices, not node ids, so
        # a plan-cache hit from a different plan instance can map them
        return jax.jit(packed_fn), feed_arrays, out_meta, self.stage_keys

    # ------------------------------------------------------------------
    def _unpack_feeds(self, flat_feeds) -> dict[int, Block]:
        blocks = {}
        i = 0
        flat = list(flat_feeds)
        for node_id, names in self._feed_index.items():
            sharded = self._feed_sharded[node_id]
            cols, nulls, valid = {}, {}, None
            for kind, cid in names:
                arr = flat[i]
                i += 1
                if sharded:
                    arr = arr[0]  # shard_map gives [1, cap] per device
                if kind == "col":
                    cols[cid] = arr
                elif kind == "null":
                    nulls[cid] = arr
                else:
                    valid = arr
            blocks[node_id] = Block(cols, valid, nulls)
        return blocks

    # ------------------------------------------------------------------
    # -- window functions -----------------------------------------------
    def _exec_window(self, node, feeds) -> Block:
        """Partition-sorted segmented scans (the WindowAgg analogue).

        Shuffle co-locates partitions (all_to_all by partition-key hash,
        like the repartition join's map+fetch), then per distinct ORDER
        BY spec: one lexsort + running segmented scans.  Results scatter
        back to pre-sort row positions (unique indices — vectorized on
        TPU), so the input block passes through unchanged with the
        window columns appended."""
        from ..ops.aggregate import _segmented_scan

        blk = self._exec(node.input, feeds)
        if node.combine == "repartition":
            cap = self.caps.repartition[id(node)]
            # routing keys with explicit NULL flags (zeroed value + flag),
            # exactly like the aggregate combine shuffle: rows of a NULL
            # partition must land on ONE device
            karr = []
            bsrc = _src(blk)
            for p in node.partition_by:
                v, nm = evaluate(p, bsrc, jnp)
                v = jnp.broadcast_to(v, blk.valid.shape)
                if jnp.issubdtype(v.dtype, jnp.floating):
                    v = jax.lax.bitcast_convert_type(
                        v, jnp.int32 if v.dtype == jnp.float32
                        else jnp.int64)
                v = v.astype(jnp.int64)
                if nm is not None:
                    nmb = jnp.broadcast_to(nm, blk.valid.shape)
                    v = jnp.where(nmb, 0, v)
                    karr.append(v)
                    karr.append(nmb.astype(jnp.int64))
                else:
                    karr.append(v)
            if not karr:
                # one global partition: constant routing key
                karr = [jnp.zeros(blk.valid.shape, jnp.int64)]
            blk = self._repartition(blk, None, self.n_dev,
                                    tuple(range(self.n_dev)), cap,
                                    key_arrays=karr, valid=blk.valid,
                                    record_nid=id(node))
        n = blk.valid.shape[0]
        src = _src(blk)

        # partition keys (NULLs form their own partition, like GROUP BY):
        # zero the value lane under NULL — the raw lane holds whatever
        # the expression computed over garbage and would split the NULL
        # partition
        pkeys = []
        for p in node.partition_by:
            v, nm = evaluate(p, src, jnp)
            v = jnp.broadcast_to(v, (n,))
            if nm is not None:
                nmb = jnp.broadcast_to(nm, (n,))
                v = jnp.where(nmb, jnp.zeros((), v.dtype), v)
                pkeys.append(v)
                pkeys.append(nmb.astype(jnp.int32))
            else:
                pkeys.append(v)

        # group functions by their ORDER BY spec: one sort per spec
        by_order: dict[tuple, list] = {}
        for w, cid in node.functions:
            by_order.setdefault(w.order_by, []).append((w, cid))

        out_cols = dict(blk.columns)
        out_nulls = dict(blk.nulls)
        iota = jnp.arange(n, dtype=jnp.int32)
        for order_spec, fns in by_order.items():
            okeys = []       # sort operands for the order keys
            peer_keys = []   # equality keys defining rank peers
            for e, desc in order_spec:
                v, nm = evaluate(e, src, jnp)
                v = jnp.broadcast_to(v, (n,))
                nmb = (jnp.zeros(n, jnp.bool_) if nm is None
                       else jnp.broadcast_to(nm, (n,)))
                null_rank = (nmb if not desc else ~nmb).astype(jnp.int8)
                # zero the lane under NULL FIRST: peers compare by
                # (zeroed value, null flag) so all NULL rows tie
                v = jnp.where(nmb, jnp.zeros((), v.dtype), v)
                peer_keys.append(v)
                peer_keys.append(nmb.astype(jnp.int8))
                if desc:
                    v = (-v if jnp.issubdtype(v.dtype, jnp.floating)
                         else ~v)
                okeys.append((null_rank, v))
            operands = []
            for null_rank, v in reversed(okeys):
                operands.append(v)
                operands.append(null_rank)
            # lexsort, primary LAST: validity > partition keys > order keys
            order = jnp.lexsort(tuple(operands)
                                + tuple(reversed(pkeys))
                                + ((~blk.valid).astype(jnp.int32),)
                                ).astype(jnp.int32)
            valid_s = blk.valid[order]

            def shift_ne(a):
                return jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                        a[1:] != a[:-1]])

            pb = jnp.zeros(n, jnp.bool_)
            for k in pkeys:
                pb = pb | shift_ne(k[order])
            if not pkeys:
                pb = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                      jnp.zeros(n - 1, jnp.bool_)])
            part_boundary = pb | shift_ne(valid_s)  # invalid tail split off
            peer_boundary = part_boundary
            for k in peer_keys:
                peer_boundary = peer_boundary | shift_ne(k[order])

            # partition/peer start positions via running max over iota
            part_start = jax.lax.cummax(
                jnp.where(part_boundary, iota, jnp.int32(0)))
            peer_start = jax.lax.cummax(
                jnp.where(peer_boundary, iota, jnp.int32(0)))
            # position of the LAST row of each peer group (running
            # aggregates include peers)
            peer_end = _seg_last(peer_boundary, iota)

            for w, cid in fns:
                res_s, null_s = self._window_value(
                    w, blk, src, order, valid_s, part_boundary,
                    peer_boundary, part_start, peer_start, peer_end,
                    iota, _segmented_scan)
                wcol = jnp.zeros(n, res_s.dtype).at[order].set(res_s)
                out_cols[cid] = wcol
                if null_s is not None:
                    out_nulls[cid] = jnp.zeros(n, jnp.bool_) \
                        .at[order].set(null_s)
        return Block(out_cols, blk.valid, out_nulls)

    def _window_value(self, w, blk, src, order, valid_s, part_boundary,
                      peer_boundary, part_start, peer_start, peer_end,
                      iota, seg_scan):
        """One window function over the sorted view → (values, nulls)."""
        n = valid_s.shape[0]
        if w.kind == "row_number":
            return (iota - part_start + 1).astype(jnp.int64), None
        if w.kind == "rank":
            return (peer_start - part_start + 1).astype(jnp.int64), None
        if w.kind == "dense_rank":
            c = jnp.cumsum(peer_boundary.astype(jnp.int32))
            at_start = jax.lax.cummax(
                jnp.where(part_boundary, c, jnp.int32(0)))
            return (c - at_start + 1).astype(jnp.int64), None

        # aggregate kinds: running (with ORDER BY, peers included) or
        # whole-partition (without)
        whole = not w.order_by
        if w.kind == "count_star":
            v = jnp.ones(n, jnp.int64)
            contrib = valid_s
        else:
            raw, nm = evaluate(w.arg, src, jnp)
            raw = jnp.broadcast_to(raw, (n,))[order]
            contrib = valid_s if nm is None else (
                valid_s & ~jnp.broadcast_to(nm, (n,))[order])
            v = raw
        kind = w.kind
        if kind in ("count", "count_star"):
            x = contrib.astype(jnp.int64)
            scan = seg_scan(x, part_boundary, jnp.add)
            res = scan[peer_end] if not whole else None
            if whole:
                res = self._partition_total(scan, part_boundary, n)
            return res, None
        if kind in ("sum", "avg"):
            acc = (self.compute_dtype
                   if jnp.issubdtype(v.dtype, jnp.floating)
                   else jnp.int64)
            x = jnp.where(contrib, v.astype(acc), jnp.zeros((), acc))
            scan = seg_scan(x, part_boundary, jnp.add)
            cnt = seg_scan(contrib.astype(jnp.int64), part_boundary,
                           jnp.add)
            if whole:
                scan = self._partition_total(scan, part_boundary, n)
                cnt = self._partition_total(cnt, part_boundary, n)
            else:
                scan = scan[peer_end]
                cnt = cnt[peer_end]
            if kind == "avg":
                res = scan.astype(self.compute_dtype) / \
                    jnp.maximum(cnt, 1).astype(self.compute_dtype)
            else:
                res = scan
            return res, cnt == 0
        if kind in ("min", "max"):
            ident = _big(v.dtype) if kind == "min" else _small(v.dtype)
            x = jnp.where(contrib, v, ident)
            op = jnp.minimum if kind == "min" else jnp.maximum
            scan = seg_scan(x, part_boundary, op)
            cnt = seg_scan(contrib.astype(jnp.int64), part_boundary,
                           jnp.add)
            if whole:
                scan = self._partition_total(scan, part_boundary, n)
                cnt = self._partition_total(cnt, part_boundary, n)
            else:
                scan = scan[peer_end]
                cnt = cnt[peer_end]
            return scan, cnt == 0
        raise ExecutionError(f"bad window kind {w.kind}")

    @staticmethod
    def _partition_total(scan, part_boundary, n):
        """Broadcast each partition's LAST scan value to all its rows."""
        iota = jnp.arange(n, dtype=jnp.int32)
        return scan[_seg_last(part_boundary, iota)]

    def _record(self, nid: int, kind: str, count, width: int) -> None:
        """Track one capacity-consuming stage's ACTUAL row count (traced
        scalar) and its buffer width (static).  Multiple records for the
        same (node, kind) — e.g. repart_both's two shuffles, or the two
        sort-path aggregation levels — merge by max: the shared buffer
        must cover the larger."""
        key = (nid, kind)
        c = count.astype(jnp.int64)
        if key in self._stage_actual:
            self._stage_actual[key] = jnp.maximum(self._stage_actual[key],
                                                  c)
        else:
            self._stage_actual[key] = c
        self._stage_width[key] = max(int(width),
                                     self._stage_width.get(key, 0))

    def _exec(self, node: PlanNode, feeds: dict[int, Block]) -> Block:
        if isinstance(node, ScanNode):
            blk = feeds[id(node)]
            if node.filter is not None:
                mask = predicate_mask(node.filter,
                                      _src(blk), jnp)
                blk = blk.with_filter(mask)
                self._record(id(node), "scan_out", blk.valid.sum(),
                             blk.valid.shape[0])
                k = self.caps.scan_out.get(id(node))
                if k is not None and k < blk.valid.shape[0]:
                    blk = self._compact(blk, k)
            return blk
        if isinstance(node, ProjectNode):
            blk = self._exec(node.input, feeds)
            return self._project(blk, node.exprs)
        if isinstance(node, JoinNode):
            return self._exec_join(node, feeds)
        if isinstance(node, WindowNode):
            return self._exec_window(node, feeds)
        if isinstance(node, AggregateNode):
            return self._exec_aggregate(node, feeds)
        raise ExecutionError(f"unknown plan node {type(node).__name__}")

    def _compact(self, blk: Block, k: int) -> Block:
        """Pack surviving rows into k slots (selection-vector compaction).

        A selective filter leaves the block mostly padding; every
        downstream sort/shuffle/join still pays for the full capacity.
        Compaction costs one cumsum + one unique-index scatter + one
        gather per column at the OLD size, and shrinks everything after
        it to the filtered-estimate size.  More survivors than k counts
        as capacity overflow (host retries with doubled slots)."""
        n = blk.valid.shape[0]
        rank = jnp.cumsum(blk.valid.astype(jnp.int32)) - 1
        n_valid = jnp.where(n > 0, rank[n - 1] + 1, 0)
        # the j-th surviving row's position, via unique-index scatter-set
        por = jnp.zeros(k, jnp.int32).at[
            jnp.where(blk.valid & (rank < k), rank, k)].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop")
        out_valid = jnp.arange(k, dtype=jnp.int32) < jnp.minimum(n_valid, k)
        cols = {cid: arr[por] for cid, arr in blk.columns.items()}
        nulls = {cid: nm[por] for cid, nm in blk.nulls.items()}
        self._overflow = self._overflow + \
            jnp.maximum(n_valid - k, 0).astype(jnp.int64)
        return Block(cols, out_valid, nulls)

    def _project(self, blk: Block, exprs) -> Block:
        cols, nulls = {}, {}
        for e, cid in exprs:
            v, nmask = evaluate(e, _src(blk), jnp)
            v = jnp.broadcast_to(v, blk.valid.shape)
            cols[cid] = v
            if nmask is not None:
                nulls[cid] = jnp.broadcast_to(nmask, blk.valid.shape)
        return Block(cols, blk.valid, nulls)

    # -- ORDER BY + LIMIT pushdown --------------------------------------
    def _device_topk(self, blk: Block, k: int) -> Block:
        """Per-device top-k by the plan's ORDER BY keys.

        Shrinks the result transfer from the full padded buffer to
        n_dev·k rows; the host's exact merge sort over those rows is
        unchanged, so the device pass only needs the same total-order
        DIRECTION as the host comparator: DESC negates floats and
        bit-complements ints (~x is a monotone-decreasing bijection with
        no overflow corner), NULL placement follows PG defaults."""
        operands = []
        keys = []
        for e, desc, nulls_first in self.plan.host_order_by:
            v, nmask = evaluate(e, _src(blk), jnp)
            v = jnp.broadcast_to(v, blk.valid.shape)
            nm = (jnp.zeros(blk.valid.shape, jnp.bool_) if nmask is None
                  else jnp.broadcast_to(nmask, blk.valid.shape))
            nulls_last = (not nulls_first if nulls_first is not None
                          else not desc)
            null_rank = (nm if nulls_last else ~nm).astype(jnp.int8)
            ranks = [null_rank]
            if jnp.issubdtype(v.dtype, jnp.floating):
                # the host comparator (np.unique factorize) ranks NaN as
                # the LARGEST value; -NaN is still NaN and would sort
                # last under DESC, so NaN placement gets its own rank key
                nanm = jnp.isnan(v)
                ranks.append((~nanm if desc else nanm).astype(jnp.int8))
                v = jnp.where(nanm, jnp.zeros((), v.dtype), v)
                if desc:
                    v = -v
            elif desc:
                v = ~v  # monotone-decreasing bijection, no overflow corner
            keys.append((ranks, v))
        # jnp.lexsort: LAST operand is the primary key.  Precedence
        # (most→least): validity, key0 nulls, key0 nan-rank, key0 value, …
        for ranks, v in reversed(keys):
            operands.append(v)
            operands.extend(reversed(ranks))
        invalid = ~blk.valid
        order = jnp.lexsort(tuple(operands) + (invalid,))[:k] \
            .astype(jnp.int32)
        cols = {cid: arr[order] for cid, arr in blk.columns.items()}
        nulls = {cid: nm[order] for cid, nm in blk.nulls.items()}
        return Block(cols, blk.valid[order], nulls)

    # -- joins ----------------------------------------------------------
    def _eval_keys(self, blk: Block, keys,
                   key_int32: tuple = ()) -> tuple[list, jnp.ndarray]:
        arrays = []
        valid = blk.valid
        if not keys:
            # keyless (cartesian) join: constant key matches every row pair
            return [jnp.zeros(blk.valid.shape, dtype=jnp.int64)], valid
        for i, e in enumerate(keys):
            v, nmask = evaluate(e, _src(blk), jnp)
            if not jnp.issubdtype(v.dtype, jnp.integer):
                if e.dtype.value in ("float32", "float64"):
                    raise PlanningError(
                        "float join keys are not supported; cast to int")
                v = v.astype(jnp.int64)
            # int64 is software-emulated on TPU (every gather/compare
            # splits into u32 pairs) — narrow to int32 whenever the
            # planner proved both sides' value ranges fit.  Like the
            # dense directory, the proof comes from statistics: a runtime
            # value outside int32 (stale stats / overlay rows) raises
            # dense_oob so the host recompiles wide instead of silently
            # wrapping keys.  dense_off retries disable narrowing too.
            narrow = (i < len(key_int32) and key_int32[i]
                      and not self.caps.dense_off)
            if narrow and v.dtype != jnp.int32:
                wide = (v < jnp.int64(-(1 << 31))) | \
                       (v > jnp.int64((1 << 31) - 1))
                if nmask is not None:
                    wide = wide & ~nmask
                self._dense_oob = self._dense_oob + \
                    (wide & blk.valid).sum().astype(jnp.int64)
            kd = jnp.int32 if narrow else jnp.int64
            arrays.append(jnp.broadcast_to(v.astype(kd), blk.valid.shape))
            if nmask is not None:
                valid = valid & ~nmask  # SQL: NULL never joins
        return arrays, valid

    def _dense_for(self, extents: tuple, keys: list) -> tuple | None:
        """(base, extent) for a single-key build side, or None."""
        from ..ops.join import dense_directory_ok

        if self.caps.dense_off or len(keys) != 1:
            return None
        if not extents or extents[0] is None:
            return None
        base, extent = extents[0]
        if not dense_directory_ok(extent, keys[0].shape[0]):
            return None
        return (int(base), int(extent))

    def _repartition(self, blk: Block, keys, shard_count: int,
                     placement: tuple[int, ...], capacity: int,
                     key_arrays: list | None = None,
                     valid: jnp.ndarray | None = None,
                     keep_null_rows: bool = False,
                     bounds: tuple[int, ...] | None = None,
                     record_nid: int | None = None) -> Block:
        """pack → all_to_all → flatten: the map+fetch phases fused.

        When repartitioning toward a TABLE's sharding (repart_left/right),
        the single key must hash exactly like host ingest routing —
        hash_token_jax.  Multi-key shuffles (repart_both second key set,
        aggregate combine) only need internal consistency and use the
        64-bit combine folded to token space.
        """
        if key_arrays is None:
            key_arrays, valid = self._eval_keys(blk, keys)
            if keep_null_rows:
                # outer-preserved side: NULL-key rows ride the shuffle
                # (routed by their zeroed storage value — deterministic;
                # they match nothing but must still emit null-extended)
                valid = blk.valid
        if len(key_arrays) == 1:
            token = hash_token_jax(key_arrays[0])
        else:
            from ..ops.hashing import combine_hash64

            h = combine_hash64(key_arrays)
            token = ((h & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
                     .astype(jnp.int64) + INT32_MIN).astype(jnp.int32)
        if bounds is not None:
            # range-aware routing: shard bounds are arbitrary after splits
            mins = jnp.asarray(np.asarray(bounds, dtype=np.int64))
            shard = (jnp.searchsorted(mins, token.astype(jnp.int64),
                                      side="right") - 1).clip(
                0, shard_count - 1).astype(jnp.int32)
        else:
            increment = HASH_TOKEN_COUNT // shard_count
            shard = jnp.minimum(
                (token.astype(jnp.int64) - INT32_MIN) // increment,
                shard_count - 1).astype(jnp.int32)
        placement_arr = jnp.asarray(np.asarray(placement, dtype=np.int32))
        target = placement_arr[shard]
        if record_nid is not None:
            # the binding constraint on this buffer is the largest
            # (source device → target device) bucket
            sent = jnp.zeros(self.n_dev, jnp.int32).at[target].add(
                valid.astype(jnp.int32), mode="drop")
            self._record(record_nid, "repartition", sent.max(), capacity)

        all_cols = dict(blk.columns)
        for cid, nmask in blk.nulls.items():
            all_cols[NULL_PREFIX + cid] = nmask
        packed, pvalid, overflow = pack_by_target(
            all_cols, valid, target, self.n_dev, capacity)
        self._overflow = self._overflow + overflow.astype(jnp.int64)

        exchanged = {}
        for cid, arr in packed.items():
            exchanged[cid] = jax.lax.all_to_all(
                arr, SHARD_AXIS, split_axis=0, concat_axis=0, tiled=True)
        new_valid = jax.lax.all_to_all(
            pvalid, SHARD_AXIS, split_axis=0, concat_axis=0, tiled=True)
        # mesh-wide exchange volume of this stage (each device moves its
        # whole [n_dev, cap] pack) — static shapes make it knowable at
        # trace time, surfaced via the Mesh: EXPLAIN line and
        # shuffle_bytes_total
        self._shuffle_bytes += self.n_dev * int(
            sum(int(a.size) * a.dtype.itemsize for a in packed.values())
            + int(pvalid.size) * pvalid.dtype.itemsize)
        flat_n = self.n_dev * capacity
        cols, nulls = {}, {}
        for cid, arr in exchanged.items():
            flat = arr.reshape(flat_n)
            if cid.startswith(NULL_PREFIX):
                nulls[cid[len(NULL_PREFIX):]] = flat
            else:
                cols[cid] = flat
        return Block(cols, new_valid.reshape(flat_n), nulls)

    def _join_inputs(self, node: JoinNode, feeds):
        """Execute both sides + repartition stages + key evaluation.

        Returns (lblk, rblk, lkeys, lmatch, rkeys, rmatch) — shared by
        pair-emission execution and the aggregate-pushdown path."""
        lblk = self._exec(node.left, feeds)
        rblk = self._exec(node.right, feeds)

        # probe side preserved: left/full null-extend; anti KEEPS null-key
        # probe rows (they match nothing, so NOT EXISTS holds for them)
        keep_l = node.join_type in ("left", "full", "anti")
        keep_r = node.join_type in ("right", "full")  # build side preserved
        if node.strategy in ("local", "broadcast"):
            pass
        elif node.strategy == "cartesian_gather":
            # sharded × sharded keyless product: replicate the build side
            # on every device with one all_gather over ICI, then the
            # normal keyless pair emission crosses it with the local
            # probe shard
            def _ag(x):
                return jax.lax.all_gather(x, SHARD_AXIS, tiled=True)

            rblk = Block({cid: _ag(a) for cid, a in rblk.columns.items()},
                         _ag(rblk.valid),
                         {cid: _ag(m) for cid, m in rblk.nulls.items()})
        elif node.strategy == "repart_right":
            # hash ONLY the key aligned with the partner's distribution
            # column — extra equi-keys don't participate in routing
            cap = self.caps.repartition[id(node)]
            rblk = self._repartition(rblk,
                                     [node.right_keys[node.repart_key_idx]],
                                     node.left.dist.shard_count,
                                     node.left.dist.placement, cap,
                                     keep_null_rows=keep_r,
                                     bounds=node.left.dist.bounds or None,
                                     record_nid=id(node))
        elif node.strategy == "repart_left":
            cap = self.caps.repartition[id(node)]
            lblk = self._repartition(lblk,
                                     [node.left_keys[node.repart_key_idx]],
                                     node.right.dist.shard_count,
                                     node.right.dist.placement, cap,
                                     keep_null_rows=keep_l,
                                     bounds=node.right.dist.bounds or None,
                                     record_nid=id(node))
        elif node.strategy == "repart_both":
            cap = self.caps.repartition[id(node)]
            identity = tuple(range(self.n_dev))
            lblk = self._repartition(lblk, node.left_keys, self.n_dev,
                                     identity, cap, keep_null_rows=keep_l,
                                     record_nid=id(node))
            rblk = self._repartition(rblk, node.right_keys, self.n_dev,
                                     identity, cap, keep_null_rows=keep_r,
                                     record_nid=id(node))
        else:
            raise ExecutionError(f"bad join strategy {node.strategy}")

        key_int32 = getattr(node, "key_int32", ())
        lkeys, lmatch = self._eval_keys(lblk, node.left_keys, key_int32)
        rkeys, rmatch = self._eval_keys(rblk, node.right_keys, key_int32)
        # ON single-side gates: restrict MATCHING without dropping rows
        if node.left_match_filter is not None:
            lmatch = lmatch & predicate_mask(node.left_match_filter,
                                             _src(lblk), jnp)
        if node.right_match_filter is not None:
            rmatch = rmatch & predicate_mask(node.right_match_filter,
                                             _src(rblk), jnp)
        return lblk, rblk, lkeys, lmatch, rkeys, rmatch

    def _exec_lookup_join(self, node: JoinNode, lblk, rblk, lkeys, lmatch,
                          rkeys, rmatch) -> Block:
        """Fused PK-side lookup join: one output row per probe row.

        No pair-expansion buffers, no emission scan — probe columns pass
        through untouched and build columns arrive by one gather.  A
        probe with >1 match means the planner's uniqueness claim was
        stale: the surplus is reported as dense_oob so the host retries
        on the general expansion path (never silently dropped pairs)."""
        from ..ops.join import (_bounds, bucketed_unique_lookup,
                                dense_unique_lookup)

        if node.join_type == "inner" and \
                getattr(node, "build_side", "right") == "left":
            bblk, bkeys, bmatch = lblk, lkeys, lmatch
            pblk, pkeys, pmatch = rblk, rkeys, rmatch
            extents = getattr(node, "left_key_extents", ())
        else:  # inner build=right, or LEFT join (build is always right)
            bblk, bkeys, bmatch = rblk, rkeys, rmatch
            pblk, pkeys, pmatch = lblk, lkeys, lmatch
            extents = getattr(node, "right_key_extents", ())
        dense = self._dense_for(extents, bkeys)
        bucket_cap = (self.caps.bucket_probe.get(id(node))
                      if getattr(node, "probe_bucketed", False) else None)
        if dense is not None and len(bkeys) == 1 and bucket_cap is not None:
            # bucketed probe (the planner's size-threshold pick for
            # large directories): pack probes by VMEM-sized directory
            # tile, probe tile-locally — random HBM gathers become
            # streaming tile traffic.  Same oob/duplicate retry contract
            # as the single gather; bucket skew overflows → grown retry.
            bidx, counts, dense_oob, boverflow, bfill = \
                bucketed_unique_lookup(bkeys[0], bmatch, pkeys[0],
                                       dense[0], dense[1], bucket_cap,
                                       kernel=self.probe_kernel)
            self._overflow = self._overflow + boverflow
            self._record(id(node), "bucket_probe", bfill, bucket_cap)
            counts = jnp.where(pmatch, counts, 0)
        elif dense is not None and len(bkeys) == 1:
            # unique build key (the fused-lookup planner claim): scatter
            # directory, NO build-side argsort per execution
            bidx, counts, dense_oob = dense_unique_lookup(
                bkeys[0], bmatch, pkeys[0], dense[0], dense[1])
            counts = jnp.where(pmatch, counts, 0)
        else:
            order, lo, hi, dense_oob = _bounds(bkeys, bmatch, pkeys,
                                               dense)
            counts = jnp.where(pmatch, hi - lo, 0)
            m0 = bkeys[0].shape[0]
            bidx = order[jnp.clip(lo, 0, m0 - 1)]
        self._dense_oob = self._dense_oob + dense_oob.astype(jnp.int64) + \
            jnp.maximum(counts - 1, 0).sum().astype(jnp.int64)
        found = counts > 0
        probe_outer = node.join_type == "left"
        out_valid = pblk.valid if probe_outer else found
        if not probe_outer and node.residual is None:
            self._record(id(node), "join_out", out_valid.sum(),
                         out_valid.shape[0])
        # selective FK join: compact the probe side BEFORE gathering
        # build columns, so the gathers and everything downstream run at
        # the join-estimate size instead of the probe capacity
        k = self.caps.join_out.get(id(node))
        if (not probe_outer and node.residual is None and k is not None
                and k < out_valid.shape[0]):
            marker = "__bidx__"
            tmp = Block({**pblk.columns, marker: bidx}, out_valid,
                        pblk.nulls)
            tmp = self._compact(tmp, k)
            bidx = tmp.columns.pop(marker)
            pblk = Block(tmp.columns, tmp.valid, tmp.nulls)
            out_valid = tmp.valid
        cols = dict(pblk.columns)
        nulls = dict(pblk.nulls)
        for cid, arr in bblk.columns.items():
            cols[cid] = arr[bidx]
            nm = bblk.nulls.get(cid)
            gathered = nm[bidx] if nm is not None else None
            if probe_outer:
                missing = ~found
                nulls[cid] = (missing if gathered is None
                              else (gathered | missing))
            elif gathered is not None:
                nulls[cid] = gathered
        return Block(cols, out_valid, nulls)

    def _exec_join(self, node: JoinNode, feeds) -> Block:
        lblk, rblk, lkeys, lmatch, rkeys, rmatch = \
            self._join_inputs(node, feeds)
        if node.join_type in ("semi", "anti"):
            return self._exec_semi_join(node, lblk, rblk, lkeys, lmatch,
                                        rkeys, rmatch)
        if getattr(node, "fuse_lookup", False) and not self.caps.dense_off:
            blk = self._exec_lookup_join(node, lblk, rblk, lkeys, lmatch,
                                         rkeys, rmatch)
            if node.residual is not None:
                blk = blk.with_filter(predicate_mask(node.residual,
                                                     _src(blk), jnp))
                if node.join_type == "inner":
                    # post-residual compaction: the residual-selective
                    # fused join can still shrink to its feedback size
                    self._record(id(node), "join_out", blk.valid.sum(),
                                 blk.valid.shape[0])
                    k = self.caps.join_out.get(id(node))
                    if k is not None and k < blk.valid.shape[0]:
                        blk = self._compact(blk, k)
            return blk
        out_cap = self.caps.join_out[id(node)]

        if node.join_type == "inner":
            # the planner picks the smaller side as build (sorted /
            # directory side); pair emission is symmetric for inner joins
            if getattr(node, "build_side", "right") == "left":
                bkeys, bmatch, bblk = lkeys, lmatch, lblk
                pkeys, pmatch, pblk = rkeys, rmatch, rblk
                extents = getattr(node, "left_key_extents", ())
            else:
                bkeys, bmatch, bblk = rkeys, rmatch, rblk
                pkeys, pmatch, pblk = lkeys, lmatch, lblk
                extents = getattr(node, "right_key_extents", ())
            dense = self._dense_for(extents, bkeys)
            bidx, pidx, out_valid, _miss, overflow, dense_oob = \
                expand_join_pairs(bkeys, bmatch, pkeys, pmatch, pmatch,
                                  out_cap, probe_outer=False, dense=dense)
            self._overflow = self._overflow + overflow.astype(jnp.int64)
            self._dense_oob = self._dense_oob + dense_oob.astype(jnp.int64)
            self._record(id(node), "join_out", out_valid.sum(), out_cap)
            cols, nulls = {}, {}
            for cid, arr in pblk.columns.items():
                cols[cid] = arr[pidx]
            for cid, nmask in pblk.nulls.items():
                nulls[cid] = nmask[pidx]
            for cid, arr in bblk.columns.items():
                cols[cid] = arr[bidx]
            for cid, nmask in bblk.nulls.items():
                nulls[cid] = nmask[bidx]
            blk = Block(cols, out_valid, nulls)
        else:
            blk = self._exec_outer_expand(node, lblk, rblk, lkeys, lmatch,
                                          rkeys, rmatch, out_cap)
        if node.residual is not None:
            blk = blk.with_filter(predicate_mask(node.residual,
                                                 _src(blk), jnp))
        return blk

    def _exec_semi_join(self, node: JoinNode, lblk: Block, rblk: Block,
                        lkeys, lmatch, rkeys, rmatch) -> Block:
        """Semi/anti join (decorrelated EXISTS / NOT EXISTS).

        Output rows ARE probe rows — no pair expansion, no emission
        buffer: without a residual this is one directory/binary-search
        bounds pass producing per-probe match counts (cheaper than any
        pair-emitting join).  With a cross-side residual (Q21's
        `l2.l_suppkey <> l1.l_suppkey`), candidate pairs expand, the
        residual evaluates per pair, and a scatter-max ORs survivors
        back onto probe rows.  With `flag_combine` (probe replicated
        over a sharded build) the per-device flags psum across the mesh.
        Reference semantics: semi/anti join rewrites in
        planner/recursive_planning.c:223."""
        from ..ops.join import _bounds

        dense = self._dense_for(getattr(node, "right_key_extents", ()),
                                rkeys)
        n = lkeys[0].shape[0] if lkeys else lblk.valid.shape[0]
        if node.residual is None:
            order, lo, hi, dense_oob = _bounds(rkeys, rmatch, lkeys, dense)
            self._dense_oob = self._dense_oob + dense_oob.astype(jnp.int64)
            matched = lmatch & (hi > lo)
        else:
            from ..planner.expr import expr_columns

            cap = self.caps.join_out[id(node)]
            bidx, pidx, out_valid, _miss, overflow, dense_oob = \
                expand_join_pairs(rkeys, rmatch, lkeys, lmatch, lmatch,
                                  cap, probe_outer=False, dense=dense)
            self._overflow = self._overflow + overflow.astype(jnp.int64)
            self._dense_oob = self._dense_oob + dense_oob.astype(jnp.int64)
            self._record(id(node), "join_out", out_valid.sum(), cap)
            # gather ONLY the residual's columns at pair capacity — the
            # output block is the probe block, so everything else would
            # be wasted HBM traffic on the widest intermediate
            need = expr_columns(node.residual)
            cols, nulls = {}, {}
            for cid in need:
                if cid in lblk.columns:
                    cols[cid] = lblk.columns[cid][pidx]
                    nm = lblk.nulls.get(cid)
                    if nm is not None:
                        nulls[cid] = nm[pidx]
                elif cid in rblk.columns:
                    cols[cid] = rblk.columns[cid][bidx]
                    nm = rblk.nulls.get(cid)
                    if nm is not None:
                        nulls[cid] = nm[bidx]
            pair = Block(cols, out_valid, nulls)
            ok = out_valid & predicate_mask(node.residual, _src(pair), jnp)
            matched = (jnp.zeros(n, jnp.int32)
                       .at[pidx].max(ok.astype(jnp.int32))) > 0
        if getattr(node, "flag_combine", False):
            matched = jax.lax.psum(matched.astype(jnp.int32),
                                   SHARD_AXIS) > 0
        if node.join_type == "anti":
            valid = lblk.valid & ~matched
        else:
            valid = lblk.valid & matched
        return Block(dict(lblk.columns), valid, dict(lblk.nulls))

    def _exec_outer_expand(self, node: JoinNode, lblk: Block, rblk: Block,
                           lkeys, lmatch, rkeys, rmatch,
                           out_cap: int) -> Block:
        """LEFT/RIGHT/FULL pair emission + null extension.

        LEFT: unmatched probe rows emit once with build columns NULL.
        RIGHT/FULL: unmatched build rows append as a second fixed-size
        segment with probe columns NULL; a replicated (broadcast) build
        side combines matched flags across devices with psum and emits
        its unmatched rows on device 0 only.  Reference semantics:
        planner/multi_router_planner.c:187 outer-join handling."""
        probe_outer = node.join_type in ("left", "full")
        build_outer = node.join_type in ("right", "full")
        replicated_build = build_outer and node.strategy == "broadcast"
        dense = self._dense_for(getattr(node, "right_key_extents", ()),
                                rkeys)
        bidx, pidx, pair_valid, bmissing, unmatched_b, overflow, dense_oob \
            = expand_join_outer(rkeys, rblk.valid, rmatch,
                                lkeys, lblk.valid, lmatch, out_cap,
                                probe_outer, build_outer,
                                replicated_build, SHARD_AXIS, dense=dense)
        self._overflow = self._overflow + overflow.astype(jnp.int64)
        self._dense_oob = self._dense_oob + dense_oob.astype(jnp.int64)
        self._record(id(node), "join_out", pair_valid.sum(), out_cap)

        cols, nulls = {}, {}
        for cid, arr in lblk.columns.items():
            cols[cid] = arr[pidx]
        for cid, nmask in lblk.nulls.items():
            nulls[cid] = nmask[pidx]
        for cid, arr in rblk.columns.items():
            cols[cid] = arr[bidx]
            gathered = rblk.nulls.get(cid)
            nulls[cid] = (bmissing if gathered is None
                          else (gathered[bidx] | bmissing))
        valid = pair_valid

        if build_outer:
            m = rblk.valid.shape[0]
            seg_cols, seg_nulls = {}, {}
            for cid, arr in lblk.columns.items():
                seg_cols[cid] = jnp.broadcast_to(arr[0], (m,))
                seg_nulls[cid] = jnp.ones(m, jnp.bool_)
            for cid, arr in rblk.columns.items():
                seg_cols[cid] = arr
                nm = rblk.nulls.get(cid)
                seg_nulls[cid] = (jnp.zeros(m, jnp.bool_) if nm is None
                                  else nm)
            out_cols, out_nulls = {}, {}
            for cid in cols:
                out_cols[cid] = jnp.concatenate([cols[cid], seg_cols[cid]])
                pn = nulls.get(cid)
                if pn is None:
                    pn = jnp.zeros(pair_valid.shape, jnp.bool_)
                out_nulls[cid] = jnp.concatenate([pn, seg_nulls[cid]])
            return Block(out_cols,
                         jnp.concatenate([valid, unmatched_b]), out_nulls)
        return Block(cols, valid, nulls)

    # -- aggregation ----------------------------------------------------
    def _agg_values(self, node: AggregateNode, blk: Block):
        """Evaluate aggregate inputs → [(value, kind, contrib_valid)]."""
        values = []
        for a, cid in node.aggs:
            if a.kind == "count_star":
                values.append((jnp.ones(blk.valid.shape, jnp.int64),
                               "count", None))
                continue
            v, nmask = evaluate(a.arg, _src(blk), jnp)
            v = jnp.broadcast_to(v, blk.valid.shape)
            if a.kind in ("sum", "avg"):
                if jnp.issubdtype(v.dtype, jnp.floating):
                    v = v.astype(self.compute_dtype)
                else:
                    v = v.astype(jnp.int64)
            kind = "count" if a.kind == "count" else a.kind
            vv = None if nmask is None else ~jnp.broadcast_to(
                nmask, blk.valid.shape)
            values.append((v, kind, vv))
        return values

    def _agg_inputs(self, node: AggregateNode, blk: Block):
        """Evaluate group keys and aggregate inputs on the input block."""
        key_arrays = []
        key_meta = []  # (cid, dtype)
        for g, cid in node.group_keys:
            v, nmask = evaluate(g, _src(blk), jnp)
            v = jnp.broadcast_to(v, blk.valid.shape)
            key_arrays.append(v)
            if nmask is not None:
                # NULLs form their own group: null flag joins the key
                key_arrays.append(
                    jnp.broadcast_to(nmask, blk.valid.shape).astype(jnp.int32))
                key_meta.append((cid, True))
            else:
                key_meta.append((cid, False))
        values = self._agg_values(node, blk)
        return key_arrays, key_meta, values

    def _segment_aggregate_maybe_packed(self, node: AggregateNode,
                                        key_arrays, key_meta, values,
                                        valid):
        """One dispatch point for both sort-path aggregation stages:
        pack the composite key when ranges are known (accumulating the
        stale-range oob), plain multi-key segment_aggregate otherwise."""
        packed, pack_oob = self._pack_group_keys(node, key_arrays,
                                                 key_meta, valid)
        if packed is not None:
            self._dense_oob = self._dense_oob + pack_oob
            return segment_aggregate([packed], values, valid,
                                     out_keys=key_arrays)
        return segment_aggregate(key_arrays, values, valid)

    def _pack_group_keys(self, node: AggregateNode, key_arrays, key_meta,
                         valid, kr=None):
        """Composite group keys → ONE int64 sort key, using the
        planner's statically-known ranges (key_ranges, or the explicit
        `kr` a caller passes — the bucketed grid reuses this exact
        layout for its slot ids so the two paths cannot diverge on
        null/oob edge cases).  Returns (packed [n] | None, oob scalar):
        single-operand argsorts are far faster on TPU than the
        multi-operand lexsort; rows whose key falls outside the planned
        range are COUNTED (they would alias another slot) so the
        dense_oob retry recompiles with packing off.  The null slot is
        always reserved — runtime null masks may exist even when the
        planner believed a key non-nullable."""
        if kr is None:
            kr = getattr(node, "key_ranges", None)
        if kr is None or self.caps.dense_off or len(kr) != len(key_meta):
            return None, None
        expected = len(key_meta) + sum(1 for _c, f in key_meta if f)
        if expected != len(key_arrays):
            return None, None
        n = valid.shape[0]
        packed = jnp.zeros(n, jnp.int64)
        oob = jnp.zeros((), jnp.int64)
        ai = 0
        for (base, extent, _hn), (cid, has_flag) in zip(kr, key_meta):
            v = key_arrays[ai].astype(jnp.int64)
            ai += 1
            nm = None
            if has_flag:
                nm = key_arrays[ai] != 0
                ai += 1
            raw = v - jnp.int64(base)
            inb = (raw >= 0) & (raw < extent)
            width = extent + 1           # slot 0 = NULL
            if nm is not None:
                slot = jnp.where(nm, 0, raw + 1)
                oob = oob + (valid & ~nm & ~inb).sum().astype(jnp.int64)
            else:
                slot = raw + 1
                oob = oob + (valid & ~inb).sum().astype(jnp.int64)
            packed = packed * width + jnp.clip(slot, 0, width - 1)
        # invalid rows sort last (PACK_SLOT_LIMIT headroom guarantees no
        # collision with a real slot)
        packed = jnp.where(valid, packed, jnp.iinfo(jnp.int64).max)
        return packed, oob

    @staticmethod
    def agg_bucket_shape(node: AggregateNode, group_kernel: str,
                         dense_off: bool) -> bool:
        """Single decision point for the bucketed dense-grid group-by:
        capacity planning (Capacities.agg_bucket sizing), the compiler
        dispatch, EXPLAIN's tag and the groupby_bucketed_total counter
        must all agree, or a compiled plan would look up per-bucket
        capacities that were never allocated."""
        if dense_off or node.combine not in ("local", "repartition"):
            return False
        if not getattr(node, "bucket_keys", None) or \
                getattr(node, "bucket_total", 0) <= 0:
            return False
        if node.dense_keys is not None:
            return False  # below the cap the flat dense grid wins
        if group_kernel == "sort":
            return False
        if group_kernel in ("bucketed", "bucketed_pallas"):
            return True
        # auto: the planner's measurement-gated (TPU-only) pick
        return bool(getattr(node, "group_bucketed", False))

    @staticmethod
    def agg_pushdown_shape(node: AggregateNode) -> bool:
        """Static mirror of _try_join_agg_pushdown's eligibility: True ⇒
        the pushdown will handle this aggregate WITHOUT pair emission, so
        capacity planning must not charge the join-output buffer (at
        scale that phantom buffer can alone trip the plan-size guard)."""
        from ..planner import expr as ir

        if node.combine != "global" or node.group_keys:
            return False
        j = node.input
        if not isinstance(j, JoinNode) or j.join_type != "inner" or \
                j.residual is not None:
            return False
        if j.dist.kind == "replicated":
            return False
        lcids = set(j.left.out_columns)
        rcids = set(j.right.out_columns)
        agg_side = None
        for a, _cid in node.aggs:
            if a.kind == "count_star":
                continue
            if a.kind not in ("count", "sum", "min", "max"):
                return False
            cids = {c.cid for c in ir.walk(a.arg) if isinstance(c, ir.BCol)}
            side = ("left" if cids <= lcids
                    else "right" if cids <= rcids else None)
            if side is None or (agg_side is not None and side != agg_side):
                return False
            agg_side = side
        return True

    def _try_join_agg_pushdown(self, node: AggregateNode, feeds):
        """Global aggregate over an inner join WITHOUT pair emission.

        count(*) over a join is sum(matches-per-probe-row); sum/min/max
        whose arguments come from one side reduce over that side weighted
        by match counts.  The O(pairs) emission buffer (and its overflow
        retries) disappear entirely — the analogue of the reference
        pushing count/sum into worker queries instead of shipping join
        rows (planner/multi_logical_optimizer.c WorkerExtendedOpNode).
        Returns None when the shape doesn't qualify (eligibility mirrors
        agg_pushdown_shape, which capacity planning consults)."""
        from ..planner import expr as ir
        from ..ops.join import _bounds

        if not self.agg_pushdown_shape(node):
            return None
        j = node.input
        lcids = set(j.left.out_columns)
        agg_side = None
        for a, _cid in node.aggs:
            if a.kind == "count_star":
                continue
            cids = {c.cid for c in ir.walk(a.arg) if isinstance(c, ir.BCol)}
            agg_side = "left" if cids <= lcids else "right"
        if agg_side is None:
            # count(*) only: probe whichever side the planner made probe
            agg_side = ("left" if getattr(j, "build_side", "right")
                        == "right" else "right")

        if j.strategy in ("repart_both", "repart_left", "repart_right"):
            # shuffle-free variant: when the build key has a dense
            # extent, a psum'd count directory replaces BOTH all_to_all
            # repartitions — the worker-partial-aggregate move done
            # mesh-natively (see _agg_pushdown_psum_directory)
            pushed = self._agg_pushdown_psum_directory(node, j, agg_side,
                                                       feeds)
            if pushed is not None:
                return pushed

        lblk, rblk, lkeys, lmatch, rkeys, rmatch = \
            self._join_inputs(j, feeds)
        if agg_side == "left":
            pblk, pkeys, pmatch = lblk, lkeys, lmatch
            bkeys, bmatch = rkeys, rmatch
            extents = getattr(j, "right_key_extents", ())
        else:
            pblk, pkeys, pmatch = rblk, rkeys, rmatch
            bkeys, bmatch = lkeys, lmatch
            extents = getattr(j, "left_key_extents", ())
        dense = self._dense_for(extents, bkeys)
        _order, lo, hi, dense_oob = _bounds(bkeys, bmatch, pkeys, dense)
        self._dense_oob = self._dense_oob + dense_oob.astype(jnp.int64)
        counts = jnp.where(pmatch, (hi - lo).astype(jnp.int64), 0)
        return self._agg_from_match_counts(node, pblk, counts)

    # psum'd count directories stay worthwhile while the collective
    # volume (extent × 4 B, once per execution) is small next to the
    # all_to_all volume it replaces (the whole input, twice); 4M slots
    # = 16 MB over ICI is the break-even neighborhood on a v5e
    PSUM_DIRECTORY_MAX_SLOTS = 1 << 22

    def _agg_pushdown_psum_directory(self, node: AggregateNode, j,
                                     agg_side: str, feeds):
        """Global aggregate over a REPARTITION join without any
        shuffle: each device scatter-adds its local build rows into a
        [extent] count directory keyed by the dense join key, ONE psum
        makes the directory global, and every probe row reads its
        global match count locally.  The two all_to_all stages (and
        their pack sorts — the dominant cost of the dual-repartition
        shape) vanish; what crosses the mesh is extent × 4 bytes.
        Returns None when ineligible (multi-key join, no dense extent,
        directory too wide) — the caller falls back to the repartition
        pushdown, and a dense_oob retry (stale statistics) lands there
        too via caps.dense_off."""
        if self.caps.dense_off:
            return None
        if len(j.left_keys) != 1 or len(j.right_keys) != 1:
            return None
        extents = (getattr(j, "right_key_extents", ())
                   if agg_side == "left"
                   else getattr(j, "left_key_extents", ()))
        if not extents or extents[0] is None:
            return None
        base, extent = int(extents[0][0]), int(extents[0][1])
        if not (0 < extent + 1 <= self.PSUM_DIRECTORY_MAX_SLOTS):
            return None

        lblk = self._exec(j.left, feeds)
        rblk = self._exec(j.right, feeds)
        key_int32 = getattr(j, "key_int32", ())
        lkeys, lmatch = self._eval_keys(lblk, j.left_keys, key_int32)
        rkeys, rmatch = self._eval_keys(rblk, j.right_keys, key_int32)
        if j.left_match_filter is not None:
            lmatch = lmatch & predicate_mask(j.left_match_filter,
                                             _src(lblk), jnp)
        if j.right_match_filter is not None:
            rmatch = rmatch & predicate_mask(j.right_match_filter,
                                             _src(rblk), jnp)
        if agg_side == "left":
            pblk, pkeys, pmatch = lblk, lkeys, lmatch
            bkeys, bmatch = rkeys, rmatch
        else:
            pblk, pkeys, pmatch = rblk, rkeys, rmatch
            bkeys, bmatch = lkeys, lmatch

        # build-side rows outside the planned extent would silently
        # miss the directory — count them into dense_oob so stale
        # statistics recompile on the repartition path.  Probe-side
        # out-of-extent keys simply match nothing (exact, no retry).
        raw_b = bkeys[0].astype(jnp.int64) - jnp.int64(base)
        b_in = (raw_b >= 0) & (raw_b < extent)
        self._dense_oob = self._dense_oob + \
            (bmatch & ~b_in).sum().astype(jnp.int64)
        idx = jnp.where(bmatch & b_in, raw_b,
                        jnp.int64(extent)).astype(jnp.int32)
        dirc = jnp.zeros(extent + 1, jnp.int32).at[idx].add(
            jnp.int32(1), mode="drop")[:extent]
        dirc = jax.lax.psum(dirc, SHARD_AXIS)
        raw_p = pkeys[0].astype(jnp.int64) - jnp.int64(base)
        p_in = (raw_p >= 0) & (raw_p < extent)
        pidx = jnp.clip(raw_p, 0, extent - 1).astype(jnp.int32)
        counts = jnp.where(pmatch & p_in, dirc[pidx],
                           jnp.int32(0)).astype(jnp.int64)
        return self._agg_from_match_counts(node, pblk, counts,
                                           counts_global=True)

    def _agg_from_match_counts(self, node: AggregateNode, pblk: Block,
                               counts, counts_global: bool = False):
        """Finish an aggregate pushdown from per-probe-row match
        counts.  `counts_global=True` ⇒ counts already include every
        device's build rows (the psum-directory path) — the cross-
        device combine over PROBE rows is identical either way, since
        each probe row lives on exactly one device."""
        values = self._agg_values(node, pblk)
        cols, nulls = {}, {}
        for (a, cid), (v, kind, vv) in zip(node.aggs, values):
            contrib = pblk.valid if vv is None else (pblk.valid & vv)
            w = jnp.where(contrib, counts, 0)
            if kind == "count":
                total = jax.lax.psum(w.sum(), SHARD_AXIS)
                cols[cid] = total[None].astype(jnp.int64)
                continue
            if kind == "sum":
                local = (jnp.where(contrib, v, jnp.zeros((), v.dtype))
                         * w.astype(v.dtype)).sum()
                total = jax.lax.psum(local, SHARD_AXIS)
            elif kind == "min":
                local = jnp.where(contrib & (w > 0), v, _big(v.dtype)).min()
                total = jax.lax.pmin(local, SHARD_AXIS)
            elif kind == "max":
                local = jnp.where(contrib & (w > 0), v,
                                  _small(v.dtype)).max()
                total = jax.lax.pmax(local, SHARD_AXIS)
            else:
                raise ExecutionError(f"bad agg kind {kind}")
            cols[cid] = total[None].astype(v.dtype)
            any_pairs = jax.lax.psum(w.sum(), SHARD_AXIS) > 0
            nulls[cid] = (~any_pairs)[None]
        my_dev = jax.lax.axis_index(SHARD_AXIS)
        return Block(cols, jnp.asarray([my_dev == 0]), nulls)

    def _exec_aggregate(self, node: AggregateNode, feeds) -> Block:
        pushed = self._try_join_agg_pushdown(node, feeds)
        if pushed is not None:
            return pushed
        blk = self._exec(node.input, feeds)
        if node.input.dist.kind == "replicated":
            # replicated rows exist on every device; aggregate them once
            blk = blk.with_filter(
                jnp.broadcast_to(jax.lax.axis_index(SHARD_AXIS) == 0,
                                 blk.valid.shape))
        if node.dense_keys is not None and not self.caps.dense_off and \
                node.combine in ("local", "repartition"):
            return self._exec_dense_aggregate(node, blk)
        if self.agg_bucket_shape(node, self.group_kernel,
                                 self.caps.dense_off) and \
                id(node) in self.caps.agg_bucket:
            bucketed = self._exec_bucketed_aggregate(node, blk)
            if bucketed is not None:
                return bucketed
            # None is a defensive invariant check (see the helper) —
            # with today's _agg_inputs/bucket_keys invariants it cannot
            # fire; falling through lands on the sort path regardless
        key_arrays, key_meta, values = self._agg_inputs(node, blk)

        if node.combine == "global":
            # no GROUP BY: reduce to one row per device, psum/pmin/pmax
            cols, nulls = {}, {}
            for (a, cid), (v, kind, vv) in zip(node.aggs, values):
                contrib_valid = blk.valid if vv is None else (blk.valid & vv)
                if kind == "count":
                    local = contrib_valid.astype(jnp.int64).sum()
                    total = jax.lax.psum(local, SHARD_AXIS)
                elif kind == "sum":
                    local = jnp.where(contrib_valid, v,
                                      jnp.zeros((), v.dtype)).sum()
                    total = jax.lax.psum(local, SHARD_AXIS)
                elif kind == "min":
                    big = _big(v.dtype)
                    local = jnp.where(contrib_valid, v, big).min()
                    total = jax.lax.pmin(local, SHARD_AXIS)
                elif kind == "max":
                    small = _small(v.dtype)
                    local = jnp.where(contrib_valid, v, small).max()
                    total = jax.lax.pmax(local, SHARD_AXIS)
                else:
                    raise ExecutionError(f"bad agg kind {kind}")
                cols[cid] = total[None].astype(v.dtype) \
                    if kind != "count" else total[None].astype(jnp.int64)
                # COUNT of zero rows is 0, not NULL; others are NULL on empty
                if kind != "count":
                    any_rows = jax.lax.psum(
                        contrib_valid.sum(), SHARD_AXIS) > 0
                    nulls[cid] = (~any_rows)[None]
            # emit exactly one valid row on device 0
            my_dev = jax.lax.axis_index(SHARD_AXIS)
            valid = jnp.asarray([my_dev == 0])
            return Block(cols, valid, nulls)

        # companion contribution-counts per value aggregate: an all-NULL
        # group must yield NULL (not the reduction identity) for
        # sum/min/max/avg — count of contributors == 0 ⇒ NULL
        companions = []
        for (a, cid), (v, kind, vv) in zip(node.aggs, values):
            if kind != "count":
                companions.append((v, "count", vv))
            else:
                companions.append(None)
        all_values = values + [c for c in companions if c is not None]
        gk, res, gvalid, ngroups = self._segment_aggregate_maybe_packed(
            node, key_arrays, key_meta, all_values, blk.valid)
        gk, res, gvalid = self._slice_groups(node, gk, res, gvalid, ngroups)
        main_res = res[:len(values)]
        comp_res = res[len(values):]
        partial = self._partial_block(node, key_meta, gk, main_res, gvalid)
        ci = 0
        for (a, cid), comp in zip(node.aggs, companions):
            if comp is not None:
                cnt = comp_res[ci]
                ci += 1
                partial = Block(
                    {**partial.columns, f"__cnt_{cid}": cnt},
                    partial.valid,
                    {**partial.nulls, cid: cnt == 0})

        if node.combine == "local":
            return partial
        if node.combine != "repartition":
            raise ExecutionError(f"bad combine mode {node.combine}")

        # shuffle partial groups by key hash, then merge partials.  Key
        # arrays include the null flags so NULL groups survive the shuffle
        # (routed by flag+zero value, consistently on every device).
        # repart_keys (DISTINCT rewrite) restricts ROUTING to a key
        # subset — co-routed rows still merge by the full key set
        route_idx = (set(node.repart_keys)
                     if getattr(node, "repart_keys", None) is not None
                     else None)
        shuffle_keys = []
        for ki, (cid, has_null) in enumerate(key_meta):
            if route_idx is not None and ki not in route_idx:
                continue
            v = partial.columns[cid]
            if jnp.issubdtype(v.dtype, jnp.floating):
                v = jax.lax.bitcast_convert_type(
                    v, jnp.int32 if v.dtype == jnp.float32 else jnp.int64)
            shuffle_keys.append(v.astype(jnp.int64))
            if has_null:
                nm = partial.null_mask(cid)
                # zero the value under NULL so routing is deterministic
                shuffle_keys[-1] = jnp.where(nm, 0, shuffle_keys[-1])
                shuffle_keys.append(nm.astype(jnp.int64))
        cap = self.caps.repartition[id(node)]
        shuffled = self._repartition(partial, None, self.n_dev,
                                     tuple(range(self.n_dev)), cap,
                                     key_arrays=shuffle_keys,
                                     valid=partial.valid,
                                     record_nid=id(node))
        key_arrays2 = []
        for cid, has_null in key_meta:
            key_arrays2.append(shuffled.columns[cid])
            if has_null:
                key_arrays2.append(
                    shuffled.null_mask(cid).astype(jnp.int32))
        values2 = []
        comp_cids = []
        for a, cid in node.aggs:
            v = shuffled.columns[cid]
            kind = {"count": "sum", "count_star": "sum", "sum": "sum",
                    "avg": "sum", "min": "min", "max": "max"}[a.kind]
            values2.append((v, kind, None))
            if f"__cnt_{cid}" in shuffled.columns:
                comp_cids.append(cid)
        for cid in comp_cids:
            values2.append((shuffled.columns[f"__cnt_{cid}"], "sum", None))
        gk2, res2, gvalid2, ngroups2 = self._segment_aggregate_maybe_packed(
            node, key_arrays2, key_meta, values2, shuffled.valid)
        gk2, res2, gvalid2 = self._slice_groups(node, gk2, res2, gvalid2,
                                                ngroups2)
        final = self._partial_block(node, key_meta, gk2,
                                    res2[:len(node.aggs)], gvalid2)
        for cid, cnt in zip(comp_cids, res2[len(node.aggs):]):
            final = Block(final.columns, final.valid,
                          {**final.nulls, cid: cnt == 0})
        return final

    def _exec_dense_aggregate(self, node: AggregateNode, blk: Block) -> Block:
        """Dense-grid aggregation: group keys with known small value ranges
        map to one slot id; aggregation is unsorted stacked segment
        reductions over [total_slots] and the cross-device combine is
        psum/pmin/pmax — no sort, no all_to_all.  This is the TPU-native
        replacement for the reference's worker hash-aggregate + coordinator
        combine on low-cardinality GROUP BYs (multi_logical_optimizer.c):
        static shapes, MXU/VPU-friendly, ICI collectives."""
        specs = node.dense_keys
        total = node.dense_total
        n = blk.valid.shape[0]

        # slot id per row (invalid rows → trash slot `total`)
        slot = jnp.zeros(n, dtype=jnp.int32)
        stride = 1
        strides = []
        for (g, _cid), (base, extent, has_null) in zip(node.group_keys,
                                                       specs):
            v, nmask = evaluate(g, _src(blk), jnp)
            v = jnp.broadcast_to(v, (n,))
            # subtract base in the key's own width FIRST — int64 keys with
            # values past int32 would wrap if narrowed before rebasing
            rebased = v - jnp.asarray(base, v.dtype)
            idx = jnp.clip(rebased, 0, extent - 1).astype(jnp.int32)
            nm = (jnp.broadcast_to(nmask, (n,)) if nmask is not None
                  else None)
            # a key outside the planned extent means the stats the grid
            # was planned from went stale — surface as dense_oob (→ the
            # host retries on the sort path) rather than silently
            # clipping into a group
            oob = (rebased < 0) | (rebased >= extent)
            if nm is not None:
                oob = oob & ~nm
            if nm is not None and not has_null:
                # runtime NULLs the planner didn't predict: force a retry
                # path instead of mis-grouping them
                oob = oob | nm
            self._dense_oob = self._dense_oob + \
                (oob & blk.valid).sum().astype(jnp.int64)
            if has_null and nm is not None:
                idx = jnp.where(nm, jnp.int32(extent), idx)
            slot = slot + idx * stride
            strides.append(stride)
            stride *= extent + (1 if has_null else 0)
        slot = jnp.where(blk.valid, slot, jnp.int32(total))

        # value inputs (value, kind, contrib_valid) — counts in int32
        # (int64 segment ops are emulated on TPU), widened after reduce
        values = self._agg_values(node, blk)
        rows_per_slot = self._dense_segment_sum(
            blk.valid.astype(jnp.int32)[:, None], slot, total)[:total, 0]

        # stacked reductions: one segment op per (reduction kind, dtype)
        results: list = [None] * len(values)
        companions: list = [None] * len(values)
        by_kind: dict[tuple, list[tuple[int, jnp.ndarray]]] = {}
        for i, (v, kind, vv) in enumerate(values):
            contrib = blk.valid if vv is None else (blk.valid & vv)
            if kind == "count":
                arr = contrib.astype(jnp.int32)
                by_kind.setdefault(("sum", jnp.int32), []).append((i, arr))
                continue
            if kind == "sum":
                z = jnp.zeros((), v.dtype)
                arr = jnp.where(contrib, v, z)
                by_kind.setdefault(("sum", v.dtype), []).append((i, arr))
            elif kind == "min":
                arr = jnp.where(contrib, v, _big(v.dtype))
                by_kind.setdefault(("min", v.dtype), []).append((i, arr))
            elif kind == "max":
                arr = jnp.where(contrib, v, _small(v.dtype))
                by_kind.setdefault(("max", v.dtype), []).append((i, arr))
            else:
                raise ExecutionError(f"bad agg kind {kind}")
            # companion: non-NULL contribution count (all-NULL group → NULL)
            comp = contrib.astype(jnp.int32)
            by_kind.setdefault(("companion", jnp.int32), []).append((i, comp))
        for (op, _dt), items in by_kind.items():
            data = jnp.stack([a for _, a in items], axis=1)
            if op in ("sum", "companion"):
                red = self._dense_segment_sum(data, slot, total)
            elif op == "min":
                red = jax.ops.segment_min(data, slot,
                                          num_segments=total + 1)
            else:
                red = jax.ops.segment_max(data, slot,
                                          num_segments=total + 1)
            red = red[:total]
            for j, (i, _a) in enumerate(items):
                if op == "companion":
                    companions[i] = red[:, j]
                else:
                    results[i] = red[:, j]

        results, companions, rows_per_slot, out_valid = \
            self._combine_grid(node, values, results, companions,
                               rows_per_slot)

        # reconstruct key columns from the slot grid
        iota = jnp.arange(total, dtype=jnp.int32)
        cols: dict[str, jnp.ndarray] = {}
        nulls: dict[str, jnp.ndarray] = {}
        for (g, cid), (base, extent, has_null), st in zip(
                node.group_keys, specs, strides):
            ext = extent + (1 if has_null else 0)
            idx = (iota // st) % ext
            cols[cid] = (idx.clip(0, extent - 1).astype(jnp.int64)
                         + base).astype(g.dtype.numpy_dtype)
            if has_null:
                nulls[cid] = idx == extent
        for i, ((a, cid), (v, kind, _vv)) in enumerate(
                zip(node.aggs, values)):
            r = results[i]
            if kind == "count":
                r = r.astype(jnp.int64)
            cols[cid] = r
            if companions[i] is not None:
                nulls[cid] = companions[i] == 0
        return Block(cols, out_valid, nulls)

    @staticmethod
    def _combine_grid(node: AggregateNode, values, results, companions,
                      rows_per_slot):
        """Cross-device combine shared by the flat and bucketed dense
        grids (repartition → psum/pmin/pmax over the slot grid, device
        0 emits; local → per-device slots).  One implementation so the
        two paths' NULL-companion and combine semantics cannot
        diverge.  Returns (results, companions, rows_per_slot,
        out_valid)."""
        if node.combine == "repartition":
            rows_per_slot = jax.lax.psum(rows_per_slot, SHARD_AXIS)
            for i, (_v, kind, _vv) in enumerate(values):
                if kind in ("count", "sum"):
                    results[i] = jax.lax.psum(results[i], SHARD_AXIS)
                elif kind == "min":
                    results[i] = jax.lax.pmin(results[i], SHARD_AXIS)
                else:
                    results[i] = jax.lax.pmax(results[i], SHARD_AXIS)
                if companions[i] is not None:
                    companions[i] = jax.lax.psum(companions[i],
                                                 SHARD_AXIS)
            out_valid = (rows_per_slot > 0) & \
                (jax.lax.axis_index(SHARD_AXIS) == 0)
        else:
            out_valid = rows_per_slot > 0
        return results, companions, rows_per_slot, out_valid

    def _exec_bucketed_aggregate(self, node: AggregateNode,
                                 blk: Block) -> Block | None:
        """Bucketed dense-grid aggregation (ops/groupby.py): the packed
        composite slot (the same key_ranges packing the sort path
        uses) radix-partitions into GROUP_TILE_SLOTS-wide dense tiles,
        each reduced sort-free — no argsort over the input capacity,
        no all_to_all combine (cross-device merge is psum/pmin/pmax
        over the slot grid, exactly like the flat dense grid).  Stale
        key ranges count into dense_oob and the host retries on the
        sort path; a hot bucket overflows its static per-bucket
        capacity and regrows through the normal retry."""
        from ..ops.groupby import bucketed_grid_aggregate
        from ..utils.faultinjection import fault_point

        # named seam: a failure while building the bucketed pack must
        # leave the plan cache without a half-built entry (fires at
        # trace time, like executor.plan_cache_fill)
        fault_point("executor.agg_bucket_fill")
        specs = node.bucket_keys
        total = node.bucket_total

        # packed slot per row — _pack_group_keys IS the slot layout
        # (width = extent + 1 per key, slot 0 = NULL, out-of-range
        # values clipped but COUNTED into dense_oob so stale statistics
        # recompile on the sort path instead of returning aliased
        # groups); sharing the helper keeps the grid bit-identical to
        # the sort path's packed keys on every null/oob edge case
        key_arrays, key_meta, values = self._agg_inputs(node, blk)
        packed, oob = self._pack_group_keys(node, key_arrays, key_meta,
                                            blk.valid, kr=specs)
        if packed is None:
            # defensive: bucket_keys is one spec per group key and
            # key_arrays/key_meta come from the same _agg_inputs walk,
            # so the helper's shape bail-outs are statically
            # unreachable today — this guard only matters if a future
            # _agg_inputs change breaks that invariant
            return None
        self._dense_oob = self._dense_oob + oob
        # valid rows pack to < total (clipped per key); the invalid-row
        # int64-max sentinel is dropped by the pack's valid mask anyway
        slot32 = jnp.clip(packed, 0, total - 1).astype(jnp.int32)

        # value inputs, masked exactly like the flat dense grid:
        # sums/counts zero under non-contribution, min/max at identity;
        # a companion contribution count per value aggregate drives the
        # all-NULL-group → NULL rule
        op_values: list[tuple[jnp.ndarray, str]] = []
        comp_idx: list[int | None] = []
        for v, kind, vv in values:
            contrib = blk.valid if vv is None else (blk.valid & vv)
            if kind == "count":
                op_values.append((contrib.astype(jnp.int32), "count"))
                comp_idx.append(None)
                continue
            if kind == "sum":
                arr = jnp.where(contrib, v, jnp.zeros((), v.dtype))
            elif kind == "min":
                arr = jnp.where(contrib, v, _big(v.dtype))
            elif kind == "max":
                arr = jnp.where(contrib, v, _small(v.dtype))
            else:
                raise ExecutionError(f"bad agg kind {kind}")
            op_values.append((arr, kind))
            comp_idx.append(len(op_values))
            op_values.append((contrib.astype(jnp.int32), "count"))

        cap = self.caps.agg_bucket[id(node)]
        kernel = ("pallas" if self.group_kernel == "bucketed_pallas"
                  else "xla")
        res, rows_per_slot, boverflow, bfill = bucketed_grid_aggregate(
            slot32, blk.valid, op_values, total, cap, kernel=kernel)
        self._overflow = self._overflow + boverflow
        self._record(id(node), "agg_bucket", bfill, cap)

        results = []
        companions = []
        for i, (_v, kind, _vv) in enumerate(values):
            pos = sum(1 for c in comp_idx[:i] if c is not None) + i
            results.append(res[pos])
            ci = comp_idx[i]
            companions.append(None if ci is None else res[ci])

        results, companions, rows_per_slot, out_valid = \
            self._combine_grid(node, values, results, companions,
                               rows_per_slot)
        # 'agg_grid', not 'agg_out': shrinking THIS buffer means
        # installing a real compaction pass over the slot grid, so
        # feedback must apply the ≥3× compaction economics — the sort
        # path's agg_out is a free slice and tightens at 0.85
        self._record(id(node), "agg_grid",
                     (rows_per_slot > 0).sum(), total)

        # reconstruct key columns from the packed slot (first key is
        # most significant; lane 0 of each key's width is NULL)
        iota = jnp.arange(total, dtype=jnp.int32)
        cols: dict[str, jnp.ndarray] = {}
        nulls: dict[str, jnp.ndarray] = {}
        stride = total
        for (base, extent, _hn), (g, cid) in zip(specs, node.group_keys):
            width = extent + 1
            stride //= width
            idx = (iota // stride) % width
            cols[cid] = ((idx - 1).clip(0, extent - 1).astype(jnp.int64)
                         + base).astype(g.dtype.numpy_dtype)
            nulls[cid] = idx == 0
        for i, ((_a, cid), (_v, kind, _vv)) in enumerate(
                zip(node.aggs, values)):
            r = results[i]
            if kind == "count":
                r = r.astype(jnp.int64)
            cols[cid] = r
            if companions[i] is not None:
                nulls[cid] = companions[i] == 0
        out = Block(cols, out_valid, nulls)

        # high-cardinality grids are mostly empty under selective
        # filters: compact live slots to the estimated group capacity
        # (underestimates overflow and regrow like every static buffer)
        k = self.caps.agg_out.get(id(node))
        if k is not None and k < total:
            out = self._compact(out, k)
        return out

    # one-hot MXU segment-sum eligibility bound: bench_kernels.py on
    # TPU v5e measured the matmul formulation 2-10× faster than XLA's
    # scatter-based segment_sum up to ~4096 slots, slower past ~8192
    # (a hand Pallas kernel of the same shape measured slower than both
    # — the measured justification for staying at the XLA level)
    DENSE_ONEHOT_MAX_SLOTS = 4096

    def _dense_segment_sum(self, data: jnp.ndarray, slot: jnp.ndarray,
                           total: int) -> jnp.ndarray:
        """Σ per slot of [n, m] data → [total+1, m].

        Routes to one-hot × data on the MXU when exactness allows:
        f32 sums accumulate in f32 either way, and int32 counts are
        exact in f32 while n < 2^24 (n is the static row capacity).
        int64 / f64 stacks stay on segment_sum (exact)."""
        n, _m = data.shape
        dt = data.dtype
        eligible = (total + 1 <= self.DENSE_ONEHOT_MAX_SLOTS
                    and (dt == jnp.float32
                         or (dt == jnp.int32 and n < (1 << 24))))
        if not eligible:
            return jax.ops.segment_sum(data, slot, num_segments=total + 1)
        onehot = (slot[:, None] == jnp.arange(
            total + 1, dtype=jnp.int32)[None, :]).astype(jnp.float32)
        red = jax.lax.dot_general(
            onehot, data.astype(jnp.float32),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return red.astype(dt) if dt == jnp.int32 else red

    def _slice_groups(self, node: AggregateNode, gk, res, gvalid, ngroups):
        """Slice front-packed group slots down to the planner's estimated
        capacity; groups beyond it count as overflow (→ retry, doubled)."""
        self._record(id(node), "agg_out", ngroups, gvalid.shape[0])
        agg_cap = self.caps.agg_out.get(id(node))
        if agg_cap is None or agg_cap >= gvalid.shape[0]:
            return gk, res, gvalid
        self._overflow = self._overflow + jnp.maximum(
            ngroups.astype(jnp.int64) - agg_cap, 0)
        return ([k[:agg_cap] for k in gk], [r[:agg_cap] for r in res],
                gvalid[:agg_cap])

    def _partial_block(self, node: AggregateNode, key_meta, gk, res,
                       gvalid) -> Block:
        cols, nulls = {}, {}
        i = 0
        for cid, has_null in key_meta:
            cols[cid] = gk[i]
            i += 1
            if has_null:
                nulls[cid] = gk[i].astype(jnp.bool_)
                i += 1
        for (a, cid), r in zip(node.aggs, res):
            cols[cid] = r
        return Block(cols, gvalid, nulls)


def _seg_last(boundary: jnp.ndarray, iota: jnp.ndarray) -> jnp.ndarray:
    """Per row: position of the LAST row of its segment (boundary marks
    segment STARTS) — reverse running-min over next-boundary positions."""
    n = iota.shape[0]
    nb = jnp.concatenate([boundary[1:], jnp.ones((1,), jnp.bool_)])
    return jnp.flip(jax.lax.cummin(
        jnp.flip(jnp.where(nb, iota, jnp.int32(n - 1)))))


def _src(blk: Block) -> ColumnSource:
    return ColumnSource(blk.columns, blk.nulls)


def _big(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)


def _small(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)
