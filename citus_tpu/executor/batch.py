"""Block: the device-side batch of rows (columns + row validity + nulls).

The tuple-at-a-time TupleTableSlot world of the reference
(executor/tuple_destination.c) collapses into one pytree of fixed-shape
arrays: a whole shard (or shuffle partition) processed as vectors.  Filters
never shrink arrays — they clear `valid` bits — so every shape stays static
under jit (the XLA contract, SURVEY §7 design stance).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclass
class Block:
    """columns: name → [N] array; valid: [N] row mask;
    nulls: name → [N] True-where-NULL (absent key = no nulls)."""

    columns: dict[str, jnp.ndarray]
    valid: jnp.ndarray
    nulls: dict[str, jnp.ndarray] = field(default_factory=dict)

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    def column(self, name: str) -> jnp.ndarray:
        return self.columns[name]

    def null_mask(self, name: str) -> jnp.ndarray:
        """[N] bool, True where value is NULL."""
        if name in self.nulls:
            return self.nulls[name]
        return jnp.zeros(self.valid.shape, dtype=jnp.bool_)

    def not_null(self, name: str) -> jnp.ndarray:
        return ~self.null_mask(name)

    def with_filter(self, mask: jnp.ndarray) -> "Block":
        return Block(self.columns, self.valid & mask, self.nulls)

    def select(self, names: list[str]) -> "Block":
        return Block({n: self.columns[n] for n in names}, self.valid,
                     {n: m for n, m in self.nulls.items() if n in names})

    def with_column(self, name: str, values: jnp.ndarray,
                    null_mask: jnp.ndarray | None = None) -> "Block":
        cols = dict(self.columns)
        cols[name] = values
        nulls = dict(self.nulls)
        if null_mask is not None:
            nulls[name] = null_mask
        else:
            nulls.pop(name, None)
        return Block(cols, self.valid, nulls)

    def row_count(self) -> jnp.ndarray:
        return self.valid.sum()


def block_from_numpy(values: dict[str, np.ndarray],
                     validity: dict[str, np.ndarray] | None = None,
                     capacity: int | None = None,
                     compute_dtype=None) -> Block:
    """Host arrays → padded device Block.

    Per-column validity from storage becomes `nulls`; rows beyond the real
    row count are padding (valid=False).  float64 storage downcasts to
    `compute_dtype` when given (the TPU f32 policy).
    """
    n = len(next(iter(values.values())))
    cap = capacity or n
    if cap < n:
        raise ValueError(f"capacity {cap} < rows {n}")
    cols = {}
    nulls = {}
    for name, arr in values.items():
        if compute_dtype is not None and arr.dtype == np.float64:
            arr = arr.astype(compute_dtype)
        pad = np.zeros(cap - n, dtype=arr.dtype)
        cols[name] = jnp.asarray(np.concatenate([arr, pad]))
        if validity and name in validity:
            v = np.asarray(validity[name], dtype=bool)
            if not v.all():
                nulls[name] = jnp.asarray(np.concatenate(
                    [~v, np.zeros(cap - n, dtype=bool)]))
    valid = jnp.asarray(np.concatenate(
        [np.ones(n, dtype=bool), np.zeros(cap - n, dtype=bool)]))
    return Block(cols, valid, nulls)


def block_to_numpy(block: Block) -> tuple[dict[str, np.ndarray], np.ndarray, dict[str, np.ndarray]]:
    """Device Block → host (columns, valid, nulls) as numpy."""
    cols = {n: np.asarray(a) for n, a in block.columns.items()}
    valid = np.asarray(block.valid)
    nulls = {n: np.asarray(a) for n, a in block.nulls.items()}
    return cols, valid, nulls


def compact_to_numpy(block: Block) -> tuple[dict[str, np.ndarray], dict[str, np.ndarray]]:
    """Gather only valid rows host-side (final result materialization)."""
    cols, valid, nulls = block_to_numpy(block)
    out = {n: a[valid] for n, a in cols.items()}
    out_nulls = {n: a[valid] for n, a in nulls.items()}
    return out, out_nulls
