"""Sort-based grouped aggregation with static output capacity.

The TPU-native replacement for the reference's two-level aggregation
(worker partial aggregate + coordinator combine,
/root/reference/src/backend/distributed/planner/multi_logical_optimizer.c:1419
MasterExtendedOpNode / WorkerExtendedOpNode): instead of a dynamic hash
table, rows are sorted by group key (XLA-friendly, deterministic) and
reduced over the sorted runs.  Output capacity == input capacity, so
there is NO overflow case: in the worst degenerate case every row is its
own group.  `group_valid` marks which output slots hold real groups.

Reduction strategy (the part that matters on TPU): `jax.ops.segment_*`
lowers to scatter-add/min/max, which the TPU executes element-at-a-time —
a 9M-row segment_sum measures >1 s on a v5e.  Because the rows are
SORTED by group, every reduction is over a contiguous run instead:

* sum / count — prefix-sum difference: `cumsum` once, subtract the values
  at each group's boundaries.  Float sums accumulate the prefix in
  float64 so the subtraction doesn't cancel (better accuracy than naive
  float32 accumulation, at linear cost).
* min / max — a segmented associative scan (value, boundary-flag) pairs
  that resets at group boundaries; the scan value at a group's last row
  is its reduction.
* group keys / first positions — one scatter-SET with provably unique
  indices (each group has exactly one boundary row), which the TPU
  handles vectorized, unlike combining scatters.

This same primitive serves: GROUP BY (partial + final), DISTINCT, and the
merge step after an all_to_all repartition.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

SUPPORTED_AGGS = ("sum", "count", "min", "max")


def _sort_order(keys: list[jnp.ndarray], valid: jnp.ndarray) -> jnp.ndarray:
    """Stable order: valid rows first, grouped by key columns."""
    invalid = (~valid).astype(jnp.int32)
    # lexsort: LAST key is primary
    return jnp.lexsort(tuple(reversed(keys)) + (invalid,)).astype(jnp.int32)


@dataclass(frozen=True)
class AggSpec:
    """One aggregate over one input array."""

    kind: str            # sum | count | min | max
    # count counts rows where contributing value is non-null (input_valid)


def _run_sum(x: jnp.ndarray, starts: jnp.ndarray, ends: jnp.ndarray,
             acc_dtype) -> jnp.ndarray:
    """Sum of each [starts[g], ends[g]) run via prefix-sum difference."""
    prefix = jnp.concatenate([jnp.zeros(1, acc_dtype),
                              jnp.cumsum(x.astype(acc_dtype))])
    return prefix[ends] - prefix[starts]


def _segmented_scan(x: jnp.ndarray, boundary: jnp.ndarray, op):
    """Inclusive segmented scan: resets at every boundary row.

    Hillis-Steele step-doubling inside ONE fori_loop body (log2(n)
    iterations of same-shape where/roll ops).  `lax.associative_scan`
    computes the same thing but UNROLLS its odd/even recursion into
    ~2·log2(n) concat/slice layers, which the TPU compiler cannot digest
    at engine scale — a 6M-row segmented max hangs XLA:TPU compilation
    for >5 minutes, while this loop compiles in seconds and runs at the
    same O(n log n) work."""
    n = x.shape[0]
    if n <= 1:
        return x
    idx = jnp.arange(n, dtype=jnp.int32)

    def body(i, carry):
        v, f = carry
        step = jnp.int32(1) << i
        pv = jnp.roll(v, step)
        pf = jnp.roll(f, step)
        has_prev = idx >= step
        nv = jnp.where(has_prev & ~f, op(v, pv), v)
        nf = jnp.where(has_prev, f | pf, f)
        return nv, nf

    n_steps = (n - 1).bit_length()
    v, _f = jax.lax.fori_loop(0, n_steps, body, (x, boundary))
    return v


def segment_aggregate(keys: list[jnp.ndarray],
                      values: list[tuple[jnp.ndarray, str, jnp.ndarray | None]],
                      valid: jnp.ndarray,
                      out_keys: list[jnp.ndarray] | None = None,
                      ) -> tuple[list[jnp.ndarray], list[jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Group rows by `keys` and reduce.

    Args:
      keys:   key columns, each [N].  With a packed composite key this
              is ONE int64 array (single-operand argsort — far faster
              on TPU than a multi-operand lexsort).
      values: (array [N], kind, value_valid [N] | None) per aggregate;
              value_valid masks per-column NULLs (count(col), sum skips null).
      valid:  row validity [N].
      out_keys: when set, group-key VALUES are extracted from these
              arrays (the original columns) while ordering/boundary
              detection runs on `keys` (the packed form — injective
              over in-range rows, so the groupings agree).

    Returns (group_keys, agg_results, group_valid, n_groups):
      group_keys:  each [N], key value of each group slot,
      agg_results: each [N],
      group_valid: [N] bool, slots < n_groups,
      n_groups:    scalar int32.
    """
    n = valid.shape[0]
    if out_keys is not None:
        # packed mode: the single int64 key already encodes invalid rows
        # as the int64-max sentinel, so this is a TRUE single-operand
        # argsort (adding the validity operand back would re-create the
        # two-operand lexsort the packing exists to avoid)
        order = jnp.argsort(keys[0], stable=True).astype(jnp.int32)
    else:
        order = _sort_order(keys, valid)
    keys_s = [k[order] for k in keys]
    valid_s = valid[order]

    # boundary: first row of each (valid) group
    def _shift_ne(a):
        return jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                a[1:] != a[:-1]])

    diff = jnp.zeros(n, dtype=jnp.bool_)
    for k in keys_s:
        diff = diff | _shift_ne(k)
    boundary = diff & valid_s
    seg_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    n_groups = boundary.sum().astype(jnp.int32)
    # invalid rows (sorted last) land in the last group's run with
    # identity contributions; the clip only guards the all-invalid case
    seg_id = jnp.clip(seg_id, 0, None)

    # group g's run is [starts[g], ends[g]) in sorted space.  One
    # boundary per group ⇒ the scatter indices are unique ⇒ scatter-set
    # (no combining — fast on TPU, unlike scatter-add/min)
    gpos = jnp.full(n + 1, n, jnp.int32).at[
        jnp.where(boundary, seg_id, n + 1)].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    starts = gpos[:n]
    ends = gpos[1:]  # last real group runs to n (trailing invalid rows
    #                  carry identity contributions, as before)

    group_keys = []
    first_c = jnp.minimum(starts, n - 1)
    if out_keys is None:
        group_keys = [k[first_c] for k in keys_s]
    else:
        first_idx = order[first_c]
        group_keys = [k[first_idx] for k in out_keys]

    results = []
    for arr, kind, value_valid in values:
        arr_s = arr[order]
        contrib_valid = valid_s if value_valid is None else (
            valid_s & value_valid[order])
        if kind == "count":
            res = _run_sum(contrib_valid.astype(jnp.int32), starts, ends,
                           jnp.int32).astype(jnp.int64)
        elif kind == "sum":
            z = jnp.zeros((), dtype=arr_s.dtype)
            x = jnp.where(contrib_valid, arr_s, z)
            acc = (jnp.float64 if jnp.issubdtype(arr_s.dtype, jnp.floating)
                   else jnp.int64)
            res = _run_sum(x, starts, ends, acc).astype(arr_s.dtype)
        elif kind in ("min", "max"):
            ident = _identity_for(arr_s.dtype, kind)
            x = jnp.where(contrib_valid, arr_s, ident)
            op = jnp.minimum if kind == "min" else jnp.maximum
            sv = _segmented_scan(x, boundary, op)
            res = sv[jnp.clip(ends - 1, 0, n - 1)]
        else:
            raise ValueError(f"unsupported aggregate kind {kind!r}")
        results.append(res)

    group_valid = jnp.arange(n) < n_groups
    group_keys = [jnp.where(group_valid, k,
                            jnp.zeros((), dtype=k.dtype)) for k in group_keys]
    results = [jnp.where(group_valid, r, jnp.zeros((), dtype=r.dtype))
               for r in results]
    return group_keys, results, group_valid, n_groups


def _identity_for(dtype, kind: str):
    if jnp.issubdtype(dtype, jnp.floating):
        inf = jnp.asarray(jnp.inf, dtype=dtype)
        return inf if kind == "min" else -inf
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if kind == "min" else info.min, dtype=dtype)


def distinct(keys: list[jnp.ndarray], valid: jnp.ndarray):
    """DISTINCT = grouping with no aggregates."""
    gk, _, gv, n = segment_aggregate(keys, [], valid)
    return gk, gv, n
