"""Sort-based grouped aggregation with static output capacity.

The TPU-native replacement for the reference's two-level aggregation
(worker partial aggregate + coordinator combine,
/root/reference/src/backend/distributed/planner/multi_logical_optimizer.c:1419
MasterExtendedOpNode / WorkerExtendedOpNode): instead of a dynamic hash
table, rows are sorted by group key (XLA-friendly, deterministic) and
reduced with segment operations.  Output capacity == input capacity, so
there is NO overflow case: in the worst degenerate case every row is its own
group.  `group_valid` marks which output slots hold real groups.

This same primitive serves: GROUP BY (partial + final), DISTINCT, and the
merge step after an all_to_all repartition.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

SUPPORTED_AGGS = ("sum", "count", "min", "max")


def _sort_order(keys: list[jnp.ndarray], valid: jnp.ndarray) -> jnp.ndarray:
    """Stable order: valid rows first, grouped by key columns."""
    invalid = (~valid).astype(jnp.int32)
    # lexsort: LAST key is primary
    return jnp.lexsort(tuple(reversed(keys)) + (invalid,))


@dataclass(frozen=True)
class AggSpec:
    """One aggregate over one input array."""

    kind: str            # sum | count | min | max
    # count counts rows where contributing value is non-null (input_valid)


def segment_aggregate(keys: list[jnp.ndarray],
                      values: list[tuple[jnp.ndarray, str, jnp.ndarray | None]],
                      valid: jnp.ndarray,
                      ) -> tuple[list[jnp.ndarray], list[jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Group rows by `keys` and reduce.

    Args:
      keys:   key columns, each [N].
      values: (array [N], kind, value_valid [N] | None) per aggregate;
              value_valid masks per-column NULLs (count(col), sum skips null).
      valid:  row validity [N].

    Returns (group_keys, agg_results, group_valid, n_groups):
      group_keys:  each [N], key value of each group slot,
      agg_results: each [N],
      group_valid: [N] bool, slots < n_groups,
      n_groups:    scalar int32.
    """
    n = valid.shape[0]
    order = _sort_order(keys, valid)
    keys_s = [k[order] for k in keys]
    valid_s = valid[order]

    # boundary: first row of each (valid) group
    def _shift_ne(a):
        return jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                a[1:] != a[:-1]])

    diff = jnp.zeros(n, dtype=jnp.bool_)
    for k in keys_s:
        diff = diff | _shift_ne(k)
    boundary = diff & valid_s
    seg_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    n_groups = boundary.sum().astype(jnp.int32)
    # invalid rows (sorted last) land in the last group's segment with
    # identity contributions; the clip only guards the all-invalid case
    # (seg_id would be -1 everywhere)
    seg_id = jnp.clip(seg_id, 0, None)

    group_keys = []
    first_idx = jax.ops.segment_min(jnp.arange(n), seg_id, num_segments=n)
    first_idx = jnp.clip(first_idx, 0, n - 1)
    for k in keys_s:
        group_keys.append(k[first_idx])

    results = []
    for arr, kind, value_valid in values:
        arr_s = arr[order]
        contrib_valid = valid_s if value_valid is None else (
            valid_s & value_valid[order])
        if kind == "count":
            res = jax.ops.segment_sum(contrib_valid.astype(jnp.int64),
                                      seg_id, num_segments=n)
        elif kind == "sum":
            z = jnp.zeros((), dtype=arr_s.dtype)
            res = jax.ops.segment_sum(jnp.where(contrib_valid, arr_s, z),
                                      seg_id, num_segments=n)
        elif kind == "min":
            big = _identity_for(arr_s.dtype, "min")
            res = jax.ops.segment_min(jnp.where(contrib_valid, arr_s, big),
                                      seg_id, num_segments=n)
        elif kind == "max":
            small = _identity_for(arr_s.dtype, "max")
            res = jax.ops.segment_max(jnp.where(contrib_valid, arr_s, small),
                                      seg_id, num_segments=n)
        else:
            raise ValueError(f"unsupported aggregate kind {kind!r}")
        results.append(res)

    group_valid = jnp.arange(n) < n_groups
    group_keys = [jnp.where(group_valid, k,
                            jnp.zeros((), dtype=k.dtype)) for k in group_keys]
    return group_keys, results, group_valid, n_groups


def _identity_for(dtype, kind: str):
    if jnp.issubdtype(dtype, jnp.floating):
        inf = jnp.asarray(jnp.inf, dtype=dtype)
        return inf if kind == "min" else -inf
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if kind == "min" else info.min, dtype=dtype)


def distinct(keys: list[jnp.ndarray], valid: jnp.ndarray):
    """DISTINCT = grouping with no aggregates."""
    gk, _, gv, n = segment_aggregate(keys, [], valid)
    return gk, gv, n
