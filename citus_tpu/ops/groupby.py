"""Bucketed dense-grid aggregation for high-cardinality GROUP BY.

The sort-path aggregation (ops/aggregate.segment_aggregate) pays a
stable argsort over the input capacity per execution — O(n log n) and
sort-bound on TPU (PERF_NOTES: ~30% of warm Q3 is the 1.5M-row group
sort).  The dense-grid path (executor/compiler._exec_dense_aggregate)
is sort-free but capped at DENSE_GROUP_LIMIT slots: the one-hot MXU
matmul it rides was measured 2-10x faster than segment_sum only while
the slot space stays <= ~4096 wide.

This module removes the cap the radix-partition way (Theseus, arXiv
2508.05029; the GPU hash-aggregation pipeline, arXiv 2606.24647; the
aggregation twin of ops.join.bucketed_unique_lookup):

  1. rows carry a PACKED dense slot id (the planner's `key_ranges`
     machinery — every group key's value range statically known, one
     int64 slot per composite key, null slot reserved per key),
  2. rows partition by slot high bits (`hashing.tile_buckets`) through
     the same counting-sort pack the repartition shuffle uses
     (`partition.pack_by_target`) into `[n_buckets, bucket_cap]`
     buffers — value-range partitioning over an already-dense slot
     space needs no avalanche mixing,
  3. each bucket reduces over its <= GROUP_TILE_SLOTS-wide dense tile:
     sums/counts through the measured-fastest one-hot `dot_general`
     formulation (batched over buckets; a Pallas variant is A/B'd by
     `bench_kernels.py groupby` exactly like the probe kernel),
     min/max through per-tile scatter (segment) reductions — tiles are
     small and bucket-major packing makes the scatters local,
  4. the [total]-slot grid emits exactly like the dense grid today:
     group keys reconstruct from the slot id, `rows_per_slot > 0`
     marks live groups.

Static shapes throughout: a hot bucket overflows its per-bucket
capacity and the host regrows + retries (`Capacities.agg_bucket`, the
same count-then-emit protocol every static buffer uses); realized max
fill feeds capacity feedback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# slots per bucket tile: the dense-grid one-hot matmul's measured win
# region tops out at ~4096 slots (PERF_NOTES segment-aggregation table:
# 2-10x faster than segment_sum at k <= 4096, slower past 8192), so
# each bucket reduces over exactly one fast-path-sized tile
GROUP_TILE_SLOTS = 4096

# packed-slot-space ceiling for the bucketed grid: the [total] result
# grid (and its psum combine) must stay HBM-reasonable — 2^24 slots is
# 128 MB per int64 aggregate column, comparable to the sort path's
# input-sized outputs under the occupancy gate below
GROUP_BUCKET_MAX_SLOTS = 1 << 24


def group_bucket_count(total: int) -> int:
    """Number of dense tiles covering [0, total)."""
    return max(1, -(-total // GROUP_TILE_SLOTS))


def group_bucket_eligible(total: int, rows: int) -> bool:
    """Planner cost threshold for the bucketed grid: the packed slot
    space must be small enough to materialize as a result grid AND the
    input dense enough to amortize reducing every tile (a sparse
    group-by over a huge key space would stream mostly-empty tiles —
    the sort path stays cheaper there).  Mirrors the shape of
    ops.join.probe_bucket_eligible."""
    return total <= GROUP_BUCKET_MAX_SLOTS and rows * 4 >= total


def _onehot_bucket_sums(loc2d: jnp.ndarray, stack: jnp.ndarray,
                        tile: int) -> jnp.ndarray:
    """Batched one-hot x values matmul: [nb, cap] local slots and
    [nb, cap, A] values -> [nb, tile, A] per-tile sums.  Garbage lanes
    carry zeroed values (pack_by_target zeroes them), so their slot-0
    contribution is exactly zero — no mask operand needed.  XLA fuses
    the one-hot construction into the contraction loop on TPU (the
    measured formulation behind DENSE_ONEHOT_MAX_SLOTS)."""
    ids = jnp.arange(tile, dtype=jnp.int32)
    onehot = (loc2d[:, :, None] == ids[None, None, :]).astype(jnp.float32)
    return jax.lax.dot_general(
        onehot, stack.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)


def _onehot_ok(n_buckets: int, bucket_cap: int, tile: int) -> bool:
    """XLA:CPU materializes the one-hot operand before the batched dot
    (no fusion into the Eigen contraction), so past a size bound the
    formulation would allocate n_buckets*cap*tile floats; route those
    shapes through segment_sum instead (same results).  TPU fuses —
    the bound only bites the CPU test/bench mesh."""
    if jax.default_backend() != "cpu":
        return True
    return n_buckets * bucket_cap * tile <= (1 << 24)


def bucketed_grid_aggregate(slot: jnp.ndarray, valid: jnp.ndarray,
                            values: list[tuple[jnp.ndarray, str]],
                            total: int, bucket_cap: int,
                            kernel: str = "xla",
                            interpret: bool = False):
    """Aggregate rows onto a [total]-slot dense grid, bucket-tiled.

    Args:
      slot:   [n] int32 dense packed slot per row, in [0, total) for
              valid rows (callers clip; out-of-range accounting happens
              upstream via the dense_oob protocol).
      valid:  [n] bool — rows to aggregate; invalid rows are dropped by
              the pack.
      values: (array [n], kind) per aggregate, kind in sum|count|min|max.
              sum/count arrays must hold 0 on non-contributing rows and
              min/max arrays the reduction identity (the caller owns
              NULL masking, exactly as with the flat dense grid).
      total:  static slot-space size.
      bucket_cap: static per-bucket row slots; a hot bucket overflows
              and the host regrows + retries.
      kernel: 'xla' (batched take-free one-hot dot_general) or 'pallas'
              (ops.pallas_kernels.bucketed_groupby_sums_pallas for the
              f32/int32 sum stacks; min/max and wide dtypes stay on the
              XLA segment ops either way, mirroring the probe kernel's
              split).  Degrades to 'xla' where pallas cannot compile.

    Returns (results, rows_per_slot, overflow, bucket_max_fill):
      results:       [total] array per input value, same order,
      rows_per_slot: [total] int32 — valid input rows per slot,
      overflow:      int64 — rows dropped by full buckets (host retries
                     with grown capacity; results are incomplete),
      bucket_max_fill: int64 — realized max bucket fill (feedback).
    """
    from .hashing import tile_buckets
    from .partition import pack_by_target

    tile = GROUP_TILE_SLOTS
    n_buckets = group_bucket_count(total)
    ext_pad = n_buckets * tile

    bucket, local = tile_buckets(slot, tile)
    cols = {f"v{i}": arr for i, (arr, _kind) in enumerate(values)}
    cols["local"] = local
    packed, pvalid, overflow = pack_by_target(cols, valid, bucket,
                                              n_buckets, bucket_cap)
    bucket_max_fill = pvalid.sum(axis=1).max().astype(jnp.int64)
    loc2d = packed["local"]  # garbage lanes: slot 0, values zeroed
    # flat slots for the scatter-based reductions: garbage lanes park at
    # the trash slot ext_pad so the pack's ZEROED garbage values can
    # never masquerade as a min/max contribution
    biota = jnp.arange(n_buckets, dtype=jnp.int32)[:, None]
    flat_slot = jnp.where(pvalid, biota * tile + loc2d,
                          ext_pad).reshape(-1)

    if kernel == "pallas" and not interpret:
        from .pallas_kernels import pallas_available

        if not pallas_available() or jax.default_backend() == "cpu":
            # same degrade rule as bucketed_unique_lookup: a config that
            # asks for the kernel where it cannot compile falls back to
            # the XLA formulation (identical results) instead of
            # crashing mid-compile
            kernel = "xla"

    def _sums(colkeys: list[str], out_dtype):
        """Per-tile sums of same-dtype packed stacks [nb, cap] each."""
        stack = jnp.stack([packed[ck] for ck in colkeys], axis=2)
        if kernel == "pallas":
            from .pallas_kernels import bucketed_groupby_sums_pallas

            red = bucketed_groupby_sums_pallas(
                loc2d, stack.astype(jnp.float32), tile,
                interpret=interpret)
        elif _onehot_ok(n_buckets, bucket_cap, tile):
            red = _onehot_bucket_sums(loc2d, stack, tile)
        else:
            flat = stack.reshape(n_buckets * bucket_cap, len(colkeys))
            return jax.ops.segment_sum(
                flat, flat_slot,
                num_segments=ext_pad + 1)[:ext_pad].astype(out_dtype)
        return red.reshape(ext_pad, len(colkeys)).astype(out_dtype)

    # ROWS marks the rows_per_slot lane: pvalid IS the packed all-ones
    # int32 column (the pack zeroes garbage lanes), so it rides the
    # int32 sum stack for free instead of paying a second one-hot pass
    ROWS = "rows"
    packed[ROWS] = pvalid.astype(jnp.int32)
    results: list = [None] * len(values)
    rows_per_slot = None
    by_kind: dict[tuple, list[tuple[object, str]]] = {}
    for i, (arr, kind) in enumerate(values):
        if kind == "count":
            # 0/1 contributions: exact through the f32 matmul while a
            # bucket holds < 2^24 rows (partial sums stay ≤ bucket_cap)
            by_kind.setdefault(("matsum", jnp.int32), []) \
                .append((i, f"v{i}"))
        elif kind == "sum":
            # f32 sums accumulate in f32 either way; every integer sum
            # stays on the exact segment path — f32 accumulation loses
            # bits once VALUES (not just row counts) pass 2^24, a bound
            # no cheap static check can guarantee for data columns.
            key = (("matsum", arr.dtype) if arr.dtype == jnp.float32
                   else ("segsum", arr.dtype))
            by_kind.setdefault(key, []).append((i, f"v{i}"))
        elif kind in ("min", "max"):
            by_kind.setdefault((kind, arr.dtype), []).append((i, f"v{i}"))
        else:
            raise ValueError(f"unsupported aggregate kind {kind!r}")
    by_kind.setdefault(("matsum", jnp.int32), []).append((ROWS, ROWS))

    for (op, dt), items in by_kind.items():
        if op == "matsum" and bucket_cap >= (1 << 24):
            op = "segsum"  # counts past f32 exactness: exact scatter
        colkeys = [ck for _slot, ck in items]
        if op == "matsum":
            red = _sums(colkeys, dt)
        else:
            seg = (jax.ops.segment_min if op == "min"
                   else jax.ops.segment_max if op == "max"
                   else jax.ops.segment_sum)
            flat = jnp.stack(
                [packed[ck] for ck in colkeys],
                axis=2).reshape(n_buckets * bucket_cap, len(colkeys))
            red = seg(flat, flat_slot, num_segments=ext_pad + 1)[:ext_pad]
        for j, (slot_i, _ck) in enumerate(items):
            if slot_i is ROWS:
                rows_per_slot = red[:total, j].astype(jnp.int32)
            else:
                results[slot_i] = red[:total, j]

    return results, rows_per_slot, overflow.astype(jnp.int64), \
        bucket_max_fill
