"""Pallas TPU kernels for the aggregation hot path.

BASELINE.json's north star calls for hand kernels on the hot ops (the
reference's equivalents are C inner loops: per-tuple hash-aggregate
transition functions reached from the plans in
planner/multi_logical_optimizer.c).  The XLA formulation used by
ops/aggregate.py covers most shapes well; the one place XLA lowers badly
on TPU is `jax.ops.segment_sum` with mid-sized segment counts — it emits
a serialized scatter-add.  This kernel replaces it with the MXU-friendly
formulation: one-hot × values matmuls accumulated in VMEM scratch across
a sequential row-tile grid.

    sums[k, a] = Σ_{i: slot[i]=k} values[i, a]

The grid walks row tiles; a [K, A] f32 scratch lives in VMEM for the
whole pass (TPU grid steps run sequentially on one core, so scratch
accumulation is safe); each step builds an f32 one-hot tile chunked over
K and feeds the MXU with f32 accumulation (one-hot entries are exact in
any float dtype; values stay f32 so sums match the XLA path).

Whether this beats the XLA segment ops on real hardware is measured by
bench_kernels.py; the executor only routes through it when
`enable_pallas_aggregate` is on and the measurement said yes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # Pallas TPU lowering may be unavailable on exotic backends
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False

ROW_TILE = 1024       # rows per grid step
K_CHUNK = 512         # one-hot width per MXU feed


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def pallas_available() -> bool:
    return _PALLAS_OK


if _PALLAS_OK:

    def _kernel(slot_ref, val_ref, out_ref, acc_ref, *, n_chunks: int):
        """One grid step: accumulate this row tile into [K, A] scratch."""
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        slots = slot_ref[:]                       # [T, 1] int32
        vals = val_ref[:]                         # [T, A] f32
        for c in range(n_chunks):
            base = c * K_CHUNK
            ids = jax.lax.broadcasted_iota(
                jnp.int32, (ROW_TILE, K_CHUNK), 1) + base
            onehot = (slots == ids).astype(jnp.float32)   # [T,1]→[T,Kc]
            part = jax.lax.dot_general(
                onehot, vals,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [Kc, A]
            sl = pl.ds(base, K_CHUNK)
            acc_ref[sl, :] = acc_ref[sl, :] + part

        @pl.when(step == pl.num_programs(0) - 1)
        def _flush():
            out_ref[:] = acc_ref[:]

    @functools.partial(jax.jit, static_argnames=("total", "interpret"))
    def dense_grid_aggregate_pallas(slot: jnp.ndarray,
                                    values: jnp.ndarray, total: int,
                                    interpret: bool = False
                                    ) -> jnp.ndarray:
        """MXU segment-sum: slot [N] int32 (== total ⇒ ignored row),
        values [N, A] float32 → sums [total, A] float32."""
        n = slot.shape[0]
        a = values.shape[1]
        n_pad = _round_up(max(n, ROW_TILE), ROW_TILE)
        k_pad = _round_up(total + 1, K_CHUNK)  # +1 keeps a trash slot
        a_pad = _round_up(a, 128)
        grid = n_pad // ROW_TILE
        # slots as [N, 1]: a block whose LAST dim equals the whole array
        # dim satisfies the TPU tiling rule, and [T, 1] == [T, Kc]
        # broadcasts without any in-kernel reshape (Mosaic rejects
        # (8,128)→(1024,1) shape casts)
        slot_p = jnp.full((n_pad, 1), k_pad - 1, jnp.int32).at[:n, 0].set(
            jnp.where(slot >= total, k_pad - 1, slot))
        vals_p = jnp.zeros((n_pad, a_pad), jnp.float32) \
            .at[:n, :a].set(values.astype(jnp.float32))

        kernel = functools.partial(_kernel, n_chunks=k_pad // K_CHUNK)
        out = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((ROW_TILE, 1), lambda i: (i, 0)),
                pl.BlockSpec((ROW_TILE, a_pad), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((k_pad, a_pad), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((k_pad, a_pad), jnp.float32),
            scratch_shapes=[pltpu.VMEM((k_pad, a_pad), jnp.float32)],
            interpret=interpret,
        )(slot_p, vals_p)
        return out[:total, :a]


def segment_sum_reference(slot: np.ndarray, values: np.ndarray,
                          total: int) -> np.ndarray:
    """numpy oracle for tests."""
    out = np.zeros((total, values.shape[1]), np.float32)
    keep = slot < total
    np.add.at(out, slot[keep], values[keep].astype(np.float32))
    return out
