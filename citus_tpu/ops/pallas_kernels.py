"""Pallas TPU kernels for the aggregation and join-probe hot paths.

BASELINE.json's north star calls for hand kernels on the hot ops (the
reference's equivalents are C inner loops: per-tuple hash-aggregate
transition functions reached from the plans in
planner/multi_logical_optimizer.c).  The XLA formulation used by
ops/aggregate.py covers most shapes well; the one place XLA lowers badly
on TPU is `jax.ops.segment_sum` with mid-sized segment counts — it emits
a serialized scatter-add.  This kernel replaces it with the MXU-friendly
formulation: one-hot × values matmuls accumulated in VMEM scratch across
a sequential row-tile grid.

    sums[k, a] = Σ_{i: slot[i]=k} values[i, a]

The grid walks row tiles; a [K, A] f32 scratch lives in VMEM for the
whole pass (TPU grid steps run sequentially on one core, so scratch
accumulation is safe); each step builds an f32 one-hot tile chunked over
K and feeds the MXU with f32 accumulation (one-hot entries are exact in
any float dtype; values stay f32 so sums match the XLA path).

Whether this beats the XLA segment ops on real hardware is measured by
bench_kernels.py; the executor only routes through it when
`enable_pallas_aggregate` is on and the measurement said yes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # Pallas TPU lowering may be unavailable on exotic backends
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False

ROW_TILE = 1024       # rows per grid step
K_CHUNK = 512         # one-hot width per MXU feed
PROBE_CHUNK = 512     # probe rows streamed per step through one tile
BITS_CHUNK = 128      # packed bytes per bit-unpack step (→ 1024 lanes)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def pallas_available() -> bool:
    return _PALLAS_OK


if _PALLAS_OK:

    def _kernel(slot_ref, val_ref, out_ref, acc_ref, *, n_chunks: int):
        """One grid step: accumulate this row tile into [K, A] scratch."""
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        slots = slot_ref[:]                       # [T, 1] int32
        vals = val_ref[:]                         # [T, A] f32
        for c in range(n_chunks):
            base = c * K_CHUNK
            ids = jax.lax.broadcasted_iota(
                jnp.int32, (ROW_TILE, K_CHUNK), 1) + base
            onehot = (slots == ids).astype(jnp.float32)   # [T,1]→[T,Kc]
            part = jax.lax.dot_general(
                onehot, vals,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [Kc, A]
            sl = pl.ds(base, K_CHUNK)
            acc_ref[sl, :] = acc_ref[sl, :] + part

        @pl.when(step == pl.num_programs(0) - 1)
        def _flush():
            out_ref[:] = acc_ref[:]

    @functools.partial(jax.jit, static_argnames=("total", "interpret"))
    def dense_grid_aggregate_pallas(slot: jnp.ndarray,
                                    values: jnp.ndarray, total: int,
                                    interpret: bool = False
                                    ) -> jnp.ndarray:
        """MXU segment-sum: slot [N] int32 (== total ⇒ ignored row),
        values [N, A] float32 → sums [total, A] float32."""
        n = slot.shape[0]
        a = values.shape[1]
        n_pad = _round_up(max(n, ROW_TILE), ROW_TILE)
        k_pad = _round_up(total + 1, K_CHUNK)  # +1 keeps a trash slot
        a_pad = _round_up(a, 128)
        grid = n_pad // ROW_TILE
        # slots as [N, 1]: a block whose LAST dim equals the whole array
        # dim satisfies the TPU tiling rule, and [T, 1] == [T, Kc]
        # broadcasts without any in-kernel reshape (Mosaic rejects
        # (8,128)→(1024,1) shape casts)
        slot_p = jnp.full((n_pad, 1), k_pad - 1, jnp.int32).at[:n, 0].set(
            jnp.where(slot >= total, k_pad - 1, slot))
        vals_p = jnp.zeros((n_pad, a_pad), jnp.float32) \
            .at[:n, :a].set(values.astype(jnp.float32))

        kernel = functools.partial(_kernel, n_chunks=k_pad // K_CHUNK)
        out = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((ROW_TILE, 1), lambda i: (i, 0)),
                pl.BlockSpec((ROW_TILE, a_pad), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((k_pad, a_pad), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((k_pad, a_pad), jnp.float32),
            scratch_shapes=[pltpu.VMEM((k_pad, a_pad), jnp.float32)],
            interpret=interpret,
        )(slot_p, vals_p)
        return out[:total, :a]


if _PALLAS_OK:

    def _probe_kernel(tile_ref, loc_ref, out_ref):
        """One grid step: gather PROBE_CHUNK probes against the resident
        directory tile.  The tile block's index map ignores the chunk
        grid dimension, so Pallas keeps it in VMEM across all of a
        bucket's probe chunks — the directory streams HBM→VMEM exactly
        once while probe chunks pipeline through it."""
        out_ref[:] = jnp.take_along_axis(tile_ref[:], loc_ref[:], axis=1)

    @functools.partial(jax.jit, static_argnames=("interpret",))
    def bucketed_probe_pallas(dir2d: jnp.ndarray, loc2d: jnp.ndarray,
                              interpret: bool = False) -> jnp.ndarray:
        """VMEM-tiled directory probe for the bucketed join path.

        dir2d [n_buckets, tile] int32 — directory values per bucket tile
        (tile is VMEM-sized, ops.join.PROBE_TILE_SLOTS by default);
        loc2d [n_buckets, cap] int32 — tile-local probe slots, packed by
        bucket (garbage lanes must hold a clipped in-range slot).
        Returns [n_buckets, cap] int32 gathered directory values.

        Grid = (bucket, probe chunk); the in-kernel gather is a 2D
        lane-dimension take_along_axis, the shape Mosaic lowers as a
        vector dynamic-gather.  Whether this beats the plain-XLA batched
        gather on real hardware is bench_kernels.bench_probe()'s call —
        the executor routes through XLA unless the measurement says
        otherwise (same contract as the aggregation kernel above)."""
        k, tile = dir2d.shape
        _, cap = loc2d.shape
        cap_pad = _round_up(max(cap, PROBE_CHUNK), PROBE_CHUNK)
        if cap_pad != cap:
            loc2d = jnp.zeros((k, cap_pad), jnp.int32).at[:, :cap].set(
                loc2d)
        out = pl.pallas_call(
            _probe_kernel,
            grid=(k, cap_pad // PROBE_CHUNK),
            in_specs=[
                pl.BlockSpec((1, tile), lambda i, j: (i, 0)),
                pl.BlockSpec((1, PROBE_CHUNK), lambda i, j: (i, j)),
            ],
            out_specs=pl.BlockSpec((1, PROBE_CHUNK), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((k, cap_pad), jnp.int32),
            interpret=interpret,
        )(dir2d, loc2d)
        return out[:, :cap]


if _PALLAS_OK:

    def _groupby_kernel(slot_ref, val_ref, out_ref, acc_ref, *,
                        n_chunks: int):
        """One grid step: accumulate ROW_TILE packed rows of bucket b
        into that bucket's [tile, A] VMEM scratch.  The grid is
        (bucket, row chunk) with the row dimension fastest, so each
        bucket's chunks run back-to-back and the scratch accumulation
        is safe (TPU grid steps are sequential on one core)."""
        r = pl.program_id(1)

        @pl.when(r == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        slots = slot_ref[:]                       # [T, 1] int32
        vals = val_ref[:]                         # [T, A] f32
        for c in range(n_chunks):
            base = c * K_CHUNK
            ids = jax.lax.broadcasted_iota(
                jnp.int32, (ROW_TILE, K_CHUNK), 1) + base
            onehot = (slots == ids).astype(jnp.float32)
            part = jax.lax.dot_general(
                onehot, vals,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [Kc, A]
            sl = pl.ds(base, K_CHUNK)
            acc_ref[sl, :] = acc_ref[sl, :] + part

        @pl.when(r == pl.num_programs(1) - 1)
        def _flush():
            out_ref[:] = acc_ref[:]

    @functools.partial(jax.jit, static_argnames=("tile", "interpret"))
    def bucketed_groupby_sums_pallas(loc2d: jnp.ndarray,
                                     stack: jnp.ndarray, tile: int,
                                     interpret: bool = False
                                     ) -> jnp.ndarray:
        """Bucket-tiled MXU segment-sum for the bucketed group-by path.

        loc2d [n_buckets, cap] int32 — tile-local slots packed by
        bucket (garbage lanes hold slot 0 with ZEROED values, as
        pack_by_target emits them, so they contribute exact zeros);
        stack [n_buckets, cap, A] f32 — value columns, same packing.
        Returns [n_buckets, tile, A] f32 per-tile sums.

        The same one-hot-matmul-in-VMEM-scratch algorithm as
        dense_grid_aggregate_pallas, batched over buckets: grid =
        (bucket, row chunk), scratch [tile, A] lives across a bucket's
        row chunks.  Whether this beats the batched-XLA one-hot
        dot_general on real hardware is bench_kernels.py groupby's
        call — the executor routes through XLA unless the measurement
        (group_by_kernel config var) says otherwise."""
        nb, cap = loc2d.shape
        a = stack.shape[2]
        cap_pad = _round_up(max(cap, ROW_TILE), ROW_TILE)
        k_pad = _round_up(tile, K_CHUNK)
        a_pad = _round_up(a, 128)
        row_steps = cap_pad // ROW_TILE

        slot_flat = jnp.zeros((nb * cap_pad, 1), jnp.int32)
        slot_flat = slot_flat.reshape(nb, cap_pad, 1).at[:, :cap, 0].set(
            loc2d).reshape(nb * cap_pad, 1)
        val_flat = jnp.zeros((nb * cap_pad, a_pad), jnp.float32) \
            .reshape(nb, cap_pad, a_pad).at[:, :cap, :a].set(
            stack.astype(jnp.float32)).reshape(nb * cap_pad, a_pad)

        kernel = functools.partial(_groupby_kernel,
                                   n_chunks=k_pad // K_CHUNK)
        out = pl.pallas_call(
            kernel,
            grid=(nb, row_steps),
            in_specs=[
                pl.BlockSpec((ROW_TILE, 1),
                             lambda b, r: (b * row_steps + r, 0)),
                pl.BlockSpec((ROW_TILE, a_pad),
                             lambda b, r: (b * row_steps + r, 0)),
            ],
            out_specs=pl.BlockSpec((k_pad, a_pad), lambda b, r: (b, 0)),
            out_shape=jax.ShapeDtypeStruct((nb * k_pad, a_pad),
                                           jnp.float32),
            scratch_shapes=[pltpu.VMEM((k_pad, a_pad), jnp.float32)],
            interpret=interpret,
        )(slot_flat, val_flat)
        return out.reshape(nb, k_pad, a_pad)[:, :tile, :a]


if _PALLAS_OK:

    def _bitunpack_kernel(packed_ref, out_ref):
        """One grid step: unpack BITS_CHUNK packed bytes into
        BITS_CHUNK×8 byte-per-bit lanes (MSB-first — numpy packbits
        order).  A lane-dimension gather picks each output bit's source
        byte (the reshape-free formulation Mosaic lowers as a vector
        dynamic-gather, like the probe kernel's take_along_axis)."""
        p = packed_ref[:].astype(jnp.int32)            # [1, C]
        j = jax.lax.broadcasted_iota(jnp.int32, (1, p.shape[1] * 8), 1)
        byte = jnp.take_along_axis(p, j // 8, axis=1)
        out_ref[:] = ((byte >> (7 - (j % 8))) & 1).astype(jnp.uint8)

    @functools.partial(jax.jit, static_argnames=("cap", "interpret"))
    def bit_unpack_pallas(packed: jnp.ndarray, cap: int,
                          interpret: bool = False) -> jnp.ndarray:
        """On-device validity-plane unpack for the pipelined scan
        (executor/scanpipe.py, scan_pipeline=device): packed
        [rows, cap//8] uint8 (numpy packbits, MSB-first) → [rows, cap]
        bool.  8× fewer bytes cross the wire than the byte-per-row
        plane the eager feed path transfers."""
        rows, w = packed.shape
        w_pad = _round_up(max(w, BITS_CHUNK), BITS_CHUNK)
        if w_pad != w:
            packed = jnp.zeros((rows, w_pad), jnp.uint8) \
                .at[:, :w].set(packed)
        out = pl.pallas_call(
            _bitunpack_kernel,
            grid=(rows, w_pad // BITS_CHUNK),
            in_specs=[pl.BlockSpec((1, BITS_CHUNK),
                                   lambda i, j: (i, j))],
            out_specs=pl.BlockSpec((1, BITS_CHUNK * 8),
                                   lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((rows, w_pad * 8),
                                           jnp.uint8),
            interpret=interpret,
        )(packed)
        return out[:, :cap].astype(bool)

    def _dictdecode_kernel(lut_ref, codes_ref, out_ref):
        """One grid step: gather PROBE_CHUNK codes against the resident
        LUT tile (index map ignores the chunk grid dim, so the LUT
        streams HBM→VMEM once per row — the probe kernel's pattern)."""
        out_ref[:] = jnp.take_along_axis(lut_ref[:], codes_ref[:],
                                         axis=1)

    @functools.partial(jax.jit, static_argnames=("interpret",))
    def dict_decode_pallas(codes: jnp.ndarray, lut: jnp.ndarray,
                           interpret: bool = False) -> jnp.ndarray:
        """On-device dictionary decode for the pipelined scan: codes
        [rows, cap] (uint8/uint16 wire dtype) + lut [n_values] →
        out[r, i] = lut[codes[r, i]].  Low-NDV columns cross the wire
        as 1-2 byte codes plus the tiny LUT instead of decoded
        float32."""
        rows, cap = codes.shape
        nv = lut.shape[0]
        l_pad = _round_up(max(nv, 128), 128)
        lut2 = jnp.zeros((1, l_pad), lut.dtype).at[0, :nv].set(lut)
        cap_pad = _round_up(max(cap, PROBE_CHUNK), PROBE_CHUNK)
        c = codes.astype(jnp.int32)
        if cap_pad != cap:
            c = jnp.zeros((rows, cap_pad), jnp.int32).at[:, :cap].set(c)
        out = pl.pallas_call(
            _dictdecode_kernel,
            grid=(rows, cap_pad // PROBE_CHUNK),
            in_specs=[
                pl.BlockSpec((1, l_pad), lambda i, j: (0, 0)),
                pl.BlockSpec((1, PROBE_CHUNK), lambda i, j: (i, j)),
            ],
            out_specs=pl.BlockSpec((1, PROBE_CHUNK),
                                   lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((rows, cap_pad), lut.dtype),
            interpret=interpret,
        )(lut2, c)
        return out[:, :cap]


def bit_unpack_reference(packed: np.ndarray, cap: int) -> np.ndarray:
    """numpy oracle for the bit unpack."""
    p = np.asarray(packed)
    bits = np.unpackbits(p, axis=-1)
    return bits[..., :cap].astype(bool)


def dict_decode_reference(codes: np.ndarray, lut: np.ndarray
                          ) -> np.ndarray:
    """numpy oracle for the dictionary decode."""
    return np.asarray(lut)[np.asarray(codes).astype(np.int64)]


def groupby_sums_reference(loc2d: np.ndarray, stack: np.ndarray,
                           tile: int) -> np.ndarray:
    """numpy oracle for the bucket-tiled segment sum."""
    nb, cap = np.asarray(loc2d).shape
    a = np.asarray(stack).shape[2]
    out = np.zeros((nb, tile, a), np.float32)
    for b in range(nb):
        np.add.at(out[b], np.asarray(loc2d)[b],
                  np.asarray(stack)[b].astype(np.float32))
    return out


def probe_gather_reference(dir2d: np.ndarray,
                           loc2d: np.ndarray) -> np.ndarray:
    """numpy oracle for the tiled probe gather."""
    return np.take_along_axis(np.asarray(dir2d), np.asarray(loc2d), axis=1)


def segment_sum_reference(slot: np.ndarray, values: np.ndarray,
                          total: int) -> np.ndarray:
    """numpy oracle for tests."""
    out = np.zeros((total, values.shape[1]), np.float32)
    keep = slot < total
    np.add.at(out, slot[keep], values[keep].astype(np.float32))
    return out
