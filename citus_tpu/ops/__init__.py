from .aggregate import distinct, segment_aggregate
from .groupby import (
    bucketed_grid_aggregate,
    group_bucket_count,
    group_bucket_eligible,
)
from .hashing import (
    combine_hash64,
    fmix32_jax,
    hash_token_jax,
    shard_index_for_values_jax,
    shard_index_from_token,
    tile_buckets,
)
from .join import (
    bucketed_unique_lookup,
    dense_unique_lookup,
    expand_join,
    expand_join_pairs,
    lookup_join,
    lower_bound,
    match_counts,
    sort_build_side,
)
from .partition import pack_by_target

__all__ = [
    "distinct", "segment_aggregate",
    "bucketed_grid_aggregate", "group_bucket_count",
    "group_bucket_eligible",
    "combine_hash64", "fmix32_jax",
    "hash_token_jax", "shard_index_for_values_jax", "shard_index_from_token",
    "tile_buckets",
    "bucketed_unique_lookup", "dense_unique_lookup",
    "expand_join", "expand_join_pairs", "lookup_join", "lower_bound",
    "match_counts",
    "sort_build_side", "pack_by_target",
]
