"""Radix partition packing: rows → fixed [n_targets, capacity] buffers.

The map phase of the shuffle (reference: worker_partition_query_result
hashing rows into N partition files, /root/reference/src/backend/distributed/
executor/partitioned_intermediate_results.c:108) — rebuilt as a dense pack
whose output feeds `jax.lax.all_to_all` over ICI directly, replacing the
fetch_intermediate_results COPY-over-TCP hop entirely (SURVEY §3.2).

The pack is formulated as a GATHER, not a scatter: rows sort by target
(one cheap int32 argsort), each target's rows then occupy a contiguous
run of sorted positions, and output slot (t, r) pulls sorted position
starts[t] + r.  Per-column work is a single gather — TPU scatters
serialize on combining, gathers don't.

Static capacity per target partition; the overflow count is returned so the
host can re-run with a larger capacity (count-then-emit at host granularity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pack_by_target(columns: dict[str, jnp.ndarray], valid: jnp.ndarray,
                   target: jnp.ndarray, n_targets: int, capacity: int,
                   ) -> tuple[dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Arrange rows into [n_targets, capacity] per column.

    Returns (packed_columns, packed_valid [n_targets, capacity],
    overflow_count — rows dropped because their partition exceeded capacity).
    Overflow > 0 ⇒ results incomplete ⇒ host retries with larger capacity.
    """
    n = target.shape[0]
    t = jnp.where(valid, target, n_targets).astype(jnp.int32)
    order = jnp.argsort(t, stable=True).astype(jnp.int32)
    counts = jax.ops.segment_sum(valid.astype(jnp.int32), t,
                                 num_segments=n_targets + 1)[:n_targets]
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts, dtype=jnp.int32)])[:-1]

    # slot (t, r) ← sorted position starts[t] + r (gather, no scatter)
    slots = jnp.arange(n_targets * capacity, dtype=jnp.int32)
    ti = slots // capacity
    r = slots - ti * capacity
    packed_valid = r < counts[ti]
    sp = jnp.clip(starts[ti] + r, 0, max(n - 1, 0))
    src_row = order[sp]
    packed = {}
    for name, col in columns.items():
        buf = jnp.where(packed_valid, col[src_row],
                        jnp.zeros((), col.dtype))
        packed[name] = buf.reshape(n_targets, capacity)
    overflow = jnp.maximum(counts - capacity, 0).sum()
    return packed, packed_valid.reshape(n_targets, capacity), overflow
