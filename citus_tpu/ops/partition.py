"""Radix partition packing: rows → fixed [n_targets, capacity] buffers.

The map phase of the shuffle (reference: worker_partition_query_result
hashing rows into N partition files, /root/reference/src/backend/distributed/
executor/partitioned_intermediate_results.c:108) — rebuilt as a dense pack
whose output feeds `jax.lax.all_to_all` over ICI directly, replacing the
fetch_intermediate_results COPY-over-TCP hop entirely (SURVEY §3.2).

The pack is formulated as a GATHER, not a scatter: rows sort by target
(one cheap int32 argsort), each target's rows then occupy a contiguous
run of sorted positions, and output slot (t, r) pulls sorted position
starts[t] + r.  Per-column work is a single gather — TPU scatters
serialize on combining, gathers don't.

Static capacity per target partition; the overflow count is returned so the
host can re-run with a larger capacity (count-then-emit at host granularity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# counting-rank eligibility bound: the pack's sort key has only
# n_targets+1 distinct values, so for the mesh-shuffle case (targets =
# devices, ≤ 8 on a v5e-8) a counting formulation — one 1-D cumsum per
# target — replaces the stable argsort entirely.  Measured on the
# 24-core CPU rig at 940k rows: argsort 322 ms vs 9 cumsums ≈ 17 ms
# (~20× on the shuffle's dominant stage; the dual-repartition join's
# 8-device wall went 1.23 s → 0.57 s end to end).  The cumsum loop
# unrolls per target, so wide radix packs (bucketed group-by / probe
# tiles, hundreds of buckets) stay on the argsort path — there the
# loop's O(n·T) work and compile size would lose.
COUNTING_PACK_MAX_TARGETS = 32


def pack_by_target(columns: dict[str, jnp.ndarray], valid: jnp.ndarray,
                   target: jnp.ndarray, n_targets: int, capacity: int,
                   ) -> tuple[dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Arrange rows into [n_targets, capacity] per column.

    Returns (packed_columns, packed_valid [n_targets, capacity],
    overflow_count — rows dropped because their partition exceeded capacity).
    Overflow > 0 ⇒ results incomplete ⇒ host retries with larger capacity.
    """
    n = target.shape[0]
    t = jnp.where(valid, target, n_targets).astype(jnp.int32)
    if n_targets <= COUNTING_PACK_MAX_TARGETS:
        # counting rank: row i's position within its target's run is
        # the inclusive prefix count of its target minus one; `order`
        # (sorted position → source row) lands by unique-index scatter.
        # Bit-identical to the stable argsort (both preserve source
        # order within a target).
        rank = jnp.zeros(n, jnp.int32)
        counts_l = []
        for d in range(n_targets):
            is_d = t == d
            c = jnp.cumsum(is_d.astype(jnp.int32))
            rank = jnp.where(is_d, c - 1, rank)
            counts_l.append(c[n - 1] if n else jnp.int32(0))
        counts = jnp.stack(counts_l)
        starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(counts, dtype=jnp.int32)]
                                 )[:-1]
        out_idx = jnp.where(t < n_targets, starts[t] + rank, n)
        order = jnp.zeros(n, jnp.int32).at[out_idx].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop")
    else:
        order = jnp.argsort(t, stable=True).astype(jnp.int32)
        counts = jax.ops.segment_sum(valid.astype(jnp.int32), t,
                                     num_segments=n_targets + 1)[:n_targets]
        starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(counts, dtype=jnp.int32)]
                                 )[:-1]

    # slot (t, r) ← sorted position starts[t] + r (gather, no scatter)
    slots = jnp.arange(n_targets * capacity, dtype=jnp.int32)
    ti = slots // capacity
    r = slots - ti * capacity
    packed_valid = r < counts[ti]
    sp = jnp.clip(starts[ti] + r, 0, max(n - 1, 0))
    src_row = order[sp]
    packed = {}
    for name, col in columns.items():
        buf = jnp.where(packed_valid, col[src_row],
                        jnp.zeros((), col.dtype))
        packed[name] = buf.reshape(n_targets, capacity)
    overflow = jnp.maximum(counts - capacity, 0).sum()
    return packed, packed_valid.reshape(n_targets, capacity), overflow
