"""Device-side hashing: the bit-exact twin of catalog.distribution.

The routing contract: host ingest (numpy) and device shuffles (jax) MUST
compute identical hash tokens, or rows land on the wrong shard after a
repartition (`all_to_all`) and joins silently lose rows.  Tests assert
bit-equality between this module and catalog/distribution.py.

Reference analogue: the worker-side hash evaluation in
worker_partition_query_result (/root/reference/src/backend/distributed/
executor/partitioned_intermediate_results.c) — there per-row C hashing over
libpq tuples; here whole-column uint32 VPU ops.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..catalog.distribution import HASH_TOKEN_COUNT, INT32_MIN


def fmix32_jax(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 finalizer over uint32 arrays (shifts/xors/mults — pure VPU)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hash_token_jax(values: jnp.ndarray) -> jnp.ndarray:
    """Column → signed int32 hash tokens (matches distribution.hash_token).

    Requires x64 mode: with it off, jnp.asarray silently downcasts int64
    columns to int32 *before* this function sees them, so the 64-bit mix
    never runs and parity with the host silently breaks.  Entry points call
    runtime.ensure_jax_configured(); this guard catches stragglers."""
    from ..runtime import require_x64

    require_x64()
    dt = values.dtype
    if dt in (jnp.int64, jnp.uint64):
        v = values.astype(jnp.uint64)
        lo = (v & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (v >> jnp.uint64(32)).astype(jnp.uint32)
        # PG hashint8-style width fold (see distribution.hash_token): makes
        # int64 hashing agree with int32 for in-range values, so executor
        # key casts to int64 keep host/device routing parity
        nonneg = hi < jnp.uint32(0x80000000)
        folded = lo ^ jnp.where(nonneg, hi, ~hi)
        return fmix32_jax(folded).astype(jnp.int32)
    if dt == jnp.float64:
        # bit pattern, not value: int64 view
        return hash_token_jax(
            jnp.asarray(values).view(jnp.int64))
    if dt == jnp.float32:
        return fmix32_jax(jnp.asarray(values).view(jnp.uint32)).astype(jnp.int32)
    if dt == jnp.bool_:
        values = values.astype(jnp.int32)
    return fmix32_jax(values.astype(jnp.int32).view(jnp.uint32)).astype(jnp.int32)


def shard_index_from_token(tokens: jnp.ndarray, shard_count: int) -> jnp.ndarray:
    """Uniform-increment owner lookup (closed form; no binary search).

    Matches distribution.shard_index_for_token: contiguous ranges of width
    HASH_TOKEN_COUNT // shard_count starting at INT32_MIN.
    """
    increment = HASH_TOKEN_COUNT // shard_count
    offset = tokens.astype(jnp.int64) - INT32_MIN
    idx = offset // increment
    return jnp.minimum(idx, shard_count - 1).astype(jnp.int32)


def shard_index_for_values_jax(values: jnp.ndarray, shard_count: int) -> jnp.ndarray:
    return shard_index_from_token(hash_token_jax(values), shard_count)


def tile_buckets(slots: jnp.ndarray, tile_slots: int,
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Directory slot → (bucket, tile-local slot) for the VMEM-tiled
    probe pack (ops.join.bucketed_unique_lookup).

    Buckets are contiguous slot ranges — value-range partitioning, the
    degenerate perfect hash over an already-dense slot space — so every
    probe landing in bucket b touches only directory tile b, and the
    pack (ops.partition.pack_by_target) turns random directory traffic
    into per-tile streams.  Lives beside the routing hashes because it
    is the same partition-for-locality contract the shard tokens
    implement cross-device, minus the mixing step (dense directory
    slots need no avalanche; sparse keys would hash first)."""
    bucket = slots // tile_slots
    return bucket, slots - bucket * tile_slots


def combine_hash64(parts: list[jnp.ndarray]) -> jnp.ndarray:
    """Mix several key columns into one uint64 (group-by composite key).

    Used ONLY where collisions are tolerable or verified downstream; exact
    multi-key comparisons use ops.join lexicographic search instead.
    """
    acc = jnp.zeros(parts[0].shape, dtype=jnp.uint64)
    for p in parts:
        h = hash_token_jax(p).astype(jnp.uint64) & jnp.uint64(0xFFFFFFFF)
        acc = acc * jnp.uint64(0x100000001B3) ^ h
    return acc
