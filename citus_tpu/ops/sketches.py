"""Approximate-aggregate sketch math (host-side estimator pieces).

The reference rewrites count(distinct) → hll and percentile → t-digest
worker/coordinator pairs when the extensions are loaded
(/root/reference/src/backend/distributed/planner/multi_logical_optimizer.c:286
GetAggregateType HLL/TDIGEST branches).  The TPU-native formulation keeps
the per-row work on device as plain grouped aggregation:

* approx_count_distinct — HyperLogLog.  Device computes
  ``group by (G, hash_bucket)`` with ``max(rho)`` — a segment max that
  rides the existing aggregate split (the registers ARE the groups) and
  psum/shuffle combine.  The estimator below folds the per-bucket
  registers into the cardinality estimate; the final fold is itself
  expressed as level-2 aggregates + host math, so everything stays in
  one plan.
* approx_percentile — DDSketch.  Device computes
  ``group by (G…, dd_bucket(x))`` counts; the fixed log-domain bucket
  mapping makes per-shard sketches merge by count addition through the
  ordinary aggregate split, and the host folds (key, count) pairs into
  quantiles with a RELATIVE error bound α = (γ-1)/(γ+1) ≈ 1%
  (t-digest bounds rank space instead — documented difference; DDSketch
  was chosen because bucketing is a pure map, TPU-friendly, where
  t-digest's centroid merge is sequential).

This module holds the constants + host estimators; the device
expressions live in planner IR (BHllBucket / BHllRho) and the plan
rewrite in planner/plan.py.
"""

from __future__ import annotations

import math

import numpy as np

# HLL precision: p=12 → m=4096 registers, standard error 1.04/sqrt(m)
# ≈ 1.6%.  Registers materialize as GROUPS (device rows), so m trades
# accuracy against the level-1 aggregate buffer — 4096 keeps grouped
# approx_count_distinct cheap while matching the reference's default
# log2m range (postgresql-hll defaults to 11–15)
HLL_P = 12
HLL_M = 1 << HLL_P


def hll_alpha(m: int) -> float:
    if m >= 128:
        return 0.7213 / (1.0 + 1.079 / m)
    if m >= 64:
        return 0.709
    if m >= 32:
        return 0.697
    return 0.673


def hll_estimate(n_buckets: np.ndarray, sum_exp2neg: np.ndarray,
                 m: int = HLL_M) -> np.ndarray:
    """Cardinality estimate per group from level-2 aggregates.

    n_buckets: count of NON-EMPTY registers; sum_exp2neg: sum of
    2^-rho_max over the non-empty registers (empty registers contribute
    2^0 = 1 each, added here).  Includes the linear-counting small-range
    correction (HyperLogLog, Flajolet et al. 2007)."""
    n_buckets = np.asarray(n_buckets, dtype=np.float64)
    sum_exp2neg = np.asarray(sum_exp2neg, dtype=np.float64)
    empty = m - n_buckets
    raw = hll_alpha(m) * m * m / (empty + sum_exp2neg)
    # small-range: linear counting when registers are sparse
    with np.errstate(divide="ignore", invalid="ignore"):
        linear = m * np.log(np.where(empty > 0, m / np.maximum(empty, 1),
                                     1.0))
    out = np.where((raw <= 2.5 * m) & (empty > 0), linear, raw)
    return np.rint(out).astype(np.int64)


# -- DDSketch quantiles ---------------------------------------------------
# Log-domain buckets (DDSketch, Masson/Lee/Rigollet VLDB 2019): bucket
# k(x) = ceil(log_γ x) for x > 0, mirrored for negatives, one zero
# bucket for |x| ≤ DD_EPS.  Guarantee: the returned quantile x̂
# satisfies |x̂ - x_q| ≤ α·|x_q| with α = (γ-1)/(γ+1) — RELATIVE error,
# independent of the data's range, so one outlier cannot stretch every
# bucket (the failure mode of the min/max linear histogram this
# replaced; r4 VERDICT weak #5).  The buckets are a FIXED value→key
# mapping, so per-shard sketches merge by adding counts — they ride the
# grouped-aggregate split (groups = (G…, key)) and psum/shuffle combine
# exactly like the HLL registers above.  γ = 1.02 → α ≈ 1.0%, ~3.1k
# buckets per sign over |x| ∈ [1e-9, 1e18].
DD_GAMMA = 1.02
DD_EPS = 1e-9
DD_ALPHA = (DD_GAMMA - 1.0) / (DD_GAMMA + 1.0)
DD_LOG_GAMMA = math.log(DD_GAMMA)
DD_KMIN = math.ceil(math.log(DD_EPS) / DD_LOG_GAMMA)   # ≈ -1046
DD_KMAX = math.ceil(math.log(1e18) / DD_LOG_GAMMA)     # ≈  2094
DD_NKEYS = 2 * (DD_KMAX - DD_KMIN + 1) + 1


def dd_bucket(v, xp=np):
    """Signed DDSketch bucket key; monotone in v (sortable).  Shared by
    the host evaluator (xp=numpy) and the device path (xp=jax.numpy —
    float32 log rounds bucket boundaries by at most one bucket, still
    within the α bound's order)."""
    av = xp.abs(v)
    k = xp.ceil(xp.log(xp.maximum(av, DD_EPS)) / DD_LOG_GAMMA)
    k = xp.clip(k, DD_KMIN, DD_KMAX) - (DD_KMIN - 1)
    sign = xp.where(v < 0, -1, 1)
    return xp.where(av <= DD_EPS, 0,
                    sign * k.astype(xp.int32)).astype(xp.int32)


def dd_bucket_scalar(v: float) -> int:
    """dd_bucket for ONE host float, pure math module — the numpy
    formulation costs ~16 µs/call on scalars (ufunc dispatch), which
    is most of the tracing recorder's per-statement budget; this is
    ~0.2 µs with identical bucket keys."""
    av = abs(v)
    if av <= DD_EPS:
        return 0
    k = math.ceil(math.log(av) / DD_LOG_GAMMA)
    k = min(max(k, DD_KMIN), DD_KMAX) - (DD_KMIN - 1)
    return -k if v < 0 else k


def dd_value(key: int) -> float:
    """Representative (log-midpoint) value of a bucket key."""
    if key == 0:
        return 0.0
    k = abs(int(key)) + DD_KMIN - 1
    v = 2.0 * (DD_GAMMA ** k) / (DD_GAMMA + 1.0)
    return v if key > 0 else -v


def dd_quantile(keys: np.ndarray, counts: np.ndarray,
                q: float) -> float | None:
    """Quantile from (bucket key, count) pairs; None on empty input.
    Keys are monotone in value, so rank selection is a sort + cumsum."""
    keys = np.asarray(keys, dtype=np.int64)
    counts = np.asarray(counts, dtype=np.int64)
    if keys.size == 0:
        return None
    order = np.argsort(keys)
    k = keys[order]
    c = counts[order]
    total = int(c.sum())
    if total == 0:
        return None
    # rank of the q-quantile (nearest-rank, 1-based)
    target = max(1, int(math.ceil(q * total)))
    cum = np.cumsum(c)
    i = int(np.searchsorted(cum, target, side="left"))
    i = min(i, len(k) - 1)
    return dd_value(int(k[i]))
