"""Approximate-aggregate sketch math (host-side estimator pieces).

The reference rewrites count(distinct) → hll and percentile → t-digest
worker/coordinator pairs when the extensions are loaded
(/root/reference/src/backend/distributed/planner/multi_logical_optimizer.c:286
GetAggregateType HLL/TDIGEST branches).  The TPU-native formulation keeps
the per-row work on device as plain grouped aggregation:

* approx_count_distinct — HyperLogLog.  Device computes
  ``group by (G, hash_bucket)`` with ``max(rho)`` — a segment max that
  rides the existing aggregate split (the registers ARE the groups) and
  psum/shuffle combine.  The estimator below folds the per-bucket
  registers into the cardinality estimate; the final fold is itself
  expressed as level-2 aggregates + host math, so everything stays in
  one plan.
* approx_percentile — bounded histogram.  Device computes
  ``group by value_bucket`` counts over the column's EXACT min/max from
  manifest statistics; the host interpolates the quantile from the
  cumulative histogram.  Error is bounded by one bucket width in value
  space (t-digest bounds rank-space instead — documented difference).

This module holds the constants + host estimators; the device
expressions live in planner IR (BHllBucket / BHllRho) and the plan
rewrite in planner/plan.py.
"""

from __future__ import annotations

import math

import numpy as np

# HLL precision: p=12 → m=4096 registers, standard error 1.04/sqrt(m)
# ≈ 1.6%.  Registers materialize as GROUPS (device rows), so m trades
# accuracy against the level-1 aggregate buffer — 4096 keeps grouped
# approx_count_distinct cheap while matching the reference's default
# log2m range (postgresql-hll defaults to 11–15)
HLL_P = 12
HLL_M = 1 << HLL_P


def hll_alpha(m: int) -> float:
    if m >= 128:
        return 0.7213 / (1.0 + 1.079 / m)
    if m >= 64:
        return 0.709
    if m >= 32:
        return 0.697
    return 0.673


def hll_estimate(n_buckets: np.ndarray, sum_exp2neg: np.ndarray,
                 m: int = HLL_M) -> np.ndarray:
    """Cardinality estimate per group from level-2 aggregates.

    n_buckets: count of NON-EMPTY registers; sum_exp2neg: sum of
    2^-rho_max over the non-empty registers (empty registers contribute
    2^0 = 1 each, added here).  Includes the linear-counting small-range
    correction (HyperLogLog, Flajolet et al. 2007)."""
    n_buckets = np.asarray(n_buckets, dtype=np.float64)
    sum_exp2neg = np.asarray(sum_exp2neg, dtype=np.float64)
    empty = m - n_buckets
    raw = hll_alpha(m) * m * m / (empty + sum_exp2neg)
    # small-range: linear counting when registers are sparse
    with np.errstate(divide="ignore", invalid="ignore"):
        linear = m * np.log(np.where(empty > 0, m / np.maximum(empty, 1),
                                     1.0))
    out = np.where((raw <= 2.5 * m) & (empty > 0), linear, raw)
    return np.rint(out).astype(np.int64)


def histogram_quantile(bucket_ids: np.ndarray, counts: np.ndarray,
                       q: float, lo: float, width: float,
                       n_buckets: int) -> float | None:
    """Quantile from per-bucket counts (bucket = floor((x-lo)/width),
    clipped to [0, n_buckets-1]); linear interpolation inside the
    selected bucket.  None for an empty input."""
    if len(bucket_ids) == 0:
        return None
    order = np.argsort(bucket_ids)
    b = np.asarray(bucket_ids, dtype=np.int64)[order]
    c = np.asarray(counts, dtype=np.int64)[order]
    total = int(c.sum())
    if total == 0:
        return None
    target = q * total
    cum = np.cumsum(c)
    i = int(np.searchsorted(cum, target, side="left"))
    i = min(i, len(b) - 1)
    prev = int(cum[i - 1]) if i > 0 else 0
    inside = (target - prev) / max(int(c[i]), 1)
    inside = min(max(inside, 0.0), 1.0)
    return float(lo + (int(b[i]) + inside) * width)


def percentile_bucket_params(vmin: float, vmax: float,
                             n_buckets: int = 8192) -> tuple[float, float]:
    """(lo, width) for the value-space histogram; degenerate ranges get
    width 1 so every value lands in bucket 0."""
    if not math.isfinite(vmin) or not math.isfinite(vmax) or vmax <= vmin:
        return float(vmin), 1.0
    return float(vmin), (float(vmax) - float(vmin)) / n_buckets
