"""Equi-join kernels: exact lexicographic binary-search lookup join.

TPU-native replacement for the reference's hash build/probe executed per
shard on workers (co-located pushdown joins,
/root/reference/src/backend/distributed/planner/query_pushdown_planning.c;
repartition merge tasks, multi_physical_planner.c BuildMapMergeJob): instead
of pointer-chasing hash tables, the build side is sorted once and probes run
a vectorized lexicographic binary search (log2(M) gather steps — all MXU/VPU
friendly dense ops, no data-dependent shapes).

Multi-column keys are compared exactly (no hash-combine collisions): the
search carries the full key tuple through the comparison at every step.

Unique-build lookup (PK-FK, the TPC-H shape) returns one match per probe
row.  `expand_join` handles the general many-to-many case with a static
output capacity + overflow flag the host retries on
(SURVEY §7 hard part #1: capacity padding + count-then-emit).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _lex_less(a: list[jnp.ndarray], b: list[jnp.ndarray]) -> jnp.ndarray:
    """a < b lexicographically; arrays broadcast elementwise."""
    out = jnp.zeros(jnp.broadcast_shapes(a[0].shape, b[0].shape), jnp.bool_)
    tie = jnp.ones_like(out)
    for x, y in zip(a, b):
        out = out | (tie & (x < y))
        tie = tie & (x == y)
    return out


def _lex_eq(a: list[jnp.ndarray], b: list[jnp.ndarray]) -> jnp.ndarray:
    out = jnp.ones(jnp.broadcast_shapes(a[0].shape, b[0].shape), jnp.bool_)
    for x, y in zip(a, b):
        out = out & (x == y)
    return out


def sort_build_side(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                    ) -> tuple[list[jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Sort build rows by key, invalid rows last.

    Returns (sorted_keys, order, n_valid).  Invalid rows keep their key
    values but sort after all valid rows, and lookups clamp to n_valid.
    """
    invalid = (~build_valid).astype(jnp.int32)
    order = jnp.lexsort(tuple(reversed(build_keys)) + (invalid,))
    sorted_keys = [k[order] for k in build_keys]
    n_valid = build_valid.sum().astype(jnp.int32)
    return sorted_keys, order, n_valid


def _search(sorted_keys: list[jnp.ndarray], n_valid: jnp.ndarray,
            probe_keys: list[jnp.ndarray], cmp) -> jnp.ndarray:
    """Vectorized binary search: first index in [0, n_valid] where
    cmp(build_key, probe_key) is False.  cmp must be monotone (True then
    False over the sorted build).  ceil(log2(M))+1 fixed iterations."""
    m = sorted_keys[0].shape[0]
    n = probe_keys[0].shape[0]
    steps = max(1, math.ceil(math.log2(m + 1)))
    lo = jnp.zeros(n, dtype=jnp.int32)
    hi = jnp.broadcast_to(n_valid.astype(jnp.int32), (n,))

    def body(_, carry):
        lo, hi = carry
        active = lo < hi  # converged lanes must stay put (fixed trip count)
        mid = (lo + hi) // 2
        mid_c = jnp.clip(mid, 0, m - 1)
        mid_keys = [k[mid_c] for k in sorted_keys]
        take = cmp(mid_keys, probe_keys)
        lo = jnp.where(active & take, mid + 1, lo)
        hi = jnp.where(active & ~take, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def lower_bound(sorted_keys: list[jnp.ndarray], n_valid: jnp.ndarray,
                probe_keys: list[jnp.ndarray]) -> jnp.ndarray:
    """First index with key >= probe (lexicographic, exact)."""
    return _search(sorted_keys, n_valid, probe_keys, _lex_less)


def lookup_join(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                probe_keys: list[jnp.ndarray], probe_valid: jnp.ndarray,
                ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One-match-per-probe equi-join (build side unique on key — PK side).

    Returns (build_row_idx [N] into the ORIGINAL build arrays, found [N]).
    If the build side has duplicate keys, the first (in sorted order) wins —
    callers that need all matches use expand_join.
    """
    sorted_keys, order, n_valid = sort_build_side(build_keys, build_valid)
    pos = lower_bound(sorted_keys, n_valid, probe_keys)
    m = sorted_keys[0].shape[0]
    pos_c = jnp.clip(pos, 0, m - 1)
    hit_keys = [k[pos_c] for k in sorted_keys]
    found = (probe_valid & (pos < n_valid) & _lex_eq(hit_keys, probe_keys))
    build_idx = order[pos_c]
    return build_idx, found


def match_counts(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                 probe_keys: list[jnp.ndarray], probe_valid: jnp.ndarray,
                 ) -> jnp.ndarray:
    """Number of build matches per probe row (count phase of count-then-emit)."""
    sorted_keys, _, n_valid = sort_build_side(build_keys, build_valid)
    lo = lower_bound(sorted_keys, n_valid, probe_keys)
    hi = _upper_bound(sorted_keys, n_valid, probe_keys)
    return jnp.where(probe_valid, hi - lo, 0)


def _lex_leq(a: list[jnp.ndarray], b: list[jnp.ndarray]) -> jnp.ndarray:
    return ~_lex_less(b, a)


def _upper_bound(sorted_keys, n_valid, probe_keys):
    """First index with key > probe — a direct search with <=, exact for
    any key dtype and any extreme values (no '+1 bump' tricks)."""
    return _search(sorted_keys, n_valid, probe_keys, _lex_leq)


def expand_join(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                probe_keys: list[jnp.ndarray], probe_valid: jnp.ndarray,
                capacity: int,
                ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """General many-to-many equi-join with static output capacity.

    Emits (build_idx [C], probe_idx [C], out_valid [C], overflow_count):
    every (build, probe) key-match pair, padded to `capacity`.  If total
    matches exceed capacity, overflow_count > 0 and the host retries with a
    larger capacity (CapacityOverflowError protocol).
    """
    build_idx, probe_idx, out_valid, _missing, overflow = _expand(
        build_keys, build_valid, probe_keys, probe_valid, probe_valid,
        capacity, probe_outer=False)
    return build_idx, probe_idx, out_valid, overflow


def _expand(build_keys, build_matchable, probe_keys, probe_valid,
            probe_matchable, capacity: int, probe_outer: bool):
    """Pair emission core.

    probe_valid = rows that exist; probe_matchable = rows whose keys may
    match (valid AND no NULL key — SQL: NULL joins nothing, but a LEFT
    join still emits the row null-extended).  With probe_outer, valid
    probe rows with zero matches emit one pair with build_missing=True.
    """
    sorted_keys, order, n_valid = sort_build_side(build_keys,
                                                  build_matchable)
    lo = lower_bound(sorted_keys, n_valid, probe_keys)
    hi = _upper_bound(sorted_keys, n_valid, probe_keys)
    counts = jnp.where(probe_matchable, hi - lo, 0)
    if probe_outer:
        emit_counts = jnp.where(probe_valid & (counts == 0), 1, counts)
    else:
        emit_counts = counts
    total = emit_counts.sum()
    starts = jnp.cumsum(emit_counts) - emit_counts  # exclusive prefix

    # emit: out slot j in [starts[i], starts[i]+emit_counts[i]) maps to
    # probe i, build sorted index lo[i] + (j - starts[i]).
    # Recover i per output slot via searchsorted over starts.
    slots = jnp.arange(capacity, dtype=emit_counts.dtype)
    probe_idx = jnp.searchsorted(starts, slots, side="right") - 1
    n = probe_keys[0].shape[0]
    probe_idx = jnp.clip(probe_idx, 0, n - 1)
    offset = slots - starts[probe_idx]
    out_valid = (slots < total) & (offset < emit_counts[probe_idx])
    m = sorted_keys[0].shape[0]
    sorted_pos = jnp.clip(lo[probe_idx] + offset, 0, m - 1)
    build_idx = order[sorted_pos]
    build_missing = out_valid & (counts[probe_idx] == 0)
    build_idx = jnp.where(build_missing, 0, build_idx)
    overflow = jnp.maximum(total - capacity, 0)
    return build_idx, probe_idx, out_valid, build_missing, overflow


def expand_join_outer(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                      build_matchable: jnp.ndarray,
                      probe_keys: list[jnp.ndarray],
                      probe_valid: jnp.ndarray,
                      probe_matchable: jnp.ndarray, capacity: int,
                      probe_outer: bool, build_outer: bool,
                      replicated_build: bool = False,
                      axis_name: str | None = None):
    """Outer-join pair emission (LEFT/RIGHT/FULL null extension).

    Returns (build_idx [C], probe_idx [C], out_valid [C],
    build_missing [C], unmatched_build [M], overflow):

    * probe_outer (LEFT): valid probe rows with zero matches emit one pair
      flagged build_missing — the consumer NULLs the build columns.
    * build_outer (RIGHT/FULL): unmatched_build marks valid build rows no
      surviving pair references; the consumer appends them as a second
      segment with probe columns NULL.  With replicated_build the matched
      flags combine across devices (psum over `axis_name`) and the extra
      segment emits on device 0 only, so a broadcast build side doesn't
      duplicate its unmatched rows once per device.
    """
    build_idx, probe_idx, out_valid, build_missing, overflow = _expand(
        build_keys, build_matchable, probe_keys, probe_valid,
        probe_matchable, capacity, probe_outer)
    m = build_keys[0].shape[0]
    if build_outer:
        hit = out_valid & ~build_missing
        matched = jnp.zeros(m, jnp.int32).at[
            jnp.where(hit, build_idx, 0)].max(hit.astype(jnp.int32))
        if replicated_build:
            matched = jax.lax.psum(matched, axis_name) > 0
        else:
            matched = matched > 0
        unmatched_build = build_valid & ~matched
        if replicated_build:
            unmatched_build = unmatched_build & (
                jax.lax.axis_index(axis_name) == 0)
    else:
        unmatched_build = jnp.zeros(m, jnp.bool_)
    return (build_idx, probe_idx, out_valid, build_missing,
            unmatched_build, overflow)
