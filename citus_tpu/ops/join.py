"""Equi-join kernels: dense-directory lookup + sorted binary-search join.

TPU-native replacement for the reference's hash build/probe executed per
shard on workers (co-located pushdown joins,
/root/reference/src/backend/distributed/planner/query_pushdown_planning.c;
repartition merge tasks, multi_physical_planner.c BuildMapMergeJob): no
pointer-chasing hash tables — the build side is arranged once (sort or
counting-sort) and probes resolve to a contiguous run of matches.

Two probe paths, chosen at trace time:

* **Dense directory** (the TPU fast path): when the build key's value
  range [base, base+extent) is known from table statistics (manifest
  min/max — exact for committed data), a counting-sort directory
  `starts[extent+1]` maps each key value straight to its sorted run.
  Probing is TWO O(1) gathers instead of 2·log2(M) serial gather steps —
  on a v5e this turns a 6.5 s binary-search phase into ~100 ms.  Build
  rows outside the declared range (stale stats / uncommitted overlay
  rows) are counted into a separate `dense_oob` overflow output; the host
  retries with the directory disabled, so stale statistics cost one
  recompile, never wrong answers.

* **Lexicographic binary search** (general path): multi-column or
  unbounded keys fall back to an exact vectorized binary search.  The
  lower and upper bounds run in ONE fused loop whose two gather chains
  are independent, letting the TPU overlap their memory traffic.

Pair emission is sort-free: probe start offsets scatter into the output
slot space and a `cummax` scan fills each probe's run (replacing a
log-time searchsorted over every output slot).  Static output capacity +
overflow counts remain the answer to data-dependent cardinalities
(SURVEY §7 hard part #1: capacity padding + count-then-emit).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


# dense-directory planning limits: the starts[] table costs O(extent)
# build work and 4·extent bytes of HBM, so it must stay proportional to
# the build side (sparse 64-bit keys fall back to binary search)
DENSE_MAX_SLOTS = 1 << 26

# bucketed probe path: directory slots per bucket tile.  An int32 tile of
# 2^15 slots is 128 KB — VMEM-resident with pipelining headroom on a
# 16 MB/core budget, and small enough that a probe stream sorted by
# bucket turns the random directory gather into sequential tile traffic.
PROBE_TILE_SLOTS = 1 << 15
# below this extent the whole directory is cache-sized and the single
# random gather is already bandwidth-friendly; the bucketed path's
# pack (one int32 argsort over the probe side) would cost more than the
# locality it buys.  Threshold = the measured knee where dense_unique_
# lookup's probe throughput collapses (~16 MB of directory, PERF_NOTES
# round-5 table: random gathers over 60M entries run ~300× below
# roofline while small directories ride the caches).
PROBE_BUCKET_MIN_EXTENT = 1 << 22


def probe_bucket_count(extent: int) -> int:
    """Number of VMEM-sized directory tiles covering [0, extent)."""
    return max(1, -(-extent // PROBE_TILE_SLOTS))


def probe_bucket_eligible(extent: int, probe_rows: int) -> bool:
    """Planner cost threshold for the bucketed probe path: the directory
    must be past the cache knee AND the probe stream must be dense enough
    to amortize streaming every tile once (a sparse probe over a huge
    directory still favors the single gather — most tiles would stream
    in for a handful of probes)."""
    return extent >= PROBE_BUCKET_MIN_EXTENT and probe_rows * 4 >= extent


def dense_directory_ok(extent: int, build_size: int) -> bool:
    """Shared eligibility predicate for the dense probe directory
    (PlanCompiler passes the padded build capacity; EXPLAIN approximates
    with the planner's row estimate)."""
    return (0 < extent <= DENSE_MAX_SLOTS
            and extent <= max(8 * max(build_size, 1), 1 << 20))


def _lex_less(a: list[jnp.ndarray], b: list[jnp.ndarray]) -> jnp.ndarray:
    """a < b lexicographically; arrays broadcast elementwise."""
    out = jnp.zeros(jnp.broadcast_shapes(a[0].shape, b[0].shape), jnp.bool_)
    tie = jnp.ones_like(out)
    for x, y in zip(a, b):
        out = out | (tie & (x < y))
        tie = tie & (x == y)
    return out


def _lex_eq(a: list[jnp.ndarray], b: list[jnp.ndarray]) -> jnp.ndarray:
    out = jnp.ones(jnp.broadcast_shapes(a[0].shape, b[0].shape), jnp.bool_)
    for x, y in zip(a, b):
        out = out & (x == y)
    return out


def _lex_leq(a: list[jnp.ndarray], b: list[jnp.ndarray]) -> jnp.ndarray:
    return ~_lex_less(b, a)


def sort_build_side(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                    ) -> tuple[list[jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Sort build rows by key, invalid rows last.

    Returns (sorted_keys, order, n_valid).  Invalid rows keep their key
    values but sort after all valid rows, and lookups clamp to n_valid.
    """
    invalid = (~build_valid).astype(jnp.int32)
    order = jnp.lexsort(tuple(reversed(build_keys)) + (invalid,))
    order = order.astype(jnp.int32)
    sorted_keys = [k[order] for k in build_keys]
    n_valid = build_valid.sum().astype(jnp.int32)
    return sorted_keys, order, n_valid


def _search(sorted_keys: list[jnp.ndarray], n_valid: jnp.ndarray,
            probe_keys: list[jnp.ndarray], cmp) -> jnp.ndarray:
    """Vectorized binary search: first index in [0, n_valid] where
    cmp(build_key, probe_key) is False.  cmp must be monotone (True then
    False over the sorted build).  ceil(log2(M))+1 fixed iterations."""
    m = sorted_keys[0].shape[0]
    n = probe_keys[0].shape[0]
    steps = max(1, math.ceil(math.log2(m + 1)))
    lo = jnp.zeros(n, dtype=jnp.int32)
    hi = jnp.broadcast_to(n_valid.astype(jnp.int32), (n,))

    def body(_, carry):
        lo, hi = carry
        active = lo < hi  # converged lanes must stay put (fixed trip count)
        mid = (lo + hi) // 2
        mid_c = jnp.clip(mid, 0, m - 1)
        mid_keys = [k[mid_c] for k in sorted_keys]
        take = cmp(mid_keys, probe_keys)
        lo = jnp.where(active & take, mid + 1, lo)
        hi = jnp.where(active & ~take, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def _dual_search(sorted_keys: list[jnp.ndarray], n_valid: jnp.ndarray,
                 probe_keys: list[jnp.ndarray],
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """lower_bound and upper_bound in ONE fused loop.

    The two binary searches are data-independent; interleaving them in a
    single fori_loop lets XLA issue both mid-gathers per iteration
    concurrently (the gathers are the serial bottleneck — each step's
    addresses depend on the previous step's loads)."""
    m = sorted_keys[0].shape[0]
    n = probe_keys[0].shape[0]
    steps = max(1, math.ceil(math.log2(m + 1)))
    zero = jnp.zeros(n, dtype=jnp.int32)
    top = jnp.broadcast_to(n_valid.astype(jnp.int32), (n,))

    def body(_, carry):
        lo1, hi1, lo2, hi2 = carry
        act1 = lo1 < hi1
        act2 = lo2 < hi2
        mid1 = (lo1 + hi1) // 2
        mid2 = (lo2 + hi2) // 2
        k1 = [k[jnp.clip(mid1, 0, m - 1)] for k in sorted_keys]
        k2 = [k[jnp.clip(mid2, 0, m - 1)] for k in sorted_keys]
        take1 = _lex_less(k1, probe_keys)   # lower: build < probe
        take2 = _lex_leq(k2, probe_keys)    # upper: build <= probe
        lo1 = jnp.where(act1 & take1, mid1 + 1, lo1)
        hi1 = jnp.where(act1 & ~take1, mid1, hi1)
        lo2 = jnp.where(act2 & take2, mid2 + 1, lo2)
        hi2 = jnp.where(act2 & ~take2, mid2, hi2)
        return lo1, hi1, lo2, hi2

    lo1, _, lo2, _ = jax.lax.fori_loop(
        0, steps, body, (zero, top, zero, top))
    return lo1, lo2


def lower_bound(sorted_keys: list[jnp.ndarray], n_valid: jnp.ndarray,
                probe_keys: list[jnp.ndarray]) -> jnp.ndarray:
    """First index with key >= probe (lexicographic, exact)."""
    return _search(sorted_keys, n_valid, probe_keys, _lex_less)


def _upper_bound(sorted_keys, n_valid, probe_keys):
    """First index with key > probe — a direct search with <=, exact for
    any key dtype and any extreme values (no '+1 bump' tricks)."""
    return _search(sorted_keys, n_valid, probe_keys, _lex_leq)


def _dense_slots(build_key: jnp.ndarray, build_matchable: jnp.ndarray,
                 base: int, extent: int):
    """Shared dense-directory build prologue: (slot [m] with out-of-range
    rows parked at `extent`, per_slot counts [extent], oob_count).  Both
    dense paths (counting-sort bounds and the sort-free unique lookup)
    derive their stale-stats oob accounting from here so the retry
    contract cannot diverge between them."""
    idx = build_key.astype(jnp.int64) - jnp.int64(base)
    inb = build_matchable & (idx >= 0) & (idx < extent)
    oob = (build_matchable & ~inb).sum().astype(jnp.int64)
    slot = jnp.where(inb, idx, extent).astype(jnp.int32)
    per_slot = jax.ops.segment_sum(
        inb.astype(jnp.int32), slot, num_segments=extent + 1)[:extent]
    return slot, per_slot, oob


def _probe_slots(probe_key: jnp.ndarray, base: int, extent: int):
    """(pin [n], pc [n]): in-range mask + clipped slot per probe row."""
    pidx = probe_key.astype(jnp.int64) - jnp.int64(base)
    pin = (pidx >= 0) & (pidx < extent)
    pc = jnp.clip(pidx, 0, extent - 1).astype(jnp.int32)
    return pin, pc


def _dense_bounds(build_key: jnp.ndarray, build_matchable: jnp.ndarray,
                  probe_key: jnp.ndarray, base: int, extent: int,
                  ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                             jnp.ndarray]:
    """Counting-sort directory over the key range [base, base+extent).

    Returns (order, lo, hi, oob_count): `order` arranges matchable
    in-range build rows first, sorted by key; lo/hi bound each probe's
    run in that order.  Matchable build rows OUTSIDE the declared range
    cannot be matched — their count comes back as `oob_count` so the
    caller can surface a retry-without-directory (stale-stats guard).
    """
    slot, counts, oob = _dense_slots(build_key, build_matchable, base,
                                     extent)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts, dtype=jnp.int32)])
    order = jnp.argsort(slot, stable=True).astype(jnp.int32)

    pin, pc = _probe_slots(probe_key, base, extent)
    lo = jnp.where(pin, starts[pc], 0)
    hi = jnp.where(pin, starts[pc + 1], 0)
    return order, lo, hi, oob


def dense_unique_lookup(build_key: jnp.ndarray,
                        build_matchable: jnp.ndarray,
                        probe_key: jnp.ndarray, base: int, extent: int,
                        ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sort-free dense lookup for a UNIQUE-keyed build side (the fused
    PK-join path): one unique-index scatter builds `directory[slot] →
    build row`, one gather probes it — no argsort over the build
    capacity (the counting-sort directory in _dense_bounds pays an
    O(m log m) argsort per execution, which dominated multi-join
    queries at SF1 on real TPUs).

    Returns (bidx [N], counts [N], oob_count).  Probing costs ONE gather
    per probe row: random HBM gathers are the measured wall of this path
    (~80M probes/s on v5e — 2 gathers over a 60M-entry directory put
    TPC-H Q3's SF10 probe stage at 1 s alone), so per-probe match counts
    come from the directory hit itself (0/1) rather than a second
    per_slot gather.  Duplicate build keys — the stale-uniqueness case —
    are detected BUILD-side: scatter-then-gather-back over the m build
    rows; overwritten rows read back a different index.  dups feed oob
    so the caller's retry-on-general-path protocol still always fires."""
    m = build_key.shape[0]
    idx = build_key.astype(jnp.int64) - jnp.int64(base)
    inb = build_matchable & (idx >= 0) & (idx < extent)
    oob = (build_matchable & ~inb).sum().astype(jnp.int64)
    slot = jnp.where(inb, idx, extent).astype(jnp.int32)
    iota_m = jnp.arange(m, dtype=jnp.int32)
    directory = jnp.full(extent, m, jnp.int32).at[slot].set(
        iota_m, mode="drop")
    dup = (inb & (jnp.minimum(directory[jnp.minimum(slot, extent - 1)], m)
                  != iota_m)).sum().astype(jnp.int64)
    pin, pc = _probe_slots(probe_key, base, extent)
    raw = directory[pc]
    found = pin & (raw != m)
    bidx = jnp.minimum(raw, m - 1)
    counts = found.astype(jnp.int32)
    return bidx, counts, oob + dup


def bucketed_unique_lookup(build_key: jnp.ndarray,
                           build_matchable: jnp.ndarray,
                           probe_key: jnp.ndarray, base: int, extent: int,
                           bucket_cap: int, kernel: str = "xla",
                           interpret: bool = False,
                           ) -> tuple[jnp.ndarray, jnp.ndarray,
                                      jnp.ndarray, jnp.ndarray,
                                      jnp.ndarray]:
    """Hash-bucketed, VMEM-tiled variant of dense_unique_lookup.

    The single-gather probe is latency-bound: random HBM touches over a
    multi-hundred-MB directory run ~300× below the memory roofline
    (~80M probes/s measured on v5e at SF10 sizes — PERF_NOTES).  This
    path restores locality the radix-join way (Theseus, arXiv
    2508.05029; shared-nothing multicore joins, arXiv 1804.09324;
    reference repartition machinery, multi_physical_planner.c
    BuildMapMergeJob): partition the probe stream by directory tile
    until each tile fits fast memory, then probe tile-by-tile so the
    directory streams through VMEM exactly once.

      1. build the dense directory as usual (one scatter; duplicate
         build keys detected build-side exactly like dense_unique_lookup
         so the stale-uniqueness retry contract cannot diverge),
      2. pack probe rows by bucket = slot // PROBE_TILE_SLOTS with the
         same counting-sort gather the repartition shuffle uses
         (pack_by_target) into a [n_buckets, bucket_cap] buffer,
      3. probe bucket-by-bucket — each bucket's tile is VMEM-sized and
         its probes are contiguous (kernel='xla': a batched row-local
         take_along_axis; kernel='pallas': the tile-resident kernel in
         ops/pallas_kernels.py),
      4. scatter hits back to original probe positions (unique-index).

    Returns (bidx [N], counts [N], oob_count, bucket_overflow,
    bucket_max_fill): oob_count follows the dense_unique_lookup contract
    (out-of-range + duplicate build rows → the host retries on the
    general path); bucket_overflow counts probe rows dropped because
    their bucket exceeded bucket_cap — results are incomplete and the
    host retries with grown per-bucket capacity (the same
    count-then-emit protocol every static buffer uses).  bucket_max_fill
    is the realized per-bucket maximum (capacity-feedback input)."""
    tile = PROBE_TILE_SLOTS
    m = build_key.shape[0]
    n = probe_key.shape[0]
    n_buckets = max(1, -(-extent // tile))
    ext_pad = n_buckets * tile

    # directory build + duplicate detection: identical accounting to
    # dense_unique_lookup (padding slots [extent, ext_pad) stay empty)
    idx = build_key.astype(jnp.int64) - jnp.int64(base)
    inb = build_matchable & (idx >= 0) & (idx < extent)
    oob = (build_matchable & ~inb).sum().astype(jnp.int64)
    slot = jnp.where(inb, idx, ext_pad).astype(jnp.int32)
    iota_m = jnp.arange(m, dtype=jnp.int32)
    directory = jnp.full(ext_pad, m, jnp.int32).at[slot].set(
        iota_m, mode="drop")
    dup = (inb & (jnp.minimum(directory[jnp.minimum(slot, ext_pad - 1)], m)
                  != iota_m)).sum().astype(jnp.int64)

    pin, pc = _probe_slots(probe_key, base, extent)
    from .hashing import tile_buckets
    from .partition import pack_by_target

    bucket, local = tile_buckets(pc, tile)

    packed, pvalid, overflow = pack_by_target(
        {"local": local, "pos": jnp.arange(n, dtype=jnp.int32)},
        pin, bucket, n_buckets, bucket_cap)
    # realized skew (max bucket fill) feeds capacity tightening; on an
    # overflowed run the retry regrows before feedback ever fires
    bucket_max_fill = pvalid.sum(axis=1).max().astype(jnp.int64)

    dir2d = directory.reshape(n_buckets, tile)
    loc2d = jnp.where(pvalid, packed["local"], 0)
    if kernel == "pallas" and not interpret:
        import jax

        from .pallas_kernels import pallas_available

        if not pallas_available() or jax.default_backend() == "cpu":
            # config asked for the kernel where it cannot compile — a
            # jax build that can't import pallas, or the CPU backend
            # (compiled pallas_call is interpret-only there): degrade
            # to the XLA formulation (same results) rather than crash
            # mid-compile
            kernel = "xla"
    if kernel == "pallas":
        from .pallas_kernels import bucketed_probe_pallas

        raw2d = bucketed_probe_pallas(dir2d, loc2d, interpret=interpret)
    else:
        raw2d = jnp.take_along_axis(dir2d, loc2d, axis=1)

    pos = jnp.where(pvalid, packed["pos"], n).reshape(-1)
    raw = jnp.full(n, m, jnp.int32).at[pos].set(
        raw2d.reshape(-1), mode="drop")
    found = pin & (raw != m)
    bidx = jnp.minimum(raw, m - 1)
    counts = found.astype(jnp.int32)
    return bidx, counts, oob + dup, overflow.astype(jnp.int64), \
        bucket_max_fill


def _bounds(build_keys, build_matchable, probe_keys,
            dense: tuple[int, int] | None,
            ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(order, lo, hi, dense_oob) via directory or binary search."""
    if dense is not None and len(build_keys) == 1:
        return _dense_bounds(build_keys[0], build_matchable, probe_keys[0],
                             dense[0], dense[1])
    sorted_keys, order, n_valid = sort_build_side(build_keys,
                                                  build_matchable)
    lo, hi = _dual_search(sorted_keys, n_valid, probe_keys)
    return order, lo, hi, jnp.zeros((), jnp.int64)


def match_counts(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                 probe_keys: list[jnp.ndarray], probe_valid: jnp.ndarray,
                 ) -> jnp.ndarray:
    """Number of build matches per probe row (count phase of count-then-emit)."""
    _, lo, hi, _ = _bounds(build_keys, build_valid, probe_keys, None)
    return jnp.where(probe_valid, hi - lo, 0)


def lookup_join(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                probe_keys: list[jnp.ndarray], probe_valid: jnp.ndarray,
                ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One-match-per-probe equi-join (build side unique on key — PK side).

    Returns (build_row_idx [N] into the ORIGINAL build arrays, found [N]).
    If the build side has duplicate keys, the first (in sorted order) wins —
    callers that need all matches use expand_join.
    """
    sorted_keys, order, n_valid = sort_build_side(build_keys, build_valid)
    pos = lower_bound(sorted_keys, n_valid, probe_keys)
    m = sorted_keys[0].shape[0]
    pos_c = jnp.clip(pos, 0, m - 1)
    hit_keys = [k[pos_c] for k in sorted_keys]
    found = (probe_valid & (pos < n_valid) & _lex_eq(hit_keys, probe_keys))
    build_idx = order[pos_c]
    return build_idx, found


def expand_join(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                probe_keys: list[jnp.ndarray], probe_valid: jnp.ndarray,
                capacity: int, dense: tuple[int, int] | None = None,
                ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """General many-to-many equi-join with static output capacity.

    Emits (build_idx [C], probe_idx [C], out_valid [C], overflow_count):
    every (build, probe) key-match pair, padded to `capacity`.  If total
    matches exceed capacity, overflow_count > 0 and the host retries with
    a larger capacity (CapacityOverflowError protocol).  `dense` is the
    optional (base, extent) of the build key's value range; see
    _dense_bounds.  overflow also reflects dense out-of-range build rows.
    """
    build_idx, probe_idx, out_valid, _missing, overflow, dense_oob = \
        expand_join_pairs(build_keys, build_valid, probe_keys, probe_valid,
                          probe_valid, capacity, probe_outer=False,
                          dense=dense)
    return build_idx, probe_idx, out_valid, overflow + dense_oob


def expand_join_pairs(build_keys, build_matchable, probe_keys, probe_valid,
                      probe_matchable, capacity: int, probe_outer: bool,
                      dense: tuple[int, int] | None = None):
    """Pair emission core.

    probe_valid = rows that exist; probe_matchable = rows whose keys may
    match (valid AND no NULL key — SQL: NULL joins nothing, but a LEFT
    join still emits the row null-extended).  With probe_outer, valid
    probe rows with zero matches emit one pair with build_missing=True.

    Returns (build_idx, probe_idx, out_valid, build_missing,
    capacity_overflow, dense_oob) — the two overflow kinds stay separate
    so the host can distinguish "grow buffers" from "stats were stale,
    drop the directory".
    """
    order, lo, hi, dense_oob = _bounds(build_keys, build_matchable,
                                       probe_keys, dense)
    m = build_keys[0].shape[0]
    n = probe_keys[0].shape[0]
    counts = jnp.where(probe_matchable, hi - lo, 0).astype(jnp.int32)
    if probe_outer:
        emit = jnp.where(probe_valid & (counts == 0), 1, counts)
    else:
        emit = counts
    total = emit.sum(dtype=jnp.int64)
    # exclusive prefix in int64 (cross joins can exceed int32), clamped to
    # capacity for the int32 slot arithmetic — slots past the clamp are
    # invalid anyway (slot < total fails or offset goes negative)
    starts64 = jnp.cumsum(emit.astype(jnp.int64)) - emit.astype(jnp.int64)
    starts = jnp.minimum(starts64, capacity).astype(jnp.int32)

    # probe id per output slot: each emitting probe scatters its index at
    # its start slot; a running max fills the run (sort-free emission —
    # replaces a log2(N) searchsorted chain over every output slot)
    marker = jnp.full(capacity, -1, jnp.int32).at[
        jnp.where(emit > 0, starts, capacity)].max(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    probe_idx = jnp.maximum(jax.lax.cummax(marker), 0)

    slots = jnp.arange(capacity, dtype=jnp.int32)
    offset = slots - starts[probe_idx]
    out_valid = ((slots.astype(jnp.int64) < total)
                 & (offset >= 0) & (offset < emit[probe_idx]))
    sorted_pos = jnp.clip(lo[probe_idx] + offset, 0, m - 1)
    build_idx = order[sorted_pos]
    build_missing = out_valid & (counts[probe_idx] == 0)
    build_idx = jnp.where(build_missing, 0, build_idx)
    overflow = jnp.maximum(total - capacity, 0)
    return build_idx, probe_idx, out_valid, build_missing, overflow, dense_oob


def expand_join_outer(build_keys: list[jnp.ndarray], build_valid: jnp.ndarray,
                      build_matchable: jnp.ndarray,
                      probe_keys: list[jnp.ndarray],
                      probe_valid: jnp.ndarray,
                      probe_matchable: jnp.ndarray, capacity: int,
                      probe_outer: bool, build_outer: bool,
                      replicated_build: bool = False,
                      axis_name: str | None = None,
                      dense: tuple[int, int] | None = None):
    """Outer-join pair emission (LEFT/RIGHT/FULL null extension).

    Returns (build_idx [C], probe_idx [C], out_valid [C],
    build_missing [C], unmatched_build [M], overflow, dense_oob):

    * probe_outer (LEFT): valid probe rows with zero matches emit one pair
      flagged build_missing — the consumer NULLs the build columns.
    * build_outer (RIGHT/FULL): unmatched_build marks valid build rows no
      surviving pair references; the consumer appends them as a second
      segment with probe columns NULL.  With replicated_build the matched
      flags combine across devices (psum over `axis_name`) and the extra
      segment emits on device 0 only, so a broadcast build side doesn't
      duplicate its unmatched rows once per device.
    """
    build_idx, probe_idx, out_valid, build_missing, overflow, dense_oob = \
        expand_join_pairs(build_keys, build_matchable, probe_keys,
                          probe_valid, probe_matchable, capacity,
                          probe_outer, dense=dense)
    m = build_keys[0].shape[0]
    if build_outer:
        hit = out_valid & ~build_missing
        matched = jnp.zeros(m, jnp.int32).at[
            jnp.where(hit, build_idx, 0)].max(hit.astype(jnp.int32))
        if replicated_build:
            matched = jax.lax.psum(matched, axis_name) > 0
        else:
            matched = matched > 0
        unmatched_build = build_valid & ~matched
        if replicated_build:
            unmatched_build = unmatched_build & (
                jax.lax.axis_index(axis_name) == 0)
    else:
        unmatched_build = jnp.zeros(m, jnp.bool_)
    return (build_idx, probe_idx, out_valid, build_missing,
            unmatched_build, overflow, dense_oob)
