"""Chrome-trace / Perfetto export for recorded statement traces.

`python -m citus_tpu.stats.trace_export <trace.json | data_dir>` reads
a persisted slow-query trace (or picks the newest one under
`<data_dir>/slow_traces/`) and emits Chrome trace-event JSON — load it
at chrome://tracing or ui.perfetto.dev.  The same conversion is
importable (:func:`chrome_trace_events`) so bench drivers can export
the trace of a measured run next to the artifact.

Event mapping: every span becomes one complete event (`ph: "X"`) with
microsecond `ts`/`dur` relative to the statement start; threads keep
their identity (`tid`), so the scanpipe producer's prefetch/encode/
transfer legs render on their own track, visibly overlapped with the
statement thread's dispatch.
"""

from __future__ import annotations

import json
import os
import sys

from .tracing import SLOW_TRACE_DIR, phase_breakdown


def chrome_trace_events(doc: dict) -> list[dict]:
    """Trace dict (Trace.to_dict() / a persisted slow-trace JSON) →
    Chrome trace-event list."""
    events: list[dict] = []
    tid_map: dict = {}

    def tid_of(raw) -> int:
        if raw not in tid_map:
            tid_map[raw] = len(tid_map) + 1
        return tid_map[raw]

    def walk(span: dict) -> None:
        events.append({
            "name": span["name"],
            "ph": "X",
            "ts": round(span.get("t0_ms", 0.0) * 1000.0, 1),
            "dur": round(span.get("dur_ms", 0.0) * 1000.0, 1),
            "pid": 1,
            "tid": tid_of(span.get("tid", 0)),
            "args": span.get("meta", {}),
        })
        for c in span.get("children", ()):
            walk(c)

    root = doc.get("root")
    if root:
        walk(root)
    meta = {"sql": doc.get("sql"), "class": doc.get("class"),
            "wall_ms": doc.get("wall_ms"),
            "truncated": doc.get("truncated"),
            "phases_ms": {k: round(v * 1000.0, 3)
                          for k, v in phase_breakdown(root).items()}
            if root else {}}
    events.append({"name": "statement_info", "ph": "M", "pid": 1,
                   "args": meta})
    return events


def newest_slow_trace(data_dir: str) -> str | None:
    d = os.path.join(data_dir, SLOW_TRACE_DIR)
    if not os.path.isdir(d):
        return None
    names = sorted(n for n in os.listdir(d)
                   if n.startswith("trace_") and n.endswith(".json"))
    return os.path.join(d, names[-1]) if names else None


def load_trace(path: str) -> dict:
    """`path` is a trace JSON file, a data_dir, or a slow_traces dir."""
    if os.path.isdir(path):
        inner = (path if os.path.basename(path) == SLOW_TRACE_DIR
                 else None)
        p = (newest_slow_trace(os.path.dirname(path)) if inner
             else newest_slow_trace(path))
        if p is None:
            raise FileNotFoundError(
                f"no slow-query traces under {path!r} (is "
                "trace_slow_statement_ms set low enough?)")
        path = p
    with open(path) as f:
        return json.load(f)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    out_path = None
    args = []
    it = iter(argv)
    for a in it:
        if a in ("-o", "--out"):
            out_path = next(it, None)
            if out_path is None:
                print("trace_export: -o needs a path", file=sys.stderr)
                return 2
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        else:
            args.append(a)
    if len(args) != 1:
        print("usage: python -m citus_tpu.stats.trace_export "
              "<trace.json | data_dir> [-o out.json]", file=sys.stderr)
        return 2
    try:
        doc = load_trace(args[0])
    except (OSError, json.JSONDecodeError) as e:
        print(f"trace_export: {e}", file=sys.stderr)
        return 1
    payload = {"traceEvents": chrome_trace_events(doc),
               "displayTimeUnit": "ms"}
    text = json.dumps(payload, indent=1)
    if out_path:
        # an export artifact, not engine durable state: the io seam's
        # checksummed atomic write is for data the engine re-reads
        with open(out_path, "w") as f:  # graftlint: ignore[raw-durable-write] — CLI export artifact for chrome://tracing, never read back by the engine
            f.write(text)
        print(f"wrote {out_path} ({len(payload['traceEvents'])} events)")
    else:
        print(text)
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
