"""Observability: counters, per-query stats, tenant stats, progress,
activity — the reference's stats/ + progress/ subsystems (SURVEY §2.10)."""

from .activity import ActivityRegistry
from .counters import ALL_COUNTERS, StatCounters
from .progress import ProgressMonitor, ProgressRegistry
from .query_stats import QueryStats, fingerprint
from .tenants import TenantStats, extract_tenants
from .tracing import TraceRecorder


class SessionStats:
    """Bundle owned by each Session (the shared-memory segment analogue).

    `data_dir`/`settings` feed the trace recorder (slow-query log
    destination + the trace_* knobs); both default to None for
    unit-test construction (tracing then runs in-memory with
    defaults)."""

    def __init__(self, data_dir: str | None = None, settings=None):
        self.counters = StatCounters()
        self.queries = QueryStats()
        self.tenants = TenantStats()
        self.progress = ProgressRegistry()
        self.activity = ActivityRegistry()
        self.tracing = TraceRecorder(data_dir, settings)


__all__ = [
    "ALL_COUNTERS", "ActivityRegistry", "ProgressMonitor",
    "ProgressRegistry", "QueryStats", "SessionStats", "StatCounters",
    "TenantStats", "TraceRecorder", "extract_tenants", "fingerprint",
]
