"""Observability: counters, per-query stats, tenant stats, progress,
activity — the reference's stats/ + progress/ subsystems (SURVEY §2.10)."""

from .activity import ActivityRegistry
from .counters import ALL_COUNTERS, StatCounters
from .progress import ProgressMonitor, ProgressRegistry
from .query_stats import QueryStats, fingerprint
from .tenants import TenantStats, extract_tenants


class SessionStats:
    """Bundle owned by each Session (the shared-memory segment analogue)."""

    def __init__(self):
        self.counters = StatCounters()
        self.queries = QueryStats()
        self.tenants = TenantStats()
        self.progress = ProgressRegistry()
        self.activity = ActivityRegistry()


__all__ = [
    "ALL_COUNTERS", "ActivityRegistry", "ProgressMonitor",
    "ProgressRegistry", "QueryStats", "SessionStats", "StatCounters",
    "TenantStats", "extract_tenants", "fingerprint",
]
