"""Cluster stat counters — citus_stat_counters analogue.

The reference keeps lock-free per-backend counter slots in shared memory,
aggregated into a per-database hash when backends exit
(/root/reference/src/backend/distributed/stats/stat_counters.c, README
§"stat counters").  Here the slot design maps to threads: each thread
increments its private slot without locking; snapshots sum across slots.
Slots are kept for the registry's lifetime (sessions are not expected to
churn thousands of threads).
"""

from __future__ import annotations

import threading
from collections import defaultdict

# counter names (the reference's are connection/query-execution oriented;
# ours mirror the TPU execution paths)
QUERIES_SINGLE_SHARD = "queries_single_shard"
QUERIES_MULTI_SHARD = "queries_multi_shard"
QUERIES_REPARTITION = "queries_repartition"
QUERIES_FAST_PATH = "queries_fast_path"
POINT_INDEX_LOOKUPS = "point_index_lookups"
SUBPLANS_EXECUTED = "subplans_executed"
ROWS_INGESTED = "rows_ingested"
ROWS_RETURNED = "rows_returned"
DML_UPDATE = "dml_update_count"
DML_DELETE = "dml_delete_count"
DML_MERGE = "dml_merge_count"
DDL_COMMANDS = "ddl_commands"
CAPACITY_RETRIES = "capacity_retries"
DEVICE_ROWS_SCANNED = "device_rows_scanned"
INSERT_SELECT_PUSHDOWN = "insert_select_pushdown"
INSERT_SELECT_REPARTITION = "insert_select_repartition"
INSERT_SELECT_PULL = "insert_select_pull"
CHUNKS_SKIPPED = "chunks_skipped"
QUERIES_STREAMED = "queries_streamed"
# pipelined columnar scan (executor/scanpipe.py): chunk groups decoded
# ahead by the prefetch producer, consumer waits on an empty prefetch
# queue (pipeline underruns), bytes expanded by on-device decode
CHUNKS_PREFETCHED_TOTAL = "chunks_prefetched_total"
PREFETCH_STALLS_TOTAL = "prefetch_stalls_total"
DEVICE_DECODED_BYTES_TOTAL = "device_decoded_bytes_total"
# statements whose plan executed the bucketed dense-grid group-by
# (ops/groupby.py) instead of the sort path
GROUPBY_BUCKETED_TOTAL = "groupby_bucketed_total"
# static all_to_all shuffle buffer volume the executed plans moved over
# the mesh (per-device capacity × devices² × row width, summed over the
# plan's repartition stages and every stream batch) — the EXPLAIN
# ANALYZE Mesh: line and bench_multichip.py read the per-statement
# delta to show what cross-device scaling actually costs
SHUFFLE_BYTES_TOTAL = "shuffle_bytes_total"
# resilient statement execution (session retry loop / deadline seams)
RETRIES_TOTAL = "retries_total"
FAILOVERS_TOTAL = "failovers_total"
TIMEOUTS_TOTAL = "timeouts_total"
QUERIES_CANCELED = "queries_canceled"
FAULTS_INJECTED_TOTAL = "faults_injected_total"
# mesh fault tolerance (session mesh-degrade path): devices observed
# lost, successful shrink-and-failover passes, and statements that
# ultimately ANSWERED because a failover rescued them (the
# kill-to-first-answer numerator bench_multichip's device_loss
# scenario publishes)
DEVICE_LOST_TOTAL = "device_lost_total"
MESH_FAILOVERS_TOTAL = "mesh_failovers_total"
QUERIES_RESCUED_TOTAL = "queries_rescued_total"
# workload manager (wlm/manager.py admission gate)
WLM_ADMITTED_TOTAL = "wlm_admitted_total"
WLM_QUEUED_TOTAL = "wlm_queued_total"
WLM_SHED_TOTAL = "wlm_shed_total"
WLM_QUEUE_WAIT_MS = "wlm_queue_wait_ms"
# serving layer (serving/ — cross-session micro-batcher + CDC-
# invalidated result cache; requester-side folds, the shared-layer
# totals live on the batcher/cache and surface via citus_stat_serving)
SERVING_BATCHED_LOOKUPS_TOTAL = "serving_batched_lookups_total"
SERVING_BATCH_DISPATCH_TOTAL = "serving_batch_dispatch_total"
SERVING_CACHE_HITS_TOTAL = "serving_cache_hits_total"
SERVING_CACHE_MISSES_TOTAL = "serving_cache_misses_total"
SERVING_CACHE_INVALIDATIONS_TOTAL = "serving_cache_invalidations_total"
# persistent executable cache + single-flight compile dedup + warm-
# before-admit (executor/execcache.py): disk adoptions vs cold misses
# vs detected-rot rejects, compiles saved by following another
# session's in-flight compile, and executables pre-adopted by the
# warmup phase before admission opened
EXEC_CACHE_HITS_TOTAL = "exec_cache_hits_total"
EXEC_CACHE_MISSES_TOTAL = "exec_cache_misses_total"
EXEC_CACHE_REJECTS_TOTAL = "exec_cache_rejects_total"
COMPILES_DEDUPED_TOTAL = "compiles_deduped_total"
WARMUP_COMPILES_TOTAL = "warmup_compiles_total"
# device-memory governance (executor/hbm.py accountant + the OOM
# degradation ladder in executor/runner.py degrade_for_oom)
OOM_EVENTS_TOTAL = "oom_events_total"
CACHE_EVICTIONS_TOTAL = "cache_evictions_total"
STREAM_BATCH_SHRINKS_TOTAL = "stream_batch_shrinks_total"
SPILL_PASSES_TOTAL = "spill_passes_total"
# storage integrity (storage/integrity.py read-path accounting folded
# in per statement; scrub counters from operations/scrubber.py)
# replication (replication/ — CDC log shipping leader→followers):
# batches staged by ship() / rolled in by apply_pending(), followers
# promoted to leader, zombie-leader ships rejected by epoch fencing,
# and the follower staleness gate's cumulative observed lag in lsns
# (the wlm_queue_wait_ms idiom: a lag-sum sample per staleness check —
# divide by checks for an average; the live per-follower lag is
# citus_stat_replication's column)
LOG_BATCHES_SHIPPED_TOTAL = "log_batches_shipped_total"
LOG_BATCHES_APPLIED_TOTAL = "log_batches_applied_total"
REPLICAS_PROMOTED_TOTAL = "replicas_promoted_total"
REPLICATION_FENCED_TOTAL = "replication_fenced_total"
REPLICA_LAG_LSN = "replica_lag_lsn"
STRIPES_VERIFIED_TOTAL = "stripes_verified_total"
CORRUPTION_DETECTED_TOTAL = "corruption_detected_total"
READ_REPAIRS_TOTAL = "read_repairs_total"
SCRUB_RUNS_TOTAL = "scrub_runs_total"
SCRUB_REPAIRS_TOTAL = "scrub_repairs_total"

ALL_COUNTERS = [
    QUERIES_SINGLE_SHARD, QUERIES_MULTI_SHARD, QUERIES_REPARTITION,
    QUERIES_FAST_PATH, POINT_INDEX_LOOKUPS,
    SUBPLANS_EXECUTED, ROWS_INGESTED, ROWS_RETURNED,
    DML_UPDATE, DML_DELETE, DML_MERGE, DDL_COMMANDS,
    CAPACITY_RETRIES, DEVICE_ROWS_SCANNED,
    INSERT_SELECT_PUSHDOWN, INSERT_SELECT_REPARTITION, INSERT_SELECT_PULL,
    CHUNKS_SKIPPED, QUERIES_STREAMED, GROUPBY_BUCKETED_TOTAL,
    SHUFFLE_BYTES_TOTAL,
    CHUNKS_PREFETCHED_TOTAL, PREFETCH_STALLS_TOTAL,
    DEVICE_DECODED_BYTES_TOTAL,
    RETRIES_TOTAL, FAILOVERS_TOTAL, TIMEOUTS_TOTAL, QUERIES_CANCELED,
    FAULTS_INJECTED_TOTAL,
    DEVICE_LOST_TOTAL, MESH_FAILOVERS_TOTAL, QUERIES_RESCUED_TOTAL,
    WLM_ADMITTED_TOTAL, WLM_QUEUED_TOTAL, WLM_SHED_TOTAL,
    WLM_QUEUE_WAIT_MS,
    SERVING_BATCHED_LOOKUPS_TOTAL, SERVING_BATCH_DISPATCH_TOTAL,
    SERVING_CACHE_HITS_TOTAL, SERVING_CACHE_MISSES_TOTAL,
    SERVING_CACHE_INVALIDATIONS_TOTAL,
    EXEC_CACHE_HITS_TOTAL, EXEC_CACHE_MISSES_TOTAL,
    EXEC_CACHE_REJECTS_TOTAL, COMPILES_DEDUPED_TOTAL,
    WARMUP_COMPILES_TOTAL,
    OOM_EVENTS_TOTAL, CACHE_EVICTIONS_TOTAL,
    STREAM_BATCH_SHRINKS_TOTAL, SPILL_PASSES_TOTAL,
    LOG_BATCHES_SHIPPED_TOTAL, LOG_BATCHES_APPLIED_TOTAL,
    REPLICAS_PROMOTED_TOTAL, REPLICATION_FENCED_TOTAL, REPLICA_LAG_LSN,
    STRIPES_VERIFIED_TOTAL, CORRUPTION_DETECTED_TOTAL,
    READ_REPAIRS_TOTAL, SCRUB_RUNS_TOTAL, SCRUB_REPAIRS_TOTAL,
]


class StatCounters:
    def __init__(self):
        self._local = threading.local()
        self._slots_lock = threading.Lock()
        self._slots: list[defaultdict] = []

    def _slot(self) -> defaultdict:
        slot = getattr(self._local, "slot", None)
        if slot is None:
            slot = defaultdict(int)
            self._local.slot = slot
            with self._slots_lock:
                self._slots.append(slot)
        return slot

    def increment(self, name: str, by: int = 1) -> None:
        self._slot()[name] += by

    def snapshot(self) -> dict[str, int]:
        with self._slots_lock:
            slots = list(self._slots)
        out: dict[str, int] = {}
        for slot in slots:
            for k, v in slot.items():
                out[k] = out.get(k, 0) + v
        return {k: out.get(k, 0) for k in ALL_COUNTERS}

    def reset(self) -> None:
        with self._slots_lock:
            for slot in self._slots:
                slot.clear()
