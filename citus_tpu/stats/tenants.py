"""Per-tenant statistics — citus_stat_tenants analogue
(/root/reference/src/backend/distributed/stats/stat_tenants.c): queries
whose filters pin the distribution column to a constant are attributed to
that tenant; per-tenant counts and time accumulate with a bounded table
evicting the coldest tenants."""

from __future__ import annotations

import threading
from dataclasses import dataclass

from ..catalog import Catalog, DistributionMethod
from ..sql import ast


@dataclass
class TenantStat:
    tenant: str
    table: str
    query_count: int = 0
    total_time_ms: float = 0.0
    # recency stamp (a per-registry logical clock, bumped on every
    # record): the eviction tie-breaker — "coldest" means fewest
    # queries AND least-recently seen
    last_seen: int = 0


class TenantStats:
    def __init__(self, limit: int = 100):
        self.limit = limit
        self._lock = threading.Lock()
        self._stats: dict[tuple[str, str], TenantStat] = {}
        self._clock = 0

    def record(self, table: str, tenant, elapsed_ms: float) -> None:
        key = (table, str(tenant))
        with self._lock:
            self._clock += 1
            st = self._stats.get(key)
            if st is None:
                if len(self._stats) >= self.limit:
                    # deterministic coldest-first eviction: fewest
                    # queries, then least-recently seen, then key order
                    # (the old min() over query_count alone broke ties
                    # by dict insertion order — which tenant survived
                    # depended on arrival history, not coldness)
                    victim = min(
                        self._stats,
                        key=lambda k: (self._stats[k].query_count,
                                       self._stats[k].last_seen, k))
                    del self._stats[victim]
                st = self._stats[key] = TenantStat(str(tenant), table)
            st.query_count += 1
            st.last_seen = self._clock
            st.total_time_ms += elapsed_ms

    def entries(self) -> list[TenantStat]:
        with self._lock:
            # hottest first; deterministic order under ties
            return sorted(self._stats.values(),
                          key=lambda s: (-s.query_count, s.table,
                                         s.tenant))

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


def extract_tenants(stmt: ast.Statement,
                    catalog: Catalog) -> list[tuple[str, object]]:
    """(table, tenant_key) pairs a statement pins via `distcol = const`
    equality — the reference's AttributeTask-style partition-key capture."""
    from ..executor.host_eval import split_conjuncts

    refs: list[tuple[str, str | None]] = []  # (table, alias)
    where = None
    if isinstance(stmt, ast.Select):
        for fi in stmt.from_items:
            _collect_tables(fi, refs)
        where = stmt.where
    elif isinstance(stmt, (ast.Update, ast.Delete)):
        refs = [(stmt.table, stmt.alias)]
        where = stmt.where
    if not refs or where is None:
        return []
    # (qualifier-or-None, dist column) → table; qualifier-aware so
    # `a.customer_id = 7` never credits a different table's tenant
    dist: list[tuple[str, str, set[str]]] = []  # (table, distcol, quals)
    for t, alias in refs:
        if not catalog.has_table(t):
            continue
        meta = catalog.table(t)
        if meta.method == DistributionMethod.HASH:
            dist.append((t, meta.distribution_column,
                         {alias or t, t} if alias else {t}))
    if not dist:
        return []
    out = []
    for c in split_conjuncts(where):
        if (isinstance(c, ast.BinaryOp) and c.op == "="):
            ref, lit = None, None
            if isinstance(c.left, ast.ColumnRef) and \
                    isinstance(c.right, ast.Literal):
                ref, lit = c.left, c.right
            elif isinstance(c.right, ast.ColumnRef) and \
                    isinstance(c.left, ast.Literal):
                ref, lit = c.right, c.left
            if ref is None or lit.value is None:
                continue
            candidates = [
                (t, col) for t, col, quals in dist
                if col == ref.name
                and (ref.table in quals if ref.table else True)]
            # an unqualified match must be unambiguous across tables
            if len(candidates) == 1:
                out.append((candidates[0][0], lit.value))
    return out


def _collect_tables(fi: ast.FromItem,
                    out: list[tuple[str, str | None]]) -> None:
    if isinstance(fi, ast.TableRef):
        out.append((fi.name, fi.alias))
    elif isinstance(fi, ast.Join):
        _collect_tables(fi.left, out)
        _collect_tables(fi.right, out)
