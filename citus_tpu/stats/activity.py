"""Active-statement tracking — citus_stat_activity / global PID analogue.

The reference assigns every backend a globally unique gpid (nodeId ·
10^10 + pid, /root/reference/src/backend/distributed/transaction/
backend_data.c) and unions per-node pg_stat_activity into cluster views.
Single-controller equivalent: session-scoped gpids + a live registry of
executing statements."""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from contextlib import contextmanager

GPID_NODE_FACTOR = 10_000_000_000  # reference encoding: nodeid*10^10 + pid

_PID = os.getpid()  # per-statement getpid() syscalls add up at high QPS


def make_gpid(node_id: int, pid: int | None = None) -> int:
    return node_id * GPID_NODE_FACTOR + (pid if pid is not None
                                         else _PID)


@dataclass
class ActivityEntry:
    gpid: int
    query: str
    state: str = "active"
    started_at: float = field(default_factory=time.time)
    # statement-retry-loop attempts for the in-flight statement (the
    # resilient executor bumps this so citus_stat_activity shows which
    # live statements are riding out transient failures)
    retries: int = 0
    # stripe reads this statement transparently served from a replica
    # copy after a checksum failure (storage/integrity.py fold)
    read_repairs: int = 0
    # (plan_hits, plan_misses, feed_hits, feed_misses) snapshot of the
    # session executor's cache counters when the statement started;
    # citus_stat_activity subtracts it from the live totals to show
    # the in-flight statement's own cache activity
    cache_base: tuple | None = None
    # workload-manager state of the in-flight statement:
    # queued (waiting for an admission slot) | admitted (slot granted,
    # not yet executing) | running (executing, or exempt from the gate)
    wait_state: str = "running"
    # time the in-flight statement spent in the admission queue
    queued_ms: float = 0.0


class ActivityRegistry:
    def __init__(self, node_id: int = 0):
        self.node_id = node_id
        self._lock = threading.Lock()
        self._seq = 0
        self._active: dict[int, ActivityEntry] = {}

    @contextmanager
    def track(self, query: str):
        with self._lock:
            self._seq += 1
            key = self._seq
            entry = ActivityEntry(make_gpid(self.node_id), query[:1024])
            self._active[key] = entry
        try:
            yield entry
        finally:
            with self._lock:
                self._active.pop(key, None)

    def entries(self) -> list[ActivityEntry]:
        with self._lock:
            return list(self._active.values())
