"""Per-statement execution statistics — citus_stat_statements analogue
(/root/reference/src/backend/distributed/stats/query_stats.c): statements
are fingerprinted by their normalized text (literals → '?'), keyed like
queryId, and accumulate calls / time / rows.  Entry count is bounded; the
least-called entries are evicted (the reference's pg_stat_statements-style
dealloc)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..sql.lexer import tokenize


_fp_memo: dict[str, str] = {}


def fingerprint(sql: str) -> str:
    """Normalized statement text: literals replaced with '?'.
    Memoized — the serving workload records the same hot texts at high
    QPS, and re-lexing each one is pure overhead (dict ops only,
    GIL-atomic; reset wholesale when full)."""
    fp = _fp_memo.get(sql)
    if fp is not None:
        return fp
    try:
        toks = tokenize(sql)
    except Exception:
        return " ".join(sql.split())
    out = []
    for t in toks:
        if t.kind in ("number", "string"):
            out.append("?")
        elif t.kind == "eof":
            break
        else:
            out.append(t.value)
    fp = " ".join(out)
    if len(_fp_memo) >= 4096:
        _fp_memo.clear()
    _fp_memo[sql] = fp
    return fp


@dataclass
class QueryStat:
    query: str
    calls: int = 0
    total_time_ms: float = 0.0
    min_time_ms: float = field(default=float("inf"))
    max_time_ms: float = 0.0
    rows: int = 0
    # executor attribution, like the reference's citus_stat_statements
    # executor column (adaptive / router / insert-select ...)
    executors: dict = field(default_factory=dict)


class QueryStats:
    def __init__(self, max_entries: int = 1000):
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._stats: dict[str, QueryStat] = {}

    def record(self, sql: str, elapsed_ms: float, rows: int,
               executor: str = "adaptive") -> None:
        fp = fingerprint(sql)
        with self._lock:
            st = self._stats.get(fp)
            if st is None:
                if len(self._stats) >= self.max_entries:
                    victim = min(self._stats, key=lambda k:
                                 self._stats[k].calls)
                    del self._stats[victim]
                st = self._stats[fp] = QueryStat(query=fp)
            st.calls += 1
            st.total_time_ms += elapsed_ms
            st.min_time_ms = min(st.min_time_ms, elapsed_ms)
            st.max_time_ms = max(st.max_time_ms, elapsed_ms)
            st.rows += rows
            st.executors[executor] = st.executors.get(executor, 0) + 1

    def entries(self) -> list[QueryStat]:
        with self._lock:
            return sorted(self._stats.values(),
                          key=lambda s: -s.total_time_ms)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
