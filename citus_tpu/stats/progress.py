"""Progress monitors for long-running operations — the reference backs
these with dynamic shared memory segments other backends can scan
(/root/reference/src/backend/distributed/progress/multi_progress.c:41
CreateProgressMonitor); here a process-wide registry serves the same
`get_rebalance_progress()`-style introspection."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class ProgressMonitor:
    operation: str          # e.g. "rebalance", "shard_move", "shard_split"
    target: str             # table / shard being operated on
    total_steps: int
    done_steps: int = 0
    detail: str = ""
    started_at: float = field(default_factory=time.time)
    finished: bool = False

    def advance(self, steps: int = 1, detail: str | None = None) -> None:
        self.done_steps += steps
        if detail is not None:
            self.detail = detail

    def finish(self) -> None:
        self.done_steps = self.total_steps
        self.finished = True


class ProgressRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._monitors: list[ProgressMonitor] = []

    def create(self, operation: str, target: str,
               total_steps: int) -> ProgressMonitor:
        mon = ProgressMonitor(operation, target, total_steps)
        with self._lock:
            # keep a short history; drop old finished monitors
            self._monitors = [m for m in self._monitors
                              if not m.finished][-50:] + [mon]
        return mon

    def active(self) -> list[ProgressMonitor]:
        with self._lock:
            return [m for m in self._monitors if not m.finished]

    def all(self) -> list[ProgressMonitor]:
        with self._lock:
            return list(self._monitors)
