"""Always-on span flight recorder: per-statement span trees with
cross-thread context propagation, per-statement-class DDSketch latency
histograms, a bounded in-memory ring of recent traces, and a slow-query
log persisted through the durable-write seam.

The counters/EXPLAIN/stat-UDF surface built by earlier PRs answers
"how much" (rows, bytes, retries); nothing answered "where did the
time go" without hand-rolled timers (bench_sf100.py's phase timers,
EXPLAIN ANALYZE's single wall clock).  This module is the timing spine
connecting them — the citus_stat_statements / EXPLAIN ANALYZE pair of
the reference, grown into a flight recorder:

* **Spans** — every statement produces a tree of named spans covering
  parse → WLM queue wait → execution attempts → plan → compile (cache
  hit vs XLA compile) → feed build (the scan pipeline's prefetch /
  wire-encode / transfer / device-decode legs, per column, carried
  across the producer thread) → mesh dispatch/fetch → host combine →
  serving (door-hold, follower wait, batch probe, result-cache
  lookup) → retry backoff and OOM/mesh degradation rungs.  Span names
  live in ``SPAN_NAMES`` (the EXPLAIN_TAGS pattern) so graftlint's
  span-registry rule holds both directions.
* **Context propagation** — the active trace rides a thread-local;
  worker threads the executor already spawns (the scanpipe prefetch
  producer, the stream batch producer) adopt the statement's context
  via :func:`capture_context` / :func:`adopt_context`, which
  force-closes anything the thread leaves open (no span leaks — the
  chaos soak asserts :func:`open_span_count` == 0 post-soak).
* **Histograms** — statement wall times fold into per-statement-class
  DDSketch bucket counts (ops/sketches.py, α ≈ 1% relative error), so
  ``citus_stat_latency()`` reports honest p50/p95/p99 without storing
  raw samples.
* **Ring + slow log** — the last `trace_ring_statements` traces stay
  in memory (span count per trace capped, so an 8-session hammer
  cannot grow memory without bound); statements slower than
  `trace_slow_statement_ms` persist their full tree as JSON through
  utils/io (newest ``SLOW_TRACE_KEEP`` kept).  ``python -m
  citus_tpu.stats.trace_export`` renders any persisted (or in-ring)
  trace as Chrome-trace/Perfetto JSON.

Overhead: an unarmed `trace_span` is one thread-local read and a None
check; an active span is two `perf_counter` calls plus one small
object.  bench.py's serving scenario A/Bs `trace_enabled` on/off and
stamps the measured overhead (PERF_NOTES round 16); the
`trace_sample_every` knob degrades full-tree recording to 1-in-N
statements (histograms always update) if that overhead ever matters
on a workload.
"""

from __future__ import annotations

import os
import threading
import time

# -- span-name registry ------------------------------------------------------
# Every named span a statement can record.  Render/record sites call
# trace_span("…") / span_name("…") with the literal, so graftlint's
# span-registry rule can hold both directions (the EXPLAIN_TAGS
# contract: a name used in source must be declared here, a declared
# name must have a live record site).
SPAN_NAMES: dict[str, str] = {
    "statement": "root span: one executed statement, wall-clock",
    "parse": "lexer+parser (hot-statement memo makes repeats ~free)",
    "queue": "WLM admission: classification + slot/HBM queue wait",
    "execute": "one execution attempt under the resilience envelope",
    "plan": "recursive planning + bind + distributed planning",
    "feed": "device feed build (eager, pipelined or per-batch)",
    "compile": "plan-cache resolution (meta cache=hit|miss; a miss "
               "traces + XLA-compiles the mesh program)",
    "compile.cache_load": "persistent executable cache probe: meta + "
                          "CRC verify + AOT deserialize on a hit",
    "compile.single_flight_wait": "follower waiting on another "
                                  "session's in-flight compile of the "
                                  "same shape (compile dedup)",
    "wlm.warmup": "warm-before-admit: one persisted executable "
                  "adopted into the plan cache pre-admission",
    "mesh.dispatch": "compiled program dispatch + on-mesh collectives",
    "mesh.fetch": "device→host pull of outputs + overflow counters",
    "combine": "host-side combine (having/order/limit/decode)",
    "fastpath": "single-shard host execution (router fast path)",
    "scan.prefetch": "scanpipe: stripe read + host decode (producer)",
    "scan.wire_encode": "scanpipe: host wire-encode for device decode",
    "scan.transfer": "scanpipe: accounted host→device placement",
    "scan.device_decode": "scanpipe: on-mesh expand of a wire payload",
    "stream.batch": "stream path: one batched execution round",
    "stream.decode": "stream path: stripe pull + decode for a batch",
    "stream.transfer": "stream path: batch host→device placement",
    "serving.cache_lookup": "result-cache key build + lookup",
    "serving.door_hold": "micro-batch leader holding the door open",
    "serving.batch_wait": "follower waiting on a batch leader",
    "serving.batch_probe": "leader executing one coalesced batch",
    "retry.backoff": "resilience envelope backoff sleep",
    "oom.degrade": "OOM ladder rung application",
    "mesh.degrade": "mesh shrink + failover after device loss",
    "replication.ship": "leader→follower batch staging (file diff + "
                        "journal segment + batch.json commit)",
    "replication.apply": "follower roll-forward of committed batches "
                         "behind the apply cursor",
    "replication.promote": "follower→leader promotion: roll forward, "
                           "fence, epoch bump, role flip",
}

# phase attribution for the EXPLAIN ANALYZE Timing line and the
# sum-to-wall contract: walking the tree, a span whose name maps here
# contributes its full duration to the phase and is NOT descended into
# (nested detail — scan.* under feed, serving.* under fastpath — stays
# in the trace but never double-counts a phase)
PHASE_OF: dict[str, str] = {
    "parse": "parse",
    "queue": "queue",
    "plan": "plan",
    "feed": "feed",
    "compile": "compile",
    "compile.cache_load": "compile",
    "compile.single_flight_wait": "compile",
    "mesh.dispatch": "device",
    "mesh.fetch": "device",
    "combine": "combine",
    "fastpath": "fastpath",
    "serving.cache_lookup": "serving",
    "serving.door_hold": "serving",
    "serving.batch_wait": "serving",
    "serving.batch_probe": "serving",
    "retry.backoff": "retry",
    "oom.degrade": "degrade",
    "mesh.degrade": "degrade",
    "replication.ship": "replication",
    "replication.apply": "replication",
    "replication.promote": "replication",
}

PHASE_ORDER = ("parse", "queue", "plan", "feed", "compile", "device",
               "combine", "fastpath", "serving", "retry", "degrade",
               "replication")

# spans kept per trace: a runaway statement (thousands of stripes ×
# columns) truncates instead of growing the ring without bound
MAX_SPANS_PER_TRACE = 8192
SLOW_TRACE_KEEP = 32
SLOW_TRACE_DIR = "slow_traces"
# statement text / class stored on traces and histogram keys is
# clamped: a bulk INSERT's normalized text is megabytes of "( ?, ?, ?"
# — the ring, the slow log and citus_stat_latency() need the head,
# not the literal list (prefixes stay stable per class, so clamped
# keys still aggregate correctly)
MAX_SQL_CHARS = 400


def clamp_sql(text: str) -> str:
    """The clamped form under which a statement appears in traces and
    histogram keys (bench drivers compare against it when checking a
    trace belongs to the statement they measured)."""
    if len(text) <= MAX_SQL_CHARS:
        return text
    return text[:MAX_SQL_CHARS] + " …"


_clamp = clamp_sql


def span_name(name: str) -> str:
    """Return the name verbatim; KeyError on an unregistered span (the
    runtime backstop for the static span-registry rule)."""
    SPAN_NAMES[name]
    return name


class Span:
    """One timed region.  `children` is appended from the owning thread
    (and, under `feed`, from an adopting producer thread) — list.append
    is GIL-atomic, and readers only walk finished traces or closed
    children, so no lock rides the hot path.

    The span is its OWN context manager (`trace_span` opens it and
    pushes it; `__exit__` closes and pops): the serving scenario runs
    thousands of statements per second, so one object per span is the
    budget — a separate handle object measurably costs QPS."""

    __slots__ = ("name", "t0", "t1", "tid", "meta", "children",
                 "_stk", "_tr")

    def __init__(self, name: str, t0: float, tid: int,
                 meta: dict | None = None, stk: list | None = None,
                 tr: "Trace | None" = None):
        self.name = name
        self.t0 = t0
        self.t1 = None
        self.tid = tid
        self.meta = meta
        # eager list: a lazy first-child init would race between the
        # statement thread and an adopted producer both appending
        # under the feed span (list.append itself is GIL-atomic)
        self.children: list[Span] = []
        self._stk = stk
        self._tr = tr

    def duration(self) -> float:
        return (self.t1 if self.t1 is not None
                else time.perf_counter()) - self.t0

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb, _pc=time.perf_counter):
        self.t1 = _pc()
        if exc_type is not None:
            m = self.meta or {}
            m["error"] = exc_type.__name__
            self.meta = m
        stack = self._stk
        # pop back to (and including) this span; anything above it was
        # opened inside the block and never closed — count the leak so
        # tests can flag it, and never corrupt the stack
        while stack and stack[-1] is not self:
            stray = stack.pop()
            if stray.t1 is None:
                stray.t1 = self.t1
            if self._tr is not None:
                self._tr.leaked += 1
        if stack:
            stack.pop()
        return False


class Trace:
    """One statement's span tree plus bookkeeping flags."""

    __slots__ = ("sql", "cls", "root", "spans", "truncated", "leaked",
                 "wall_ms", "error")

    def __init__(self, sql: str, root: Span):
        self.sql = sql
        self.cls: str | None = None
        self.root = root
        # `spans`/`leaked` are bumped with plain `+=` from the
        # statement thread AND adopted producer threads: a lost
        # increment under that race only softens the (8192-span)
        # truncation backstop by a few spans — to_dict() recounts
        # exactly from the tree, so the published number is never the
        # racy one
        self.spans = 1
        self.truncated = False
        self.leaked = 0
        self.wall_ms: float | None = None
        self.error: str | None = None

    def to_dict(self) -> dict:
        base = self.root.t0
        exact = 0

        def span_dict(s: Span) -> dict:
            nonlocal exact
            exact += 1
            t1 = s.t1 if s.t1 is not None else s.t0
            d = {"name": s.name,
                 "t0_ms": round((s.t0 - base) * 1000.0, 4),
                 "dur_ms": round((t1 - s.t0) * 1000.0, 4),
                 "tid": s.tid}
            if s.meta:
                d["meta"] = dict(s.meta)
            kids = sorted(s.children, key=lambda c: c.t0)
            if kids:
                d["children"] = [span_dict(c) for c in kids]
            return d

        root = span_dict(self.root)
        return {"schema": 1, "sql": self.sql, "class": self.cls,
                "wall_ms": self.wall_ms, "spans": exact,
                "truncated": self.truncated, "leaked": self.leaked,
                "error": self.error, "root": root}


# -- thread-local context ----------------------------------------------------
_tls = threading.local()
# tid → open-span stack, registered on a thread's first span so
# open_span_count() can see every thread (the StatCounters slot
# pattern); dead threads' entries are pruned on new registrations
_stacks_lock = threading.Lock()
_stacks: dict[int, list] = {}


def _tls_state():
    st = getattr(_tls, "state", None)
    if st is None:
        st = _tls.state = {"trace": None, "stack": []}
        tid = threading.get_ident()
        with _stacks_lock:
            live = {t.ident for t in threading.enumerate()}
            for dead in [t for t in _stacks if t not in live]:
                del _stacks[dead]
            _stacks[tid] = st["stack"]
    return st


def open_span_count() -> int:
    """Spans currently open across EVERY thread that ever recorded one
    — 0 whenever no statement is in flight (the post-soak no-leak
    assert, like the prefetch-charge ledger)."""
    with _stacks_lock:
        stacks = list(_stacks.values())
    return sum(len(s) for s in stacks)


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()


def trace_span(name: str, _pc=time.perf_counter,
               _ident=threading.get_ident, **meta):
    """Open a named span under the current statement trace; a cheap
    no-op when no trace is active on this thread (tracing off, sampled
    out, or a non-statement thread that never adopted a context).
    The span starts NOW (at the call), is pushed immediately, and the
    `with` block's exit closes it."""
    st = getattr(_tls, "state", None)
    if st is None or st["trace"] is None or not st["stack"]:
        return _NOOP
    tr = st["trace"]
    if tr.spans >= MAX_SPANS_PER_TRACE:
        tr.truncated = True
        return _NOOP
    SPAN_NAMES[name]  # runtime backstop of the span-registry rule
    stack = st["stack"]
    sp = Span(name, _pc(), _ident(), meta or None, stack, tr)
    tr.spans += 1
    stack[-1].children.append(sp)
    stack.append(sp)
    return sp


def capture_context():
    """Token for handing the current statement's trace to a worker
    thread (None when nothing is being traced — adopt_context then
    no-ops)."""
    st = getattr(_tls, "state", None)
    if st is None or st["trace"] is None or not st["stack"]:
        return None
    return (st["trace"], st["stack"][-1])


class _AdoptCtx:
    __slots__ = ("token", "prev")

    def __init__(self, token):
        self.token = token
        self.prev = None

    def __enter__(self):
        if self.token is None:
            return None
        trace, parent = self.token
        st = _tls_state()
        self.prev = (st["trace"], list(st["stack"]))
        st["trace"] = trace
        st["stack"][:] = [parent]
        return trace

    def __exit__(self, exc_type, exc, tb):
        if self.token is None:
            return False
        st = _tls_state()
        trace = self.token[0]
        # the adopting thread must close everything it opened: spans
        # still above the borrowed parent are leaks — close them with
        # an honest end time and count them
        now = time.perf_counter()
        while len(st["stack"]) > 1:
            sp = st["stack"].pop()
            if sp.t1 is None:
                sp.t1 = now
            trace.leaked += 1
        prev_trace, prev_stack = self.prev
        st["trace"] = prev_trace
        st["stack"][:] = prev_stack
        return False


def adopt_context(token):
    """Adopt a captured statement context on a worker thread for the
    duration of the block: spans recorded inside nest under the span
    that was open at capture time.  Leak-proof by construction — on
    exit anything the thread left open is force-closed and counted."""
    return _AdoptCtx(token)


# -- per-class latency histograms (DDSketch) --------------------------------
class ClassHist:
    __slots__ = ("calls", "sum_ms", "max_ms", "buckets")

    def __init__(self):
        self.calls = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0
        self.buckets: dict[int, int] = {}

    def record(self, ms: float) -> None:
        from ..ops.sketches import dd_bucket_scalar

        key = dd_bucket_scalar(float(ms))
        self.calls += 1
        self.sum_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms
        self.buckets[key] = self.buckets.get(key, 0) + 1

    @staticmethod
    def quantile_of(buckets: dict[int, int], q: float) -> float | None:
        """Quantile from a bucket-dict SNAPSHOT — callers must pass a
        copy taken under the recorder lock (iterating the live dict
        races concurrent record() calls: torn keys/counts pairs)."""
        import numpy as np

        from ..ops.sketches import dd_quantile

        if not buckets:
            return None
        keys = np.fromiter(buckets.keys(), dtype=np.int64)
        counts = np.fromiter(buckets.values(), dtype=np.int64)
        return dd_quantile(keys, counts, q)


class _StatementHandle:
    """What begin() returns and end() consumes: the wall clock always,
    the span tree only when this statement samples in."""

    __slots__ = ("sql", "t0", "trace", "nested")

    def __init__(self, sql, t0, trace, nested=False):
        self.sql = sql
        self.t0 = t0
        self.trace = trace
        self.nested = nested


class TraceRecorder:
    """ONE per Session (it rides SessionStats).  Thread-safe: concurrent
    execute() callers each trace their own statement on their own
    thread; the ring/histograms fold under a lock once per statement."""

    def __init__(self, data_dir: str | None = None, settings=None):
        self.data_dir = data_dir
        self.settings = settings
        import itertools

        self._mu = threading.Lock()
        self._ring: list[Trace] = []
        self._hists: dict[str, ClassHist] = {}
        self._seq = itertools.count(1)
        # separate tick stream for the fast-class auto-degrade: fed
        # from _seq, an even trace_sample_every would alias the two
        # modulos (survivors of the first check always land on the
        # same residue at the second) and fast classes would never
        # record a tree at all
        self._fast_seq = itertools.count(1)
        self._slow_seq = 0
        self.max_hist_classes = 512
        # settings-profile memo keyed by Settings.version: four
        # registry lookups per statement are measurable at serving QPS
        self._cfg_memo = None

    def _cfg(self):
        """(enabled, sample_every, ring_keep, slow_ms, fast_ms,
        fast_every) — memoized per settings version (a benign race
        installs the same tuple)."""
        settings = self.settings
        if settings is None:
            return (True, 1, 128, 0, 0.0, 1)
        c = self._cfg_memo
        if c is not None and c[0] == settings.version:
            return c[1]
        vals = (bool(settings.get("trace_enabled")),
                max(1, int(settings.get("trace_sample_every"))),
                max(1, int(settings.get("trace_ring_statements"))),
                settings.get("trace_slow_statement_ms"),
                float(settings.get("trace_fast_statement_ms")),
                max(1, int(settings.get("trace_fast_sample_every"))))
        self._cfg_memo = (settings.version, vals)
        return vals

    # -- statement lifecycle ------------------------------------------------
    def begin(self, sql: str, t0: float | None = None) -> _StatementHandle:
        t0 = time.perf_counter() if t0 is None else t0
        st = _tls_state()
        if st["trace"] is not None:
            # re-entrant execute on one thread (internal fallback
            # paths): never corrupt the outer statement's stack, and
            # record NOTHING for the inner statement — the outer
            # statement's wall already covers it, so a histogram entry
            # here would double-count the time
            return _StatementHandle(sql, t0, None, nested=True)
        enabled, every, _keep, _slow, fast_ms, fast_every = self._cfg()
        if not enabled:
            return _StatementHandle(sql, t0, None, nested=True)
        if every > 1 and next(self._seq) % every:
            return _StatementHandle(sql, t0, None)
        if fast_ms > 0.0 and fast_every > 1:
            # auto-degrade to sampling for PROVEN-fast statement
            # classes (the serving cache-hit hammer): a class whose
            # observed mean wall sits under the threshold after ≥8
            # calls records trees 1-in-N — span trees cost ~15 µs,
            # which is real money on a 0.3 ms statement and nothing on
            # the ≥2 ms statements attribution exists for.  Histograms
            # always update; cold/slow classes always record.  (Racy
            # dict/attr reads are fine: both sides are GIL-atomic and
            # a stale mean only shifts WHEN sampling engages.)
            from .query_stats import fingerprint

            h = self._hists.get(_clamp(fingerprint(sql)))
            if h is not None and h.calls >= 8 and \
                    h.sum_ms < fast_ms * h.calls and \
                    next(self._fast_seq) % fast_every:
                return _StatementHandle(sql, t0, None)
        root = Span(span_name("statement"), t0, threading.get_ident())
        trace = Trace(_clamp(sql), root)
        st["trace"] = trace
        st["stack"].append(root)
        return _StatementHandle(sql, t0, trace)

    def end(self, h: _StatementHandle, error: BaseException | None = None,
            ) -> Trace | None:
        t1 = time.perf_counter()
        wall_ms = (t1 - h.t0) * 1000.0
        trace = h.trace
        if trace is not None:
            st = _tls_state()
            root = trace.root
            # close anything the statement left open on this thread
            # (exception unwinding skips no __exit__, so normally only
            # the root is here)
            while st["stack"] and st["stack"][-1] is not root:
                sp = st["stack"].pop()
                if sp.t1 is None:
                    sp.t1 = t1
                trace.leaked += 1
            root.t1 = t1
            if st["stack"]:
                st["stack"].pop()
            st["trace"] = None
            trace.wall_ms = round(wall_ms, 4)
            if error is not None:
                trace.error = type(error).__name__
        if h.nested and trace is None:
            return None
        from .query_stats import fingerprint

        cls = _clamp(fingerprint(h.sql))
        if trace is not None:
            trace.cls = cls
        with self._mu:
            hist = self._hists.get(cls)
            if hist is None:
                if len(self._hists) >= self.max_hist_classes:
                    victim = min(self._hists,
                                 key=lambda k: self._hists[k].calls)
                    del self._hists[victim]
                hist = self._hists[cls] = ClassHist()
            hist.record(wall_ms)
            if trace is not None:
                self._ring.append(trace)
                keep = self._cfg()[2]
                if len(self._ring) > keep:
                    del self._ring[:len(self._ring) - keep]
        if trace is not None:
            slow_ms = self._cfg()[3]
            if slow_ms and wall_ms >= slow_ms and self.data_dir:
                try:
                    self._persist_slow(trace)
                except OSError:
                    pass  # a full/readonly disk must not fail the query
        return trace

    # -- slow-query log -----------------------------------------------------
    def _persist_slow(self, trace: Trace) -> None:
        from ..utils.io import atomic_write_json

        d = os.path.join(self.data_dir, SLOW_TRACE_DIR)
        os.makedirs(d, exist_ok=True)
        with self._mu:
            self._slow_seq += 1
            seq = self._slow_seq
        doc = trace.to_dict()
        doc["recorded_unix"] = time.time()
        fname = f"trace_{int(time.time() * 1000):015d}_{seq:04d}.json"
        atomic_write_json(os.path.join(d, fname), doc)
        # bound the log: keep the newest SLOW_TRACE_KEEP files
        names = sorted(n for n in os.listdir(d)
                       if n.startswith("trace_") and n.endswith(".json"))
        for stale in names[:-SLOW_TRACE_KEEP]:
            try:
                os.remove(os.path.join(d, stale))
            except OSError:
                pass  # raced with another session's prune

    # -- read side ----------------------------------------------------------
    def traces(self) -> list[Trace]:
        with self._mu:
            return list(self._ring)

    def last_trace(self) -> dict | None:
        """Newest completed trace as a dict (bench drivers re-derive
        their phase_*_seconds keys from this instead of hand timers)."""
        with self._mu:
            if not self._ring:
                return None
            return self._ring[-1].to_dict()

    def latency_rows(self) -> list[dict]:
        """citus_stat_latency() rows: per-class calls + DDSketch
        quantiles, busiest classes first.  Per-class state is COPIED
        under the lock; quantiles compute on the snapshots (the live
        bucket dicts mutate under concurrent end() calls)."""
        with self._mu:
            items = sorted(
                ((cls, h.calls, h.sum_ms, h.max_ms, dict(h.buckets))
                 for cls, h in self._hists.items()),
                key=lambda t: -t[2])
        rows = []
        qof = ClassHist.quantile_of
        for cls, calls, sum_ms, max_ms, buckets in items:
            rows.append({
                "statement_class": cls,
                "calls": calls,
                "mean_ms": round(sum_ms / calls, 3) if calls else 0,
                "p50_ms": _round_q(qof(buckets, 0.50)),
                "p95_ms": _round_q(qof(buckets, 0.95)),
                "p99_ms": _round_q(qof(buckets, 0.99)),
                "max_ms": round(max_ms, 3),
            })
        return rows

    def reset_latency(self) -> None:
        with self._mu:
            self._hists.clear()

    def ring_bytes(self) -> int:
        """Rough in-memory footprint of the ring (span count × a fixed
        per-span estimate) — the boundedness assert's measuring stick."""
        with self._mu:
            return sum(t.spans for t in self._ring) * 200


def _round_q(v):
    return None if v is None else round(float(v), 3)


# -- phase attribution -------------------------------------------------------
def phase_breakdown(root) -> dict[str, float]:
    """Coarse phase walls in SECONDS from a span tree (`root` is either
    a live Span or a to_dict() span dict).  A span whose name maps in
    PHASE_OF contributes its whole duration and is not descended into,
    so phases never double-count; "other" is the root wall minus every
    attributed phase (glue code, counter folds)."""
    phases = dict.fromkeys(PHASE_ORDER, 0.0)

    def dur_s(s) -> float:
        if isinstance(s, dict):
            return s.get("dur_ms", 0.0) / 1000.0
        return max(0.0, s.duration())

    def kids(s):
        if isinstance(s, dict):
            return s.get("children", ())
        return list(s.children)

    def name_of(s):
        return s["name"] if isinstance(s, dict) else s.name

    def walk(s):
        # an EXPLAIN ANALYZE reads the breakdown mid-statement: spans
        # still open (the in-flight "execute") are containers to
        # descend, never durations to attribute
        still_open = not isinstance(s, dict) and s.t1 is None
        ph = PHASE_OF.get(name_of(s))
        if ph is not None and not still_open:
            phases[ph] += dur_s(s)
            return
        for c in kids(s):
            walk(c)

    for c in kids(root):
        walk(c)
    total = dur_s(root)
    phases["total"] = total
    phases["other"] = max(0.0, total - sum(
        phases[p] for p in PHASE_ORDER))
    return phases


def span_seconds(root, *names: str) -> float:
    """Summed duration of every span named in `names` across the whole
    tree (dict or Span form) — the bench drivers' phase_*_seconds
    derivation."""
    want = set(names)
    out = 0.0

    def walk(s):
        nonlocal out
        if isinstance(s, dict):
            if s["name"] in want:
                out += s.get("dur_ms", 0.0) / 1000.0
            for c in s.get("children", ()):
                walk(c)
        else:
            if s.name in want and s.t1 is not None:
                out += s.duration()
            for c in list(s.children):
                walk(c)

    walk(root)
    return out


def current_root() -> Span | None:
    """The in-flight statement's root span on this thread, or None —
    EXPLAIN ANALYZE reads its own trace-so-far through this."""
    st = getattr(_tls, "state", None)
    if st is None or st["trace"] is None:
        return None
    return st["trace"].root


def format_timing_line(root) -> str:
    """The EXPLAIN ANALYZE Timing payload: total + every nonzero phase,
    in ms (phase names are stable — tests and trace_summarize key on
    them)."""
    ph = phase_breakdown(root)
    parts = [f"total={ph['total'] * 1000:.2f}ms"]
    for name in PHASE_ORDER + ("other",):
        v = ph.get(name, 0.0)
        if v > 0.0005 or name in ("plan", "device"):
            parts.append(f"{name}={v * 1000:.2f}ms")
    return " ".join(parts)
