"""citus_tpu — a TPU-native distributed query-execution framework.

Brand-new framework with the capabilities of Citus (distributed PostgreSQL,
surveyed at /root/reference — see SURVEY.md): hash-sharded columnar tables,
a router/pushdown/repartition planner cascade, and distributed execution —
rebuilt TPU-first:

* tables live as host-side columnar stripes streamed into HBM as fixed-width
  padded arrays;
* co-located and broadcast joins run per-device under ``shard_map``;
* repartition joins replace COPY-over-TCP shuffles with
  ``jax.lax.all_to_all`` over ICI;
* distributed aggregates split into per-device partial aggregation and a
  collective combine.
"""

from .config import Settings, registered_vars
from .errors import (
    AdmissionRejected,
    CapacityOverflowError,
    CatalogError,
    CitusTpuError,
    ConfigError,
    CorruptStripe,
    DeviceMemoryExhausted,
    ExecutionError,
    IngestError,
    ParseError,
    PlanningError,
    QueryCanceled,
    ResourceExhausted,
    StatementTimeout,
    StorageError,
    TransactionError,
    UnsupportedQueryError,
)
from .types import ColumnDef, DataType, TableSchema, sql_type_to_datatype

# CITUS_TPU_TSAN=1 arms the runtime lock-order sanitizer BEFORE any
# session/manager lock is created (analysis/sanitizer.py; the runtime
# half of graftlint).  No-op — and no sanitizer import — otherwise.
import os as _os

if _os.environ.get("CITUS_TPU_TSAN") == "1":
    from .analysis.sanitizer import maybe_enable_from_env

    maybe_enable_from_env()

__version__ = "0.1.0"

__all__ = [
    "Settings", "registered_vars", "ColumnDef", "DataType", "TableSchema",
    "sql_type_to_datatype", "CitusTpuError", "ConfigError", "CatalogError",
    "StorageError", "CorruptStripe", "ParseError", "PlanningError",
    "UnsupportedQueryError",
    "ExecutionError", "CapacityOverflowError", "IngestError",
    "TransactionError", "QueryCanceled", "StatementTimeout",
    "AdmissionRejected", "ResourceExhausted", "DeviceMemoryExhausted",
    "__version__",
]


def connect(data_dir: str | None = None, **settings):
    """Open a Session (the psql-connection analogue). Lazy import to keep
    `import citus_tpu` light."""
    try:
        from .session import Session
    except ImportError as exc:  # pragma: no cover - build-order guard
        raise CitusTpuError(
            "the session layer is not available in this build") from exc

    return Session(data_dir=data_dir, **settings)
