"""Error hierarchy for citus_tpu.

The reference (Citus) reports errors through PostgreSQL's ereport() with
dedicated error codes; the closest structural analogues here are a small
exception hierarchy.  Reference behavior surveyed from
/root/reference/src/backend/distributed/planner/multi_router_planner.c
(deferred error machinery) and shared_library_init.c (GUC validation).
"""

from __future__ import annotations


class CitusTpuError(Exception):
    """Base class for all framework errors."""


class ConfigError(CitusTpuError):
    """Invalid configuration variable or value (GUC analogue)."""


class CatalogError(CitusTpuError):
    """Metadata/catalog inconsistency (pg_dist_* analogue)."""


class StorageError(CitusTpuError):
    """Columnar storage format or IO error.

    When raised from a shard read, carries `table`/`shard_id` attributes
    so the statement retry loop can mark the failing placement suspect
    and re-derive routing onto a surviving replica (the adaptive-executor
    placement-failover analogue, adaptive_executor.c:95-116)."""

    table: str | None = None
    shard_id: int | None = None


class CorruptStripe(StorageError):
    """On-disk integrity violation: a stripe/manifest checksum mismatch,
    torn tail, or structural damage detected by the end-to-end CRC path
    (storage/format.py v2 footers, storage/integrity.py).

    Subclasses StorageError so the PR-3 resilience machinery classifies
    it as a placement failure: the read path marks the owning placement
    suspect and re-routes onto a surviving replica copy (the
    data_checksums + ereport(ERROR) analogue — wrong bytes are NEVER
    returned as data)."""


class ParseError(CitusTpuError):
    """SQL syntax error."""

    def __init__(self, message: str, line: int = 0, column: int = 0):
        self.line = line
        self.column = column
        if line:
            message = f"{message} (line {line}, column {column})"
        super().__init__(message)


class PlanningError(CitusTpuError):
    """Query cannot be planned distributedly.

    Mirrors Citus's "deferred error" pattern: the planner cascade records why
    each strategy failed and reports the most specific reason
    (multi_router_planner.c DeferredErrorMessage).
    """


class UnsupportedQueryError(PlanningError):
    """Query shape recognized but not supported by any planner stage."""


class QueryCanceled(CitusTpuError):
    """Statement canceled cooperatively (the pg_cancel_backend analogue):
    Session.cancel() sets a flag the executing thread notices at the next
    seam — fault point, stream/COPY batch boundary, retry iteration."""


class StatementTimeout(QueryCanceled):
    """`statement_timeout_ms` deadline passed (PostgreSQL
    statement_timeout analogue; the reference enforces
    citus.node_connection_timeout per connection — here the whole
    statement carries one cooperative deadline)."""


class AdmissionRejected(CitusTpuError):
    """The workload manager shed this statement instead of queueing it
    without bound: the admission queue for its priority class was full
    (wlm_queue_depth).  The analogue of the reference failing a query
    when citus.max_shared_pool_size leaves no connection slot and the
    wait would exceed its bounds — a clean, immediately-retryable-by-
    the-client error, never a half-executed statement."""


class PlacementLostError(CatalogError):
    """A shard has placements, but none on a live node: every copy sits
    on nodes that are disabled or marked dead by the mesh health ledger
    (device loss).  Subclasses CatalogError so existing callers keep
    their semantics; the session's mesh-degrade path re-raises it as a
    MeshDegradedError when devices have actually been lost, so an
    unreplicated shard stranded on a dead device surfaces as the
    device-loss terminal error it really is."""


class ExecutionError(CitusTpuError):
    """Runtime failure during distributed execution."""


class DeviceLostError(ExecutionError):
    """A mesh device died, hung past its deadline, or errored
    mid-statement — the TPU preemption / ICI-link-loss failure mode
    (the reference's "connection to worker lost", classified there by
    the adaptive executor as a task-level failover trigger).

    Raised at the mesh seams (``mesh.device_put`` per-device transfer,
    ``mesh.collective`` dispatch, ``mesh.fetch`` result pull) either by
    the armed MeshSim (utils/faultinjection.py) or by wrapping a real
    backend error that matches the device-loss signature
    (distributed/mesh.py is_device_loss).  Classified by the session
    retry envelope as *retryable-after-mesh-degrade*: the session marks
    the device suspect in the catalog health ledger, rebuilds a
    shrunken mesh from the survivors, re-plans through the node↔device
    map (replicated shard placements fail over to surviving nodes) and
    re-executes.  ``device_id`` is the failing jax device id when
    known (None when a collective failed opaquely — the session then
    probes the mesh to find the corpse); ``seam`` names where it
    died."""

    def __init__(self, message: str, device_id: int | None = None,
                 seam: str | None = None):
        self.device_id = device_id
        self.seam = seam
        super().__init__(message)


class MeshDegradedError(DeviceLostError):
    """Device loss that cannot be failed over: no surviving devices, a
    shard whose only placement (shard_replication_factor=1) sits on the
    dead device, or the failover budget is spent.  The clean,
    client-facing terminal error of the mesh-degrade path — never wrong
    rows, never a hung process."""


class ResourceExhausted(ExecutionError):
    """Device memory could not be made to fit even after the OOM
    degradation ladder (cache eviction → stream-batch shrink → forced
    streaming → multi-pass partitioned execution) ran out of rungs —
    the clean, client-facing terminal error.  The analogue of the
    reference failing a query with 53200 out_of_memory after the
    executor exhausted its options; never a dead process, never wrong
    rows."""


class DeviceMemoryExhausted(ResourceExhausted):
    """An HBM allocation failed (XLA RESOURCE_EXHAUSTED, or the
    accountant's armed MemSim budget/fault injection).  Raised at the
    device-placement seam (executor/hbm.py) and classified by the
    session retry envelope as *retryable-after-degradation*: each
    retry first applies the next rung of the degradation ladder
    (executor.Executor.degrade_for_oom) so the re-run needs less
    device memory.  Subclasses ResourceExhausted so an unhandled
    escape is still a clean framework error."""


class CapacityOverflowError(ExecutionError):
    """A static-capacity device buffer overflowed (join/shuffle output).

    The host executor catches this and retries with a larger capacity —
    the TPU-native replacement for data-dependent output cardinality.
    """

    def __init__(self, message: str, required: int = 0, capacity: int = 0):
        self.required = required
        self.capacity = capacity
        super().__init__(message)


class ReplicationError(CitusTpuError):
    """Log-shipping state violation (replication/): a fenced zombie
    leader trying to ship from a superseded epoch, a batch spool whose
    ordering invariants broke, or a role mismatch (promoting a leader,
    shipping from a follower).  Clean and terminal — replication never
    half-applies a batch (the cursor is the only commit point)."""


class ReadOnlyReplica(ReplicationError):
    """A write reached a follower data_dir.  Followers serve reads at
    bounded staleness; every mutation belongs on the leader (the
    reference's hot-standby `cannot execute ... in a read-only
    transaction`).  Clean reroute signal, nothing executed."""


class ReplicaTooStale(ReplicationError):
    """The follower's applied lsn lags its leader beyond
    `replica_max_staleness_lsn`.  The bounded-VISIBLE-staleness
    contract: a replica that cannot prove freshness refuses with this
    clean error for the client to reroute — it never silently serves
    old rows as if they were current."""


class IngestError(CitusTpuError):
    """COPY/bulk-load failure."""


class TransactionError(CitusTpuError):
    """Commit-log / recovery failure (2PC analogue)."""
