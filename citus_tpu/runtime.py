"""JAX runtime configuration guard.

The framework's routing contract (host/device hash parity, int64 shard keys)
requires 64-bit types on device.  JAX defaults to x64-off and silently
downcasts int64 → int32 at jnp.asarray, which would silently break shuffle
routing (rows land on wrong shards, joins lose rows).  Every entry point —
Session, executors, bench — calls ensure_jax_configured() before touching
device arrays.
"""

from __future__ import annotations

import os

_configured = False


def ensure_jax_configured(platform: str | None = None,
                          host_device_count: int | None = None) -> None:
    """Idempotently enable x64 (and optionally pick a platform / virtual
    device count).  Must run before the first JAX backend use; platform and
    device-count changes after backend init raise RuntimeError."""
    global _configured
    if host_device_count is not None and not _configured:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={host_device_count}")

    import jax

    # NB: env vars (JAX_PLATFORMS / JAX_ENABLE_X64) are not reliably honored
    # in every deployment (TPU plugins can win); the config API is.
    jax.config.update("jax_enable_x64", True)
    if platform is not None:
        jax.config.update("jax_platforms", platform)
    if not _configured:
        # persistent XLA executable cache: repeated plan shapes skip the
        # (tens of seconds, on remote TPUs) cold compile across processes.
        # CPU-backend processes skip it: XLA's CPU executable.serialize()
        # segfaults after a few hundred distinct compilations in one
        # process (observed killing 500-query fuzz runs), and the
        # in-process plan cache covers repeats there anyway.
        plat = (platform or str(getattr(jax.config, "jax_platforms", "")
                                or os.environ.get("JAX_PLATFORMS") or ""))
        if not plat:
            # nothing configured explicitly: ask the backend (a plain
            # CPU-only machine must hit the cpu opt-out too)
            try:
                plat = jax.default_backend()
            except Exception:
                plat = ""
        cache_dir = os.environ.get(
            "CITUS_TPU_COMPILE_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "citus_tpu_xla"))
        try:
            if "cpu" in plat:
                jax.config.update("jax_enable_compilation_cache", False)
            else:
                jax.config.update("jax_compilation_cache_dir", cache_dir)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0)
        except (AttributeError, KeyError, ValueError):
            pass  # older jax without persistent-cache config
    _configured = True


def require_x64() -> None:
    import jax

    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "citus_tpu requires jax_enable_x64 (int64 shard keys); call "
            "citus_tpu.runtime.ensure_jax_configured() before device work")
