"""CDC-invalidated result cache for repeated read statements.

The prepared-statement-caching analogue taken one level further: where
the plan cache reuses the compiled program and the feed cache reuses
HBM-resident arrays, this cache reuses the FINISHED ResultSet of a
repeated read statement — keyed on (statement shape, bound params,
catalog version, compute dtype) and shared by every session on one
data_dir.

Freshness is proven, not assumed:

* **CDC subscription** — every logical mutation lands in the change
  journal at its commit point (cdc/feed.py); the cache consumes the
  journal incrementally (`ChangeFeedCursor`, one size-stat per poll)
  and drops exactly the touched tables' entries.  Never a wall-clock
  TTL: a hit is as-of the latest journaled LSN for every table it
  reads, and internal data movement (shard move/split/rebalance —
  suppressed at the CDC source) correctly invalidates nothing.
* **Manifest-identity backstop** — `cdc.append` is post-visibility: a
  crash between the manifest flip and the journal append leaves a
  committed-but-unjournaled mutation.  Each entry therefore records
  every read table's on-disk manifest identity (mtime_ns, size, inode)
  at fill time, captured BEFORE execution; a hit re-stats and a
  mismatch invalidates.  This also covers out-of-band surgery
  (restore_cluster) for free.

Entries are LRU in a byte-bounded store (`serving_result_cache_bytes`)
with a per-table key index, so DML invalidation touches only the
written table's entries instead of scanning the whole cache under the
lock (the FeedCache got the same index this round).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ..cdc.feed import ChangeFeedCursor
from ..sql import ast


@dataclass
class _Entry:
    result: object
    tables: tuple[str, ...]
    sigs: dict[str, tuple | None]   # table → manifest identity at fill
    nbytes: int


def _result_nbytes(result) -> int:
    """Rough retained-bytes estimate for LRU accounting."""
    total = 256
    for col in result.columns.values():
        if isinstance(col, np.ndarray):
            total += int(col.nbytes)
            if col.dtype == object:
                total += 32 * col.size  # boxed values
        else:
            total += 64 * len(col)
    if result.null_masks:
        for m in result.null_masks.values():
            total += int(np.asarray(m).nbytes)
    return total


class ResultCache:
    """Per-data_dir LRU of read-statement results with CDC-driven,
    table-indexed invalidation."""

    def __init__(self, data_dir: str):
        self._mu = threading.Lock()
        self._cursor = ChangeFeedCursor(
            os.path.join(data_dir, "cdc_changes.jsonl"))
        self._entries: OrderedDict[tuple, _Entry] = OrderedDict()
        self._by_table: dict[str, set] = {}
        # monotone fill-epoch: bumped per invalidation batch; an entry
        # filled under an older epoch than its tables' last invalidation
        # is discarded at put() (the mid-execution-write race)
        self._epoch = 0
        self._table_epoch: dict[str, int] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    # -- invalidation ------------------------------------------------------
    def _poll_locked(self) -> None:
        events = self._cursor.poll()
        if events is None:
            # the journal regressed (restore_cluster replaced it):
            # nothing is provably fresh — drop everything
            self._clear_locked()
            return
        touched = {ev["table"] for ev in events}
        if touched:
            self._epoch += 1
            for t in touched:
                self._table_epoch[t] = self._epoch
                self._invalidate_table_locked(t)

    def _invalidate_table_locked(self, table: str) -> None:
        keys = self._by_table.pop(table, None)
        if not keys:
            return
        for k in keys:
            e = self._entries.pop(k, None)
            if e is None:
                continue
            self._bytes -= e.nbytes
            self.invalidations += 1
            for t in e.tables:
                if t != table:
                    other = self._by_table.get(t)
                    if other is not None:
                        other.discard(k)

    def _drop_locked(self, key: tuple) -> None:
        e = self._entries.pop(key, None)
        if e is None:
            return
        self._bytes -= e.nbytes
        for t in e.tables:
            s = self._by_table.get(t)
            if s is not None:
                s.discard(key)
                if not s:
                    del self._by_table[t]

    def _clear_locked(self) -> None:
        n = len(self._entries)
        self._entries.clear()
        self._by_table.clear()
        self._bytes = 0
        self.invalidations += n
        if n:
            self._epoch += 1
            for t in list(self._table_epoch):
                self._table_epoch[t] = self._epoch

    def clear(self) -> None:
        with self._mu:
            self._clear_locked()

    # -- lookup / fill -----------------------------------------------------
    def lookup(self, key: tuple, sig_fn=None):
        """(cached ResultSet or None, entries THIS call dropped).
        Polls the change feed first; when `sig_fn(table) -> sig` is
        given, the entry's manifest identities are re-checked (the
        crash-window backstop).  The drop count is per-call — folding
        it into a session counter never attributes another session's
        concurrent poll (`invalidations` only moves under `_mu`, so the
        delta inside one locked section is exactly this call's).

        The per-table stat()s run OUTSIDE `_mu` (a slow filesystem must
        not serialize every session's hit behind one stat — the same
        scan-under-the-lock shape FeedCache.invalidate_table shed this
        round); the verdict is re-applied under the lock only if the
        entry survived untouched (`_Entry` is immutable after put)."""
        with self._mu:
            inv0 = self.invalidations
            self._poll_locked()
            e = self._entries.get(key)
            sigs = e.sigs if (e is not None and sig_fn is not None) \
                else None
            poll_dropped = self.invalidations - inv0
        stale = False
        if sigs is not None:
            stale = any(sig_fn(t) != sigs.get(t) for t in e.tables)
        with self._mu:
            dropped = poll_dropped
            if e is not None and self._entries.get(key) is not e:
                e = None  # raced with a concurrent invalidation/refill
            elif e is not None and stale:
                self._drop_locked(key)
                self.invalidations += 1
                dropped += 1
                e = None
            if e is None:
                self.misses += 1
                return None, dropped
            self._entries.move_to_end(key)
            self.hits += 1
            return e.result, dropped

    def get(self, key: tuple, sig_fn=None):
        """`lookup()` without the per-call drop count."""
        return self.lookup(key, sig_fn)[0]

    def fill_token(self) -> int:
        """Epoch snapshot taken at miss time, BEFORE executing: put()
        refuses the fill when any read table was invalidated after this
        point (the result may predate a concurrent write)."""
        with self._mu:
            self._poll_locked()
            return self._epoch

    def put(self, key: tuple, result, tables, sigs: dict,
            token: int, max_bytes: int) -> bool:
        """Insert a finished result.  Returns False when the fill was
        refused (stale token / oversized entry / cache disabled).
        The fill is a named fault seam: an injected failure here errors
        the STATEMENT cleanly (a SELECT has no visibility effect, so
        the retry loop safely re-executes) and must never leave a
        half-inserted entry."""
        from ..utils.faultinjection import fault_point

        if max_bytes <= 0:
            return False
        fault_point("serving.cache_fill")
        nbytes = _result_nbytes(result)
        if nbytes > max(1, max_bytes // 4):
            return False  # one answer must not evict the working set
        with self._mu:
            self._poll_locked()
            if any(self._table_epoch.get(t, 0) > token for t in tables):
                return False  # a write landed mid-execution
            if key in self._entries:
                self._drop_locked(key)
            entry = _Entry(result, tuple(tables), dict(sigs), nbytes)
            self._entries[key] = entry
            self._bytes += nbytes
            for t in entry.tables:
                self._by_table.setdefault(t, set()).add(key)
            while self._bytes > max_bytes and len(self._entries) > 1:
                old_key = next(iter(self._entries))
                self._drop_locked(old_key)
            return True

    def probe(self, key: tuple) -> bool:
        """Membership check without traffic accounting (EXPLAIN)."""
        with self._mu:
            self._poll_locked()
            return key in self._entries

    # -- observability -----------------------------------------------------
    @property
    def total_bytes(self) -> int:
        return self._bytes

    def __len__(self):
        return len(self._entries)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits_total": self.hits,
                "misses_total": self.misses,
                "invalidations_total": self.invalidations,
                "last_lsn": self._cursor.last_lsn,
            }


# -- statement fingerprinting ----------------------------------------------
def _walk_nodes(node):
    """Every ast dataclass node in a statement tree (generic traversal —
    the same shape _substitute_params walks)."""
    import dataclasses

    stack = [node]
    while stack:
        n = stack.pop()
        if dataclasses.is_dataclass(n) and not isinstance(n, type):
            yield n
            for f in dataclasses.fields(n):
                stack.append(getattr(n, f.name))
        elif isinstance(n, (tuple, list)):
            stack.extend(n)


def read_closure(stmt, catalog, _depth: int = 0):
    """Base tables the statement may READ, views expanded recursively —
    the invalidation subscription set.  Conservative: CTE names that
    shadow base tables over-approximate (extra invalidation is safe;
    a missed table would serve stale rows).  Returns None when the
    statement is not provably cacheable (mutating kinds, unresolvable
    views)."""
    if _depth > 8:
        return None
    if not isinstance(stmt, (ast.Select, ast.SetOp)):
        return None
    tables: set[str] = set()
    for n in _walk_nodes(stmt):
        if isinstance(n, ast.TableRef):
            view = catalog.views.get(n.name)
            if view is not None:
                from ..sql import parse

                try:
                    body = parse(view["sql"])[0]
                except Exception:
                    return None
                inner = read_closure(body, catalog, _depth + 1)
                if inner is None:
                    return None
                tables |= inner
            elif catalog.has_table(n.name):
                tables.add(n.name)
            # else: a CTE/derived name — its body's tables are walked
    return tables


def cache_key(stmt, params, catalog, settings, udfs):
    """(key, tables) for a cacheable read statement, else None.

    The key covers everything that determines the result AND its
    execution metadata: the statement tree (frozen-dataclass reprs are
    stable value serializations), the bound EXECUTE literals, the
    catalog version (DDL fences), and the session's full settings
    profile.  The row values only depend on compute_dtype, but EXPLAIN
    ANALYZE / tests read metadata (fast_path, streamed_batches) off the
    result — a hit filled under different knobs would replay metadata
    the current knobs could not have produced, so a knob flip simply
    misses.  Data freshness is NOT in the key — that is the CDC
    subscription's job.

    The statement-shape half (UDF scan, read closure, tree repr) is
    memoized ON the statement node per catalog version: the session's
    hot-statement memo replays the same frozen tree for a repeated
    text, so the serving path walks it once, not per request (the
    settings profile rides Settings.profile()'s own version cache)."""
    memo = getattr(stmt, "_serving_key_memo", None)
    if memo is None or memo[0] != catalog.version:
        shape = None  # uncacheable under this catalog version
        if not any(isinstance(n, ast.FuncCall) and n.name in udfs
                   for n in _walk_nodes(stmt)):
            tables = read_closure(stmt, catalog)
            if tables is not None:
                shape = (repr(stmt), tuple(sorted(tables)))
        memo = (catalog.version, shape)
        # frozen dataclass, no slots: attach without thawing
        object.__setattr__(stmt, "_serving_key_memo", memo)
    shape = memo[1]
    if shape is None:
        return None  # admin/volatile UDF call or unresolvable view
    key = (shape[0], tuple(repr(p) for p in params), catalog.version,
           settings.profile())
    return key, shape[1]


# -- registry ---------------------------------------------------------------
_registry: dict[str, ResultCache] = {}
_refs: dict[str, int] = {}
_registry_mu = threading.Lock()


def peek_result_cache(data_dir: str) -> "ResultCache | None":
    """The registry's existing cache for `data_dir`, or None — WITHOUT
    creating one.  For best-effort consumers (the OOM ladder's
    eviction rung) that must not resurrect an entry the refcounted
    acquire/release lifecycle already dropped."""
    key = os.path.realpath(data_dir)
    with _registry_mu:
        return _registry.get(key)


def result_cache_for(data_dir: str) -> ResultCache:
    key = os.path.realpath(data_dir)
    with _registry_mu:
        if key not in _registry:
            _registry[key] = ResultCache(data_dir)
        return _registry[key]


def acquire_result_cache(data_dir: str) -> ResultCache:
    """result_cache_for + a liveness reference.  Unlike the batcher
    registry (counters only), a ResultCache pins up to
    serving_result_cache_bytes of finished result arrays — a process
    churning through data_dirs (the test suite, a bench driver) must
    not accrete every dir's working set forever.  Sessions acquire on
    first use and release on close; the last release drops the
    registry entry and its bytes."""
    key = os.path.realpath(data_dir)
    with _registry_mu:
        if key not in _registry:
            _registry[key] = ResultCache(data_dir)
        _refs[key] = _refs.get(key, 0) + 1
        return _registry[key]


def release_result_cache(data_dir: str) -> None:
    key = os.path.realpath(data_dir)
    with _registry_mu:
        n = _refs.get(key, 0) - 1
        if n > 0:
            _refs[key] = n
            return
        _refs.pop(key, None)
        cache = _registry.pop(key, None)
    if cache is not None:
        cache.clear()


def reset_serving_state(data_dir: str) -> None:
    """Drop the serving layer's cached state for a data_dir — called by
    out-of-band surgery (restore_cluster) that rewrites storage without
    emitting CDC events.  The manifest-identity backstop would catch
    the stale entries lazily; this makes it eager."""
    key = os.path.realpath(data_dir)
    with _registry_mu:
        cache = _registry.get(key)
    if cache is not None:
        cache.clear()
