"""Cross-session micro-batcher for point-index lookups.

The inference-serving move (PystachIO, PAPERS.md): concurrent small
requests coalesce into one batched probe so the fixed per-request cost
(index load, stripe open, chunk read + decompress, delete-mask apply)
amortizes across the batch.  ONE batcher per data_dir (the
lock_manager_for / workload_manager_for pattern — sessions sharing a
data directory share the storage those lookups hit).

Leader/follower protocol, no background thread:

* a lookup enqueues and, when no leader is active, BECOMES the leader;
* a leader whose request is alone dispatches immediately
  (**single-flight** — an idle system pays zero added latency);
* a leader that finds company waits ``serving_batch_window_ms`` once
  to accumulate arrivals, then drains up to ``serving_max_batch``
  requests per round until the queue is empty — requests that arrive
  while a batch executes form the next batch (adaptive batching);
* followers wait on their request's event in cancellation-aware slices
  (statement_timeout_ms / Session.cancel() abort a queued lookup the
  same way they abort a WLM queue wait — the abandoned queue slot is
  removed and counted as cleanly errored); a follower that finds
  leadership free with its request still queued SELF-PROMOTES, so a
  leader dying (or cancelled — the leader honors its own deadline
  between rounds, after its own request resolved) never strands the
  queue on dead air.

Each batch groups requests by (table, shard, column), resolves every
key against the shared point index (storage/pkindex.py), and reads the
UNION of hits in one stripe/chunk pass (`pkindex.read_rows_multi`),
demuxed back per request.  A request the index cannot serve (an overlay
materialized after eligibility) resolves as a fallback — the caller
runs the ordinary scan path.

Ledger invariant (chaos-soak enforced): every enqueued lookup resolves
as answered XOR cleanly errored XOR fallback — never lost in a dead
batch.  A leader dying mid-batch (even on BaseException) delivers a
clean error to every unresolved request in the batch before
propagating, and requests it never dispatched go back to the queue for
the next (self-promoted) leader.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from ..errors import StorageError


class LookupResult:
    """One resolved lookup: the rows (or fallback), plus the dispatch
    metadata the requester folds into its own session counters."""

    __slots__ = ("vals", "mask", "n", "fallback", "batch_size",
                 "dispatches_led")

    def __init__(self):
        self.vals = None
        self.mask = None
        self.n = 0
        self.fallback = False
        self.batch_size = 0
        self.dispatches_led = 0


class _Lookup:
    __slots__ = ("store", "table", "shard_id", "column", "value",
                 "columns", "evt", "result", "error")

    def __init__(self, store, table, shard_id, column, value, columns):
        self.store = store
        self.table = table
        self.shard_id = shard_id
        self.column = column
        self.value = value
        self.columns = tuple(columns)
        self.evt = threading.Event()
        self.result: LookupResult | None = None
        self.error: BaseException | None = None


def _clone_error(e: BaseException) -> BaseException:
    """A per-waiter copy of the batch failure (sharing one exception
    object across raising threads would share tracebacks); classifier
    markers (injected_fault / fault_point / shard_id / post_visibility)
    ride along so each session's retry loop classifies it exactly like
    a solo failure."""
    if not isinstance(e, Exception):
        # a BaseException (crash-sim power cut, interpreter teardown)
        # killed the leader: followers get a clean retryable error —
        # the non-Exception kind must only unwind its own session
        return StorageError(
            f"batch leader died mid-dispatch ({type(e).__name__})")
    try:
        clone = type(e)(*e.args)
    except Exception:
        clone = StorageError(f"batched lookup failed: {e}")
    for attr in ("injected_fault", "fault_point", "post_visibility",
                 "shard_id", "table"):
        if hasattr(e, attr):
            try:
                setattr(clone, attr, getattr(e, attr))
            except Exception:  # graftlint: ignore[silent-exception] — best-effort marker copy: a clone type refusing ONE attr (slots/property) must not drop the remaining markers or the error itself
                continue
    return clone


class MicroBatcher:
    """Per-data_dir cross-session point-lookup coalescer."""

    def __init__(self):
        self._mu = threading.Lock()
        self._queue: deque[_Lookup] = deque()
        self._leader_active = False
        # shared-layer totals (citus_stat_serving); per-session counters
        # fold requester-side from LookupResult
        self.requests_total = 0
        self.answered_total = 0
        self.errored_total = 0
        self.fallback_total = 0
        self.dispatch_total = 0
        self.batched_lookups_total = 0
        self.max_batch_seen = 0

    # -- public ------------------------------------------------------------
    def lookup(self, store, table: str, shard_id: int, column: str,
               value: int, columns, max_batch: int,
               window_s: float) -> LookupResult:
        """Resolve one point lookup through the shared batch queue.
        Returns a LookupResult (fallback=True when the index cannot
        answer); raises the batch failure as a clean error."""
        from ..utils.cancellation import check_cancel

        from ..stats.tracing import trace_span

        req = _Lookup(store, table, shard_id, column, value, columns)
        with self._mu:
            self.requests_total += 1
            self._queue.append(req)
            lead = not self._leader_active
            if lead:
                self._leader_active = True
        led = 0
        if lead:
            led = self._lead(max(1, max_batch), max(0.0, window_s))
        else:
            with trace_span("serving.batch_wait"):
                while not req.evt.wait(0.005):
                    try:
                        check_cancel()  # deadline / cancel() seam
                    except BaseException:
                        # leaving the wait: resolve our queue slot so
                        # the ledger never holds an abandoned request
                        with self._mu:
                            if not req.evt.is_set():
                                try:
                                    self._queue.remove(req)
                                except ValueError:
                                    pass  # already in a running batch
                                else:
                                    self.errored_total += 1
                                    req.evt.set()
                        raise
                    promote = False
                    with self._mu:
                        if not self._leader_active and \
                                not req.evt.is_set():
                            # the leader died or was cancelled with
                            # work still queued: self-promote so no
                            # lookup ever waits on dead air
                            self._leader_active = True
                            promote = True
                    if promote:
                        led += self._lead(max(1, max_batch),
                                          max(0.0, window_s))
        if req.error is not None:
            raise req.error
        req.result.dispatches_led = led
        return req.result

    # -- leader ------------------------------------------------------------
    def _lead(self, max_batch: int, window_s: float) -> int:
        """Drain the queue in batches until empty; returns the number of
        batches this leader dispatched.  Leadership is released
        atomically with the final emptiness check, so a request that
        enqueues while we lead is always served — by us or by itself."""
        from ..utils.cancellation import check_cancel

        first = True
        dispatched = 0
        batch: list[_Lookup] = []
        try:
            while True:
                if not first:
                    # the leader's own request resolved in an earlier
                    # round; later rounds serve OTHER sessions — honor
                    # this statement's deadline / Session.cancel()
                    # between rounds (the stranded queue is handed to a
                    # self-promoting follower, see lookup())
                    check_cancel()
                with self._mu:
                    if not self._queue:
                        self._leader_active = False
                        return dispatched
                    if not first or len(self._queue) > 1:
                        # company: drain a batch now (the first round
                        # waited its window below; later rounds batch
                        # whatever accumulated during execution)
                        batch = [self._queue.popleft()
                                 for _ in range(min(max_batch,
                                                    len(self._queue)))]
                    else:
                        batch = [self._queue.popleft()]  # single-flight
                if first and len(batch) > 1 and window_s > 0:
                    # arrivals already queued: hold the window once so
                    # the coalescing batch catches the burst's tail
                    from ..stats.tracing import trace_span

                    with trace_span("serving.door_hold"):
                        time.sleep(window_s)
                    with self._mu:
                        while self._queue and len(batch) < max_batch:
                            batch.append(self._queue.popleft())
                first = False
                dispatched += 1
                self._execute_batch(batch)
                batch = []
        except BaseException:
            with self._mu:
                if batch:
                    # popped but never executed (cancel / power cut in
                    # the window sleep): hand the requests back — a
                    # waiting follower self-promotes and serves them
                    self._queue.extendleft(
                        r for r in reversed(batch)
                        if not r.evt.is_set())
                self._leader_active = False
            raise

    def _execute_batch(self, batch: list[_Lookup]) -> None:
        """Run one coalesced probe.  Resolves EVERY request in the batch
        (answered / errored / fallback) before returning; only
        BaseException (crash-sim power cuts, interpreter teardown)
        propagates — after delivering clean errors to the batch."""
        from ..stats.tracing import trace_span

        with self._mu:
            self.dispatch_total += 1
            self.batched_lookups_total += len(batch)
            self.max_batch_seen = max(self.max_batch_seen, len(batch))
        # the probe span lives on the LEADER's statement trace: the
        # flight recorder attributes coalesced work to the thread that
        # actually did it (followers record serving.batch_wait)
        with trace_span("serving.batch_probe", batched=len(batch)):
            self._execute_batch_inner(batch)

    def _execute_batch_inner(self, batch: list[_Lookup]) -> None:
        from ..errors import QueryCanceled
        from ..utils.faultinjection import fault_point

        try:
            # named seam: a fault at dispatch must error the WHOLE batch
            # cleanly — the ledger proves no request is ever lost here
            fault_point("serving.batch_dispatch")
            groups: dict[tuple, list[_Lookup]] = {}
            for r in batch:
                groups.setdefault((r.table, r.shard_id, r.column),
                                  []).append(r)
            for (table, sid, col), group in groups.items():
                try:
                    self._probe_group(table, sid, col, group)
                except QueryCanceled:
                    raise  # the LEADER's deadline, not the group's
                except Exception as e:
                    self._deliver_error(group, e)
        except QueryCanceled:
            # the leader's own cancel/timeout fired on its thread (the
            # fault_point/check_cancel seams run there): innocent
            # coalesced lookups must not inherit a timeout they never
            # set — requeue them for the next (self-promoted) leader
            with self._mu:
                pending = [r for r in batch if not r.evt.is_set()]
                self._queue.extendleft(reversed(pending))
            # the resolution belt below must skip the requeued requests
            batch[:] = [r for r in batch if r.evt.is_set()]
            raise
        except Exception as e:  # graftlint: ignore[swallowed-fault-seam] — not swallowed: the fault (clone per waiter, markers intact) re-raises in EVERY batched session; the leader must survive to drain the queue
            self._deliver_error(batch, e)
        except BaseException as e:
            self._deliver_error(batch, e)
            raise
        finally:
            for r in batch:  # belt: nothing leaves the batch unresolved
                if not r.evt.is_set():
                    self._deliver_error(
                        [r], StorageError(
                            "batched lookup left unresolved (batcher "
                            "bug — please report)"))

    def _deliver_error(self, reqs: list[_Lookup], e: BaseException) -> None:
        n = 0
        for r in reqs:
            if r.evt.is_set():
                continue
            r.error = _clone_error(e)
            r.evt.set()
            n += 1
        if n:
            with self._mu:
                self.errored_total += n

    def _probe_group(self, table: str, shard_id: int, column: str,
                     group: list[_Lookup]) -> None:
        """One (table, shard, column) group: resolve every key against
        the shared index, read the union of hits in ONE stripe/chunk
        pass, demux per request.  The probe store's cached manifest is
        refreshed first: a follower may have loaded a NEWER committed
        manifest at its statement start than this store has cached, and
        probing through the older view would un-see a row that
        follower's session already observed committed (read-committed /
        monotonic-read violation the solo path cannot produce).  One
        stat() per dispatch group; refreshes are monotone, so after it
        this store is at least as new as every requester's view."""
        from ..storage import pkindex

        store = group[0].store
        store.refresh_if_stale(table)
        batch_size = len(group)
        hit_lists = []
        live: list[_Lookup] = []
        for r in group:
            hits = pkindex.lookup(store, table, shard_id, column, r.value)
            if hits is None:
                # an overlay materialized between eligibility and
                # dispatch: this request re-runs its own scan path
                res = LookupResult()
                res.fallback = True
                res.batch_size = batch_size
                r.result = res
                r.evt.set()
                with self._mu:
                    self.fallback_total += 1
                continue
            hit_lists.append(hits)
            live.append(r)
        if not live:
            return
        union_cols: list[str] = []
        for r in live:
            for c in r.columns:
                if c not in union_cols:
                    union_cols.append(c)
        per_req = pkindex.read_rows_multi(store, table, shard_id,
                                          union_cols, hit_lists)
        answered = 0
        for r, (vals, mask, n) in zip(live, per_req):
            res = LookupResult()
            res.vals = {c: vals[c] for c in r.columns}
            res.mask = {c: mask[c] for c in r.columns}
            res.n = n
            res.batch_size = batch_size
            r.result = res
            r.evt.set()
            answered += 1
        with self._mu:
            self.answered_total += answered

    # -- observability -----------------------------------------------------
    def reset_totals(self) -> None:
        """Zero the shared-layer totals — for A/B harnesses (bench.py
        serving) that run sequential modes over one data_dir and must
        report per-mode numbers: `max_batch_seen` is a monotone max, so
        snapshot deltas cannot isolate a mode the way they do for the
        monotone sums."""
        with self._mu:
            self.requests_total = 0
            self.answered_total = 0
            self.errored_total = 0
            self.fallback_total = 0
            self.dispatch_total = 0
            self.batched_lookups_total = 0
            self.max_batch_seen = 0

    def snapshot(self) -> dict:
        """citus_stat_serving() source (shared-layer totals)."""
        with self._mu:
            occ = (self.batched_lookups_total / self.dispatch_total
                   if self.dispatch_total else 0.0)
            return {
                "queue_depth": len(self._queue),
                "leader_active": self._leader_active,
                "requests_total": self.requests_total,
                "answered_total": self.answered_total,
                "errored_total": self.errored_total,
                "fallback_total": self.fallback_total,
                "batch_dispatch_total": self.dispatch_total,
                "batched_lookups_total": self.batched_lookups_total,
                "max_batch_seen": self.max_batch_seen,
                "avg_batch_occupancy": round(occ, 3),
            }


# process-wide registry: sessions sharing a data_dir share the batcher
# (the lock_manager_for / workload_manager_for pattern)
_registry: dict[str, MicroBatcher] = {}
_registry_mu = threading.Lock()


def batcher_for(data_dir: str) -> MicroBatcher:
    key = os.path.realpath(data_dir)
    with _registry_mu:
        if key not in _registry:
            _registry[key] = MicroBatcher()
        return _registry[key]
