"""Serving layer: cross-session micro-batched point reads + a
CDC-invalidated result cache.

The reference serves high-QPS point-read traffic through two
amortizations: the fast-path router planner skips distributed planning
for ``distcol = const`` statements (fast_path_router_planner.c:530) and
prepared-statement caching reuses the shard plan across EXECUTEs
(planner/local_plan_cache.c).  PystachIO (PAPERS.md) adds the
inference-serving move for accelerator query engines: coalesce many
concurrent small requests into one batched device dispatch so the fixed
per-request cost amortizes across the batch.

This package is that layer for the TPU-native engine:

* ``classify``  — the ONE parse-tree fast-path point-read shape
  classifier, shared by WLM admission exemption and the serving path
  (one matcher, two call sites — they can never drift);
* ``batcher``   — a per-data_dir cross-session micro-batcher: point-
  index lookups from concurrent sessions coalesce into one batched
  stripe/chunk probe over the union of keys, demuxed back per session
  (single-flight when idle, so an unloaded system adds no latency);
* ``result_cache`` — a per-data_dir LRU of finished read-statement
  results keyed on (statement shape, bound params, catalog version),
  invalidated by consuming the CDC manifest-delta journal per table —
  never by wall-clock TTLs — with a manifest-identity backstop for the
  post-visibility crash window cdc.append leaves open.
"""

from .batcher import MicroBatcher, batcher_for
from .classify import PointRead, classify_point_read
from .result_cache import ResultCache, result_cache_for, reset_serving_state

__all__ = [
    "MicroBatcher", "PointRead", "ResultCache", "batcher_for",
    "classify_point_read", "reset_serving_state", "result_cache_for",
]
