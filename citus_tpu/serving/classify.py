"""Parse-tree fast-path point-read shape classifier.

ONE matcher answers "is this statement a single-table
``distcol = const`` point read?" for every consumer:

* WLM admission exemption (wlm/admission.statement_exempt) — point
  reads skip the slot gate because the serving micro-batcher is their
  governor (they coalesce instead of queueing);
* the serving layer's EXPLAIN/observability surface (the "Serving:"
  line reports the statement's shape);
* tests, which assert both call sites classify a shared corpus
  identically.

The check mirrors (conservatively) the bound-plan matcher in
executor/fastpath.fast_path_shape — the reference accepts the same
slack between FastPathRouterQuery's parse-tree check and the real
router plan.  A statement classified here that the planner then routes
to the device still executes correctly; it just bypassed the gate.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..catalog import Catalog, DistributionMethod
from ..sql import ast


@dataclass(frozen=True)
class PointRead:
    """A classified point read: the pinned table / distribution column /
    literal key (the citus_stat_tenants attribution triple)."""

    table: str
    column: str
    value: object


def classify_point_read(sel: ast.Select, catalog: Catalog,
                        settings=None) -> PointRead | None:
    """Parse-tree fast-path shape: one hash-distributed table, the
    distribution column pinned to a non-NULL literal, no aggregates,
    subqueries, grouping or CTEs.  Returns the pinned (table, column,
    value) or None."""
    if settings is not None and \
            not settings.get("enable_fast_path_router"):
        return None
    if not isinstance(sel, ast.Select):
        return None
    if sel.ctes or sel.group_by or sel.having is not None or \
            sel.distinct or sel.semi_joins:
        return None
    if len(sel.from_items) != 1 or \
            not isinstance(sel.from_items[0], ast.TableRef):
        return None
    ref = sel.from_items[0]
    if not catalog.has_table(ref.name):
        return None
    meta = catalog.table(ref.name)
    if meta.method != DistributionMethod.HASH:
        return None
    if sel.where is None:
        return None
    # any function call (aggregate or otherwise) or nested subquery
    # disqualifies — the device path would run it
    exprs = [it.expr for it in sel.items] + [sel.where]
    for e in exprs:
        for n in ast.walk_expr(e):
            if isinstance(n, (ast.FuncCall, ast.ScalarSubquery,
                              ast.InSubquery, ast.Exists)):
                return None
    from ..executor.host_eval import split_conjuncts

    dcol = meta.distribution_column
    quals = {ref.alias or ref.name, ref.name}
    for c in split_conjuncts(sel.where):
        if not (isinstance(c, ast.BinaryOp) and c.op == "="):
            continue
        col, lit = c.left, c.right
        if not isinstance(col, ast.ColumnRef):
            col, lit = c.right, c.left
        if isinstance(col, ast.ColumnRef) and \
                isinstance(lit, ast.Literal) and lit.value is not None \
                and col.name == dcol and \
                (col.table is None or col.table in quals):
            return PointRead(ref.name, dcol, lit.value)
    return None
