"""Benchmark driver: TPC-H Q1 scan-aggregate throughput on one chip.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference's only published scan-aggregate number — the
columnar engine aggregating 75M rows in 16 s (≈4.69M rows/s) on a 2-vCPU
Azure VM (/root/reference/src/backend/columnar/README.md:303-321, the "27×
vs row tables" measurement).  Q1 is the same shape of work (scan + filter +
grouped aggregation over lineitem) so rows/sec is directly comparable.

Env knobs: BENCH_SF (scale factor, default 0.2), BENCH_REPEATS (default 3),
BENCH_QUERY (default Q1).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

BASELINE_ROWS_PER_SEC = 75_000_000 / 16.0  # reference columnar agg scan


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", "0.2"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    qname = os.environ.get("BENCH_QUERY", "Q1")

    from citus_tpu.session import Session
    from citus_tpu.ingest.tpch import QUERIES, load_into_session

    data_dir = tempfile.mkdtemp(prefix="citus_tpu_bench_")
    try:
        sess = Session(data_dir=data_dir)
        counts = load_into_session(sess, sf=sf, seed=0)
        lineitem_rows = sess.store.table_row_count("lineitem")
        sql = QUERIES[qname]

        # warmup: compile + populate host caches
        sess.execute(sql)
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            result = sess.execute(sql)
            dt = time.perf_counter() - t0
            best = min(best, dt)
        assert result.row_count > 0
        rows_per_sec = lineitem_rows / best
        print(json.dumps({
            "metric": f"tpch_{qname.lower()}_rows_per_sec",
            "value": round(rows_per_sec, 1),
            "unit": "rows/s",
            "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
        }))
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
