"""Benchmark driver: the five BASELINE.json configs on one chip, plus the
SF10 scale configs and a columnar-scan bandwidth line.

Prints one JSON line per config; the LAST line is the headline metric
(TPC-H Q1 scan-aggregate throughput), matching the driver contract of a
final `{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}` line.

Baseline yardstick: the reference's only published absolute number — the
columnar engine aggregating 75M rows in 16 s (≈4.69M rows/s) on a 2-vCPU
Azure VM (/root/reference/src/backend/columnar/README.md:303-321).  Every
rows/s config reports against that scan rate; the GB/s line reports
against the same workload expressed in bytes (75M rows × 20 scanned
bytes/row ≈ 0.088 GB/s).

Configs (BASELINE.json):
  1. TPC-H Q1 scan + grouped aggregate over lineitem      [headline]
  2. co-located hash join (orders ⋈ lineitem on orderkey)
  3. single-repartition join (customer ⋈ orders on custkey)
  4. dual-repartition join + global aggregate (psum combine); also at SF10
  5. TPC-H Q3 multi-join (repartition + colocated + grouped agg); also SF10
  +  columnar cold-scan bandwidth (stripe read → HBM → aggregate)

Driver contract hardening: every JSON line is printed and flushed the
moment its config finishes, so a timeout mid-run still leaves parseable
output; a wall-clock budget (BENCH_BUDGET seconds) skips remaining
optional configs once exceeded so the headline always prints.  The SF10
section is ON by default (round-4 VERDICT #1: the scale numbers must be
driver-captured); its ingest caches in .benchdata/bench_sf10 so only
the first run pays the ~14 min single-core generation, and the budget
check skips the section rather than truncating the run.

`python bench.py concurrency` runs the workload-manager A/B instead
(bench_concurrency: N concurrent mixed-tenant sessions, admission gate
off vs on, rows/sec + p50/p99 queue wait — PERF_NOTES round 8).
`python bench.py cold_start` runs the restart-survival A/B
(bench_cold_start: child-process restart-to-first-answer and 8-session
compile-storm p99, executable cache on vs off, plus the single-flight
zero-redundant-compiles ledger — PERF_NOTES round 17).
`python bench.py replica_fleet` runs the log-shipped replica fleet
(bench_replica_fleet: per-process replica QPS scale-out, replica-kill
zero-wrong-rows, leader-kill-to-first-promoted-answer and cold-replica
provision-to-first-answer — PERF_NOTES round 18).

Env knobs: BENCH_SF (default 1.0), BENCH_REPEATS (default 3),
BENCH_REPEAT (best-of-N authority: forces EVERY config — the SF10
section's reduced repeat counts included — to at least N measured
executions and stamps each timed JSON line with the `"repeats"` count
that actually ran, so the emitted artifact itself is the authoritative
best-of-N instead of a hand-curated "best run I saw"), BENCH_ONLY (comma list of config names),
BENCH_SF10 (default 1; 0 disables the SF10 section), BENCH_SF10_SCALE
(default 10.0), BENCH_SF10_DIR (persistent SF10 data dir),
BENCH_EXTRAS (default 0; 1 adds approx/exact count-distinct and
INSERT..SELECT mode configs), BENCH_BUDGET (default 2400 s).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

BASELINE_ROWS_PER_SEC = 75_000_000 / 16.0  # reference columnar agg scan
# the same reference scan in bytes: vendor_id int4 + quantity int8 ≈ 12
# logical bytes/row, but the table had 8 more columns the row engine read;
# charge the columnar engine only what it scanned (2 cols ≈ 12 B/row)
BASELINE_SCAN_GB_PER_SEC = (75_000_000 * 12) / 16.0 / 1e9


def bench_query(sess, sql: str, rows_processed: int, repeats: int):
    sess.execute(sql)  # warmup: compile + populate caches
    best = float("inf")
    result = None
    # measured reps always record a span tree (the fast-class
    # auto-degrade must not sample out the very run whose trace the
    # artifact keys derive from)
    with sess.settings.override(trace_fast_statement_ms=0):
        for _ in range(repeats):
            t0 = time.perf_counter()
            result = sess.execute(sql)
            best = min(best, time.perf_counter() - t0)
    assert result is not None and result.row_count > 0
    return rows_processed / best, best


def trace_phase_keys(doc, wall_seconds=None, sql=None):
    """phase_*_seconds derived FROM THE SPAN TRACE of a measured run
    (stats/tracing.py) — the drivers used to hand-roll these from
    ScanPhaseStats timers; deriving them from the same trace EXPLAIN
    ANALYZE renders makes artifact and EXPLAIN agree by construction.
    Stamps phase_source="trace" so test_bench_artifacts can gate README
    phase-attribution quotes on trace-derived keys.

    `sql`: the measured statement — when the recorder's fast-class
    auto-degrade sampled THIS run's tree out, last_trace() returns an
    OLDER statement's trace; pairing its walls with this run's wall
    clock would stamp wrong numbers under the provenance tag, so a
    mismatched doc stamps nothing."""
    from citus_tpu.stats.tracing import clamp_sql, span_seconds

    if doc is None or (sql is not None
                       and doc.get("sql") != clamp_sql(sql)):
        return {}
    root = doc["root"]
    transfer = (span_seconds(root, "scan.transfer")
                + span_seconds(root, "stream.transfer"))
    out = {
        "phase_source": "trace",
        "phase_prefetch_decode_seconds": round(
            span_seconds(root, "scan.prefetch")
            + span_seconds(root, "stream.decode"), 4),
        "phase_wire_encode_seconds": round(
            span_seconds(root, "scan.wire_encode"), 4),
        "phase_transfer_dispatch_seconds": round(transfer, 4),
        "phase_device_decode_seconds": round(
            span_seconds(root, "scan.device_decode"), 4),
        "phase_compile_seconds": round(
            span_seconds(root, "compile"), 4),
        "phase_device_execute_seconds": round(
            span_seconds(root, "mesh.dispatch")
            + span_seconds(root, "mesh.fetch"), 4),
    }
    if wall_seconds:
        out["transfer_wall_share"] = round(
            min(1.0, transfer / wall_seconds), 4)
    return out


def trace_acceptance_keys(sess, export_path=None, sql=None):
    """Acceptance evidence for the newest measured statement: the
    top-level-spans-sum-to-wall share of ITS trace, p50/p99 of its
    statement class from the DDSketch histograms, and (optionally) a
    Chrome-trace JSON export next to the artifact.  `sql` guards
    against last_trace() returning a different (auto-degrade-sampled)
    statement's trace — see trace_phase_keys."""
    from citus_tpu.stats.tracing import clamp_sql

    doc = sess.stats.tracing.last_trace()
    if doc is None or (sql is not None
                       and doc.get("sql") != clamp_sql(sql)):
        return {}
    root = doc["root"]
    top_ms = sum(c["dur_ms"] for c in root.get("children", ()))
    out = {"trace_wall_ms": doc["wall_ms"],
           "trace_top_span_share": (round(top_ms / root["dur_ms"], 4)
                                    if root["dur_ms"] else None)}
    cls = doc.get("class")  # traces carry their histogram key
    for row in sess.stats.tracing.latency_rows():
        if row["statement_class"] == cls:
            out["trace_p50_ms"] = row["p50_ms"]
            out["trace_p99_ms"] = row["p99_ms"]
            out["trace_calls"] = row["calls"]
            break
    if export_path:
        from citus_tpu.stats.trace_export import chrome_trace_events

        payload = {"traceEvents": chrome_trace_events(doc),
                   "displayTimeUnit": "ms"}
        with open(export_path, "w") as f:
            json.dump(payload, f, indent=1)
        out["trace_export"] = os.path.basename(export_path)
    return out


def bench_cold_scan(sess, n_rows: int):
    """Cold columnar scan: stripe read + decompress + pad + device_put +
    aggregate, with the HBM feed cache emptied first (the plan stays
    compiled — this measures the data path, not XLA).

    Runs the cold scan in the session's resolved scan_pipeline mode AND
    with the pipeline forced off, so the artifact itself carries the
    overlapped-vs-eager A/B; the pipelined run's per-phase walls
    (prefetch+decode, host wire-encode, transfer dispatch, on-device
    decode) and its bytes_on_wire vs bytes_decoded ratio come from the
    executor's ScanPhaseStats (reset per rep; the best rep's snapshot
    is published).  Returns (rate, best, parts, reps, eager_rate,
    eager_best); `parts` keeps the legacy host-decode/transfer split
    (measured separately over the same columns) next to the new phase
    keys so older artifact consumers still parse."""
    from citus_tpu.executor.scanpipe import resolve_scan_mode

    sql = ("select sum(l_quantity), sum(l_extendedprice), "
           "sum(l_discount), sum(l_tax) from lineitem")
    sess.execute(sql)  # compile + warm
    bytes_scanned = n_rows * 4 * 8  # four float64 columns as stored
    reps = 2
    mode = resolve_scan_mode(sess.settings)

    def run_mode(m):
        best, best_stats, best_doc = float("inf"), {}, None
        # trace_fast_statement_ms=0: the measured rep's tree must
        # exist — the phase keys below are derived from it
        with sess.settings.override(scan_pipeline=m,
                                    trace_fast_statement_ms=0):
            for _ in range(reps):
                sess.executor.feed_cache.clear()
                sess.executor.scan_stats.reset()
                t0 = time.perf_counter()
                r = sess.execute(sql)
                dt = time.perf_counter() - t0
                if dt < best:
                    best = dt
                    best_stats = sess.executor.scan_stats.snapshot()
                    best_doc = sess.stats.tracing.last_trace()
                assert r.row_count == 1
        return best, best_stats, best_doc

    best, stats, doc = run_mode(mode)
    eager_best, _, _ = run_mode("off")
    # host-only leg: same stripe read + decompress, no device
    cols = ["l_quantity", "l_extendedprice", "l_discount", "l_tax"]
    decode_best = float("inf")
    decoded_bytes = 0
    for _ in range(reps):
        sess.store._manifests.clear()
        t0 = time.perf_counter()
        decoded_bytes = 0
        for shard in sess.catalog.table_shards("lineitem"):
            vals, _mask, cnt = sess.store.read_shard(
                "lineitem", shard.shard_id, cols)
            decoded_bytes += sum(v.nbytes for v in vals.values())
        decode_best = min(decode_best, time.perf_counter() - t0)
    parts = {
        "host_decode_seconds": round(decode_best, 4),
        "host_decode_gb_per_sec": round(
            decoded_bytes / decode_best / 1e9, 3),
        # legacy split: decode vs remainder of the EAGER arm (the
        # pipelined arm overlaps the phases, so subtracting the serial
        # decode leg from its wall would not decompose anything and
        # could go negative) — the pipelined arm's decomposition is
        # the phase_* keys below
        "transfer_and_dispatch_seconds": round(
            max(0.0, eager_best - decode_best), 4),
        "bytes_decoded": decoded_bytes,
        "bytes_to_device": bytes_scanned,
        # pipelined-scan phase breakdown (best pipelined rep): the
        # phase_*_seconds walls come from the run's SPAN TRACE (the
        # same spans EXPLAIN ANALYZE's Timing line renders), byte
        # totals from ScanPhaseStats (the trace carries no byte
        # ledger); phase_source stamps the provenance for the README
        # honesty test
        "scan_pipeline": mode,
        "prefetch_stalls": stats.get("prefetch_stalls", 0),
        "bytes_on_wire": stats.get("bytes_on_wire", 0),
        "bytes_decoded_pipeline": stats.get("bytes_decoded", 0),
        "wire_ratio": (round(stats["bytes_on_wire"]
                             / stats["bytes_decoded"], 4)
                       if stats.get("bytes_decoded") else None),
        "eager_seconds": round(eager_best, 4),
        "vs_eager": round(eager_best / best, 3) if best else None,
    }
    parts.update(trace_phase_keys(doc, wall_seconds=best, sql=sql))
    return (bytes_scanned / best / 1e9, best, parts, reps,
            bytes_scanned / eager_best / 1e9, eager_best)


def bench_concurrency() -> None:
    """`python bench.py concurrency` — concurrent-throughput A/B for the
    workload manager (PERF_NOTES round 8): N worker sessions over one
    data_dir run an identical mixed-tenant statement stream twice, with
    the admission gate off then on (`wlm_enabled`, 2 slots), printing
    one JSON line per mode with aggregate rows/sec and the p50/p99
    admission queue wait.  Knobs: BENCH_CONC_WORKERS (default 4),
    BENCH_CONC_ITERS (statements per worker, default 10), BENCH_SF
    (default 0.05 — the scenario measures scheduling, not scan speed)."""
    import threading

    from citus_tpu.ingest.tpch import load_into_session
    from citus_tpu.session import Session

    n_workers = int(os.environ.get("BENCH_CONC_WORKERS", "4"))
    n_iters = int(os.environ.get("BENCH_CONC_ITERS", "10"))
    sf = float(os.environ.get("BENCH_SF", "0.05"))
    data_dir = tempfile.mkdtemp(prefix="citus_tpu_conc_")
    try:
        seed_sess = Session(data_dir=data_dir)
        counts = load_into_session(seed_sess, sf=sf, seed=0,
                                   tables={"orders", "lineitem"})
        n_li = counts["lineitem"]
        n_ord = counts["orders"]
        # per-iteration statement mix: a grouped scan-agg, a colocated
        # join, and a fast-path point read (exempt — rides free)
        mix = [
            ("select l_returnflag, count(*), sum(l_quantity) "
             "from lineitem group by l_returnflag", n_li),
            ("select count(*), sum(l_extendedprice) from orders, "
             "lineitem where o_orderkey = l_orderkey", n_ord + n_li),
            ("select o_totalprice from orders where o_orderkey = 1", 1),
        ]

        def run_mode(wlm_on: bool):
            # result cache off: the scenario measures admission
            # scheduling over real executions, not cache hits
            sessions = [Session(
                data_dir=data_dir, wlm_enabled=wlm_on,
                serving_result_cache_bytes=0,
                max_concurrent_statements=2,
                wlm_tenant=f"tenant{i % 2}",
                wlm_tenant_weights="tenant0:3,tenant1:1",
                wlm_default_priority="interactive" if i % 2 == 0
                else "batch")
                for i in range(n_workers)]
            for s in sessions:  # warm every plan cache off the clock
                for sql, _ in mix:
                    s.execute(sql)
            waits: list[float] = []
            waits_lock = threading.Lock()
            rows_done = [0] * n_workers

            def worker(i, s):
                local_waits = []
                for it in range(n_iters):
                    for sql, rows in mix:
                        s.execute(sql)
                        rows_done[i] += rows
                        info = getattr(s._wlm_tls, "last", None)
                        if info is not None:
                            local_waits.append(info["queued_ms"])
                with waits_lock:
                    waits.extend(local_waits)

            threads = [threading.Thread(target=worker, args=(i, s))
                       for i, s in enumerate(sessions)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            for s in sessions:
                s.close()
            waits.sort()

            def pct(p):
                return (round(waits[min(len(waits) - 1,
                                        int(p * len(waits)))], 2)
                        if waits else 0.0)

            return {
                "metric": "concurrency_rows_per_sec_wlm_"
                          + ("on" if wlm_on else "off"),
                "value": round(sum(rows_done) / elapsed, 1),
                "unit": "rows/s",
                "seconds": round(elapsed, 4),
                "sf": sf,
                "workers": n_workers,
                "iters": n_iters,
                "slots": 2 if wlm_on else None,
                "statements": n_workers * n_iters * len(mix),
                "p50_queue_wait_ms": pct(0.50),
                "p99_queue_wait_ms": pct(0.99),
            }

        seed_sess.close()
        for wlm_on in (False, True):
            print(json.dumps(run_mode(wlm_on)), flush=True)
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def bench_memory_pressure() -> None:
    """`python bench.py memory_pressure` — graceful-degradation A/B
    under device-memory starvation (PERF_NOTES round 12): N worker
    sessions over one data_dir run a mixed join/agg statement stream
    while the shared device-memory accountant (executor/hbm.py) is
    armed with a MemSim budget deliberately sized BELOW the workload's
    rehearsed peak, in two modes:

      * `memory_pressure_completed_share_ungoverned` — oom_degradation
        OFF: every allocator OOM surfaces immediately as a clean
        ResourceExhausted (the pre-PR-10 behavior minus the dead
        process);
      * `memory_pressure_completed_share_governed` — the degradation
        ladder ON: evict → shrink → stream → multi-pass before giving
        up.

    Each line reports the completed-statement share, the OOM-error
    rate, ladder counters (oom events / evictions / spill passes) and
    aggregate rows/s, so the artifact records BOTH what the ladder
    saves and what it costs.  Knobs: BENCH_MEM_WORKERS (default 8),
    BENCH_MEM_ITERS (statements per worker, default 6),
    BENCH_MEM_BUDGET_SHARE (budget as a fraction of rehearsed peak,
    default 0.5), BENCH_SF (default 0.05)."""
    import threading

    from citus_tpu.executor.hbm import accountant_for, oom_budget
    from citus_tpu.errors import ResourceExhausted
    from citus_tpu.ingest.tpch import load_into_session
    from citus_tpu.session import Session
    from citus_tpu.stats import counters as mem_sc

    n_workers = int(os.environ.get("BENCH_MEM_WORKERS", "8"))
    n_iters = int(os.environ.get("BENCH_MEM_ITERS", "6"))
    share = float(os.environ.get("BENCH_MEM_BUDGET_SHARE", "0.5"))
    sf = float(os.environ.get("BENCH_SF", "0.05"))
    data_dir = tempfile.mkdtemp(prefix="citus_tpu_mem_")
    try:
        seed_sess = Session(data_dir=data_dir,
                            serving_result_cache_bytes=0)
        counts = load_into_session(seed_sess, sf=sf, seed=0,
                                   tables={"orders", "lineitem"})
        n_li, n_ord = counts["lineitem"], counts["orders"]
        mix = [
            ("select l_returnflag, count(*), sum(l_quantity) "
             "from lineitem group by l_returnflag", n_li),
            ("select count(*), sum(l_extendedprice) from orders, "
             "lineitem where o_orderkey = l_orderkey", n_ord + n_li),
            ("select count(*) from orders, lineitem "
             "where o_custkey = l_suppkey", n_ord + n_li),
        ]
        acc = accountant_for(data_dir)
        # rehearsal: un-failing MemSim records the workload's peak live
        # bytes; the armed budget is a deliberate fraction of it
        for sql, _ in mix:
            seed_sess.execute(sql)
        peak0 = acc.peak_bytes
        with oom_budget(acc):
            seed_sess.executor.feed_cache.clear()
            for sql, _ in mix:
                seed_sess.execute(sql)
        budget = max(1, int(max(acc.peak_bytes, peak0) * share))
        seed_sess.close()

        def run_mode(governed: bool):
            # BOTH arms run with the WLM HBM gate aligned to the armed
            # budget (planned-estimate + measured-pressure admission,
            # oversized statements admit solo, streaming engages by
            # sizing) — the A/B isolates the LADDER: what happens when
            # an allocation still fails anyway
            sessions = [Session(
                data_dir=data_dir, serving_result_cache_bytes=0,
                oom_degradation=governed,
                max_feed_bytes_per_device=budget,
                retry_backoff_base_ms=1, retry_backoff_max_ms=5)
                for _ in range(n_workers)]
            for s in sessions:  # warm plan caches off the clock
                for sql, _ in mix:
                    s.execute(sql)
                s.executor.feed_cache.clear()
            tallies = {"completed": 0, "oom_errors": 0, "other": 0}
            tlock = threading.Lock()
            rows_done = [0] * n_workers
            snap0 = [s.stats.counters.snapshot() for s in sessions]

            def worker(i, s):
                local = {"completed": 0, "oom_errors": 0, "other": 0}
                for _ in range(n_iters):
                    for sql, rows in mix:
                        try:
                            s.execute(sql)
                            local["completed"] += 1
                            rows_done[i] += rows
                        except ResourceExhausted:
                            local["oom_errors"] += 1
                        except Exception:
                            local["other"] += 1
                with tlock:
                    for k, v in local.items():
                        tallies[k] += v

            threads = [threading.Thread(target=worker, args=(i, s))
                       for i, s in enumerate(sessions)]
            t0 = time.perf_counter()
            with oom_budget(acc, budget=budget):
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            elapsed = time.perf_counter() - t0

            def counter_delta(name):
                return sum(
                    s.stats.counters.snapshot().get(name, 0)
                    - snap0[i].get(name, 0)
                    for i, s in enumerate(sessions))

            oom_events = counter_delta(mem_sc.OOM_EVENTS_TOTAL)
            evictions = counter_delta(mem_sc.CACHE_EVICTIONS_TOTAL)
            spills = counter_delta(mem_sc.SPILL_PASSES_TOTAL)
            shrinks = counter_delta(
                mem_sc.STREAM_BATCH_SHRINKS_TOTAL)
            for s in sessions:
                s.close()
            total = n_workers * n_iters * len(mix)
            return {
                "metric": "memory_pressure_completed_share_"
                          + ("governed" if governed else "ungoverned"),
                "value": round(tallies["completed"] / total, 4),
                "unit": "share",
                "seconds": round(elapsed, 4),
                "sf": sf,
                "workers": n_workers,
                "iters": n_iters,
                "statements": total,
                "budget_bytes": budget,
                "budget_share_of_peak": share,
                "completed": tallies["completed"],
                "oom_errors": tallies["oom_errors"],
                "other_errors": tallies["other"],
                "oom_error_share": round(
                    tallies["oom_errors"] / total, 4),
                "oom_events": oom_events,
                "cache_evictions": evictions,
                "stream_batch_shrinks": shrinks,
                "spill_passes": spills,
                "rows_per_sec": round(sum(rows_done) / elapsed, 1),
            }

        for governed in (False, True):
            print(json.dumps(run_mode(governed)), flush=True)
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def bench_serving() -> None:
    """`python bench.py serving` — high-QPS point-lookup A/B for the
    serving layer (PERF_NOTES round 11): N concurrent sessions over one
    data_dir fire repeated literal point reads (keys drawn from a hot
    pool — the serving workload shape, routed via the persistent point
    index) in three modes, one JSON line each:

      * `point_lookup_qps_baseline`  — serving OFF (per-statement solo
        dispatch, the pre-PR-8 path);
      * `point_lookup_qps_batched`   — micro-batcher ON, result cache
        OFF (isolates the coalescing win; batch occupancy reported);
      * `point_lookup_qps`           — the full serving layer (batcher
        + CDC-invalidated result cache; cache hit rate reported) —
        the headline stamped into the BENCH artifact.

    Every line reports QPS + per-lookup p50/p99 latency.  Knobs:
    BENCH_SRV_SESSIONS (default 8), BENCH_SRV_ITERS (lookups per
    session, default 150 — long enough that the hot pool's one-time
    misses amortize the way a resident working set does),
    BENCH_SRV_HOT_KEYS (hot-pool size, default 32 — the Zipf head a
    read-mostly serving tier actually absorbs), BENCH_SF (default
    0.05 — the scenario measures dispatch amortization, not scan
    speed)."""
    import threading

    from citus_tpu.ingest.tpch import load_into_session
    from citus_tpu.session import Session
    from citus_tpu.stats import counters as srv_sc

    n_sessions = int(os.environ.get("BENCH_SRV_SESSIONS", "8"))
    n_iters = int(os.environ.get("BENCH_SRV_ITERS", "150"))
    n_hot = int(os.environ.get("BENCH_SRV_HOT_KEYS", "32"))
    sf = float(os.environ.get("BENCH_SF", "0.05"))
    data_dir = tempfile.mkdtemp(prefix="citus_tpu_srv_")
    try:
        # seed with the result cache OFF so warming the point index
        # below cannot pre-fill the cache the measured modes report on
        seed_sess = Session(data_dir=data_dir,
                            serving_result_cache_bytes=0)
        load_into_session(seed_sess, sf=sf, seed=0, tables={"orders"})
        n_ord = seed_sess.store.table_row_count("orders")
        # hot keys that actually exist (orders keys are sparse ints)
        rows = seed_sess.execute(
            f"select o_orderkey from orders where o_orderkey >= 0 "
            f"order by o_orderkey limit {n_hot}").rows()
        hot = [int(k) for (k,) in rows]
        for k in hot:  # build the per-shard index sidecars off the clock
            seed_sess.execute(
                f"select o_totalprice from orders where o_orderkey = {k}")
        seed_sess.close()

        def run_mode(name, serving_on, cache_on, trace_on=True,
                     shared_sessions=None):
            # `shared_sessions`: the trace-overhead A/B flips ONE knob
            # on one warmed session set instead of rebuilding sessions
            # per arm — fresh-session warmup variance (~8% run to run
            # on this sandbox) would otherwise drown a ~1% effect
            own = shared_sessions is None
            if own:
                sessions = [Session(
                    data_dir=data_dir, serving_enabled=serving_on,
                    trace_enabled=trace_on,
                    serving_result_cache_bytes=(256 << 20) if cache_on
                    else 0) for _ in range(n_sessions)]
            else:
                sessions = shared_sessions
                for s in sessions:
                    s.settings.set("trace_enabled", trace_on)
            for s in sessions:  # warm parse/plan caches off the clock
                s.execute("select o_totalprice from orders "
                          f"where o_orderkey = {hot[0]}")
            from citus_tpu.serving.batcher import batcher_for

            # per-mode totals: max_batch_seen is a monotone max, so a
            # snapshot delta cannot isolate this mode — reset instead
            batcher_for(data_dir).reset_totals()
            b0 = batcher_for(data_dir).snapshot()
            lats: list[float] = []
            lats_lock = threading.Lock()
            barrier = threading.Barrier(n_sessions)

            def worker(wid, s):
                rng = __import__("random").Random(wid)
                local = []
                barrier.wait()
                for _ in range(n_iters):
                    k = hot[rng.randrange(len(hot))]
                    t0 = time.perf_counter()
                    r = s.execute("select o_totalprice from orders "
                                  f"where o_orderkey = {k}")
                    local.append(time.perf_counter() - t0)
                    assert r.row_count >= 1
                with lats_lock:
                    lats.extend(local)

            threads = [threading.Thread(target=worker, args=(i, s))
                       for i, s in enumerate(sessions)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            snaps = [s.stats.counters.snapshot() for s in sessions]
            hits = sum(sn[srv_sc.SERVING_CACHE_HITS_TOTAL]
                       for sn in snaps)
            misses = sum(sn[srv_sc.SERVING_CACHE_MISSES_TOTAL]
                         for sn in snaps)
            b1 = batcher_for(data_dir).snapshot()
            d_disp = b1["batch_dispatch_total"] - \
                b0["batch_dispatch_total"]
            d_lk = b1["batched_lookups_total"] - \
                b0["batched_lookups_total"]
            if own:
                for s in sessions:
                    s.close()
            lats.sort()

            def pct(p):
                return round(lats[min(len(lats) - 1,
                                      int(p * len(lats)))] * 1000, 3)

            total = n_sessions * n_iters
            return {
                "metric": name,
                "value": round(total / elapsed, 1),
                "unit": "lookups/s",
                "seconds": round(elapsed, 4),
                "sf": sf,
                "sessions": n_sessions,
                "iters": n_iters,
                "hot_keys": len(hot),
                "orders_rows": n_ord,
                "p50_ms": pct(0.50),
                "p99_ms": pct(0.99),
                "avg_batch_occupancy": (round(d_lk / d_disp, 3)
                                        if d_disp else 0.0),
                "max_batch_seen": b1["max_batch_seen"],
                "cache_hit_rate": (round(hits / (hits + misses), 3)
                                   if hits + misses else None),
            }

        for name, srv, cache in (
                ("point_lookup_qps_baseline", False, False),
                ("point_lookup_qps_batched", True, False)):
            print(json.dumps(run_mode(name, srv, cache)), flush=True)
        # span-recorder overhead A/B: the full serving stack traced vs
        # trace_enabled=off, measured as paired order-alternating
        # rounds over ONE warmed session set (flipping only the knob).
        # Methodology matters more than the effect here: fresh
        # sessions per arm plus a fixed order charged the sandbox's
        # run-to-run drift to whichever arm ran first and "measured"
        # the recorder at 13% — an overhead that flipped sign when the
        # order flipped.  The always-on recorder must cost ≲2% of
        # steady-state QPS (PERF_NOTES r16).
        import statistics

        ab_rounds = int(os.environ.get("BENCH_SRV_AB_ROUNDS", "4"))
        if ab_rounds < 1:
            # A/B disabled: still print the headline serving line the
            # artifact contract expects
            print(json.dumps(run_mode("point_lookup_qps", True, True)),
                  flush=True)
            return
        ab_sessions = [Session(
            data_dir=data_dir, serving_enabled=True,
            serving_result_cache_bytes=256 << 20)
            for _ in range(n_sessions)]
        try:
            on_lines, off_lines = [], []
            for rnd in range(ab_rounds):
                arms = [("point_lookup_qps", True),
                        ("point_lookup_qps_trace_off", False)]
                if rnd % 2:
                    arms.reverse()
                for aname, tr in arms:
                    line = run_mode(aname, True, True, tr,
                                    shared_sessions=ab_sessions)
                    (on_lines if tr else off_lines).append(line)
        finally:
            for s in ab_sessions:
                s.close()
        on_best = max(on_lines, key=lambda x: x["value"])
        off_best = max(off_lines, key=lambda x: x["value"])
        # overhead from MEDIANS over the post-warmup rounds (a
        # difference of noisy maxima is noisier than either; round 0
        # is cold for both arms), plus the derived per-statement CPU
        # cost in µs — the number that transfers off this sandbox:
        # this scenario's cache-hit statement is ~0.4 ms of pure
        # Python, so the share is its worst case; on any ≥2 ms
        # statement the same µs is <2% of wall
        med_on = statistics.median(
            x["value"] for x in on_lines[1:] or on_lines)
        med_off = statistics.median(
            x["value"] for x in off_lines[1:] or off_lines)
        if med_off:
            off_best["trace_overhead_pct"] = round(
                100.0 * (1.0 - med_on / med_off), 2)
        if med_on and med_off:
            # the hammer is GIL-bound: aggregate QPS ≈ one core's
            # statement rate, so 1/QPS deltas are CPU-per-statement
            off_best["trace_overhead_us_per_stmt"] = round(
                (1.0 / med_on - 1.0 / med_off) * 1e6, 1)
        off_best["trace_ab_rounds"] = ab_rounds
        off_best["trace_ab_qps_on"] = [x["value"] for x in on_lines]
        off_best["trace_ab_qps_off"] = [x["value"] for x in off_lines]
        print(json.dumps(on_best), flush=True)
        print(json.dumps(off_best), flush=True)
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def bench_cold_start() -> None:
    """`python bench.py cold_start` — restart-survival A/B for the
    persistent executable cache (PERF_NOTES round 17).  Every arm runs
    in a CHILD PROCESS (the bench_multichip pattern): a restart is a
    process boundary, and an in-process "fresh session" would still
    share jax's in-memory state.  One JSON line per measurement:

      * `cold_start_first_answer_s_cache_on/off` — connect → first Q3
        answer on a fresh process over a warm data_dir, with the
        persisted cache adopted (warm-before-admit engaged) vs the
        recompile-per-process baseline;
      * `cold_start_storm_p99_ms_cache_on/off` — 8 sessions in a fresh
        process all hitting one cold shape concurrently (the deploy-
        under-live-traffic storm): worst first-answer latency, cache
        loads vs 8 redundant compiles;
      * `cold_start_redundant_compiles` — the dedup contract measured
        with an EMPTY disk cache: 8-session cold fan-in through the
        single-flight gate must produce exactly 1 compile for 1
        distinct shape (value = compiles beyond that, i.e. 0);
      * `cold_start_first_answer_speedup` / `cold_start_storm_speedup`
        — the A/B ratios (the ≥10× acceptance numbers).

    Knobs: BENCH_COLD_SF (default 0.01 — compile cost is structural,
    not data-sized, so the dataset stays small), BENCH_COLD_SESSIONS
    (default 8), BENCH_COLD_QUERY (TPC-H name overriding the default
    FK-chain probe)."""
    import subprocess

    here = os.path.abspath(__file__)
    base = tempfile.mkdtemp(prefix="citus_tpu_coldstart_")
    data_dir = os.path.join(base, "data")
    vals: dict[str, float] = {}

    lines: dict[str, dict] = {}

    def child(*args) -> None:
        out = subprocess.run(
            [sys.executable, here, "_cold_child", data_dir, *args],
            capture_output=True, text=True, timeout=1800)
        sys.stderr.write(out.stderr)
        if out.returncode != 0:
            raise RuntimeError(
                f"cold_start child {args} rc={out.returncode}")
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            obj = json.loads(line)
            if "metric" in obj:
                vals[obj["metric"]] = obj["value"]
                lines[obj["metric"]] = obj
                print(json.dumps(obj), flush=True)

    try:
        child("seed")
        child("first_answer", "on")
        child("first_answer", "off")
        child("storm", "on")
        child("storm", "off")
        child("storm_dedup")
        for name, on, off, unit in (
                ("cold_start_first_answer_speedup",
                 "cold_start_first_answer_s_cache_on",
                 "cold_start_first_answer_s_cache_off", "x"),
                ("cold_start_storm_speedup",
                 "cold_start_storm_p99_ms_cache_on",
                 "cold_start_storm_p99_ms_cache_off", "x")):
            if vals.get(on) and vals.get(off):
                print(json.dumps({
                    "metric": name, "unit": unit,
                    # off/on: how many times FASTER the cache makes it
                    "value": round(vals[off] / vals[on], 2),
                }), flush=True)
        # executable-acquisition ratio: compile phase + warmup
        # adoption, trace-derived — the isolated cost the cache
        # replaces (wall ratios above additionally carry session
        # init/plan/feed costs both arms pay identically)
        acq_on = lines.get("cold_start_first_answer_s_cache_on",
                           {}).get("executable_acquisition_s")
        acq_off = lines.get("cold_start_first_answer_s_cache_off",
                            {}).get("executable_acquisition_s")
        if acq_on and acq_off:
            print(json.dumps({
                "metric": "cold_start_compile_speedup", "unit": "x",
                "value": round(acq_off / acq_on, 2),
                "acquisition_s_cache_on": acq_on,
                "acquisition_s_cache_off": acq_off,
            }), flush=True)
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _cold_child(data_dir: str, mode: str, arm: str = "on") -> None:
    """One cold_start measurement arm in its own process (see
    bench_cold_start).  Prints JSON metric lines on stdout."""
    import threading

    import numpy as np

    from citus_tpu.executor.execcache import exec_cache_for
    from citus_tpu.ingest.tpch import QUERIES, load_into_session
    from citus_tpu.session import Session
    from citus_tpu.stats import counters as cs

    sf = float(os.environ.get("BENCH_COLD_SF", "0.01"))
    n_sessions = int(os.environ.get("BENCH_COLD_SESSIONS", "8"))
    # the probe is a compile-heavy 7-table FK join chain (multiple
    # repartition stages — the statement class a restart hurts most)
    # WITHOUT subqueries: subplan temp tables are per-session, so a
    # subquery shape would fingerprint differently in every session
    # and the storm would measure temp-table churn, not compile dedup.
    # BENCH_COLD_QUERY swaps in a named TPC-H query instead.
    probe_name = os.environ.get("BENCH_COLD_QUERY", "")
    storm_sql = QUERIES[probe_name] if probe_name else (
        "select n_name, count(*), "
        "sum(l_extendedprice * (1 - l_discount)), min(o_totalprice), "
        "max(s_acctbal), sum(ps_supplycost * l_quantity) "
        "from orders, lineitem, part, partsupp, supplier, customer, "
        "nation where o_orderkey = l_orderkey "
        "and l_partkey = p_partkey and ps_partkey = l_partkey "
        "and ps_suppkey = l_suppkey and s_suppkey = l_suppkey "
        "and o_custkey = c_custkey and c_nationkey = n_nationkey "
        "group by n_name")
    on = arm == "on"
    # result cache OFF everywhere: a cache-served repeat would measure
    # the serving layer, not restart survival; capacity feedback OFF in
    # the storm arms so one statement is exactly one executable shape
    common = dict(data_dir=data_dir, serving_result_cache_bytes=0)

    if mode == "seed":
        sess = Session(**common)
        load_into_session(sess, sf=sf, seed=0)
        sess.execute(storm_sql)
        sess.close()
        print(json.dumps({"seeded": True, "sf": sf,
                          "probe": probe_name or "fk_chain_7table"}),
              flush=True)
        return

    if mode == "first_answer":
        t0 = time.perf_counter()
        sess = Session(exec_cache_enabled=on,
                       warmup_budget_ms=30_000 if on else 0,
                       **common)
        t_init = time.perf_counter()
        # warm-before-admit runs on its own thread; join it so the
        # adoption cost is measured explicitly (warmup_wall_s) instead
        # of hiding inside the first statement's admission wait
        if sess._warmup_thread is not None:
            sess._warmup_thread.join()
        warmup_wall = time.perf_counter() - t_init
        r = sess.execute(storm_sql)
        wall = time.perf_counter() - t0
        assert r.row_count > 0
        snap = sess.stats.counters.snapshot()
        line = {
            "metric": f"cold_start_first_answer_s_cache_{arm}",
            "value": round(wall, 4), "unit": "s", "sf": sf,
            "exec_cache_hits": snap[cs.EXEC_CACHE_HITS_TOTAL],
            "warmup_compiles": snap[cs.WARMUP_COMPILES_TOTAL],
            "warmup_wall_s": round(warmup_wall, 4),
        }
        # compile-phase attribution from the span trace: the wall
        # above includes session init + feed build (paid identically
        # by both arms); executable ACQUISITION — in-statement compile
        # phase plus the explicit warmup adoption above — is what the
        # cache replaces.  Trace-derived, same provenance contract as
        # the scan phase keys (phase_source="trace")
        phases = trace_phase_keys(sess.stats.tracing.last_trace(),
                                  sql=storm_sql)
        if "phase_compile_seconds" in phases:
            line["phase_source"] = "trace"
            line["phase_compile_seconds"] = \
                phases["phase_compile_seconds"]
            line["executable_acquisition_s"] = round(
                phases["phase_compile_seconds"] + warmup_wall, 4)
        print(json.dumps(line), flush=True)
        sess.close()
        return

    if mode in ("storm", "storm_dedup"):
        ec = exec_cache_for(data_dir)
        if mode == "storm_dedup":
            # the dedup contract needs a COLD disk: wipe the persisted
            # entries so all 8 sessions race one genuinely cold shape
            cache_dir = ec.dir
            for f in (os.listdir(cache_dir)
                      if os.path.isdir(cache_dir) else []):
                os.unlink(os.path.join(cache_dir, f))
        sessions = [Session(exec_cache_enabled=(on or
                                                mode == "storm_dedup"),
                            enable_capacity_feedback=False, **common)
                    for _ in range(n_sessions)]
        barrier = threading.Barrier(n_sessions)
        lats = [0.0] * n_sessions

        def worker(i):
            barrier.wait(timeout=60)
            t0 = time.perf_counter()
            r = sessions[i].execute(storm_sql)
            lats[i] = time.perf_counter() - t0
            assert r.row_count > 0

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_sessions)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = ec.snapshot()
        if mode == "storm":
            print(json.dumps({
                "metric": f"cold_start_storm_p99_ms_cache_{arm}",
                "value": round(
                    float(np.percentile(lats, 99)) * 1000.0, 2),
                "unit": "ms", "sessions": n_sessions, "sf": sf,
                "latencies_ms": [round(x * 1000.0, 2) for x in lats],
                "compiles": snap["compiles_total"],
            }), flush=True)
        else:
            # 1 distinct shape, N sessions: redundant = compiles - 1
            print(json.dumps({
                "metric": "cold_start_redundant_compiles",
                "value": snap["compiles_total"] - 1,
                "unit": "compiles",
                "sessions": n_sessions, "distinct_shapes": 1,
                "compiles_total": snap["compiles_total"],
                "compiles_deduped": snap["gate_deduped_total"],
                "exec_cache_hits": ec.hits_total,
            }), flush=True)
        for s in sessions:
            s.close()
        return
    raise SystemExit(f"unknown _cold_child mode {mode!r}")


def bench_replica_fleet() -> None:
    """`python bench.py replica_fleet` — CDC log-shipped replica fleet
    (PERF_NOTES round 18).  A leader data_dir ships committed stripes +
    the CDC journal to three follower data_dirs; each replica serves
    point lookups from its OWN PROCESS (the cold_start child pattern:
    scale-out is a process boundary).  One JSON line per measurement:

      * `replica_process_capacity_qps` — UNPACED point-lookup QPS of
        one replica process: the raw per-process capacity of this
        host.  On a single-core sandbox this is also the hard ceiling
        of any aggregate (processes share the core), which is why the
        fleet lines below measure OFFERED LOAD instead;
      * `replica_fleet_single_qps` — one replica serving a paced
        offered load (capacity/(fleet+1) QPS, stamped as
        `offered_qps`): the per-replica serving baseline;
      * `replica_fleet_aggregate_qps` — three replica processes each
        serving the same offered load concurrently while the leader
        keeps committing and shipping; every answer verified.  The
        acceptance bar is ≥2× the single-replica line — shared-nothing
        replicas sustain the multiplied offered load (CPU-bound
        unpaced scaling is flat on one core: PERF_NOTES round 18);
      * `replica_kill_wrong_rows` — one replica process is SIGKILLed
        mid-storm; every answer the fleet returned must verify against
        the seeded oracle (value is the wrong-answer count: 0);
      * `replica_promote_first_answer_s` — leader death to first
        WRITE answered by a freshly promoted replica, in a cold
        process (connect → citus_promote_replica() → INSERT → SELECT);
      * `replica_provision_first_answer_s` — cold-replica provision:
        empty dir → full reseed ship/apply → first verified answer,
        in a cold process.

    Knobs: BENCH_REPLICA_ROWS (default 20000), BENCH_REPLICA_SECONDS
    (storm length per arm, default 6), BENCH_REPLICA_FLEET (default 3
    replicas)."""
    import signal
    import subprocess

    from citus_tpu.replication import provision_replica, ship_all
    from citus_tpu.session import Session

    here = os.path.abspath(__file__)
    n_rows = int(os.environ.get("BENCH_REPLICA_ROWS", "20000"))
    seconds = float(os.environ.get("BENCH_REPLICA_SECONDS", "6"))
    fleet = int(os.environ.get("BENCH_REPLICA_FLEET", "3"))
    base = tempfile.mkdtemp(prefix="citus_tpu_replfleet_")
    lead = os.path.join(base, "leader")
    vals: dict[str, float] = {}

    def emit(obj) -> None:
        vals[obj["metric"]] = obj["value"]
        print(json.dumps(obj), flush=True)

    def spawn(dirname, *args):
        return subprocess.Popen(
            [sys.executable, here, "_replica_child", dirname, *args],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

    def collect(procs, allow_kill=False):
        out = []
        for p in procs:
            stdout, stderr = p.communicate(timeout=600)
            if p.returncode != 0:
                if allow_kill and p.returncode == -signal.SIGKILL:
                    continue  # the chaos victim
                sys.stderr.write(stderr)
                raise RuntimeError(
                    f"replica child rc={p.returncode}")
            for line in stdout.splitlines():
                if line.strip().startswith("{"):
                    out.append(json.loads(line))
        return out

    try:
        sess = Session(data_dir=lead,
                       serving_result_cache_bytes=0)
        sess.execute("CREATE TABLE kv (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('kv', 'id', 4)")
        step = 5000
        for lo in range(0, n_rows, step):
            sess.execute("INSERT INTO kv VALUES " + ", ".join(
                f"({i}, {i * 3})" for i in range(lo,
                                                 min(lo + step, n_rows))))
        replicas = [os.path.join(base, f"replica{i}")
                    for i in range(fleet)]
        for rdir in replicas:
            provision_replica(lead, rdir,
                              counters=sess.stats.counters)

        # raw per-process capacity (unpaced): the host's ceiling
        res = collect([spawn(replicas[0], "storm", str(seconds),
                             str(n_rows), "1", "0")])
        assert res[0]["wrong"] == 0, "capacity storm wrong rows"
        capacity = res[0]["qps"]
        emit({"metric": "replica_process_capacity_qps",
              "value": round(capacity, 1), "unit": "queries/s",
              "queries": res[0]["queries"], "rows": n_rows,
              "paced": False, "storm_seconds": seconds})

        # offered load per replica, sized so the WHOLE fleet plus the
        # leader's churn fits the host's capacity (the scale-out
        # question is "does each shared-nothing replica sustain its
        # load", not "does one core run three processes faster")
        offered = max(10.0, capacity / (fleet + 2))

        # single-replica baseline at the offered load
        res = collect([spawn(replicas[0], "storm", str(seconds),
                             str(n_rows), "1", f"{offered:.3f}")])
        assert res[0]["wrong"] == 0, "single-replica storm wrong rows"
        emit({"metric": "replica_fleet_single_qps",
              "value": round(res[0]["qps"], 1), "unit": "queries/s",
              "queries": res[0]["queries"], "rows": n_rows,
              "paced": True, "offered_qps": round(offered, 1),
              "storm_seconds": seconds})

        def leader_churn(stop_after: float) -> int:
            """Mid-storm leader work: commit fresh rows and ship them
            while the fleet serves (replicas drain applies at their
            read gates)."""
            t0, shipped = time.perf_counter(), 0
            nid = 10_000_000
            while time.perf_counter() - t0 < stop_after:
                sess.execute(
                    f"INSERT INTO kv VALUES ({nid}, {nid * 3})")
                nid += 1
                ship_all(lead, counters=sess.stats.counters)
                shipped += 1
                time.sleep(0.05)
            return shipped

        # fleet storm: N processes at the offered load + live leader
        # churn
        procs = [spawn(r, "storm", str(seconds), str(n_rows),
                       str(i + 2), f"{offered:.3f}")
                 for i, r in enumerate(replicas)]
        shipped = leader_churn(seconds * 0.8)
        res = collect(procs)
        agg = sum(r["qps"] for r in res)
        wrong = sum(r["wrong"] for r in res)
        assert wrong == 0, f"fleet storm wrong rows: {wrong}"
        emit({"metric": "replica_fleet_aggregate_qps",
              "value": round(agg, 1), "unit": "queries/s",
              "replicas": fleet, "paced": True,
              "offered_qps_per_replica": round(offered, 1),
              "per_replica_qps": [round(r["qps"], 1) for r in res],
              "batches_shipped_mid_storm": shipped,
              "scaleout_x": round(agg / max(vals[
                  "replica_fleet_single_qps"], 1e-9), 2)})
        emit({"metric": "replica_fleet_scaleout", "unit": "x",
              "value": round(agg / max(vals[
                  "replica_fleet_single_qps"], 1e-9), 2)})

        # replica-kill mid-storm: SIGKILL one child, survivors keep
        # answering; zero wrong rows across every answered lookup
        procs = [spawn(r, "storm", str(seconds), str(n_rows),
                       str(i + 20), f"{offered:.3f}")
                 for i, r in enumerate(replicas)]
        time.sleep(seconds / 2)
        procs[0].kill()
        res = collect(procs, allow_kill=True)
        wrong = sum(r["wrong"] for r in res)
        answered = sum(r["queries"] for r in res)
        emit({"metric": "replica_kill_wrong_rows", "value": wrong,
              "unit": "rows", "survivors": len(res),
              "answered_by_survivors": answered})
        assert wrong == 0 and len(res) == fleet - 1

        # leader-kill → first promoted answer (cold process)
        sess.close()  # the leader process "dies"
        res = collect([spawn(replicas[0], "promote", str(n_rows))])
        emit({"metric": "replica_promote_first_answer_s",
              "value": res[0]["wall_s"], "unit": "s",
              "epoch": res[0]["epoch"],
              "promote_s": res[0]["promote_s"]})

        # cold-replica provision → first verified answer: a brand-new
        # follower of the PROMOTED leader (the post-failover refill)
        res = collect([spawn(os.path.join(base, "replica_new"),
                             "provision", replicas[0], str(n_rows))])
        emit({"metric": "replica_provision_first_answer_s",
              "value": res[0]["wall_s"], "unit": "s",
              "files_shipped": res[0]["files"]})
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _replica_child(data_dir: str, mode: str, *args: str) -> None:
    """One replica_fleet measurement arm in its own process (see
    bench_replica_fleet).  Prints JSON lines on stdout."""
    import random

    from citus_tpu.session import Session

    if mode == "storm":
        seconds, n_rows, seed = (float(args[0]), int(args[1]),
                                 int(args[2]))
        # rate 0 = unpaced (capacity); >0 = closed-loop offered load
        rate = float(args[3]) if len(args) > 3 else 0.0
        sess = Session(data_dir=data_dir,
                       serving_result_cache_bytes=0)
        rng = random.Random(seed)
        # answer once before the clock starts: session warm-up is the
        # provision/promote arms' metric, not the storm's
        sess.execute("SELECT v FROM kv WHERE id = 0")
        t0 = time.perf_counter()
        queries = wrong = 0
        while True:
            now = time.perf_counter() - t0
            if now >= seconds:
                break
            if rate > 0:
                due = queries / rate
                if due > now:
                    time.sleep(min(due - now, seconds - now))
                    continue
            k = rng.randrange(n_rows)
            rows = sess.execute(
                f"SELECT v FROM kv WHERE id = {k}").rows()
            queries += 1
            if len(rows) != 1 or int(rows[0][0]) != k * 3:
                wrong += 1
        wall = time.perf_counter() - t0
        print(json.dumps({"qps": queries / wall, "queries": queries,
                          "wrong": wrong, "wall_s": round(wall, 3),
                          "offered_qps": rate}),
              flush=True)
        sess.close()
        return

    if mode == "promote":
        n_rows = int(args[0])
        t0 = time.perf_counter()
        sess = Session(data_dir=data_dir,
                       serving_result_cache_bytes=0)
        t1 = time.perf_counter()
        epoch = sess.execute(
            "SELECT citus_promote_replica()").rows()[0][0]
        t2 = time.perf_counter()
        sess.execute(f"INSERT INTO kv VALUES ({n_rows + 1}, -1)")
        r = sess.execute(
            f"SELECT v FROM kv WHERE id = {n_rows + 1}").rows()
        assert int(r[0][0]) == -1
        wall = time.perf_counter() - t0
        print(json.dumps({"wall_s": round(wall, 4),
                          "connect_s": round(t1 - t0, 4),
                          "promote_s": round(t2 - t1, 4),
                          "epoch": int(epoch)}), flush=True)
        sess.close()
        return

    if mode == "provision":
        from citus_tpu.replication import provision_replica

        leader_dir, n_rows = args[0], int(args[1])
        t0 = time.perf_counter()
        provision_replica(leader_dir, data_dir)
        sess = Session(data_dir=data_dir,
                       serving_result_cache_bytes=0)
        k = n_rows // 2
        r = sess.execute(f"SELECT v FROM kv WHERE id = {k}").rows()
        assert int(r[0][0]) == k * 3
        wall = time.perf_counter() - t0
        nfiles = sum(len(fs) for _, _, fs in
                     os.walk(os.path.join(data_dir, "tables")))
        print(json.dumps({"wall_s": round(wall, 4),
                          "files": nfiles}), flush=True)
        sess.close()
        return
    raise SystemExit(f"unknown _replica_child mode {mode!r}")


def main() -> None:
    if sys.argv[1:2] == ["concurrency"]:
        bench_concurrency()
        return
    if sys.argv[1:2] == ["serving"]:
        bench_serving()
        return
    if sys.argv[1:2] == ["memory_pressure"]:
        bench_memory_pressure()
        return
    if sys.argv[1:2] == ["cold_start"]:
        bench_cold_start()
        return
    if sys.argv[1:2] == ["_cold_child"]:
        _cold_child(sys.argv[2], sys.argv[3], *sys.argv[4:5])
        return
    if sys.argv[1:2] == ["replica_fleet"]:
        bench_replica_fleet()
        return
    if sys.argv[1:2] == ["_replica_child"]:
        _replica_child(sys.argv[2], sys.argv[3], *sys.argv[4:])
        return
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    # BENCH_REPEAT=N: best-of-N authority — every config (SF10 lines
    # included) runs at least N measured executions, and each emitted
    # line records it, so the artifact is self-describing best-of-N
    rep_override = int(os.environ.get("BENCH_REPEAT", "0"))

    def n_reps(default: int) -> int:
        return max(default, rep_override)

    repeats = n_reps(repeats)
    sf10 = os.environ.get("BENCH_SF10", "1") not in ("0", "false", "")
    sf10_scale = float(os.environ.get("BENCH_SF10_SCALE", "10.0"))
    extras = os.environ.get("BENCH_EXTRAS", "0") not in ("0", "false", "")
    budget = float(os.environ.get("BENCH_BUDGET", "2400"))
    t_start = time.perf_counter()
    only = os.environ.get("BENCH_ONLY")
    only = set(only.split(",")) if only else None

    from citus_tpu.session import Session
    from citus_tpu.ingest.tpch import QUERIES, load_into_session

    lines = []

    def over_budget(share: float = 1.0) -> bool:
        """True once `share` of the wall-clock budget is spent; optional
        configs check this before starting so the headline always runs."""
        return time.perf_counter() - t_start > budget * share

    # measured CPU rows (bench_cpu_baseline.py; sqlite3 on this host) —
    # a second, honest denominator next to the reference yardstick
    cpu_rows = {}
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as f:
            cpu_rows = json.load(f).get("cpu_baseline", {})
    except Exception:
        pass

    def emit(name, rate, best, this_sf, unit="rows/s",
             baseline=BASELINE_ROWS_PER_SEC, extra=None, reps=None,
             sess_obj=None):
        line = {
            "metric": name,
            "value": round(rate, 3 if unit != "rows/s" else 1),
            "unit": unit,
            "vs_baseline": round(rate / baseline, 3),
            "seconds": round(best, 4),
            "sf": this_sf,
        }
        if extra:
            line.update(extra)
        if reps is not None:
            # the ACTUAL measured-execution count for EVERY line (a
            # config default above BENCH_REPEAT runs its default) —
            # the artifact must describe what actually ran, not just
            # the lines BENCH_REPEAT happened to touch
            line["repeats"] = reps
        # cumulative plan-cache traffic of the emitting session at the
        # moment this line lands: warm-vs-cold is auditable from the
        # JSON alone (a config whose misses didn't grow ran entirely
        # on cached executables)
        s = sess_obj if sess_obj is not None else sess
        line["plan_cache_hits"] = s.executor.plan_cache.hits
        line["plan_cache_misses"] = s.executor.plan_cache.misses
        cpu = cpu_rows.get(name)
        if cpu and cpu.get("sf") == this_sf and cpu.get("rows_per_sec"):
            line["vs_cpu"] = round(rate / cpu["rows_per_sec"], 3)
            line["cpu_engine"] = cpu.get("engine", "")
        lines.append(line)
        # print + flush immediately: a timeout later in the run must not
        # erase configs that already finished (round-3 postmortem).
        print(json.dumps(line), flush=True)

    data_dir = tempfile.mkdtemp(prefix="citus_tpu_bench_")
    try:
        # result cache OFF: bench_query repeats the same SQL — serving a
        # repeat from the result cache would measure the cache, not the
        # engine (the serving scenario measures the cache explicitly)
        sess = Session(data_dir=data_dir, serving_result_cache_bytes=0)
        load_into_session(sess, sf=sf, seed=0)
        n_li = sess.store.table_row_count("lineitem")
        n_ord = sess.store.table_row_count("orders")
        n_cust = sess.store.table_row_count("customer")

        configs = [
            # (name, sql, rows processed by the query)
            ("colocated_join_rows_per_sec",
             "select count(*), sum(l_extendedprice) from orders, lineitem "
             "where o_orderkey = l_orderkey",
             n_ord + n_li),
            ("single_repartition_join_rows_per_sec",
             "select count(*), sum(o_totalprice) from customer, orders "
             "where c_custkey = o_custkey",
             n_cust + n_ord),
            ("dual_repartition_join_rows_per_sec",
             "select count(*) from orders, lineitem "
             "where o_custkey = l_suppkey",
             n_ord + n_li),
            ("tpch_q3_rows_per_sec", QUERIES["Q3"], n_cust + n_ord + n_li),
            # high-cardinality GROUP BY (~0.25·n_li distinct orderkeys
            # over the full lineitem): the aggregation-stage wall the
            # bucketed dense-grid path (ops/groupby.py, group_by_kernel)
            # targets — bench_kernels.py groupby is the kernel-level A/B
            ("high_card_groupby_rows_per_sec",
             "select l_orderkey, count(*), sum(l_quantity) "
             "from lineitem group by l_orderkey",
             n_li),
        ]
        distinct_extras = {"approx_count_distinct_rows_per_sec",
                           "exact_count_distinct_rows_per_sec"}
        if extras or (only is not None and only & distinct_extras):
            # HLL sketch build + register fold (vs the exact two-level
            # DISTINCT split the next line measures).  Opt-in: remote
            # compiles of these programs cost minutes on tunnel-attached
            # chips, and the driver run must stay inside its budget
            configs += [
                ("approx_count_distinct_rows_per_sec",
                 "select approx_count_distinct(l_partkey) from lineitem",
                 n_li),
                ("exact_count_distinct_rows_per_sec",
                 "select count(distinct l_partkey) from lineitem",
                 n_li),
            ]
        for name, sql, rows in configs:
            if only is not None and name not in only:
                continue
            if over_budget(0.6):
                print(f"# budget: skipping {name}", file=sys.stderr)
                continue
            rate, best = bench_query(sess, sql, rows, repeats)
            # Q3 carries the tracing acceptance evidence: top-level
            # spans of the measured run's trace must tile its wall,
            # and the DDSketch histogram quotes its p50/p99
            extra = (trace_acceptance_keys(sess, sql=sql)
                     if name == "tpch_q3_rows_per_sec" else None)
            emit(name, rate, best, sf, reps=repeats, extra=extra)
        if ((only is None or "columnar_scan_gb_per_sec" in only)
                and not over_budget(0.7)):
            (rate, best, parts, scan_reps,
             eager_rate, eager_best) = bench_cold_scan(sess, n_li)
            emit("columnar_scan_gb_per_sec", rate, best, sf, unit="GB/s",
                 baseline=BASELINE_SCAN_GB_PER_SEC, extra=parts,
                 reps=scan_reps)
            # the eager (scan_pipeline=off) arm of the same cold scan:
            # the artifact itself carries the pipelined-vs-eager A/B
            emit("columnar_scan_gb_per_sec_eager", eager_rate,
                 eager_best, sf, unit="GB/s",
                 baseline=BASELINE_SCAN_GB_PER_SEC, reps=scan_reps)
            # the host-only decode leg as its own line: on a
            # tunnel-attached rig the end-to-end number above measures
            # the link, not the stripe reader
            emit("columnar_host_decode_gb_per_sec",
                 parts["host_decode_gb_per_sec"],
                 parts["host_decode_seconds"], sf, unit="GB/s",
                 baseline=BASELINE_SCAN_GB_PER_SEC, reps=scan_reps)

        # -- INSERT..SELECT modes (reference README: pushdown ~100M vs
        #    repartition ~10M rows/s — here the colocated path writes
        #    per-device blocks directly, no hash routing) ----------------
        is_wanted = {"insert_select_colocated_rows_per_sec",
                     "insert_select_repartition_rows_per_sec"}
        is_run = ((is_wanted if extras else set())
                  if only is None else is_wanted & only)
        if is_run and over_budget(0.75):
            print("# budget: skipping INSERT..SELECT section",
                  file=sys.stderr)
            is_run = set()
        for name, dist_col in (
                ("insert_select_colocated_rows_per_sec", "o_orderkey"),
                ("insert_select_repartition_rows_per_sec", "o_custkey")):
            if name not in is_run:
                continue
            from citus_tpu.ingest.tpch import SCHEMAS

            best = float("inf")
            is_reps = n_reps(2)
            for _ in range(is_reps):  # first run pays the source-plan compile
                ddl = SCHEMAS["orders"].replace("orders", "bench_is_dst")
                sess.execute(ddl)
                sess.create_distributed_table(
                    "bench_is_dst", dist_col,
                    colocate_with="orders" if dist_col == "o_orderkey"
                    else None)
                t0 = time.perf_counter()
                sess.execute(
                    "insert into bench_is_dst select * from orders")
                best = min(best, time.perf_counter() - t0)
                sess.execute("drop table bench_is_dst")
            emit(name, n_ord / best, best, sf, reps=is_reps)

        # -- SF10 section (BASELINE configs at scale; on by default —
        #    r4 VERDICT #1: the scale story must be driver-captured) ----
        sf10_wanted = {"dual_repartition_join_sf10_rows_per_sec",
                       "single_repartition_join_sf10_rows_per_sec",
                       "tpch_q3_sf10_rows_per_sec"}
        sf10_run = (sf10_wanted if only is None
                    else sf10_wanted & only) if sf10 else set()
        if sf10_run and over_budget(0.5):
            print("# budget: skipping SF10 section", file=sys.stderr)
            sf10_run = set()
        if sf10_run:
            # persistent data dir: SF10 ingest costs ~14 min of pure
            # host-side generation on one core — cache it across runs
            # (first run pays it once inside the budget check above)
            sf10_tag = ("sf%g" % sf10_scale).replace(".", "_")
            sf10_dir = os.environ.get(
                "BENCH_SF10_DIR",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".benchdata", sf10_tag))
            s10 = Session(data_dir=sf10_dir,
                          serving_result_cache_bytes=0)
            if s10.store.table_row_count("lineitem") == 0:
                load_into_session(
                    s10, sf=sf10_scale, seed=0,
                    tables={"customer", "orders", "lineitem"})
            n_li10 = s10.store.table_row_count("lineitem")
            n_ord10 = s10.store.table_row_count("orders")
            n_cust10 = s10.store.table_row_count("customer")
            if "dual_repartition_join_sf10_rows_per_sec" in sf10_run:
                r = n_reps(1)
                rate, best = bench_query(
                    s10,
                    "select count(*) from orders, lineitem "
                    "where o_custkey = l_suppkey",
                    n_ord10 + n_li10, r)
                emit("dual_repartition_join_sf10_rows_per_sec", rate,
                     best, sf10_scale, reps=r, sess_obj=s10)
            if "single_repartition_join_sf10_rows_per_sec" in sf10_run:
                # the SF1 config is tunnel-latency-bound (~14 ms of
                # device work behind a ~95 ms round trip); at SF10 the
                # same shape shows the engine's actual rate
                r = n_reps(2)
                rate, best = bench_query(
                    s10,
                    "select count(*), sum(o_totalprice) "
                    "from customer, orders "
                    "where c_custkey = o_custkey",
                    n_cust10 + n_ord10, r)
                emit("single_repartition_join_sf10_rows_per_sec", rate,
                     best, sf10_scale, reps=r, sess_obj=s10)
            if "tpch_q3_sf10_rows_per_sec" in sf10_run:
                r = n_reps(2)
                rate, best = bench_query(
                    s10, QUERIES["Q3"], n_cust10 + n_ord10 + n_li10, r)
                # the acceptance run: EXPLAIN-equal phase walls from
                # the trace, a Chrome-trace export next to the
                # artifacts, and the class's DDSketch p50/p99
                extra = trace_phase_keys(
                    s10.stats.tracing.last_trace(), wall_seconds=best,
                    sql=QUERIES["Q3"])
                extra.update(trace_acceptance_keys(
                    s10, sql=QUERIES["Q3"],
                    export_path=os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "TRACE_sf10_q3.json")))
                emit("tpch_q3_sf10_rows_per_sec", rate, best,
                     sf10_scale, reps=r, sess_obj=s10, extra=extra)

        # -- serving scenario (PR 8): the three point_lookup_qps lines
        #    land in the driver artifact so the README/PERF_NOTES
        #    serving claims stay honesty-checkable ---------------------
        if (only is None or "point_lookup_qps" in only) \
                and not over_budget(0.85):
            bench_serving()

        # -- memory-pressure scenario (PR 10): the governed/ungoverned
        #    A/B lands in the driver artifact so the README/PERF_NOTES
        #    degradation claims stay honesty-checkable ----------------
        if (only is None or "memory_pressure" in only) \
                and not over_budget(0.9):
            bench_memory_pressure()

        # -- cold-start scenario (PR 15): restart-to-first-answer and
        #    compile-storm A/B land in the driver artifact so the
        #    README/PERF_NOTES zero-cold-start claims stay
        #    honesty-checkable ------------------------------------------
        if (only is None or "cold_start" in only) \
                and not over_budget(0.92):
            bench_cold_start()

        # -- replica-fleet scenario (PR 18): scale-out QPS, replica-
        #    kill zero-wrong-rows, promote/provision-to-first-answer
        #    land in the driver artifact so the README/PERF_NOTES
        #    replication claims stay honesty-checkable ----------------
        if (only is None or "replica_fleet" in only) \
                and not over_budget(0.95):
            bench_replica_fleet()

        # headline LAST (driver contract: final JSON line)
        if only is None or "tpch_q1_rows_per_sec" in only:
            rate, best = bench_query(sess, QUERIES["Q1"], n_li, repeats)
            emit("tpch_q1_rows_per_sec", rate, best, sf, reps=repeats)

        _publish(lines)
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def _publish(lines) -> None:
    """Record measurements in BASELINE.json's `published` map.  Skipped
    for non-default scale factors (smoke runs must not clobber real
    published numbers)."""
    if float(os.environ.get("BENCH_SF", "1.0")) != 1.0:
        return
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        doc.setdefault("published", {})
        for line in lines:
            doc["published"][line["metric"]] = {
                f"{line['unit'].replace('/', '_per_')}":
                    line["value"],
                "vs_baseline": line["vs_baseline"],
                "sf": line["sf"],
            }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)
    except Exception:
        pass  # publishing is best-effort; the JSON lines are the contract


if __name__ == "__main__":
    main()
