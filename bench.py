"""Benchmark driver: the five BASELINE.json configs on one chip.

Prints one JSON line per config; the LAST line is the headline metric
(TPC-H Q1 scan-aggregate throughput), matching the driver contract of a
final `{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}` line.

Baseline yardstick: the reference's only published absolute number — the
columnar engine aggregating 75M rows in 16 s (≈4.69M rows/s) on a 2-vCPU
Azure VM (/root/reference/src/backend/columnar/README.md:303-321).  Every
config reports rows-processed/sec against that scan rate.

Configs (BASELINE.json):
  1. TPC-H Q1 scan + grouped aggregate over lineitem      [headline]
  2. co-located hash join (orders ⋈ lineitem on orderkey)
  3. single-repartition join (customer ⋈ orders on custkey)
  4. dual-repartition join + global aggregate (psum combine)
  5. TPC-H Q3 multi-join (repartition + colocated + grouped aggregate)

Env knobs: BENCH_SF (default 1.0), BENCH_REPEATS (default 3),
BENCH_ONLY (comma list of config names to run).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time

BASELINE_ROWS_PER_SEC = 75_000_000 / 16.0  # reference columnar agg scan


def bench_query(sess, sql: str, rows_processed: int, repeats: int):
    sess.execute(sql)  # warmup: compile + populate caches
    best = float("inf")
    result = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        result = sess.execute(sql)
        best = min(best, time.perf_counter() - t0)
    assert result is not None and result.row_count > 0
    return rows_processed / best, best


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    only = os.environ.get("BENCH_ONLY")
    only = set(only.split(",")) if only else None

    from citus_tpu.session import Session
    from citus_tpu.ingest.tpch import QUERIES, load_into_session

    data_dir = tempfile.mkdtemp(prefix="citus_tpu_bench_")
    lines = []
    try:
        sess = Session(data_dir=data_dir)
        load_into_session(sess, sf=sf, seed=0)
        n_li = sess.store.table_row_count("lineitem")
        n_ord = sess.store.table_row_count("orders")
        n_cust = sess.store.table_row_count("customer")

        configs = [
            # (name, sql, rows processed by the query)
            ("colocated_join_rows_per_sec",
             "select count(*), sum(l_extendedprice) from orders, lineitem "
             "where o_orderkey = l_orderkey",
             n_ord + n_li),
            ("single_repartition_join_rows_per_sec",
             "select count(*), sum(o_totalprice) from customer, orders "
             "where c_custkey = o_custkey",
             n_cust + n_ord),
            ("dual_repartition_join_rows_per_sec",
             "select count(*) from orders, lineitem "
             "where o_custkey = l_suppkey",
             n_ord + n_li),
            ("tpch_q3_rows_per_sec", QUERIES["Q3"], n_cust + n_ord + n_li),
            ("tpch_q1_rows_per_sec", QUERIES["Q1"], n_li),  # headline LAST
        ]
        for name, sql, rows in configs:
            if only is not None and name not in only:
                continue
            rate, best = bench_query(sess, sql, rows, repeats)
            lines.append({
                "metric": name,
                "value": round(rate, 1),
                "unit": "rows/s",
                "vs_baseline": round(rate / BASELINE_ROWS_PER_SEC, 3),
                "seconds": round(best, 4),
                "sf": sf,
            })
        for line in lines:
            print(json.dumps(line))
        _publish(lines)
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)


def _publish(lines) -> None:
    """Record measurements in BASELINE.json's `published` map."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        doc.setdefault("published", {})
        for line in lines:
            doc["published"][line["metric"]] = {
                "rows_per_sec": line["value"],
                "vs_baseline": line["vs_baseline"],
                "sf": line["sf"],
            }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)
    except Exception:
        pass  # publishing is best-effort; the JSON lines are the contract


if __name__ == "__main__":
    main()
