"""Multi-chip scaling driver: measured rows/s at 1/2/4/8 devices.

The scale axis the BENCH_r* artifacts never had: every published number
so far ran on ONE chip, and the MULTICHIP_r* artifacts were empty
shells (r05: rc 0, empty tail).  This driver makes the mesh dimension a
measured fact:

* the parent prepares ONE persistent dataset (shard_count=8 — divisible
  by every mesh width) and then spawns one CHILD PROCESS per device
  count.  A separate process per count is mandatory: the XLA device
  count is fixed at backend init (`xla_force_host_platform_device_count`
  must be set before the first jax import), so one process can never
  measure two mesh widths;
* each child runs Q1 (scan-aggregate), Q3 (repartition + colocated
  joins + grouped agg) and the dual-repartition join at its mesh width,
  printing one JSON line per config with rows/s, the hot device's
  measured cold-feed wire bytes (`feed_bytes_per_device` — the
  device-owned slice seam charges each device its own slice, so this is
  ≈ 1/N of the 1-device transfer when placement is spread), and the
  statement's static all_to_all volume (`shuffle_bytes` — what the
  cross-device dimension costs);
* the parent folds the lines into MULTICHIP_r<next>.json with
  per-device-count rows/s, speedup-vs-1-device and scaling-efficiency
  keys (rate_N / (N × rate_1)), and stamps `host_fake_devices` honestly
  when the mesh is virtual CPU devices.  A run that produces no metric
  lines records `skipped: true` WITH a reason or a nonzero rc — the
  silent-success shell (rc 0, empty tail, skipped false) is a shape
  tests/test_bench_artifacts.py rejects.

What CPU fake devices can and cannot predict is documented in
PERF_NOTES (round 14): the data-parallel compute split and the
per-device transfer split are real; ICI all_to_all latency/bandwidth is
not (fake-device collectives are memcpys through host RAM).

Env knobs: BENCH_MC_SF (default 2.0 — large enough that per-device
compute dominates fake-device dispatch overhead; the first run pays
a ~3 min single-core ingest, cached under BENCH_MC_DIR after),
BENCH_MC_REPEATS (default 3),
BENCH_MC_DEVICES (default "1,2,4,8"), BENCH_MC_DIR (persistent dataset
dir, default .benchdata/multichip_sf<sf>), MULTICHIP_OUT (artifact
path; "0" disables writing, default MULTICHIP_r<next>.json).
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.abspath(__file__))

QUERY_CONFIGS = (
    # (metric, query key or SQL, rows-processed spec)
    ("multichip_q1_rows_per_sec", "Q1", ("lineitem",)),
    ("multichip_q3_rows_per_sec", "Q3",
     ("customer", "orders", "lineitem")),
    ("multichip_dual_repartition_rows_per_sec",
     "select count(*) from orders, lineitem where o_custkey = l_suppkey",
     ("orders", "lineitem")),
    # high-cardinality GROUP BY on a non-distribution key: the partial
    # groups MUST cross the mesh (all_to_all combine) at every width >1
    # — the psum-directory pushdown cannot compile this shuffle away,
    # so the line measures what paying a genuine all_to_all costs/buys
    ("multichip_groupby_shuffle_rows_per_sec",
     "select l_partkey, count(*), sum(l_quantity) from lineitem "
     "group by l_partkey",
     ("lineitem",)),
)


def _sf() -> float:
    return float(os.environ.get("BENCH_MC_SF", "2.0"))


def _data_dir() -> str:
    tag = ("sf%g" % _sf()).replace(".", "_")
    return os.environ.get(
        "BENCH_MC_DIR",
        os.path.join(ROOT, ".benchdata", f"multichip_{tag}"))


# ---------------------------------------------------------------------------
# child: one mesh width, one process


def _child(n_devices: int) -> None:
    from citus_tpu.runtime import ensure_jax_configured

    platform = os.environ.get("JAX_PLATFORMS") or None
    ensure_jax_configured(platform=platform,
                          host_device_count=n_devices)
    import jax

    if len(jax.devices()) < n_devices:
        ensure_jax_configured(platform="cpu")
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(jax.devices())}")

    from citus_tpu.ingest.tpch import QUERIES, load_into_session
    from citus_tpu.session import Session
    from citus_tpu.stats import counters as sc

    repeats = int(os.environ.get("BENCH_MC_REPEATS", "3"))
    sess = Session(data_dir=_data_dir(), n_devices=n_devices,
                   serving_result_cache_bytes=0)
    try:
        if sess.store.table_row_count("lineitem") == 0:
            load_into_session(sess, sf=_sf(), seed=0, shard_count=8,
                              tables={"customer", "orders", "lineitem"})
        counts = {t: sess.store.table_row_count(t)
                  for t in ("customer", "orders", "lineitem")}
        platform = str(jax.default_backend())
        for metric, q, tables in QUERY_CONFIGS:
            sql = QUERIES.get(q, q)
            rows = sum(counts[t] for t in tables)
            # cold pass: measure the per-device feed transfer through
            # the pipelined scan's per-device wire ledger (feed cache
            # emptied so the bytes actually cross)
            sess.executor.feed_cache.clear()
            sess.executor.scan_stats.reset()
            sess.execute(sql)  # also warms the compile
            scan = sess.executor.scan_stats.snapshot()
            by_dev = scan.get("wire_bytes_by_device") or []
            feed_per_dev = max(by_dev) if by_dev else None
            snap0 = sess.stats.counters.snapshot()
            best = float("inf")
            # measured reps always record a span tree (the phase keys
            # stamped below derive from the last one)
            with sess.settings.override(trace_fast_statement_ms=0):
                for _ in range(repeats):
                    t0 = time.perf_counter()
                    r = sess.execute(sql)
                    best = min(best, time.perf_counter() - t0)
                    assert r.row_count > 0
            shuffle = (sess.stats.counters.snapshot().get(
                sc.SHUFFLE_BYTES_TOTAL, 0)
                - snap0.get(sc.SHUFFLE_BYTES_TOTAL, 0)) // repeats
            line = {
                "metric": metric,
                "n_devices": n_devices,
                "value": round(rows / best, 1),
                "unit": "rows/s",
                "seconds": round(best, 4),
                "sf": _sf(),
                "repeats": repeats,
                "rows_processed": rows,
                "feed_bytes_per_device": feed_per_dev,
                "shuffle_bytes": int(shuffle),
                "platform": platform,
                "host_fake_devices": platform == "cpu",
            }
            # phase walls of the last measured rep, derived from its
            # span trace (bench.trace_phase_keys — same provenance as
            # bench.py/bench_sf100.py, stamped phase_source="trace")
            from bench import trace_phase_keys

            line.update(trace_phase_keys(
                sess.stats.tracing.last_trace(), sql=sql))
            print(json.dumps(line), flush=True)
        if n_devices >= 2:
            # LAST (the failover shrinks this session's mesh): measured
            # kill-to-first-answer recovery under a mid-query device
            # loss — the number a preemption-tolerant stack lives by
            _device_loss_scenario(sess, n_devices, platform)
    finally:
        sess.close()


def _device_loss_scenario(sess, n_devices: int, platform: str) -> None:
    """Kill one fake device mid-query (MeshSim) and measure the wall
    clock from the kill to the first correct answer through the
    shrink-and-failover path.  Runs on its own replication-2 table
    (the TPC-H bench tables are replication 1 by design); the table is
    dropped afterward so the cached dataset dir stays canonical."""
    from citus_tpu.stats import counters as sc
    from citus_tpu.utils import faultinjection as fi

    sess.execute("DROP TABLE IF EXISTS dl_kv")
    sess.execute("SET shard_replication_factor = 2")
    sess.execute("CREATE TABLE dl_kv (id INT, v INT, grp INT)")
    sess.execute(
        f"SELECT create_distributed_table('dl_kv', 'id', {n_devices})")
    n = 60_000
    for base in range(0, n, 10_000):
        sess.execute("INSERT INTO dl_kv VALUES " + ", ".join(
            f"({base + i}, {(base + i) * 3}, {(base + i) % 13})"
            for i in range(10_000)))
    q = "select grp, count(*), sum(v) from dl_kv group by grp"
    warm = sorted(map(tuple, sess.execute(q).rows()))
    t_warm0 = time.perf_counter()
    sess.execute(q)
    warm_s = time.perf_counter() - t_warm0
    victim = sess.mesh.devices.flat[n_devices - 1].id
    snap0 = sess.stats.counters.snapshot()
    # after=1: feeds are warm, so the kill lands at the result fetch —
    # the program RAN and its answer died on the wire (mid-query)
    with fi.simulate_mesh(kill={victim}, after=1):
        t0 = time.perf_counter()
        r = sess.execute(q)
        recovery_s = time.perf_counter() - t0
    ok = sorted(map(tuple, r.rows())) == warm
    snap = sess.stats.counters.snapshot()
    rescued = (snap.get(sc.QUERIES_RESCUED_TOTAL, 0)
               - snap0.get(sc.QUERIES_RESCUED_TOTAL, 0))
    sess.execute("DROP TABLE dl_kv")
    print(json.dumps({
        "metric": "multichip_device_loss_recovery_seconds",
        "n_devices": n_devices,
        "value": round(recovery_s, 4),
        "unit": "s",
        "sf": _sf(),
        "rows_processed": n,
        "warm_seconds": round(warm_s, 4),
        "recovery_over_warm": (round(recovery_s / warm_s, 2)
                               if warm_s > 0 else None),
        "devices_after_failover": sess.n_devices,
        "queries_rescued_total": int(rescued),
        "oracle_identical": bool(ok),
        "platform": platform,
        "host_fake_devices": platform == "cpu",
    }), flush=True)


# ---------------------------------------------------------------------------
# parent: spawn one child per device count, fold the artifact


def _next_artifact_path() -> str:
    out = os.environ.get("MULTICHIP_OUT")
    if out:
        return out
    rounds = [int(m.group(1)) for p in glob.glob(
        os.path.join(ROOT, "MULTICHIP_r*.json"))
        if (m := re.search(r"MULTICHIP_r(\d+)\.json$", p))]
    nxt = (max(rounds) + 1) if rounds else 1
    return os.path.join(ROOT, f"MULTICHIP_r{nxt:02d}.json")


def main() -> int:
    if sys.argv[1:2] == ["--child"]:
        _child(int(sys.argv[2]))
        return 0

    device_counts = [int(x) for x in os.environ.get(
        "BENCH_MC_DEVICES", "1,2,4,8").split(",")]
    tail_lines: list[str] = []
    rc = 0
    # widest mesh first: the first child to touch an empty dataset dir
    # creates the catalog, and its node set must span the WIDEST mesh
    # (8 nodes fold onto narrower meshes through node_device_map;
    # 1 node on an 8-device mesh would serialize everything onto
    # device 0 — the skew rebalance_mesh exists to fix, not to bench)
    for n in sorted(device_counts, reverse=True):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             str(n)],
            cwd=ROOT, capture_output=True, text=True, timeout=3600)
        for line in proc.stdout.splitlines():
            print(line, flush=True)
            tail_lines.append(line)
        if proc.returncode != 0:
            rc = proc.returncode
            err = proc.stderr.strip().splitlines()[-8:]
            msg = f"# child n_devices={n} rc={proc.returncode}: " + \
                " | ".join(err)
            print(msg, file=sys.stderr, flush=True)
            tail_lines.append(msg)

    # fold metric lines into per-device-count tables
    results: dict[str, dict[str, dict]] = {}
    for line in tail_lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" in obj and "n_devices" in obj:
            results.setdefault(obj["metric"], {})[
                str(obj["n_devices"])] = obj

    speedup: dict[str, dict[str, float]] = {}
    efficiency: dict[str, dict[str, float]] = {}
    for metric, by_n in results.items():
        base = by_n.get("1")
        if base is None or not base.get("value"):
            continue
        for nd, obj in by_n.items():
            n = int(nd)
            if n <= 1:
                continue
            sp = obj["value"] / base["value"]
            speedup.setdefault(metric, {})[nd] = round(sp, 3)
            efficiency.setdefault(metric, {})[nd] = round(sp / n, 3)

    have_metrics = bool(results)
    host_fake = any(obj.get("host_fake_devices")
                    for by_n in results.values()
                    for obj in by_n.values())
    artifact = {
        "n_devices": device_counts,
        "rc": rc,
        "ok": rc == 0 and have_metrics,
        # a run that measured nothing must say WHY — the silent-success
        # shell (rc 0, empty tail, skipped false) is a rejected shape
        "skipped": not have_metrics,
        "skip_reason": (None if have_metrics
                        else "no child produced a metric line "
                             f"(rc={rc}; see tail)"),
        "host_fake_devices": host_fake,
        "sf": _sf(),
        "results": results,
        "speedup_vs_1dev": speedup,
        "scaling_efficiency": efficiency,
        "tail": "\n".join(tail_lines),
    }
    out = os.environ.get("MULTICHIP_OUT", "")
    if out != "0":
        path = _next_artifact_path()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f, indent=2)
        os.replace(tmp, path)
        print(f"# wrote {os.path.basename(path)}", file=sys.stderr,
              flush=True)
    # headline LAST (driver contract: final JSON line)
    q3 = results.get("multichip_q3_rows_per_sec", {})
    top = max(q3, key=lambda nd: q3[nd]["value"], default=None)
    if top is not None:
        print(json.dumps({
            "metric": "multichip_q3_best_rows_per_sec",
            "value": q3[top]["value"], "unit": "rows/s",
            "n_devices": int(top),
            "speedup_vs_1dev": speedup.get(
                "multichip_q3_rows_per_sec", {}).get(top),
            "host_fake_devices": host_fake,
        }), flush=True)
    return rc if have_metrics else (rc or 1)


if __name__ == "__main__":
    raise SystemExit(main())
