"""Tier-1 budget tooling: rank the slowest tests from pytest
``--durations`` output.

The tier-1 gate (ROADMAP.md) runs ``pytest -q -m 'not slow'`` under a
fixed wall-clock budget and counts passing dots — tests past the
timeout never run, so every second a slow test burns near the front of
the suite is a dot some later file loses.  This tool turns a profiling
run into the marking decision:

    # profile once (takes the full suite duration):
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
        --durations=0 -p no:xdist > /tmp/t1_durations.log
    # rank:
    python tools/t1_times.py /tmp/t1_durations.log --top 25
    python tools/t1_times.py /tmp/t1_durations.log --by-file

Tests whose cost dwarfs their dot contribution are candidates for the
``slow`` marker (they still run in the full suite); ``--budget 870``
estimates where the tier-1 cutoff would land in file order.
"""

from __future__ import annotations

import re
import sys
from collections import defaultdict

# pytest --durations lines: "12.34s call     tests/test_x.py::test_y"
_DURATION_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)")


def parse_durations(text: str) -> dict[str, float]:
    """test nodeid → total seconds across its call/setup/teardown."""
    totals: dict[str, float] = defaultdict(float)
    for line in text.splitlines():
        m = _DURATION_RE.match(line)
        if m:
            totals[m.group(3)] += float(m.group(1))
    return dict(totals)


def by_file(totals: dict[str, float]) -> dict[str, float]:
    out: dict[str, float] = defaultdict(float)
    for nodeid, secs in totals.items():
        out[nodeid.split("::", 1)[0]] += secs
    return dict(out)


# must mirror tests/conftest.py::_TIER1_FIRST — the collection hook
# runs these files before the alphabetical remainder
TIER1_FIRST = ("test_lint.py", "test_tools.py", "test_wlm.py",
               "test_tracing.py", "test_exec_cache.py",
               "test_multichip.py", "test_mesh_failover.py",
               "test_scan_pipeline.py", "test_replication.py",
               "test_serving.py", "test_integrity.py",
               "test_crash_torture.py", "test_oom_torture.py")


def budget_cutoff(totals: dict[str, float], budget: float) -> list[str]:
    """Files (in the suite's ACTUAL run order: conftest's front-loaded
    files first, then alphabetical) whose cumulative time exceeds
    `budget` — the tests a timed tier-1 run never reaches.  An
    estimate: per-test durations undercount collection/import time, so
    the real cutoff lands somewhat earlier."""
    import os

    files = by_file(totals)
    spent = 0.0
    unreached = []
    run_order = sorted(files, key=lambda f: (
        0 if os.path.basename(f) in TIER1_FIRST else 1, f))
    for f in run_order:
        spent += files[f]
        if spent > budget:
            unreached.append(f)
    return unreached


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    top = 20
    budget = None
    show_files = False
    path = None
    it = iter(argv)
    for a in it:
        if a == "--top":
            top = int(next(it))
        elif a == "--budget":
            budget = float(next(it))
        elif a == "--by-file":
            show_files = True
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        else:
            path = a
    text = open(path).read() if path else sys.stdin.read()
    totals = parse_durations(text)
    if not totals:
        print("no --durations lines found (run pytest with "
              "--durations=0)", file=sys.stderr)
        return 1
    if show_files:
        files = by_file(totals)
        print(f"{'seconds':>9}  file")
        for f, secs in sorted(files.items(), key=lambda kv: -kv[1]):
            print(f"{secs:9.2f}  {f}")
    else:
        print(f"{'seconds':>9}  test")
        ranked = sorted(totals.items(), key=lambda kv: -kv[1])
        for nodeid, secs in ranked[:top]:
            print(f"{secs:9.2f}  {nodeid}")
        rest = sum(s for _, s in ranked[top:])
        print(f"{rest:9.2f}  ({max(0, len(ranked) - top)} more tests)")
        print(f"{sum(totals.values()):9.2f}  total")
    if budget is not None:
        unreached = budget_cutoff(totals, budget)
        if unreached:
            print(f"\nfiles a {budget:.0f}s tier-1 run never reaches "
                  "(alphabetical order):")
            for f in unreached:
                print(f"  {f}")
        else:
            print(f"\nthe whole suite fits the {budget:.0f}s budget")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
