"""Print the phase breakdown of a recorded statement trace.

    python tools/trace_summarize.py <data_dir | trace.json> [--top N]

Given a data_dir, picks the NEWEST slow-query trace under
``<data_dir>/slow_traces/`` (written when a statement exceeds
``trace_slow_statement_ms``); given a file, summarizes that trace.
Output: the statement, its wall clock, the per-phase attribution the
EXPLAIN ANALYZE ``Timing:`` line shows (same phase names — both come
from stats/tracing.phase_breakdown), and the N slowest individual
spans with their tree paths — the "where did the time go" answer
without opening chrome://tracing (``python -m
citus_tpu.stats.trace_export`` renders the same trace there).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(
    __file__)), ".."))


def summarize(doc: dict, top: int = 10) -> list[str]:
    """Render one trace dict (Trace.to_dict() / persisted slow-trace
    JSON) as report lines."""
    from citus_tpu.stats.tracing import PHASE_ORDER, phase_breakdown

    root = doc.get("root") or {}
    wall = doc.get("wall_ms") or root.get("dur_ms", 0.0)
    lines = [
        f"statement: {doc.get('sql', '?')!r}",
        f"class:     {doc.get('class', '?')}",
        f"wall:      {wall:.2f} ms"
        + ("  [truncated trace]" if doc.get("truncated") else "")
        + (f"  [error: {doc['error']}]" if doc.get("error") else ""),
        "",
        "phase breakdown (Timing):",
    ]
    ph = phase_breakdown(root)
    total = max(ph.get("total", 0.0), 1e-12)
    for name in PHASE_ORDER + ("other",):
        v = ph.get(name, 0.0)
        if v <= 0.0:
            continue
        share = 100.0 * v / total
        lines.append(f"  {name:<10s} {v * 1000.0:10.2f} ms  "
                     f"{share:5.1f}%")
    lines.append(f"  {'total':<10s} {total * 1000.0:10.2f} ms")
    # slowest individual spans with their tree path
    flat: list[tuple[float, str]] = []

    def walk(span: dict, path: str) -> None:
        p = f"{path}/{span['name']}" if path else span["name"]
        flat.append((span.get("dur_ms", 0.0), p))
        for c in span.get("children", ()):
            walk(c, p)

    for c in root.get("children", ()):
        walk(c, "")
    flat.sort(key=lambda t: -t[0])
    if flat:
        lines += ["", f"slowest spans (top {min(top, len(flat))}):"]
        for dur, path in flat[:top]:
            lines.append(f"  {dur:10.2f} ms  {path}")
    return lines


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    top = 10
    args = []
    it = iter(argv)
    for a in it:
        if a == "--top":
            nxt = next(it, None)
            if nxt is None or not nxt.isdigit():
                print("trace_summarize: --top needs an integer",
                      file=sys.stderr)
                return 2
            top = int(nxt)
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        else:
            args.append(a)
    if len(args) != 1:
        print("usage: python tools/trace_summarize.py "
              "<data_dir | trace.json> [--top N]", file=sys.stderr)
        return 2
    from citus_tpu.stats.trace_export import load_trace

    try:
        doc = load_trace(args[0])
    except (OSError, ValueError) as e:
        print(f"trace_summarize: {e}", file=sys.stderr)
        return 1
    try:
        for line in summarize(doc, top=top):
            print(line)
    except BrokenPipeError:
        pass  # piped into head — normal CLI citizenship
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
