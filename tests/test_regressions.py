"""Regression tests for reviewed wrong-result bugs.

Each test reproduces a once-broken scenario:
1. int32 distribution columns + repartition join (hash width-fold parity)
2. multi-key repart_both falsely claiming per-column partitioning
3. ORDER BY on non-selected columns / aggregates
4. DATE values folding back from scalar/IN subqueries
5. SQL truncating %, / on negative integers
"""

import numpy as np
import pytest

import citus_tpu
from citus_tpu.catalog.distribution import hash_token


@pytest.fixture()
def sess(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64")
    yield s
    s.close()


def test_hash_width_fold_parity():
    """hash(int64 v) == hash(int32 v) for every v in int32 range."""
    vals32 = np.array([0, 1, -1, 7, -7, 2**31 - 1, -(2**31), 123456789],
                      dtype=np.int32)
    vals64 = vals32.astype(np.int64)
    np.testing.assert_array_equal(hash_token(vals32), hash_token(vals64))
    # device twin agrees on the widened values
    import jax.numpy as jnp
    from citus_tpu.ops.hashing import hash_token_jax

    dev = np.asarray(hash_token_jax(jnp.asarray(vals64)))
    np.testing.assert_array_equal(dev, hash_token(vals64))


def test_int32_distcol_repartition_join(sess):
    """Single-repartition join between tables distributed on int columns."""
    sess.execute("create table a (k int, v int)")
    sess.execute("create table b (k2 int, w int)")
    sess.create_distributed_table("a", "k", shard_count=8)
    sess.create_distributed_table("b", "w", shard_count=8)  # NOT on k2
    rows_a = ",".join(f"({i},{i * 10})" for i in range(50))
    rows_b = ",".join(f"({i},{i + 1000})" for i in range(50))
    sess.execute(f"insert into a values {rows_a}")
    sess.execute(f"insert into b values {rows_b}")
    # b repartitions onto a's hash(k) placement; parity bug dropped all rows
    r = sess.execute("select count(*) from a, b where k = k2")
    assert int(r.rows()[0][0]) == 50


def test_repart_both_then_single_key_join(sess):
    """Dual repartition on (a,b) must not claim colocation with a later
    single-key join partner that is hash-placed on that key alone."""
    sess.execute("create table t1 (a int, b int, x int)")
    sess.execute("create table t2 (a2 int, b2 int, y int)")
    sess.execute("create table t3 (a3 int, z int)")
    # distribute on the NON-join columns to force repart_both on (a,b)
    sess.create_distributed_table("t1", "x", shard_count=4)
    sess.create_distributed_table("t2", "y", shard_count=4)
    sess.create_distributed_table("t3", "a3", shard_count=4)  # == n_dev
    n = 40
    sess.execute("insert into t1 values " + ",".join(
        f"({i % 10},{i % 7},{i})" for i in range(n)))
    sess.execute("insert into t2 values " + ",".join(
        f"({i % 10},{i % 7},{i + 100})" for i in range(n)))
    sess.execute("insert into t3 values " + ",".join(
        f"({i},{i})" for i in range(10)))
    r = sess.execute("""
        select count(*) from t1, t2, t3
        where a = a2 and b = b2 and a2 = a3""")
    expect = sum(1 for i in range(n) for j in range(n)
                 if i % 10 == j % 10 and i % 7 == j % 7)
    assert int(r.rows()[0][0]) == expect


def test_order_by_non_selected_column(sess):
    sess.execute("create table o1 (x int, y int)")
    sess.create_distributed_table("o1", "x", shard_count=4)
    sess.execute("insert into o1 values (1, 30), (2, 10), (3, 20)")
    r = sess.execute("select x from o1 order by y")
    assert [v for (v,) in r.rows()] == [2, 3, 1]


def test_order_by_aggregate_not_in_select(sess):
    sess.execute("create table o2 (g int, y int)")
    sess.create_distributed_table("o2", "g", shard_count=4)
    sess.execute("insert into o2 values (1,5),(1,5),(2,100),(3,1)")
    r = sess.execute("select g from o2 group by g order by sum(y) desc")
    assert [v for (v,) in r.rows()] == [2, 1, 3]


def test_order_by_ungrouped_column_rejected(sess):
    from citus_tpu.errors import PlanningError

    sess.execute("create table o3 (g int, y int)")
    sess.create_distributed_table("o3", "g", shard_count=4)
    sess.execute("insert into o3 values (1,2)")
    with pytest.raises(PlanningError, match="ORDER BY"):
        sess.execute("select g from o3 group by g order by y")


def test_date_in_subquery_roundtrip(sess):
    sess.execute("create table ev (id int, d date)")
    sess.create_distributed_table("ev", "id", shard_count=4)
    sess.execute("""insert into ev values
        (1, date '1994-01-01'), (2, date '1995-06-15'),
        (3, date '1994-01-01'), (4, date '1996-03-03')""")
    r = sess.execute(
        "select count(*) from ev where d in (select d from ev where id = 1)")
    assert int(r.rows()[0][0]) == 2
    r2 = sess.execute(
        "select count(*) from ev where d = (select d from ev where id = 2)")
    assert int(r2.rows()[0][0]) == 1
    # materialized CTE keeps DATE typed (temp-table path)
    r3 = sess.execute("""
        with dd as (select d from ev where id <= 3)
        select count(*) from ev, dd where ev.d = dd.d""")
    assert int(r3.rows()[0][0]) == 5  # 2 dup dates x2 matches + 1995 x1


def test_modulo_truncates_toward_zero(sess):
    sess.execute("create table m (v int)")
    sess.create_distributed_table("m", "v", shard_count=4)
    sess.execute("insert into m values (7), (-7)")
    r = sess.execute("select v, v % 2 from m order by v")
    assert [tuple(map(int, row)) for row in r.rows()] == [(-7, -1), (7, 1)]
    # device-side predicate: (0 - 7) % 2 = 1 must NOT match
    r2 = sess.execute("select count(*) from m where (0 - v) % 2 = 1")
    # v=-7: (0-(-7))%2 = 7%2 = 1 → matches; v=7: (0-7)%2 = -1 → no
    assert int(r2.rows()[0][0]) == 1


def test_device_topk_nan_desc_matches_host_order(sess):
    """ORDER BY <float with NaN> DESC LIMIT k: the per-device top-k pass
    must rank NaN like the host comparator (NaN = largest) or devices
    drop exactly the rows the host would put first."""
    sess.execute("create table tk (id int, a double precision, "
                 "b double precision)")
    sess.create_distributed_table("tk", "id", shard_count=4)
    rows = [(i, float(i), 0.0 if i % 10 == 0 else 1.0) for i in range(1, 41)]
    vals = ",".join(f"({i},{a},{b})" for i, a, b in rows)
    sess.execute(f"insert into tk values {vals}")
    with_limit = sess.execute(
        "select id from tk order by a / b desc limit 5").rows()
    no_limit = sess.execute(
        "select id from tk order by a / b desc").rows()
    assert [int(r[0]) for r in with_limit] == \
        [int(r[0]) for r in no_limit[:5]]
    # NaN rows (b = 0) come first under DESC, like the host sort
    assert {int(r[0]) for r in with_limit[:4]} == {10, 20, 30, 40}


def test_stale_join_extent_falls_back_without_wrong_results(sess):
    """A dense join directory / int32 narrowing planned from stale key
    ranges must surface dense_oob and retry on the general path — never
    silently drop or wrap matches."""
    from citus_tpu.executor.feed import walk_plan
    from citus_tpu.planner.plan import JoinNode
    from citus_tpu.sql.parser import parse_one

    sess.execute("create table sa (k bigint, v int)")
    sess.create_distributed_table("sa", "k", shard_count=4)
    sess.execute("create table sb (k bigint, w int)")
    sess.create_distributed_table("sb", "k", shard_count=4)
    big = (1 << 33)  # outside any int32 narrowing
    sess.execute(f"insert into sa values (1,10),(2,20),({big},30)")
    sess.execute(f"insert into sb values (1,1),(2,2),({big},3)")
    plan, cleanup = sess._plan_select(parse_one(
        "select count(*), sum(v + w) from sa, sb where sa.k = sb.k"))
    # simulate stale statistics: claim the keys fit [0, 4) and int32
    for node in walk_plan(plan.root):
        if isinstance(node, JoinNode):
            node.left_key_extents = ((0, 4),)
            node.right_key_extents = ((0, 4),)
            node.key_int32 = (True,)
    result = sess.executor.execute_plan(plan)
    assert result.retries >= 1  # dense_oob retry happened
    row = result.rows()[0]
    assert int(row[0]) == 3 and int(row[1]) == 66

    # warm re-execution of a FRESH plan instance (new node ids): the
    # converged capacities memo must translate across plan instances and
    # skip the retry entirely
    plan2, _ = sess._plan_select(parse_one(
        "select count(*), sum(v + w) from sa, sb where sa.k = sb.k"))
    for node in walk_plan(plan2.root):
        if isinstance(node, JoinNode):
            node.left_key_extents = ((0, 4),)
            node.right_key_extents = ((0, 4),)
            node.key_int32 = (True,)
    result2 = sess.executor.execute_plan(plan2)
    assert result2.retries == 0
    row2 = result2.rows()[0]
    assert int(row2[0]) == 3 and int(row2[1]) == 66


def test_outer_join_reduction_prevents_cartesian_blowup(sess):
    """Fuzz-found (seed 424246 #67): a LEFT JOIN whose nullable side is
    later inner-joined AND filtered strictly must reduce to inner joins
    (reduce_outer_joins) — the un-reduced plan cartesian-joined lineitem
    below the outer join and sized a ~155 GB buffer."""
    import sqlite3

    s = sess
    s.execute("create table c (ck bigint, cnk bigint)")
    s.create_distributed_table("c", "ck", shard_count=4)
    s.execute("create table o (ok bigint, ock bigint, pri bigint)")
    s.create_distributed_table("o", "ok", shard_count=4)
    s.execute("create table li (lok bigint, q bigint)")
    s.create_distributed_table("li", "lok", shard_count=4,
                               colocate_with="o")
    s.execute("create table n (nnk bigint, rk bigint)")
    s.create_reference_table("n")
    rows_c = [(i, i % 5) for i in range(40)]
    rows_o = [(i, i % 40, i % 3) for i in range(120)]
    rows_li = [(i % 120, i % 7) for i in range(360)]
    rows_n = [(i, i % 2) for i in range(5)]
    s.execute("insert into c values " + ",".join(map(str, rows_c)))
    s.execute("insert into o values " + ",".join(map(str, rows_o)))
    s.execute("insert into li values " + ",".join(map(str, rows_li)))
    s.execute("insert into n values " + ",".join(map(str, rows_n)))
    sql = ("select rk, count(*), max(q) from c "
           "left join o on ck = ock "
           "join n on cnk = nnk "
           "join li on ok = lok "
           "where pri < 2 group by rk order by rk")
    # reduction must kick in: no outer JoinNode survives in the plan
    from citus_tpu.executor.feed import walk_plan
    from citus_tpu.planner.plan import JoinNode
    from citus_tpu.sql import parse

    plan, _ = s._plan_select(parse(sql)[0])
    assert all(n.join_type == "inner" for n in walk_plan(plan.root)
               if isinstance(n, JoinNode)), "outer join not reduced"
    got = [tuple(map(int, r)) for r in s.execute(sql).rows()]
    con = sqlite3.connect(":memory:")
    for t, cols, rows in (("c", "ck,cnk", rows_c),
                          ("o", "ok,ock,pri", rows_o),
                          ("li", "lok,q", rows_li), ("n", "nnk,rk", rows_n)):
        con.execute(f"create table {t} ({cols})")
        con.executemany(
            f"insert into {t} values ({','.join('?' * len(rows[0]))})", rows)
    want = [tuple(map(int, r)) for r in con.execute(sql).fetchall()]
    assert got == want


def test_left_join_without_strict_pred_stays_outer(sess):
    """Reduction must NOT fire when nothing rejects the null-extended
    side: unmatched left rows keep their NULL right columns."""
    s = sess
    s.execute("create table a (k bigint)")
    s.create_distributed_table("a", "k", shard_count=4)
    s.execute("create table b (k2 bigint, v bigint)")
    s.create_distributed_table("b", "k2", shard_count=4)
    s.execute("insert into a values (1),(2),(3)")
    s.execute("insert into b values (1, 10)")
    r = s.execute("select k, v from a left join b on k = k2 order by k")
    assert [tuple(x) for x in r.rows()] == [(1, 10), (2, None), (3, None)]
    # IS NULL is not strict either — the filter SELECTS null-extended rows
    r = s.execute("select count(*) from a left join b on k = k2 "
                  "where v is null")
    assert r.rows()[0][0] == 2


def test_plan_buffer_guard(sess):
    """An extreme-fanout KEYED join over the byte guard no longer
    hard-rejects: its shape is stream/multipass-eligible, so the guard
    routes it into the OOM degradation ladder — it must land on the
    correct answer (degraded) XOR a clean ResourceExhausted, never a
    PlanningError and never an allocator OOM.  (Keyless cartesian
    blowups keep the clean PlanningError — tests/test_oom_torture.py
    pins that half.)"""
    from citus_tpu.errors import ResourceExhausted

    s = sess
    s.execute("create table g1 (x bigint)")
    s.create_distributed_table("g1", "x", shard_count=4)
    s.execute("create table g2 (y bigint)")
    s.create_distributed_table("g2", "y", shard_count=4)
    s.execute("insert into g1 values " + ",".join(
        f"({i})" for i in range(3000)))
    s.execute("insert into g2 values " + ",".join(
        f"({i})" for i in range(3000)))
    s.execute("set max_plan_buffer_bytes = 4000000")
    try:
        # expression join keys have no ndv stats → est_expansion 1 →
        # overflow retries double the pair buffer until the guard
        # trips; the ladder then shrinks/streams/splits before a
        # clean error is allowed
        try:
            r = s.execute("select x, y from g1 join g2 "
                          "on x % 2 = y % 2 limit 5")
            assert r.row_count == 5  # degradation actually answered
        except ResourceExhausted:
            pass  # clean, classified, post-ladder
    finally:
        s.execute("set max_plan_buffer_bytes = 34359738368")
        from citus_tpu.executor.runner import OomState

        s.executor.oom = OomState()  # sticky ladder state ends here


def test_case_predicate_does_not_reduce_outer_join(sess):
    """Review-found: a comparison wrapping a CASE must not count as
    null-rejecting — the CASE can turn NULL inputs into non-NULL results,
    and this exact shape SELECTS the null-extended rows."""
    s = sess
    s.execute("create table ra (k bigint)")
    s.create_distributed_table("ra", "k", shard_count=4)
    s.execute("create table rb (k2 bigint, v bigint)")
    s.create_distributed_table("rb", "k2", shard_count=4)
    s.execute("insert into ra values (1),(2),(3)")
    s.execute("insert into rb values (1, 10)")
    r = s.execute("select k from ra left join rb on k = k2 "
                  "where (case when v is null then 1 else 0 end) = 1 "
                  "order by k")
    assert [row[0] for row in r.rows()] == [2, 3]


def test_intermediate_results_invisible_to_cdc(sess):
    """Review-found: derived-table materialization must not emit change
    events (and a read-only SELECT must not touch the journal)."""
    s = sess
    s.execute("create table ce (k bigint, v bigint)")
    s.create_distributed_table("ce", "k", shard_count=4)
    s.execute("insert into ce values (1, 10), (2, 20)")
    lsn0 = s.store.change_log.last_lsn()
    r = s.execute("select x from (select v as x from ce) t order by x")
    assert [row[0] for row in r.rows()] == [10, 20]
    assert s.store.change_log.last_lsn() == lsn0
    assert s.change_events() == s.change_events()  # no phantom tables
    assert all(not e["table"].startswith("__intermediate")
               for e in s.change_events())


def test_params_inside_subqueries(sess):
    """Review-found: $n must resolve inside CTEs / IN-subqueries, which
    execute before the outer binder sees the EXECUTE arguments."""
    s = sess
    s.execute("create table pa (k bigint, v bigint)")
    s.create_distributed_table("pa", "k", shard_count=4)
    s.execute("create table pb (k2 bigint, w bigint)")
    s.create_distributed_table("pb", "k2", shard_count=4)
    s.execute("insert into pa values " + ",".join(
        f"({i}, {i * 10})" for i in range(20)))
    s.execute("insert into pb values " + ",".join(
        f"({i}, {i % 4})" for i in range(20)))
    s.execute("prepare sub as select count(*) from pa "
              "where k in (select k2 from pb where w = $1) and v >= $2")
    assert s.execute("execute sub(1, 0)").rows()[0][0] == 5
    assert s.execute("execute sub(2, 100)").rows()[0][0] == 3  # {10,14,18}
    s.execute("prepare csub as "
              "with big as (select k2 from pb where w > $1) "
              "select count(*) from pa join big on k = k2")
    assert s.execute("execute csub(1)").rows()[0][0] == 10
    assert s.execute("execute csub(2)").rows()[0][0] == 5


def test_full_join_one_sided_reduction_direction(sess):
    """Review-found: strict WHERE on the RIGHT side of a FULL join must
    keep RIGHT-preservation (dropping only tree-preserved rows), not the
    other way around."""
    s = sess
    s.execute("create table fa (k bigint, av bigint)")
    s.create_distributed_table("fa", "k", shard_count=4)
    s.execute("create table fb (k2 bigint, bv bigint)")
    s.create_distributed_table("fb", "k2", shard_count=4)
    s.execute("insert into fa values (1, 100), (2, 200)")
    s.execute("insert into fb values (1, 10), (5, 50)")
    r = s.execute("select k, bv from fa full join fb on k = k2 "
                  "where bv > 0 order by bv")
    assert [tuple(x) for x in r.rows()] == [(1, 10), (None, 50)]
    # symmetric: strict on the tree side keeps tree-preservation
    r = s.execute("select k, bv from fa full join fb on k = k2 "
                  "where av > 0 order by av")
    assert [tuple(x) for x in r.rows()] == [(1, 10), (2, None)]


def test_not_over_and_does_not_reduce_outer_join(sess):
    """Review-found: NOT(a AND b) can be TRUE for a null-extended row
    (NOT(NULL AND FALSE) = TRUE), so it must not count as strict."""
    s = sess
    s.execute("create table na (k bigint, av bigint)")
    s.create_distributed_table("na", "k", shard_count=4)
    s.execute("create table nb (k2 bigint, bv bigint)")
    s.create_distributed_table("nb", "k2", shard_count=4)
    s.execute("insert into na values (1, 100), (2, 200)")
    s.execute("insert into nb values (1, 10)")
    r = s.execute("select k, bv from na left join nb on k = k2 "
                  "where not (bv = 10 and av = 999) order by k")
    assert [tuple(x) for x in r.rows()] == [(1, 10), (2, None)]
    # NOT over a bare comparison IS strict (NULL comparison stays NULL)
    r = s.execute("select k, bv from na left join nb on k = k2 "
                  "where not (bv = 99) order by k")
    assert [tuple(x) for x in r.rows()] == [(1, 10)]


def test_prepare_duplicate_name_rejected(sess):
    from citus_tpu.errors import PlanningError

    s = sess
    s.execute("create table pp (k bigint)")
    s.create_distributed_table("pp", "k", shard_count=4)
    s.execute("prepare dup1 as select count(*) from pp")
    with pytest.raises(PlanningError, match="already exists"):
        s.execute("prepare dup1 as select k from pp")
    s.execute("deallocate dup1")
    s.execute("prepare dup1 as select k from pp")  # freed name reusable


def test_stale_unique_claim_with_duplicate_build_keys(sess):
    """The sort-free dense directory (dense_unique_lookup) banks on the
    planner's build-side uniqueness claim; duplicate build rows must
    surface dense_oob and retry on the general expansion path — never a
    silently-arbitrary single match."""
    from citus_tpu.executor.feed import walk_plan
    from citus_tpu.planner.plan import JoinNode
    from citus_tpu.sql.parser import parse_one

    sess.execute("create table ua (k bigint, v int)")
    sess.create_distributed_table("ua", "k", shard_count=4)
    sess.execute("create table ub (k bigint, w int)")
    sess.create_distributed_table("ub", "k", shard_count=4)
    sess.execute("insert into ua values (1,10),(2,20),(3,30)")
    # build side has DUPLICATE k=2 — a correct result needs both matches
    sess.execute("insert into ub values (1,1),(2,2),(2,5),(3,3)")
    # a plain row-returning join (aggregates would take the pushdown
    # path, which never fuses lookups)
    plan, _cleanup = sess._plan_select(parse_one(
        "select v, w from ua, ub where ua.k = ub.k"))
    from citus_tpu.planner.plan import ScanNode

    for node in walk_plan(plan.root):
        if isinstance(node, JoinNode):
            # force the DUPLICATED side (ub) as build with a stale
            # "unique" claim
            left_is_ub = isinstance(node.left, ScanNode) and \
                node.left.rel.table == "ub"
            node.fuse_lookup = True
            node.build_side = "left" if left_is_ub else "right"
            node.left_key_extents = ((1, 3),)
            node.right_key_extents = ((1, 3),)
    result = sess.executor.execute_plan(plan)
    assert result.retries >= 1
    rows = sorted(result.rows())
    # pairs: (10,1) (20,2) (20,5) (30,3) — BOTH k=2 matches present
    assert rows == [(10, 1), (20, 2), (20, 5), (30, 3)]


def test_stale_group_key_range_retries_on_packed_sort(sess):
    """The packed composite sort key (AggregateNode.key_ranges) clips
    out-of-range key values, which would silently merge groups — stale
    ranges must surface dense_oob and retry with packing off."""
    from citus_tpu.executor.feed import walk_plan
    from citus_tpu.planner.plan import AggregateNode
    from citus_tpu.sql.parser import parse_one

    sess.execute("create table pg1 (k bigint, g bigint, h bigint, v int)")
    sess.create_distributed_table("pg1", "k", shard_count=4)
    sess.execute("insert into pg1 values (1,1,1,10),(2,2,1,20),"
                 "(3,7,2,30),(4,8,2,40)")
    plan, _cleanup = sess._plan_select(parse_one(
        "select g, h, sum(v) from pg1 group by g, h"))
    for node in walk_plan(plan.root):
        if isinstance(node, AggregateNode):
            # stale claim: g in [1, 3), h in [1, 2) — rows with g=7,8 and
            # h=2 fall outside and would clip onto other slots
            node.key_ranges = ((1, 2, False), (1, 1, False))
            node.dense_keys = None
    result = sess.executor.execute_plan(plan)
    assert result.retries >= 1
    rows = sorted(result.rows())
    assert rows == [(1, 1, 10), (2, 1, 20), (7, 2, 30), (8, 2, 40)]


def test_mixed_count_and_distinct_over_empty_input(sess):
    """Fuzz catch (seed 20260730 #47): count(col) re-aggregated as sum
    through the DISTINCT split returned NULL over zero rows; SQL count
    is never NULL."""
    sess.execute("create table ce (k bigint, a bigint, b bigint)")
    sess.create_distributed_table("ce", "k", shard_count=4)
    sess.execute("insert into ce values (1, 2, 3), (4, 5, 6)")
    r = sess.execute("select count(a), count(distinct a) from ce "
                     "where b > 100").rows()[0]
    assert r == (0, 0), r
    # approx split re-aggregates plain counts the same way
    r = sess.execute("select approx_count_distinct(a), count(b) from ce "
                     "where b > 100").rows()[0]
    assert r == (0, 0), r
    # non-empty sanity
    r = sess.execute(
        "select count(a), count(distinct a) from ce").rows()[0]
    assert r == (2, 2), r


def _force_bucketed_lookup(plan, build_table, base, extent):
    """Flip every join in `plan` onto the fused bucketed-probe path with
    `build_table` as the (claimed-unique) build side."""
    from citus_tpu.executor.feed import walk_plan
    from citus_tpu.planner.plan import JoinNode, ScanNode

    for node in walk_plan(plan.root):
        if isinstance(node, JoinNode):
            left_is_build = isinstance(node.left, ScanNode) and \
                node.left.rel.table == build_table
            node.fuse_lookup = True
            node.probe_bucketed = True
            node.build_side = "left" if left_is_build else "right"
            node.left_key_extents = ((base, extent),)
            node.right_key_extents = ((base, extent),)


def test_bucketed_probe_join_matches_oracle(sess, monkeypatch):
    """The VMEM-tiled bucketed probe path must return exactly what the
    single-gather path returns — pinned end-to-end on the CPU mesh with
    the tile patched small so the 200-slot directory spans 13 buckets."""
    import citus_tpu.ops.join as J
    from citus_tpu.sql.parser import parse_one

    monkeypatch.setattr(J, "PROBE_TILE_SLOTS", 16)
    calls = []
    orig = J.bucketed_unique_lookup

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(J, "bucketed_unique_lookup", spy)

    sess.execute("create table bua (k bigint, v int)")
    sess.create_distributed_table("bua", "k", shard_count=4)
    sess.execute("create table bub (k bigint, w int)")
    sess.create_distributed_table("bub", "k", shard_count=4)
    sess.execute("insert into bua values " + ",".join(
        f"({k},{k * 10})" for k in range(1, 201)))
    # probes: two rows per key over a wider range, so some keys miss
    # the directory entirely and some buckets stay empty
    sess.execute("insert into bub values " + ",".join(
        f"({i % 250 + 1},{i})" for i in range(400)))
    plan, _cleanup = sess._plan_select(parse_one(
        "select v, w from bua, bub where bua.k = bub.k"))
    _force_bucketed_lookup(plan, "bua", base=1, extent=200)
    result = sess.executor.execute_plan(plan)
    assert calls, "bucketed probe path was never traced"
    assert result.retries == 0  # clean first execution, no overflow
    expect = sorted(((i % 250 + 1) * 10, i) for i in range(400)
                    if i % 250 + 1 <= 200)
    assert sorted(tuple(r) for r in result.rows()) == expect


def test_bucketed_probe_duplicate_build_keys_fallback(sess, monkeypatch):
    """Stale uniqueness under the bucketed probe: duplicate build keys
    must surface dense_oob and retry on the general expansion path,
    exactly like dense_unique_lookup — never an arbitrary single match."""
    import citus_tpu.ops.join as J
    from citus_tpu.sql.parser import parse_one

    monkeypatch.setattr(J, "PROBE_TILE_SLOTS", 16)
    sess.execute("create table dua (k bigint, v int)")
    sess.create_distributed_table("dua", "k", shard_count=4)
    sess.execute("create table dub (k bigint, w int)")
    sess.create_distributed_table("dub", "k", shard_count=4)
    sess.execute("insert into dua values (1,10),(2,20),(3,30)")
    # build side duplicates k=2: the correct result needs BOTH matches
    sess.execute("insert into dub values (1,1),(2,2),(2,5),(3,3)")
    plan, _cleanup = sess._plan_select(parse_one(
        "select v, w from dua, dub where dua.k = dub.k"))
    _force_bucketed_lookup(plan, "dub", base=1, extent=3)
    result = sess.executor.execute_plan(plan)
    assert result.retries >= 1
    assert sorted(tuple(r) for r in result.rows()) == \
        [(10, 1), (20, 2), (20, 5), (30, 3)]


def test_bucketed_probe_skew_overflow_regrows(sess, monkeypatch):
    """A hot bucket (every probe hits one key) overflows its per-bucket
    capacity; the count-then-emit contract must regrow and retry — rows
    must never be silently dropped.  (A row-returning join: GLOBAL
    aggregates take the join-agg pushdown, which probes via _bounds and
    never fuses lookups.)"""
    import citus_tpu.ops.join as J
    from citus_tpu.sql.parser import parse_one

    monkeypatch.setattr(J, "PROBE_TILE_SLOTS", 16)
    sess.execute("set join_probe_bucket_factor = 1.0")
    sess.execute("create table sua (k bigint, v int)")
    sess.create_distributed_table("sua", "k", shard_count=4)
    sess.execute("create table sub_ (k bigint, w int)")
    sess.create_distributed_table("sub_", "k", shard_count=4)
    sess.execute("insert into sua values " + ",".join(
        f"({k},{k * 10})" for k in range(1, 65)))
    # 600 probes of k=5 — all in ONE bucket on ONE device — plus a thin
    # uniform spread so other buckets are nonempty
    rows = [f"(5,{i})" for i in range(600)]
    rows += [f"({i % 64 + 1},{1000 + i})" for i in range(64)]
    sess.execute("insert into sub_ values " + ",".join(rows))
    plan, _cleanup = sess._plan_select(parse_one(
        "select v, w from sua, sub_ where sua.k = sub_.k"))
    _force_bucketed_lookup(plan, "sua", base=1, extent=64)
    result = sess.executor.execute_plan(plan)
    assert result.retries >= 1  # the hot bucket overflowed and regrew
    expect = sorted([(50, i) for i in range(600)] +
                    [((i % 64 + 1) * 10, 1000 + i) for i in range(64)])
    assert sorted(tuple(r) for r in result.rows()) == expect


def test_stripe_row_limit_splits_and_stays_atomic(tmp_path):
    """graftlint round: columnar_stripe_row_limit was a registered,
    documented, test-SET knob consumed by nothing.  Now the ingest
    path honors it — an oversized batch splits into several stripes —
    and the single-shard (reference-table) path must flip the manifest
    ONCE for the whole batch: a failure on a later stripe leaves zero
    rows visible, exactly like the hash path."""
    import glob
    import os

    from citus_tpu.utils.faultinjection import InjectedFault, inject

    d = str(tmp_path / "sl")
    s = citus_tpu.connect(data_dir=d, columnar_stripe_row_limit=1000)
    s.execute("CREATE TABLE ref (id INT, v INT)")
    s.execute("SELECT create_reference_table('ref')")
    csv = str(tmp_path / "r.csv")
    with open(csv, "w") as f:
        for i in range(3500):
            f.write(f"{i},{i}\n")
    # fail on the 3rd of 4 stripes: nothing may become visible
    with inject("store.append_stripe", after=2):
        with pytest.raises(InjectedFault):
            s.execute(f"COPY ref FROM '{csv}' WITH (FORMAT csv)")
    assert int(s.execute(
        "SELECT count(*) FROM ref").rows()[0][0]) == 0
    # clean retry: all rows exactly once, split across 4 stripes (and
    # the failed attempt's invisible stripes were discarded)
    s.execute(f"COPY ref FROM '{csv}' WITH (FORMAT csv)")
    assert int(s.execute(
        "SELECT count(*) FROM ref").rows()[0][0]) == 3500
    stripes = glob.glob(os.path.join(
        d, "tables", "ref", "**", "stripe_*.ctps"), recursive=True)
    assert len(stripes) == 4
    s.close()


def test_stripe_split_hash_path_discards_partial_on_fault(tmp_path):
    """Hash-path sibling of the test above (code-review finding): a
    fault mid-way through a shard's multi-stripe loop must hand the
    already-written invisible stripes to discard_pending — no orphaned
    stripe files, no visible rows."""
    import glob
    import os

    from citus_tpu.utils.faultinjection import InjectedFault, inject

    d = str(tmp_path / "hl")
    s = citus_tpu.connect(data_dir=d, columnar_stripe_row_limit=1000)
    s.execute("CREATE TABLE h (id INT, v INT)")
    s.execute("SELECT create_distributed_table('h', 'id', 2)")
    csv = str(tmp_path / "h.csv")
    with open(csv, "w") as f:
        for i in range(6000):   # ~3000/shard → 3 stripes per shard
            f.write(f"{i},{i}\n")
    with inject("store.append_stripe", after=2):
        with pytest.raises(InjectedFault):
            s.execute(f"COPY h FROM '{csv}' WITH (FORMAT csv)")
    assert int(s.execute("SELECT count(*) FROM h").rows()[0][0]) == 0
    leaked = glob.glob(os.path.join(
        d, "tables", "h", "**", "stripe_*.ctps"), recursive=True)
    assert leaked == []
    s.execute(f"COPY h FROM '{csv}' WITH (FORMAT csv)")
    assert int(s.execute("SELECT count(*) FROM h").rows()[0][0]) == 6000
    s.close()


def test_feed_cache_keys_on_skip_filter_fingerprint(tmp_path):
    """A skip-pruned (possibly prefetched) feed must never be served to
    a statement with a different chunk filter: the feed-cache key
    carries the storage-name-mapped skip-test fingerprint, so two
    filters that read different chunk sets get different slots — and a
    repeat of the SAME filter still hits."""
    sess = citus_tpu.connect(data_dir=str(tmp_path / "fc"), n_devices=2,
                             serving_result_cache_bytes=0,
                             scan_pipeline="host")
    sess.execute("CREATE TABLE ranges (id INT, v INT)")
    sess.execute("SELECT create_distributed_table('ranges', 'id', 2)")
    # two value bands in separate stripes per shard, so min/max skip
    # nodes actually prune: filter A reads only band 1, filter B only
    # band 2.  A key that ignored the filter would serve band-1 rows
    # to the band-2 statement.
    sess.execute("INSERT INTO ranges VALUES " + ", ".join(
        f"({i}, {i})" for i in range(1000)))
    sess.execute("INSERT INTO ranges VALUES " + ", ".join(
        f"({i}, {i})" for i in range(100000, 101000)))
    lo = sess.execute(
        "SELECT count(*), min(v), max(v) FROM ranges WHERE v < 1000"
    ).rows()
    assert lo == [(1000, 0, 999)]
    hi = sess.execute(
        "SELECT count(*), min(v), max(v) FROM ranges "
        "WHERE v >= 100000").rows()
    assert hi == [(1000, 100000, 100999)]
    # same filter again: the pruned feed is reusable — and must hit
    h0 = sess.executor.feed_cache.hits
    again = sess.execute(
        "SELECT count(*), min(v), max(v) FROM ranges "
        "WHERE v >= 100000").rows()
    assert again == hi
    assert sess.executor.feed_cache.hits > h0
    # a rename must not alias the fingerprint either (the key maps
    # current names to the storage names the chunk filter tested)
    sess.execute("ALTER TABLE ranges RENAME COLUMN v TO w")
    renamed = sess.execute(
        "SELECT count(*) FROM ranges WHERE w < 1000").rows()
    assert renamed == [(1000,)]
    sess.close()


def test_manifest_identity_strictly_monotone(tmp_path):
    """Cross-session visibility keys on the manifest's stat identity
    (mtime_ns, size, inode).  Two same-size commits inside one
    filesystem timestamp tick (warm DML lands back-to-back) could
    reissue an identity a reader already cached — refresh_if_stale
    would serve the old rows.  The writer now forces mtime_ns strictly
    monotone along the commit chain; simulate the colliding tick by
    pushing the current manifest's mtime a second into the future and
    committing again."""
    import os

    sess = citus_tpu.connect(data_dir=str(tmp_path / "mono"),
                             n_devices=2)
    sess.execute("CREATE TABLE kv (id INT, v INT)")
    sess.execute("SELECT create_distributed_table('kv', 'id', 2)")
    sess.execute("INSERT INTO kv VALUES (1, 10), (2, 20)")
    path = sess.store._manifest_path("kv")
    st1 = os.stat(path).st_mtime_ns
    future = st1 + 10 ** 9
    os.utime(path, ns=(future, future))
    sess.execute("UPDATE kv SET v = 11 WHERE id = 1")
    st2 = os.stat(path).st_mtime_ns
    assert st2 > future, (st2, future)
    # and a second session actually sees the write
    s2 = citus_tpu.connect(data_dir=str(tmp_path / "mono"), n_devices=2)
    assert s2.execute("SELECT v FROM kv WHERE id = 1").rows() == [(11,)]
    sess.close()
    s2.close()


def test_manifest_load_records_pre_read_identity(tmp_path):
    """Companion race to the monotone-identity fix above (found by the
    serving invalidation hammer once PR 13's mesh seams shifted thread
    timing): `TableStore.manifest()` used to read the manifest CONTENT
    and then stat the file to record its identity.  A commit renaming a
    new manifest between those two steps paired the NEW identity with
    the OLD content — every later refresh_if_stale compared new == new
    and the reader served old rows forever (and poisoned the shared
    serving result cache with a fresh-token stale fill).  The identity
    is now recorded from a stat taken BEFORE the read, so a mid-read
    commit costs one redundant reload instead of permanent blindness.
    Force the exact interleaving by committing from a writer session
    inside the reader's content read."""
    data_dir = str(tmp_path / "preread")
    w = citus_tpu.connect(data_dir=data_dir, n_devices=2)
    w.execute("CREATE TABLE kv (id INT, v INT)")
    w.execute("SELECT create_distributed_table('kv', 'id', 2)")
    w.execute("INSERT INTO kv VALUES (1, 10)")

    r = citus_tpu.connect(data_dir=data_dir, n_devices=2,
                          serving_result_cache_bytes=0)
    from citus_tpu.storage import table_store as ts

    orig = ts.dio.read_json_checked
    manifest_path = r.store._manifest_path("kv")
    fired = {"n": 0}

    def racing_read(path, *a, **kw):
        content = orig(path, *a, **kw)
        if path == manifest_path and fired["n"] == 0:
            fired["n"] = 1
            # the racing commit lands AFTER the reader's content read
            # but BEFORE it returns (i.e. before any post-read stat)
            w.execute("UPDATE kv SET v = 99 WHERE id = 1")
        return content

    ts.dio.read_json_checked = racing_read
    try:
        # this read loads the pre-update manifest content mid-race
        r.execute("SELECT v FROM kv WHERE id = 1")
    finally:
        ts.dio.read_json_checked = orig
    assert fired["n"] == 1, "race window never exercised"
    # the next read must DETECT the racing commit and serve v=99
    assert r.execute("SELECT v FROM kv WHERE id = 1").rows() == [(99,)]
    w.close()
    r.close()
