"""Regression tests for reviewed wrong-result bugs.

Each test reproduces a once-broken scenario:
1. int32 distribution columns + repartition join (hash width-fold parity)
2. multi-key repart_both falsely claiming per-column partitioning
3. ORDER BY on non-selected columns / aggregates
4. DATE values folding back from scalar/IN subqueries
5. SQL truncating %, / on negative integers
"""

import numpy as np
import pytest

import citus_tpu
from citus_tpu.catalog.distribution import hash_token


@pytest.fixture()
def sess(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64")
    yield s
    s.close()


def test_hash_width_fold_parity():
    """hash(int64 v) == hash(int32 v) for every v in int32 range."""
    vals32 = np.array([0, 1, -1, 7, -7, 2**31 - 1, -(2**31), 123456789],
                      dtype=np.int32)
    vals64 = vals32.astype(np.int64)
    np.testing.assert_array_equal(hash_token(vals32), hash_token(vals64))
    # device twin agrees on the widened values
    import jax.numpy as jnp
    from citus_tpu.ops.hashing import hash_token_jax

    dev = np.asarray(hash_token_jax(jnp.asarray(vals64)))
    np.testing.assert_array_equal(dev, hash_token(vals64))


def test_int32_distcol_repartition_join(sess):
    """Single-repartition join between tables distributed on int columns."""
    sess.execute("create table a (k int, v int)")
    sess.execute("create table b (k2 int, w int)")
    sess.create_distributed_table("a", "k", shard_count=8)
    sess.create_distributed_table("b", "w", shard_count=8)  # NOT on k2
    rows_a = ",".join(f"({i},{i * 10})" for i in range(50))
    rows_b = ",".join(f"({i},{i + 1000})" for i in range(50))
    sess.execute(f"insert into a values {rows_a}")
    sess.execute(f"insert into b values {rows_b}")
    # b repartitions onto a's hash(k) placement; parity bug dropped all rows
    r = sess.execute("select count(*) from a, b where k = k2")
    assert int(r.rows()[0][0]) == 50


def test_repart_both_then_single_key_join(sess):
    """Dual repartition on (a,b) must not claim colocation with a later
    single-key join partner that is hash-placed on that key alone."""
    sess.execute("create table t1 (a int, b int, x int)")
    sess.execute("create table t2 (a2 int, b2 int, y int)")
    sess.execute("create table t3 (a3 int, z int)")
    # distribute on the NON-join columns to force repart_both on (a,b)
    sess.create_distributed_table("t1", "x", shard_count=4)
    sess.create_distributed_table("t2", "y", shard_count=4)
    sess.create_distributed_table("t3", "a3", shard_count=4)  # == n_dev
    n = 40
    sess.execute("insert into t1 values " + ",".join(
        f"({i % 10},{i % 7},{i})" for i in range(n)))
    sess.execute("insert into t2 values " + ",".join(
        f"({i % 10},{i % 7},{i + 100})" for i in range(n)))
    sess.execute("insert into t3 values " + ",".join(
        f"({i},{i})" for i in range(10)))
    r = sess.execute("""
        select count(*) from t1, t2, t3
        where a = a2 and b = b2 and a2 = a3""")
    expect = sum(1 for i in range(n) for j in range(n)
                 if i % 10 == j % 10 and i % 7 == j % 7)
    assert int(r.rows()[0][0]) == expect


def test_order_by_non_selected_column(sess):
    sess.execute("create table o1 (x int, y int)")
    sess.create_distributed_table("o1", "x", shard_count=4)
    sess.execute("insert into o1 values (1, 30), (2, 10), (3, 20)")
    r = sess.execute("select x from o1 order by y")
    assert [v for (v,) in r.rows()] == [2, 3, 1]


def test_order_by_aggregate_not_in_select(sess):
    sess.execute("create table o2 (g int, y int)")
    sess.create_distributed_table("o2", "g", shard_count=4)
    sess.execute("insert into o2 values (1,5),(1,5),(2,100),(3,1)")
    r = sess.execute("select g from o2 group by g order by sum(y) desc")
    assert [v for (v,) in r.rows()] == [2, 1, 3]


def test_order_by_ungrouped_column_rejected(sess):
    from citus_tpu.errors import PlanningError

    sess.execute("create table o3 (g int, y int)")
    sess.create_distributed_table("o3", "g", shard_count=4)
    sess.execute("insert into o3 values (1,2)")
    with pytest.raises(PlanningError, match="ORDER BY"):
        sess.execute("select g from o3 group by g order by y")


def test_date_in_subquery_roundtrip(sess):
    sess.execute("create table ev (id int, d date)")
    sess.create_distributed_table("ev", "id", shard_count=4)
    sess.execute("""insert into ev values
        (1, date '1994-01-01'), (2, date '1995-06-15'),
        (3, date '1994-01-01'), (4, date '1996-03-03')""")
    r = sess.execute(
        "select count(*) from ev where d in (select d from ev where id = 1)")
    assert int(r.rows()[0][0]) == 2
    r2 = sess.execute(
        "select count(*) from ev where d = (select d from ev where id = 2)")
    assert int(r2.rows()[0][0]) == 1
    # materialized CTE keeps DATE typed (temp-table path)
    r3 = sess.execute("""
        with dd as (select d from ev where id <= 3)
        select count(*) from ev, dd where ev.d = dd.d""")
    assert int(r3.rows()[0][0]) == 5  # 2 dup dates x2 matches + 1995 x1


def test_modulo_truncates_toward_zero(sess):
    sess.execute("create table m (v int)")
    sess.create_distributed_table("m", "v", shard_count=4)
    sess.execute("insert into m values (7), (-7)")
    r = sess.execute("select v, v % 2 from m order by v")
    assert [tuple(map(int, row)) for row in r.rows()] == [(-7, -1), (7, 1)]
    # device-side predicate: (0 - 7) % 2 = 1 must NOT match
    r2 = sess.execute("select count(*) from m where (0 - v) % 2 = 1")
    # v=-7: (0-(-7))%2 = 7%2 = 1 → matches; v=7: (0-7)%2 = -1 → no
    assert int(r2.rows()[0][0]) == 1


def test_device_topk_nan_desc_matches_host_order(sess):
    """ORDER BY <float with NaN> DESC LIMIT k: the per-device top-k pass
    must rank NaN like the host comparator (NaN = largest) or devices
    drop exactly the rows the host would put first."""
    sess.execute("create table tk (id int, a double precision, "
                 "b double precision)")
    sess.create_distributed_table("tk", "id", shard_count=4)
    rows = [(i, float(i), 0.0 if i % 10 == 0 else 1.0) for i in range(1, 41)]
    vals = ",".join(f"({i},{a},{b})" for i, a, b in rows)
    sess.execute(f"insert into tk values {vals}")
    with_limit = sess.execute(
        "select id from tk order by a / b desc limit 5").rows()
    no_limit = sess.execute(
        "select id from tk order by a / b desc").rows()
    assert [int(r[0]) for r in with_limit] == \
        [int(r[0]) for r in no_limit[:5]]
    # NaN rows (b = 0) come first under DESC, like the host sort
    assert {int(r[0]) for r in with_limit[:4]} == {10, 20, 30, 40}


def test_stale_join_extent_falls_back_without_wrong_results(sess):
    """A dense join directory / int32 narrowing planned from stale key
    ranges must surface dense_oob and retry on the general path — never
    silently drop or wrap matches."""
    from citus_tpu.executor.feed import walk_plan
    from citus_tpu.planner.plan import JoinNode
    from citus_tpu.sql.parser import parse_one

    sess.execute("create table sa (k bigint, v int)")
    sess.create_distributed_table("sa", "k", shard_count=4)
    sess.execute("create table sb (k bigint, w int)")
    sess.create_distributed_table("sb", "k", shard_count=4)
    big = (1 << 33)  # outside any int32 narrowing
    sess.execute(f"insert into sa values (1,10),(2,20),({big},30)")
    sess.execute(f"insert into sb values (1,1),(2,2),({big},3)")
    plan, cleanup = sess._plan_select(parse_one(
        "select count(*), sum(v + w) from sa, sb where sa.k = sb.k"))
    # simulate stale statistics: claim the keys fit [0, 4) and int32
    for node in walk_plan(plan.root):
        if isinstance(node, JoinNode):
            node.left_key_extents = ((0, 4),)
            node.right_key_extents = ((0, 4),)
            node.key_int32 = (True,)
    result = sess.executor.execute_plan(plan)
    assert result.retries >= 1  # dense_oob retry happened
    row = result.rows()[0]
    assert int(row[0]) == 3 and int(row[1]) == 66

    # warm re-execution of a FRESH plan instance (new node ids): the
    # converged capacities memo must translate across plan instances and
    # skip the retry entirely
    plan2, _ = sess._plan_select(parse_one(
        "select count(*), sum(v + w) from sa, sb where sa.k = sb.k"))
    for node in walk_plan(plan2.root):
        if isinstance(node, JoinNode):
            node.left_key_extents = ((0, 4),)
            node.right_key_extents = ((0, 4),)
            node.key_int32 = (True,)
    result2 = sess.executor.execute_plan(plan2)
    assert result2.retries == 0
    row2 = result2.rows()[0]
    assert int(row2[0]) == 3 and int(row2[1]) == 66
