"""End-to-end SQL tests on an 8-device virtual mesh, cross-checked against
the sqlite oracle — the framework's multi_schedule + query-generator
equivalent."""

import numpy as np
import pytest

import citus_tpu
from citus_tpu.ingest import tpch
from oracle import compare_results, make_oracle, run_oracle

DATE_COLUMNS = {
    "orders": ["o_orderdate"],
    "lineitem": ["l_shipdate", "l_commitdate", "l_receiptdate"],
}


@pytest.fixture(scope="module")
def tpch_session(tmp_path_factory):
    sess = citus_tpu.connect(
        data_dir=str(tmp_path_factory.mktemp("tpch")),
        n_devices=8, compute_dtype="float64")
    counts = tpch.load_into_session(sess, sf=0.002, seed=7, shard_count=8)
    assert counts["lineitem"] > 5000
    return sess


@pytest.fixture(scope="module")
def oracle_conn():
    data = tpch.generate_tables(0.002, seed=7)
    return make_oracle(data, DATE_COLUMNS)


def check(sess, conn, sql, ordered=None, tol=1e-6):
    result = sess.execute(sql)
    want = run_oracle(conn, sql)
    is_ordered = ordered if ordered is not None else "order by" in sql.lower()
    compare_results(result.rows(), want, is_ordered, tol)
    return result


class TestTPCH:
    def test_q1(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q1)

    def test_q3(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q3)

    def test_q5(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q5)

    def test_q6(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q6)

    def test_q9(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q9)

    def test_q7(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q7)

    def test_q8(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q8)

    def test_q10(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q10)

    def test_q12(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q12)

    def test_q14(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q14)

    def test_q18(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q18)

    def test_q19(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q19)

    # correlated-subquery queries (decorrelate.py semi/anti + grouped
    # derived tables) — Q2/Q4/Q17/Q20/Q21/Q22
    def test_q2(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q2)

    def test_q4(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q4)

    def test_q17(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q17)

    def test_q20(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q20)

    def test_q21(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q21)

    def test_q22(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn, tpch.Q22)


class TestQueryShapes:
    """Smaller targeted shapes (multi_schedule-style coverage)."""

    def test_global_aggregates(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select count(*), sum(l_quantity), min(l_shipdate), "
              "max(l_extendedprice), avg(l_discount) from lineitem")

    def test_filtered_scan_projection(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select o_orderkey, o_totalprice * 1.1 as up "
              "from orders where o_totalprice > 300000 "
              "order by o_orderkey limit 20")

    def test_colocated_join(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select count(*) from orders, lineitem "
              "where o_orderkey = l_orderkey and o_totalprice > 100000")

    def test_broadcast_join_reference(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select n_name, count(*) as c from supplier, nation "
              "where s_nationkey = n_nationkey group by n_name "
              "order by c desc, n_name limit 5")

    def test_single_repartition_join(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select count(*) from customer, orders "
              "where c_custkey = o_custkey and c_acctbal > 0")

    def test_dual_repartition_join(self, tpch_session, oracle_conn):
        # join on non-distribution columns on both sides
        check(tpch_session, oracle_conn,
              "select count(*) from customer, supplier "
              "where c_nationkey = s_nationkey")

    def test_group_by_string(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select l_returnflag, count(*) from lineitem "
              "group by l_returnflag order by l_returnflag")

    def test_group_by_distribution_column_stays_local(self, tpch_session,
                                                      oracle_conn):
        r = tpch_session.execute(
            "explain select l_orderkey, count(*) from lineitem "
            "group by l_orderkey")
        text = "\n".join(r.columns["QUERY PLAN"])
        assert "device-local groups" in text
        check(tpch_session, oracle_conn,
              "select l_orderkey, count(*) from lineitem "
              "group by l_orderkey order by l_orderkey limit 25")

    def test_having(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select c_nationkey, count(*) as c from customer "
              "group by c_nationkey having count(*) > 10 "
              "order by c desc, c_nationkey")

    def test_distinct(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select distinct l_returnflag, l_linestatus from lineitem "
              "order by l_returnflag, l_linestatus")

    def test_case_when(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select sum(case when l_discount > 0.05 then 1 else 0 end), "
              "count(*) from lineitem")

    def test_in_list_and_like(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select count(*) from lineitem "
              "where l_shipmode in ('AIR', 'RAIL') "
              "and l_shipinstruct like '%RETURN%'")

    def test_between_dates(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select count(*) from orders where o_orderdate between "
              "date '1994-01-01' and date '1994-12-31'")

    def test_scalar_subquery(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select count(*) from orders where o_totalprice > "
              "(select avg(o_totalprice) from orders)")

    def test_in_subquery(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select count(*) from orders where o_custkey in "
              "(select c_custkey from customer where c_mktsegment = "
              "'BUILDING')")

    def test_cte(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "with big as (select o_orderkey, o_totalprice from orders "
              "where o_totalprice > 200000) "
              "select count(*) from big, lineitem "
              "where big.o_orderkey = l_orderkey")

    def test_from_subquery(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select seg, c from (select c_mktsegment as seg, count(*) "
              "as c from customer group by c_mktsegment) s "
              "order by c desc, seg limit 3")

    def test_explicit_join_syntax(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select count(*) from orders join lineitem "
              "on o_orderkey = l_orderkey join customer "
              "on o_custkey = c_custkey where c_acctbal > 5000")

    def test_order_by_desc_nulls_and_offset(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select o_orderkey, o_totalprice from orders "
              "order by o_totalprice desc limit 10 offset 5")

    def test_extract_year_group(self, tpch_session, oracle_conn):
        check(tpch_session, oracle_conn,
              "select extract(year from o_orderdate) as y, count(*) "
              "from orders group by y order by y")


class TestDDLAndDML:
    def test_insert_and_router_read(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                                 compute_dtype="float64")
        sess.execute("create table kv (k bigint, v text)")
        sess.execute("select create_distributed_table('kv', 'k', 4)")
        sess.execute("insert into kv values (1, 'one'), (2, 'two'), "
                     "(3, NULL)")
        r = sess.execute("select k, v from kv order by k")
        assert r.rows() == [(1, "one"), (2, "two"), (3, None)]

    def test_shard_pruning_on_dist_key(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                                 compute_dtype="float64")
        sess.execute("create table kv (k bigint, v double precision)")
        sess.execute("select create_distributed_table('kv', 'k', 8)")
        sess.execute("insert into kv values " +
                     ",".join(f"({i}, {i})" for i in range(100)))
        r = sess.execute("explain select v from kv where k = 42")
        text = "\n".join(r.columns["QUERY PLAN"])
        assert "shards pruned" in text
        r = sess.execute("select v from kv where k = 42")
        assert r.rows() == [(42.0,)]

    def test_insert_select(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2,
                                 compute_dtype="float64")
        sess.execute("create table a (x bigint)")
        sess.execute("create table b (x bigint)")
        sess.execute("select create_distributed_table('a', 'x', 4)")
        sess.execute("select create_distributed_table('b', 'x', 4)")
        sess.execute("insert into a values " +
                     ",".join(f"({i})" for i in range(50)))
        sess.execute("insert into b select x from a where x < 10")
        r = sess.execute("select count(*) from b")
        assert r.rows() == [(10,)]

    def test_drop_and_recreate(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2)
        sess.execute("create table t (x int)")
        sess.execute("drop table t")
        sess.execute("create table t (x int, y int)")
        assert sess.catalog.table("t").schema.names == ["x", "y"]

    def test_set_show(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2)
        sess.execute("set citus.shard_count = 16")
        r = sess.execute("show shard_count")
        assert r.rows() == [("16",)]

    def test_session_persistence(self, tmp_path):
        d = str(tmp_path / "d")
        sess = citus_tpu.connect(data_dir=d, n_devices=2,
                                 compute_dtype="float64")
        sess.execute("create table t (x bigint)")
        sess.execute("select create_distributed_table('t', 'x', 4)")
        sess.execute("insert into t values (1), (2), (3)")
        sess.close()
        sess2 = citus_tpu.connect(data_dir=d, n_devices=2,
                                  compute_dtype="float64")
        r = sess2.execute("select count(*) from t")
        assert r.rows() == [(3,)]

    def test_constant_false_predicate(self, tmp_path):
        # regression: rel-free conjuncts must not be dropped
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2,
                                 compute_dtype="float64")
        sess.execute("create table t (x bigint)")
        sess.execute("select create_distributed_table('t', 'x', 2)")
        sess.execute("insert into t values (1), (2)")
        assert sess.execute("select x from t where 1 = 2").rows() == []
        assert len(sess.execute("select x from t where 1 = 1").rows()) == 2

    def test_not_in_subquery_null_semantics(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2,
                                 compute_dtype="float64")
        sess.execute("create table t (k bigint)")
        sess.execute("create table e (x bigint, f bigint)")
        sess.execute("select create_distributed_table('t', 'k', 2)")
        sess.execute("select create_distributed_table('e', 'f', 2)")
        sess.execute("insert into t values (1), (2), (3)")
        sess.execute("insert into e (x, f) values (1, 1), (NULL, 2)")
        # NOT IN with a NULL in the subquery: never TRUE → zero rows
        r = sess.execute("select k from t where k not in (select x from e)")
        assert r.rows() == []
        # NOT IN over an empty subquery: TRUE for all rows
        r = sess.execute("select k from t where k not in "
                         "(select x from e where f > 100) order by k")
        assert [x[0] for x in r.rows()] == [1, 2, 3]
        # IN over empty: no rows
        r = sess.execute("select k from t where k in "
                         "(select x from e where f > 100)")
        assert r.rows() == []

    def test_all_null_group_aggregates_are_null(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2,
                                 compute_dtype="float64")
        sess.execute("create table t (k bigint, v double precision)")
        sess.execute("select create_distributed_table('t', 'k', 2)")
        sess.execute("insert into t values (1, NULL), (1, NULL), (2, 3.5)")
        r = sess.execute("select k, min(v), max(v), sum(v), avg(v), "
                         "count(v) from t group by k order by k")
        assert r.rows() == [(1, None, None, None, None, 0),
                            (2, 3.5, 3.5, 3.5, 3.5, 1)]

    def test_aggregate_in_where_rejected(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2)
        sess.execute("create table t (k bigint)")
        sess.execute("select create_distributed_table('t', 'k', 2)")
        with pytest.raises(citus_tpu.PlanningError,
                           match="aggregate not allowed"):
            sess.execute("select k from t where sum(k) > 5")

    def test_explain_analyze(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2,
                                 compute_dtype="float64")
        sess.execute("create table t (x bigint)")
        sess.execute("select create_distributed_table('t', 'x', 2)")
        sess.execute("insert into t values (1), (2)")
        r = sess.execute("explain analyze select count(*) from t")
        text = "\n".join(r.columns["QUERY PLAN"])
        assert "Execution Time" in text and "Rows: 1" in text
