"""Streamed (batched stripe→HBM) execution: results must equal the
resident-feed path / sqlite oracle on every eligible plan shape, and
ineligible shapes must fall back to the resident path.

The reference analogue is the stripe-at-a-time columnar read feeding task
execution (columnar/columnar_reader.c:323) — tables never need to fit in
executor memory at once."""

import numpy as np
import pytest

import citus_tpu
from citus_tpu.ingest import tpch
from oracle import compare_results, make_oracle, run_oracle

DATE_COLUMNS = {
    "orders": ["o_orderdate"],
    "lineitem": ["l_shipdate", "l_commitdate", "l_receiptdate"],
}

# small batches force several batches per query at sf=0.002
STREAM_SETUP = ("set max_feed_bytes_per_device = 1; "
                "set stream_batch_rows = 512")
STREAM_RESET = ("set max_feed_bytes_per_device = 6442450944; "
                "set stream_batch_rows = 0")


@pytest.fixture(scope="module")
def sess(tmp_path_factory):
    s = citus_tpu.connect(
        data_dir=str(tmp_path_factory.mktemp("stream_tpch")),
        n_devices=8, compute_dtype="float64",
        columnar_stripe_row_limit=1000)
    tpch.load_into_session(s, sf=0.002, seed=11, shard_count=8)
    return s


@pytest.fixture(scope="module")
def oracle_conn():
    data = tpch.generate_tables(0.002, seed=11)
    return make_oracle(data, DATE_COLUMNS)


def check_streamed(sess, conn, sql, min_batches=2, tol=1e-6):
    """Run under a tiny feed budget, assert the stream path actually ran
    and the result matches sqlite."""
    sess.execute(STREAM_SETUP)
    try:
        result = sess.execute(sql)
    finally:
        sess.execute(STREAM_RESET)
    assert result.streamed_batches >= min_batches, \
        f"expected streamed execution, got {result.streamed_batches} batches"
    want = run_oracle(conn, sql)
    compare_results(result.rows(), want, "order by" in sql.lower(), tol)
    return result


class TestStreamedShapes:
    def test_global_agg_scan(self, sess, oracle_conn):
        check_streamed(sess, oracle_conn,
                       "select count(*), sum(l_quantity), min(l_shipdate), "
                       "max(l_extendedprice), avg(l_discount) from lineitem")

    def test_grouped_agg(self, sess, oracle_conn):
        check_streamed(sess, oracle_conn,
                       "select l_returnflag, l_linestatus, count(*), "
                       "sum(l_quantity) from lineitem "
                       "group by l_returnflag, l_linestatus")

    def test_q1(self, sess, oracle_conn):
        check_streamed(sess, oracle_conn, tpch.Q1)

    def test_q3(self, sess, oracle_conn):
        check_streamed(sess, oracle_conn, tpch.Q3)

    def test_colocated_join_agg(self, sess, oracle_conn):
        check_streamed(sess, oracle_conn,
                       "select count(*), sum(l_extendedprice) "
                       "from orders, lineitem where o_orderkey = l_orderkey")

    def test_dual_repartition_join_agg(self, sess, oracle_conn):
        check_streamed(sess, oracle_conn,
                       "select count(*) from orders, lineitem "
                       "where o_custkey = l_suppkey")

    def test_row_output_with_order_limit(self, sess, oracle_conn):
        check_streamed(sess, oracle_conn,
                       "select l_orderkey, l_extendedprice from lineitem "
                       "where l_quantity > 45 "
                       "order by l_extendedprice desc, l_orderkey limit 25")

    def test_select_distinct(self, sess, oracle_conn):
        check_streamed(sess, oracle_conn,
                       "select distinct l_linenumber from lineitem "
                       "order by l_linenumber")

    def test_left_join_stream_preserved_side(self, sess, oracle_conn):
        # stream side (lineitem) is the preserved/left side — eligible
        check_streamed(sess, oracle_conn,
                       "select count(*), sum(o_totalprice) from lineitem "
                       "left join orders on l_suppkey = o_custkey")

    def test_having(self, sess, oracle_conn):
        check_streamed(sess, oracle_conn,
                       "select l_suppkey, sum(l_quantity) as q from lineitem "
                       "group by l_suppkey having sum(l_quantity) > 100 "
                       "order by q desc, l_suppkey limit 10")


class TestStreamFallback:
    """Shapes the stream path must refuse (resident path still answers)."""

    def _not_streamed(self, sess, conn, sql, tol=1e-6):
        sess.execute(STREAM_SETUP)
        try:
            result = sess.execute(sql)
        finally:
            sess.execute(STREAM_RESET)
        assert result.streamed_batches == 0
        want = run_oracle(conn, sql)
        compare_results(result.rows(), want, "order by" in sql.lower(), tol)

    def test_count_distinct_not_streamed(self, sess, oracle_conn):
        # nested dedupe aggregate would dedupe per batch only
        self._not_streamed(sess, oracle_conn,
                           "select count(distinct l_suppkey) from lineitem")

    def test_window_not_streamed(self, sess, oracle_conn):
        self._not_streamed(
            sess, oracle_conn,
            "select l_orderkey, sum(l_quantity) over "
            "(partition by l_orderkey) as s from lineitem "
            "where l_orderkey < 50 order by l_orderkey, s")

    def test_full_join_not_streamed(self, sess):
        # FULL JOIN preserves both sides: neither scan may batch (a batch
        # cannot know global match flags for the other side's unmatched
        # segment).  Cross-check streamed-budget run vs resident run.
        sql = ("select count(*), sum(o_totalprice), sum(l_quantity) "
               "from lineitem full join orders on l_suppkey = o_custkey")
        resident = sess.execute(sql)
        sess.execute(STREAM_SETUP)
        try:
            result = sess.execute(sql)
        finally:
            sess.execute(STREAM_RESET)
        assert result.streamed_batches == 0
        compare_results(result.rows(), resident.rows(), False, 1e-9)


class TestStreamNullBatches:
    def test_nulls_only_in_later_batches(self, tmp_path):
        """NULL presence differing across stripe batches must not change
        the compiled program's input structure (regression: pytree
        mismatch crash when batch 0 had no NULLs but batch N did)."""
        s = citus_tpu.connect(data_dir=str(tmp_path / "nb"), n_devices=2,
                              compute_dtype="float64",
                              columnar_stripe_row_limit=1000)
        try:
            s.execute("create table t (k bigint, v double precision)")
            s.create_distributed_table("t", "k", shard_count=2)
            # first stripes: all non-NULL; later stripes: all NULL
            s.execute("insert into t values " + ",".join(
                f"({i}, {i * 1.0})" for i in range(4000)))
            s.execute("insert into t values " + ",".join(
                f"({i + 4000}, null)" for i in range(4000)))
            s.execute("set max_feed_bytes_per_device = 1; "
                      "set stream_batch_rows = 512")
            r = s.execute("select count(*), count(v), sum(v) from t")
            assert r.streamed_batches >= 2
            assert r.rows() == [(8000, 4000, sum(range(4000)) * 1.0)]
        finally:
            s.close()


class TestStreamEquivalence:
    """Streamed vs resident execution of the same query byte-compare."""

    @pytest.mark.parametrize("sql", [
        "select l_returnflag, count(*), sum(l_extendedprice) "
        "from lineitem group by l_returnflag",
        "select count(*) from lineitem, orders where l_orderkey = o_orderkey"
        " and o_totalprice > 150000",
    ])
    def test_same_answer(self, sess, sql):
        resident = sess.execute(sql)
        sess.execute(STREAM_SETUP)
        try:
            streamed = sess.execute(sql)
        finally:
            sess.execute(STREAM_RESET)
        assert streamed.streamed_batches >= 2
        compare_results(streamed.rows(), resident.rows(), False, 1e-9)
