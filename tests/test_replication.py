"""Replica placements + read failover (VERDICT round-2 missing item 1;
reference: pg_dist_placement multiple placements per shard and the
adaptive executor's read failover, adaptive_executor.c:95-116)."""

import pytest

import citus_tpu
from citus_tpu.errors import CatalogError


@pytest.fixture()
def sess(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64",
                          shard_replication_factor=2)
    s.execute("create table r (k bigint, v bigint)")
    s.create_distributed_table("r", "k", shard_count=8)
    vals = ",".join(f"({i},{i * 2})" for i in range(1, 401))
    s.execute(f"insert into r values {vals}")
    yield s
    s.close()


def test_replicated_placements_created(sess):
    for shard in sess.catalog.table_shards("r"):
        ps = sess.catalog.shard_placements(shard.shard_id)
        assert len(ps) == 2
        assert len({p.node_id for p in ps}) == 2


def test_failover_on_disable_node_mid_workload(sess):
    total = int(sess.execute("select sum(v) from r").rows()[0][0])
    assert total == sum(i * 2 for i in range(1, 401))
    # kill a node (catalog-level): every query keeps answering correctly
    victim = sess.catalog.active_nodes()[0].name
    sess.execute(f"select citus_disable_node('{victim}')")
    assert int(sess.execute("select sum(v) from r").rows()[0][0]) == total
    assert int(sess.execute(
        "select count(*) from r where k = 17").rows()[0][0]) == 1
    # primary placements moved off the dead node
    for shard in sess.catalog.table_shards("r"):
        p = sess.catalog.active_placement(shard.shard_id)
        assert sess.catalog.nodes[p.node_id].is_active
    # node comes back: queries still correct
    sess.execute(f"select citus_activate_node('{victim}')")
    assert int(sess.execute("select sum(v) from r").rows()[0][0]) == total


def test_remove_node_drops_replicas_keeps_answers(sess):
    total = int(sess.execute("select sum(v) from r").rows()[0][0])
    victim = sess.catalog.active_nodes()[-1].name
    sess.execute(f"select citus_remove_node('{victim}')")
    assert int(sess.execute("select sum(v) from r").rows()[0][0]) == total
    # replication dropped to 1 for shards that had a replica there
    counts = {len(sess.catalog.shard_placements(s.shard_id))
              for s in sess.catalog.table_shards("r")}
    assert counts <= {1, 2}
    # removing another node that now holds sole placements must refuse
    for other in list(sess.catalog.active_nodes()):
        try:
            sess.catalog.remove_node(other.name)
        except CatalogError as e:
            assert "only active placement" in str(e)
            break
    else:
        pytest.fail("expected sole-placement removal to be refused")


def test_unreplicated_node_removal_refused(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64")
    s.execute("create table u (k bigint)")
    s.create_distributed_table("u", "k", shard_count=4)
    s.execute("insert into u values (1), (2), (3)")
    victim = s.catalog.active_nodes()[0].name
    with pytest.raises(CatalogError, match="only active placement"):
        s.catalog.remove_node(victim)
    s.close()


def test_split_preserves_replication(sess):
    shard = sess.catalog.table_shards("r")[0]
    mid = (shard.min_value + shard.max_value) // 2
    sess.execute(f"select citus_split_shard_by_split_points("
                 f"{shard.shard_id}, '{mid}')")
    for s in sess.catalog.table_shards("r"):
        ps = sess.catalog.shard_placements(s.shard_id)
        assert len(ps) == 2, f"shard {s.shard_id} lost its replica"
    total = sum(i * 2 for i in range(1, 401))
    assert int(sess.execute("select sum(v) from r").rows()[0][0]) == total


# ===========================================================================
# CDC log-shipped read replicas (PR 18): leader → follower shipping,
# bounded visible staleness, promotion + zombie-leader fencing,
# power-cut torture over the ship/apply seams, and the replica fuzz
# (leader ≡ follower-at-caught-up-lsn, row for row).

import os
import random
import shutil

from citus_tpu.catalog import Catalog
from citus_tpu.errors import ReadOnlyReplica, ReplicaTooStale, \
    ReplicationError
from citus_tpu.replication import (
    apply_pending,
    journal_tail_lsn,
    load_cursor,
    load_state,
    promote,
    provision_replica,
    ship,
    ship_all,
    staleness,
)
from citus_tpu.stats import counters as sc
from citus_tpu.storage import TableStore
from citus_tpu.utils import faultinjection as fi
from citus_tpu.utils.crashsim import PowerCut, power_cut_at
from fuzzer import generate_replica

_QUIET = dict(n_devices=2, recover_2pc_interval_ms=-1,
              defer_shard_delete_interval_ms=-1,
              health_check_interval_ms=-1, retry_backoff_base_ms=1)


def _connect(path, **kw):
    merged = dict(_QUIET)
    merged.update(kw)
    return citus_tpu.connect(data_dir=str(path), **merged)


def _seed_leader(path, rows=30):
    s = _connect(path)
    s.execute("CREATE TABLE kv (id INT, v INT)")
    s.execute("SELECT create_distributed_table('kv', 'id', 4)")
    s.execute("INSERT INTO kv VALUES " + ", ".join(
        f"({i}, {i * 3})" for i in range(rows)))
    return s


def _rows(sess, sql="SELECT id, v FROM kv ORDER BY id"):
    return [(int(a), int(b)) for a, b in sess.execute(sql).rows()]


def _rows_cold(data_dir, table="kv"):
    """Read a data_dir without a Session (the crashed-follower view)."""
    cat = Catalog.load(os.path.join(data_dir, "catalog.json"))
    store = TableStore(str(data_dir), cat)
    out = {}
    for shard in cat.table_shards(table):
        vals, _mask, n = store.read_shard(table, shard.shard_id,
                                          ["id", "v"])
        for i in range(n):
            out[int(vals["id"][i])] = int(vals["v"][i])
    return sorted(out.items())


@pytest.fixture
def pair(tmp_path):
    """A leader session with seeded rows + a provisioned follower."""
    lead = str(tmp_path / "leader")
    foll = str(tmp_path / "replica")
    s = _seed_leader(lead)
    provision_replica(lead, foll, counters=s.stats.counters)
    yield s, lead, foll
    s.close()


class TestProvisionShipApply:
    def test_provisioned_replica_serves_rows(self, pair):
        s, lead, foll = pair
        r = _connect(foll)
        try:
            assert _rows(r) == _rows(s)
            # bounded staleness surface: caught up means lag 0
            st = staleness(foll)
            assert st["lag_lsn"] == 0 and st["lag_bytes"] == 0
        finally:
            r.close()

    def test_follower_journal_is_byte_identical(self, pair):
        s, lead, foll = pair
        s.execute("INSERT INTO kv VALUES (900, 1), (901, 2)")
        s.execute("DELETE FROM kv WHERE id = 901")
        ship(lead, foll, counters=s.stats.counters)
        apply_pending(foll)
        with open(os.path.join(lead, "cdc_changes.jsonl"), "rb") as f:
            lj = f.read()
        with open(os.path.join(foll, "cdc_changes.jsonl"), "rb") as f:
            fj = f.read()
        # the follower's copy is a byte-exact PREFIX of the leader's
        # (equal when nothing committed after the ship)
        assert fj == lj[: len(fj)] and len(fj) >= 1
        cur = load_cursor(foll)
        assert cur["journal_size"] == len(fj)
        assert cur["applied_lsn"] == journal_tail_lsn(foll)

    def test_read_only_replica_rejects_writes(self, pair):
        s, lead, foll = pair
        r = _connect(foll)
        try:
            with pytest.raises(ReadOnlyReplica):
                r.execute("INSERT INTO kv VALUES (999, 1)")
            with pytest.raises(ReadOnlyReplica):
                r.execute("UPDATE kv SET v = 0 WHERE id = 1")
            with pytest.raises(ReadOnlyReplica):
                r.execute("CREATE TABLE t2 (a INT)")
            with pytest.raises(ReadOnlyReplica):
                r.execute("SELECT citus_rebalance_start()")
            # reads keep answering
            assert _rows(r) == _rows(s)
        finally:
            r.close()

    def test_incremental_ship_and_apply_on_read(self, pair):
        s, lead, foll = pair
        r = _connect(foll)
        try:
            before = _rows(r)
            s.execute("INSERT INTO kv VALUES (500, 7)")
            s.execute("UPDATE kv SET v = v + 1 WHERE id < 3")
            res = ship(lead, foll, counters=s.stats.counters)
            assert res["status"] == "shipped" and not res["reseed"]
            # the follower session drains the spool on its next read —
            # no restart, no explicit apply call
            after = _rows(r)
            assert after == _rows(s) and after != before
        finally:
            r.close()

    def test_ship_is_noop_when_caught_up(self, pair):
        s, lead, foll = pair
        assert ship(lead, foll)["status"] == "noop"

    def test_dropped_table_ships(self, pair):
        s, lead, foll = pair
        s.execute("CREATE TABLE gone (a INT)")
        s.execute("SELECT create_distributed_table('gone', 'a', 2)")
        s.execute("INSERT INTO gone VALUES (1)")
        ship(lead, foll)
        apply_pending(foll)
        assert os.path.isdir(os.path.join(foll, "tables", "gone"))
        s.execute("DROP TABLE gone")
        ship(lead, foll)
        apply_pending(foll)
        assert not os.path.isdir(os.path.join(foll, "tables", "gone"))
        r = _connect(foll)
        try:
            assert "gone" not in r.catalog.tables
        finally:
            r.close()

    def test_staleness_gate_raises_replica_too_stale(self, pair):
        s, lead, foll = pair
        r = _connect(foll)
        try:
            s.execute("INSERT INTO kv VALUES (600, 1)")
            # nothing shipped yet: the replica is visibly behind
            lag0 = r.stats.counters.snapshot().get(sc.REPLICA_LAG_LSN, 0)
            with r.settings.override(replica_max_staleness_lsn=0):
                with pytest.raises(ReplicaTooStale):
                    r.execute("SELECT count(*) FROM kv")
            assert r.stats.counters.snapshot()[sc.REPLICA_LAG_LSN] > lag0
            # unbounded (-1, the default): old rows are fine
            assert (500, 7) not in _rows(r) or True
            # catch up: the same bounded read now answers
            ship(lead, foll)
            with r.settings.override(replica_max_staleness_lsn=0):
                assert _rows(r) == _rows(s)
        finally:
            r.close()

    def test_stat_replication_udf_both_roles(self, pair):
        s, lead, foll = pair
        s.execute("INSERT INTO kv VALUES (700, 1)")
        rows = s.execute("SELECT citus_stat_replication()").rows()
        assert len(rows) == 1
        peer, role, applied, leader_lsn, lag_lsn, lag_bytes, epoch = \
            rows[0]
        assert peer == os.path.realpath(foll) and role == "follower"
        assert int(lag_lsn) >= 1 and int(lag_bytes) >= 1
        assert int(leader_lsn) == int(applied) + int(lag_lsn)
        r = _connect(foll)
        try:
            fr = r.execute("SELECT citus_stat_replication()").rows()[0]
            assert fr[1] == "leader"  # the peer column names the leader
            assert int(fr[4]) >= 1   # follower sees its own lag too
        finally:
            r.close()

    def test_explain_analyze_replication_line(self, pair):
        s, lead, foll = pair
        r = _connect(foll)
        try:
            text = "\n".join(r.execute(
                "EXPLAIN ANALYZE SELECT count(*) FROM kv"
            ).columns["QUERY PLAN"])
            assert "Replication: role=follower" in text
            assert "lag_lsn=" in text
            ltext = "\n".join(s.execute(
                "EXPLAIN ANALYZE SELECT count(*) FROM kv"
            ).columns["QUERY PLAN"])
            assert "Replication: role=leader" in ltext
            assert "followers=1" in ltext
        finally:
            r.close()

    def test_exec_cache_and_caps_memo_ship(self, pair):
        s, lead, foll = pair
        # the leader compiled + persisted executables during seeding;
        # a provisioned replica must hold the same warm artifacts
        lcache = os.path.join(lead, "exec_cache")
        if os.path.isdir(lcache):
            lfiles = sorted(os.listdir(lcache))
            assert sorted(os.listdir(
                os.path.join(foll, "exec_cache"))) == lfiles
        if os.path.exists(os.path.join(lead, "caps_memo.json")):
            assert os.path.exists(os.path.join(foll, "caps_memo.json"))


class TestPromotionAndFencing:
    def test_promote_serves_writes_and_fences_old_leader(self, pair):
        s, lead, foll = pair
        s.execute("INSERT INTO kv VALUES (800, 8)")
        ship_all(lead, counters=s.stats.counters)
        r = _connect(foll)
        try:
            epoch = r.execute(
                "SELECT citus_promote_replica()").rows()[0][0]
            assert int(epoch) == 2
            assert load_state(foll)["role"] == "leader"
            # the promoted replica serves writes on the SAME lsn line
            pre_lsn = journal_tail_lsn(foll)
            r.execute("INSERT INTO kv VALUES (801, 9)")
            assert journal_tail_lsn(foll) > pre_lsn
            assert (801, 9) in _rows(r)
            # the old leader is fenced: its late ship is rejected and
            # counted, never applied
            base = s.stats.counters.snapshot().get(
                sc.REPLICATION_FENCED_TOTAL, 0)
            with pytest.raises(ReplicationError, match="fenced"):
                ship(lead, foll, counters=s.stats.counters)
            assert s.stats.counters.snapshot()[
                sc.REPLICATION_FENCED_TOTAL] == base + 1
            assert s.stats.counters.snapshot()[
                sc.REPLICAS_PROMOTED_TOTAL] >= 0  # registered
            assert r.stats.counters.snapshot()[
                sc.REPLICAS_PROMOTED_TOTAL] == 1
        finally:
            r.close()

    def test_zombie_batch_in_spool_rejected_by_applier(self, pair):
        s, lead, foll = pair
        promote(foll)  # epoch 2, fence stamped into the old leader
        # a zombie that never read its fence: simulate by deleting the
        # fence file (e.g. a partitioned filesystem view) and shipping
        os.unlink(os.path.join(lead, "replication", "fence.json"))
        s.execute("INSERT INTO kv VALUES (802, 1)")
        # shipper-side backstop fires off the follower's newer cursor
        with pytest.raises(ReplicationError, match="stale"):
            ship(lead, foll)
        # force a stale batch PAST the shipper checks: rewind the
        # follower cursor epoch as the zombie would have seen it
        cur = load_cursor(foll)
        from citus_tpu.replication.state import save_cursor
        save_cursor(foll, dict(cur, epoch=1))
        ship(lead, foll)
        save_cursor(foll, cur)  # the real (promoted) cursor returns
        counters = s.stats.counters
        base = counters.snapshot().get(sc.REPLICATION_FENCED_TOTAL, 0)
        res = apply_pending(foll, counters=counters)
        assert res["fenced"] == 1 and res["applied"] == 0
        assert counters.snapshot()[
            sc.REPLICATION_FENCED_TOTAL] == base + 1
        assert (802, 1) not in dict(_rows_cold(foll)).items()

    def test_promote_is_idempotent_under_directed_fault(self, pair):
        s, lead, foll = pair
        with pytest.raises(fi.InjectedFault):
            with fi.inject("replication.promote", require_fired=True):
                promote(foll)
        # the interrupted promotion left a follower; retry completes
        assert load_state(foll)["role"] == "follower"
        assert promote(foll) == 2
        assert load_state(foll)["role"] == "leader"


class TestDirectedFaults:
    def test_ship_fault_fires_and_is_clean(self, pair):
        s, lead, foll = pair
        s.execute("INSERT INTO kv VALUES (810, 1)")
        with pytest.raises(fi.InjectedFault):
            with fi.inject("replication.ship", require_fired=True):
                ship(lead, foll)
        # nothing committed: the follower never sees a half batch
        assert apply_pending(foll)["applied"] == 0
        ship(lead, foll)
        apply_pending(foll)
        assert (810, 1) in dict(_rows_cold(foll)).items()

    def test_apply_fault_fires_and_retry_lands(self, pair):
        s, lead, foll = pair
        s.execute("INSERT INTO kv VALUES (811, 1)")
        ship(lead, foll)
        with pytest.raises(fi.InjectedFault):
            with fi.inject("replication.apply", require_fired=True):
                apply_pending(foll)
        # batch still pending; the retry applies it idempotently
        res = apply_pending(foll)
        assert res["applied"] == 1
        assert (811, 1) in dict(_rows_cold(foll)).items()


class TestRestoreClusterReplication:
    def test_restore_on_leader_reseeds_followers(self, tmp_path):
        from citus_tpu.operations.restore_point import restore_cluster

        lead = str(tmp_path / "leader")
        foll = str(tmp_path / "replica")
        s = _seed_leader(lead)
        s.execute("SELECT citus_create_restore_point('rp')")
        s.execute("INSERT INTO kv VALUES (900, 1), (901, 2)")
        provision_replica(lead, foll, counters=s.stats.counters)
        assert (900, 1) in dict(_rows_cold(foll)).items()
        old_history = load_state(lead)["history_id"]
        old_cursor = load_cursor(foll)
        s.close()
        restore_cluster(lead, "rp")
        # the restore rotated the journal history: the follower cursor
        # (pinned past the wipe) must never replay as a delta
        new_state = load_state(lead)
        assert new_state["history_id"] != old_history
        assert int(old_cursor["applied_lsn"]) > 0
        s = _connect(lead)
        try:
            res = ship(lead, foll, counters=s.stats.counters)
            assert res["status"] == "shipped" and res["reseed"]
            apply_pending(foll)
            assert _rows_cold(foll) == _rows_cold(lead)
            assert (900, 1) not in dict(_rows_cold(foll)).items()
            cur = load_cursor(foll)
            assert cur["history_id"] == new_state["history_id"]
        finally:
            s.close()


# ---------------------------------------------------------------------------
# power-cut torture over ship + apply (the CrashSim every-N sweep):
# cutting power at ANY durable write op of a ship+apply cycle leaves
# the follower's VISIBLE rows at exactly pre-batch XOR post-batch (the
# single per-table manifest is the visibility flip), every checksum
# verifies, and redoing ship+apply converges on post-batch.


@pytest.fixture(scope="module")
def repl_base(tmp_path_factory):
    """A frozen leader+follower pair with one UNSHIPPED increment:
    the follower holds the seed rows; the leader added, updated and
    deleted rows since.  Each crashpoint copies both dirs."""
    base = tmp_path_factory.mktemp("repl_torture")
    lead, foll = str(base / "leader"), str(base / "replica")
    s = _seed_leader(lead, rows=20)
    provision_replica(lead, foll, counters=s.stats.counters)
    pre = _rows_cold(foll)
    s.execute("INSERT INTO kv VALUES (100, 1), (101, 2), (102, 3)")
    s.execute("UPDATE kv SET v = 999 WHERE id < 4")
    s.execute("DELETE FROM kv WHERE id = 7")
    post = _rows_cold(lead)
    s.close()
    assert pre != post
    return lead, foll, pre, post


def _ship_apply(lead, foll):
    ship(lead, foll)
    return apply_pending(foll)


def _torture_one(repl_base, tmp_path, n: int, mode: str | None) -> str:
    lead, foll, pre, post = repl_base
    wl = str(tmp_path / f"l{mode or 'cyc'}{n:03d}")
    wf = str(tmp_path / f"f{mode or 'cyc'}{n:03d}")
    shutil.copytree(lead, wl)
    shutil.copytree(foll, wf)
    with power_cut_at(n, mode=mode) as sim:
        try:
            _ship_apply(wl, wf)
            raise AssertionError(f"op {n} never reached")
        except PowerCut:
            pass
    # the crashed follower's visible rows: exactly pre XOR post (reads
    # CRC-verify every stripe — a torn file would refuse, not lie)
    got = _rows_cold(wf)
    assert got in (pre, post), (
        f"crash at op {n} (tear={sim.tear_applied}): follower is "
        f"neither pre- nor post-batch\n got: {got}")
    # cold redo (the follower process restarting): converges on post
    res = _ship_apply(wl, wf)
    assert _rows_cold(wf) == post, f"redo after op {n} did not land"
    # journal byte-identical after catch-up, cursor committed
    with open(os.path.join(wl, "cdc_changes.jsonl"), "rb") as f:
        lj = f.read()
    with open(os.path.join(wf, "cdc_changes.jsonl"), "rb") as f:
        fj = f.read()
    assert fj == lj, f"follower journal diverged after crash at op {n}"
    assert not apply_pending(wf)["applied"], "spool not drained"
    shutil.rmtree(wl, ignore_errors=True)
    shutil.rmtree(wf, ignore_errors=True)
    return sim.tear_applied or "none"


def _rehearse_repl(repl_base, tmp_path) -> int:
    lead, foll, _pre, post = repl_base
    wl, wf = str(tmp_path / "rl"), str(tmp_path / "rf")
    shutil.copytree(lead, wl)
    shutil.copytree(foll, wf)
    with power_cut_at(None) as sim:
        _ship_apply(wl, wf)
    assert _rows_cold(wf) == post
    shutil.rmtree(wl, ignore_errors=True)
    shutil.rmtree(wf, ignore_errors=True)
    assert sim.ops >= 8, f"ship+apply too small to sweep ({sim.ops})"
    return sim.ops


class TestShipApplyPowerCut:
    def test_tier1_every_op_cycled_tears(self, repl_base, tmp_path):
        """EVERY durable write op of one ship+apply cycle, tear mode
        cycled deterministically by op index."""
        total = _rehearse_repl(repl_base, tmp_path)
        modes = set()
        for n in range(1, total + 1):
            modes.add(_torture_one(repl_base, tmp_path, n, None))
        assert modes >= {"lost", "torn", "complete"}

    @pytest.mark.slow
    def test_full_sweep_every_mode(self, repl_base, tmp_path):
        """Acceptance: every op × every forced tear mode."""
        total = _rehearse_repl(repl_base, tmp_path)
        for mode in ("lost", "torn", "complete"):
            for n in range(1, total + 1):
                _torture_one(repl_base, tmp_path, n, mode)


# ---------------------------------------------------------------------------
# replica fuzz: leader ≡ follower-at-caught-up-lsn, row for row, under
# interleaved DML / COPY / transactional writes from TWO leader
# sessions.  Chaos actors: replica-kill (the follower session dies
# abruptly mid-storm and a cold successor must answer identically) and
# leader-kill (promotion mid-storm; the promoted replica must hold
# exactly the rows of the last synced lsn — the zero-wrong-rows
# oracle).


def _sync(lead, foll, counters=None):
    """Ship until the spool drains to a noop — the caught-up barrier."""
    for _ in range(6):
        res = ship(lead, foll, counters=counters)
        apply_pending(foll, counters=counters)
        if res["status"] == "noop":
            return
    raise AssertionError("ship never reached noop with writers idle")


def _run_replica_fuzz(tmp_path, n_ops: int, seed: int,
                      kill_replica: bool = False,
                      kill_leader: bool = False) -> dict:
    lead = str(tmp_path / "leader")
    foll = str(tmp_path / "replica")
    w = [_seed_leader(lead, rows=60), _connect(lead)]
    provision_replica(lead, foll, counters=w[0].stats.counters)
    reader = _connect(foll)
    rng = random.Random(seed)
    state = {"next_id": 60}
    stats = {"reads": 0, "writes": 0, "syncs": 0, "kills": 0}
    try:
        for op in range(n_ops):
            kind, sql, rows, who = generate_replica(rng, state)
            if kind == "copy":
                csv = str(tmp_path / f"rf_{op}.csv")
                with open(csv, "w") as f:
                    for i, v in rows:
                        f.write(f"{i},{v}\n")
                sql = f"COPY kv FROM '{csv}' WITH (FORMAT csv)"
                kind = "write"
            if kind == "txn_write":
                w[who].execute("BEGIN")
                w[who].execute(sql)
                w[who].execute("COMMIT")
                stats["writes"] += 1
                continue
            if kind == "write":
                w[who].execute(sql)
                stats["writes"] += 1
                continue
            # a read op is a sync barrier: catch the follower up to
            # the leader's lsn, then the replica must answer the
            # generated read AND the full table row-for-row
            stats["reads"] += 1
            stats["syncs"] += 1
            if kill_replica and rng.random() < 0.2:
                # replica-kill actor: abrupt session death (threads
                # stopped, nothing saved), cold successor takes over
                reader.maintenance.stop()
                reader.jobs.shutdown()
                reader = _connect(foll)
                stats["kills"] += 1
            _sync(lead, foll, counters=w[0].stats.counters)
            assert sorted(reader.execute(sql).rows()) == \
                sorted(w[who].execute(sql).rows()), \
                f"replica diverged on {sql!r} (step {op})"
            assert _rows(reader) == _rows(w[0]), \
                f"row-for-row divergence at step {op}"
        _sync(lead, foll, counters=w[0].stats.counters)
        oracle = _rows(w[0])
        assert _rows(reader) == oracle
        if kill_leader:
            # leader-kill actor: both leader sessions die; the
            # follower promotes and must hold EXACTLY the synced rows
            for s in w:
                s.maintenance.stop()
                s.jobs.shutdown()
            reader.execute("SELECT citus_promote_replica()")
            assert _rows(reader) == oracle, \
                "promotion changed visible rows (wrong-rows oracle)"
            reader.execute("INSERT INTO kv VALUES (999999, 1)")
            assert (999999, 1) in _rows(reader)
            with pytest.raises(ReplicationError):
                ship(lead, foll)  # the zombie stays fenced
        return stats
    finally:
        reader.close()
        for s in w:
            s.close()


def test_replica_fuzz_smoke_slice(tmp_path):
    """Deterministic tier-1 slice: two leader sessions interleave
    DML/COPY/txn writes; at every sync barrier the follower equals the
    leader row-for-row at the caught-up lsn."""
    stats = _run_replica_fuzz(tmp_path, n_ops=45, seed=1806)
    assert stats["writes"] >= 8 and stats["syncs"] >= 10


@pytest.mark.slow
def test_replica_fuzz_full(tmp_path):
    stats = _run_replica_fuzz(tmp_path, n_ops=250, seed=20260806,
                              kill_replica=True, kill_leader=True)
    assert stats["writes"] >= 40 and stats["syncs"] >= 60
    assert stats["kills"] >= 1
