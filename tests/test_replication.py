"""Replica placements + read failover (VERDICT round-2 missing item 1;
reference: pg_dist_placement multiple placements per shard and the
adaptive executor's read failover, adaptive_executor.c:95-116)."""

import pytest

import citus_tpu
from citus_tpu.errors import CatalogError


@pytest.fixture()
def sess(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64",
                          shard_replication_factor=2)
    s.execute("create table r (k bigint, v bigint)")
    s.create_distributed_table("r", "k", shard_count=8)
    vals = ",".join(f"({i},{i * 2})" for i in range(1, 401))
    s.execute(f"insert into r values {vals}")
    yield s
    s.close()


def test_replicated_placements_created(sess):
    for shard in sess.catalog.table_shards("r"):
        ps = sess.catalog.shard_placements(shard.shard_id)
        assert len(ps) == 2
        assert len({p.node_id for p in ps}) == 2


def test_failover_on_disable_node_mid_workload(sess):
    total = int(sess.execute("select sum(v) from r").rows()[0][0])
    assert total == sum(i * 2 for i in range(1, 401))
    # kill a node (catalog-level): every query keeps answering correctly
    victim = sess.catalog.active_nodes()[0].name
    sess.execute(f"select citus_disable_node('{victim}')")
    assert int(sess.execute("select sum(v) from r").rows()[0][0]) == total
    assert int(sess.execute(
        "select count(*) from r where k = 17").rows()[0][0]) == 1
    # primary placements moved off the dead node
    for shard in sess.catalog.table_shards("r"):
        p = sess.catalog.active_placement(shard.shard_id)
        assert sess.catalog.nodes[p.node_id].is_active
    # node comes back: queries still correct
    sess.execute(f"select citus_activate_node('{victim}')")
    assert int(sess.execute("select sum(v) from r").rows()[0][0]) == total


def test_remove_node_drops_replicas_keeps_answers(sess):
    total = int(sess.execute("select sum(v) from r").rows()[0][0])
    victim = sess.catalog.active_nodes()[-1].name
    sess.execute(f"select citus_remove_node('{victim}')")
    assert int(sess.execute("select sum(v) from r").rows()[0][0]) == total
    # replication dropped to 1 for shards that had a replica there
    counts = {len(sess.catalog.shard_placements(s.shard_id))
              for s in sess.catalog.table_shards("r")}
    assert counts <= {1, 2}
    # removing another node that now holds sole placements must refuse
    for other in list(sess.catalog.active_nodes()):
        try:
            sess.catalog.remove_node(other.name)
        except CatalogError as e:
            assert "only active placement" in str(e)
            break
    else:
        pytest.fail("expected sole-placement removal to be refused")


def test_unreplicated_node_removal_refused(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64")
    s.execute("create table u (k bigint)")
    s.create_distributed_table("u", "k", shard_count=4)
    s.execute("insert into u values (1), (2), (3)")
    victim = s.catalog.active_nodes()[0].name
    with pytest.raises(CatalogError, match="only active placement"):
        s.catalog.remove_node(victim)
    s.close()


def test_split_preserves_replication(sess):
    shard = sess.catalog.table_shards("r")[0]
    mid = (shard.min_value + shard.max_value) // 2
    sess.execute(f"select citus_split_shard_by_split_points("
                 f"{shard.shard_id}, '{mid}')")
    for s in sess.catalog.table_shards("r"):
        ps = sess.catalog.shard_placements(s.shard_id)
        assert len(ps) == 2, f"shard {s.shard_id} lost its replica"
    total = sum(i * 2 for i in range(1, 401))
    assert int(sess.execute("select sum(v) from r").rows()[0][0]) == total
