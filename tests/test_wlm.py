"""Workload manager: admission control, per-tenant fair queueing,
overload shedding (citus_tpu/wlm/).

The reference governs concurrent work with citus.max_shared_pool_size /
max_adaptive_executor_pool_size and attributes it via
citus_stat_tenants; here every non-exempt statement passes one
process-wide admission gate per data_dir.  These tests cover the
manager's scheduling contract directly (deterministic WRR dispatch,
shedding, the never-lost ledger) and the session integration
(exemption, activity wait states, cancel/timeout while queued, the
wlm.admit fault seam, and the 8-concurrent-sessions acceptance run).
"""

import threading
import time

import pytest

import citus_tpu
from citus_tpu.errors import (
    AdmissionRejected,
    ConfigError,
    QueryCanceled,
    StatementTimeout,
)
from citus_tpu.utils.cancellation import deadline_scope
from citus_tpu.utils.faultinjection import InjectedFault, inject
from citus_tpu.utils.faultinjection import reset as fi_reset
from citus_tpu.wlm import (
    AdmissionRequest,
    WorkloadManager,
    parse_tenant_weights,
    workload_manager_for,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    fi_reset()
    yield
    fi_reset()


def _ledger_ok(snap) -> bool:
    return snap["requests_total"] == (
        snap["admitted_total"] + snap["shed_total"]
        + snap["timedout_total"] + snap["canceled_total"])


# ---------------------------------------------------------------------------
# manager unit tests (no session, no device)


class TestManagerScheduling:
    def test_parse_tenant_weights(self):
        assert parse_tenant_weights("") == {}
        assert parse_tenant_weights("a:3, b:1") == {"a": 3, "b": 1}
        assert parse_tenant_weights("solo") == {"solo": 1}
        with pytest.raises(ConfigError):
            parse_tenant_weights("a:x")
        with pytest.raises(ConfigError):
            parse_tenant_weights("a:0")
        with pytest.raises(ConfigError):
            parse_tenant_weights(":3")

    def test_slots_bound_then_release_dispatches(self):
        mgr = WorkloadManager()
        t1 = mgr.admit(AdmissionRequest(max_slots=2))
        t2 = mgr.admit(AdmissionRequest(max_slots=2))
        got = []
        th = threading.Thread(target=lambda: got.append(
            mgr.admit(AdmissionRequest(max_slots=2))))
        th.start()
        time.sleep(0.1)
        assert not got, "third statement must queue behind 2 slots"
        mgr.release(t1)
        th.join(timeout=5)
        assert len(got) == 1 and got[0].was_queued
        assert got[0].queued_ms > 0
        mgr.release(t2)
        mgr.release(got[0])
        snap = mgr.snapshot()
        assert snap["slots_in_use"] == 0
        assert snap["admitted_total"] == 3 and snap["queued_total"] == 1
        assert _ledger_ok(snap)

    def _drain_order(self, tenants_weights, per_tenant, priority=None):
        """Block the single slot, enqueue per_tenant waiters for each
        tenant, release, record dispatch order."""
        mgr = WorkloadManager()
        blocker = mgr.admit(AdmissionRequest(tenant="_b", max_slots=1))
        order: list[str] = []
        threads = []

        def worker(tenant, weight, cls):
            t = mgr.admit(AdmissionRequest(
                tenant=tenant, weight=weight, max_slots=1,
                priority=cls))
            order.append(tenant)
            mgr.release(t)

        for i in range(per_tenant):
            for j, (ten, w) in enumerate(tenants_weights):
                cls = (priority[j] if priority else "interactive")
                th = threading.Thread(target=worker, args=(ten, w, cls))
                th.start()
                threads.append(th)
                # settle enqueue order deterministically
                while mgr.snapshot()["queued_total"] < len(threads):
                    time.sleep(0.001)
        mgr.release(blocker)
        for th in threads:
            th.join(timeout=10)
        assert _ledger_ok(mgr.snapshot())
        return order

    def test_weighted_round_robin_no_tenant_starved(self):
        """Acceptance: weighted fairness — while both tenants stay
        backlogged, each completes at least its weight share − 20%."""
        order = self._drain_order([("a", 3), ("b", 1)], per_tenant=12)
        assert len(order) == 24
        # both backlogged through the first 16 dispatches
        window = order[:16]
        share_a, share_b = 3 / 4, 1 / 4
        assert window.count("a") >= share_a * len(window) * 0.8
        assert window.count("b") >= share_b * len(window) * 0.8
        # the exact DRR pattern: 3×a then 1×b per round
        assert "".join(window) == "aaab" * 4

    def test_equal_weights_alternate(self):
        order = self._drain_order([("x", 1), ("y", 1)], per_tenant=4)
        assert "".join(order[:8]) == "xyxyxyxy"

    def test_priority_classes_dispatch_strictly(self):
        """interactive dispatches before batch before background, even
        when enqueued later."""
        order = self._drain_order(
            [("bg", 1), ("it", 1), ("bt", 1)], per_tenant=2,
            priority=["background", "interactive", "batch"])
        assert order == ["it", "it", "bt", "bt", "bg", "bg"]

    def test_shed_on_full_queue(self):
        mgr = WorkloadManager()
        blocker = mgr.admit(AdmissionRequest(max_slots=1, queue_depth=0))
        with pytest.raises(AdmissionRejected):
            mgr.admit(AdmissionRequest(max_slots=1, queue_depth=0))
        snap = mgr.snapshot()
        assert snap["shed_total"] == 1 and _ledger_ok(snap)
        mgr.release(blocker)

    def test_hbm_budget_gate(self):
        mgr = WorkloadManager()
        big = mgr.admit(AdmissionRequest(
            feed_bytes=100, max_slots=8, max_feed_bytes=150))
        got = []
        th = threading.Thread(target=lambda: got.append(mgr.admit(
            AdmissionRequest(feed_bytes=80, max_slots=8,
                             max_feed_bytes=150))))
        th.start()
        time.sleep(0.1)
        assert not got, "80 bytes must wait: 100/150 already admitted"
        mgr.release(big)
        th.join(timeout=5)
        assert len(got) == 1
        mgr.release(got[0])
        # a statement bigger than the whole budget admits when idle
        # (the stream pipeline bounds its actual residency)
        solo = mgr.admit(AdmissionRequest(
            feed_bytes=10**12, max_slots=8, max_feed_bytes=150))
        mgr.release(solo)
        assert _ledger_ok(mgr.snapshot())

    def test_timeout_while_queued(self):
        mgr = WorkloadManager()
        blocker = mgr.admit(AdmissionRequest(max_slots=1))
        with deadline_scope(80):
            with pytest.raises(StatementTimeout):
                mgr.admit(AdmissionRequest(max_slots=1))
        snap = mgr.snapshot()
        assert snap["timedout_total"] == 1
        assert _ledger_ok(snap)
        # the timed-out waiter left the queue: release admits nobody
        mgr.release(blocker)
        assert mgr.snapshot()["slots_in_use"] == 0

    def test_registry_shared_per_data_dir(self, tmp_path):
        a = workload_manager_for(str(tmp_path / "d"))
        b = workload_manager_for(str(tmp_path / "d"))
        c = workload_manager_for(str(tmp_path / "e"))
        assert a is b and a is not c


# ---------------------------------------------------------------------------
# session integration


@pytest.fixture()
def sess(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2)
    s.execute("CREATE TABLE kv (id INT, v INT)")
    s.execute("SELECT create_distributed_table('kv', 'id', 4)")
    s.execute("INSERT INTO kv VALUES " + ", ".join(
        f"({i}, {i * 2})" for i in range(60)))
    yield s
    s.close()


class TestSessionIntegration:
    def test_exemption_classes(self, sess):
        before = sess.wlm.snapshot()["requests_total"]
        sess.execute("SET wlm_queue_depth = 32")
        sess.execute("SHOW wlm_queue_depth")
        sess.execute("BEGIN")
        sess.execute("COMMIT")
        sess.execute("SELECT citus_stat_counters()")   # admin UDF
        sess.execute("SELECT v FROM kv WHERE id = 7")  # fast-path point read
        assert sess.wlm.snapshot()["requests_total"] == before
        sess.execute("SELECT count(*) FROM kv")        # device path: admitted
        sess.execute("UPDATE kv SET v = v + 1 WHERE id >= 0")  # DML: admitted
        assert sess.wlm.snapshot()["requests_total"] == before + 2

    def test_open_transaction_statements_bypass_gate(self, sess):
        """A transaction owns its resources once begun (the reference
        holds pool connections per-txn): its statements must not queue
        for a slot while holding 2PL locks — that slot↔lock edge is
        invisible to the deadlock detector."""
        sess.execute("SELECT count(*) FROM kv")  # baseline admission
        before = sess.wlm.snapshot()["requests_total"]
        sess.execute("BEGIN")
        sess.execute("UPDATE kv SET v = v + 1 WHERE id = 3")
        sess.execute("SELECT count(*) FROM kv")
        sess.execute("COMMIT")
        assert sess.wlm.snapshot()["requests_total"] == before
        # autocommit statements go back through the gate
        sess.execute("SELECT count(*) FROM kv")
        assert sess.wlm.snapshot()["requests_total"] == before + 1

    def test_counters_and_stat_wlm(self, sess):
        sess.execute("SELECT count(*) FROM kv")
        counters = dict(sess.execute(
            "SELECT citus_stat_counters()").rows())
        assert counters["wlm_admitted_total"] >= 1
        r = sess.execute("SELECT citus_stat_wlm()")
        row = dict(zip(r.column_names, r.rows()[0]))
        assert row["admitted_total"] >= 1
        assert row["priority"] == "interactive"
        assert _ledger_ok(sess.wlm.snapshot())

    def test_activity_wait_states_and_queue_wait(self, sess):
        sess.execute("SELECT count(*) FROM kv")  # warm the compile
        sess.settings.set("max_concurrent_statements", 1)
        blocker = sess.wlm.admit(AdmissionRequest(max_slots=1))
        done = []
        th = threading.Thread(target=lambda: done.append(
            sess.execute("SELECT count(*) FROM kv")))
        th.start()
        # observe the queued statement via the (exempt) activity UDF
        deadline = time.monotonic() + 5
        states = {}
        while time.monotonic() < deadline:
            r = sess.execute("SELECT citus_stat_activity()")
            states = dict(zip(r.columns["query"],
                              r.columns["wait_state"]))
            if states.get("SELECT count(*) FROM kv") == "queued":
                break
            time.sleep(0.01)
        assert states.get("SELECT count(*) FROM kv") == "queued"
        # activity flips to "queued" just BEFORE the waiter enqueues in
        # the manager — wait for the real enqueue, then let it accrue a
        # measurable wait so wlm_queue_wait_ms cannot round to 0
        while not any(r["queued"]
                      for r in sess.wlm.snapshot()["tenants"]):
            time.sleep(0.005)
        time.sleep(0.03)
        sess.wlm.release(blocker)
        th.join(timeout=10)
        assert done and int(done[0].rows()[0][0]) == 60
        counters = dict(sess.execute(
            "SELECT citus_stat_counters()").rows())
        assert counters["wlm_queued_total"] >= 1
        assert counters["wlm_queue_wait_ms"] >= 1

    def test_cancel_while_queued(self, sess):
        sess.settings.set("max_concurrent_statements", 1)
        blocker = sess.wlm.admit(AdmissionRequest(max_slots=1))
        errs = []

        def run():
            try:
                sess.execute("SELECT count(*) FROM kv")
            except Exception as e:
                errs.append(e)

        th = threading.Thread(target=run)
        th.start()
        while sess.wlm.snapshot()["queued_total"] < 1:
            time.sleep(0.005)
        sess.cancel()
        th.join(timeout=10)
        sess.wlm.release(blocker)
        assert errs and isinstance(errs[0], QueryCanceled)
        snap = sess.wlm.snapshot()
        assert snap["canceled_total"] == 1 and _ledger_ok(snap)

    def test_statement_timeout_bounds_queue_wait(self, sess):
        sess.settings.set("max_concurrent_statements", 1)
        sess.settings.set("statement_timeout_ms", 120)
        blocker = sess.wlm.admit(AdmissionRequest(max_slots=1))
        try:
            with pytest.raises(StatementTimeout):
                sess.execute("SELECT count(*) FROM kv")
        finally:
            sess.wlm.release(blocker)
            sess.settings.set("statement_timeout_ms", 0)
        counters = dict(sess.execute(
            "SELECT citus_stat_counters()").rows())
        assert counters["timeouts_total"] >= 1

    def test_shed_surfaces_as_admission_rejected(self, sess):
        sess.settings.set("max_concurrent_statements", 1)
        sess.settings.set("wlm_queue_depth", 0)
        blocker = sess.wlm.admit(AdmissionRequest(max_slots=1))
        try:
            with pytest.raises(AdmissionRejected):
                sess.execute("SELECT count(*) FROM kv")
        finally:
            sess.wlm.release(blocker)
        counters = dict(sess.execute(
            "SELECT citus_stat_counters()").rows())
        assert counters["wlm_shed_total"] == 1

    def test_wlm_admit_fault_point_directed(self, sess):
        """The named seam: armed, a non-exempt statement dies cleanly at
        the gate; exempt statements never reach it."""
        with inject("wlm.admit"):
            sess.execute("SET wlm_queue_depth = 64")  # exempt: no trigger
            with pytest.raises(InjectedFault):
                sess.execute("SELECT count(*) FROM kv")
        # nothing leaked: the gate is empty and consistent
        snap = sess.wlm.snapshot()
        assert snap["slots_in_use"] == 0 and _ledger_ok(snap)
        assert int(sess.execute(
            "SELECT count(*) FROM kv").rows()[0][0]) == 60

    def test_explain_analyze_workload_line(self, sess):
        r = sess.execute("EXPLAIN ANALYZE SELECT count(*) FROM kv")
        lines = [ln for ln in r.columns["QUERY PLAN"]
                 if ln.startswith("Workload:")]
        assert len(lines) == 1
        assert "class=interactive" in lines[0]
        assert "wlm_admitted_total=" in lines[0]

    def test_wlm_disabled_bypasses_gate(self, sess):
        before = sess.wlm.snapshot()["requests_total"]
        sess.settings.set("wlm_enabled", False)
        try:
            sess.execute("SELECT count(*) FROM kv")
        finally:
            sess.settings.set("wlm_enabled", True)
        assert sess.wlm.snapshot()["requests_total"] == before

    def test_feed_estimate_counts_read_side_only(self, sess):
        """The HBM gate charges what actually feeds HBM: reads.  A
        small INSERT into a large table must not be billed the table."""
        from citus_tpu.sql import parse
        from citus_tpu.wlm import planned_feed_bytes

        read = parse("SELECT count(*) FROM kv")[0]
        ins = parse("INSERT INTO kv VALUES (999, 1)")[0]
        upd = parse("UPDATE kv SET v = 0 WHERE id = 1")[0]
        assert planned_feed_bytes(read, sess.catalog, sess.store, 2) > 0
        assert planned_feed_bytes(ins, sess.catalog, sess.store, 2) == 0
        # UPDATE reads its target before writing — it IS charged
        assert planned_feed_bytes(upd, sess.catalog, sess.store, 2) > 0

    def test_feed_estimate_charges_plan_intermediates(self, sess):
        """Under-charge regression: a dual-repartition join allocates
        all_to_all shuffle buffers + join outputs far beyond its base
        feeds — the gate estimate must include them, or statements
        whose intermediates alone exceed the budget admit freely and
        OOM mid-flight."""
        from citus_tpu.sql import parse
        from citus_tpu.wlm import (
            planned_feed_bytes,
            planned_intermediate_bytes,
        )

        # kv joined to itself on the NON-distribution column: neither
        # side is pre-partitioned on the join key ⇒ dual repartition
        dual = parse("SELECT count(*) FROM kv x, kv y "
                     "WHERE x.v = y.v")[0]
        scan = parse("SELECT count(*) FROM kv")[0]
        inter = planned_intermediate_bytes(dual, sess.catalog,
                                           sess.store, 2,
                                           sess.settings)
        assert inter > 0, "join plan charged no intermediates"
        base_only = planned_feed_bytes(dual, sess.catalog, sess.store,
                                       2, sess.settings) - inter
        assert base_only > 0
        assert inter > base_only, (
            "a dual-repartition join's shuffle buffers dwarf its base "
            f"feeds; estimate says {inter} <= {base_only}")
        # a plain scan of the same table charges no join intermediates
        scan_inter = planned_intermediate_bytes(
            scan, sess.catalog, sess.store, 2, sess.settings)
        assert scan_inter == 0

    def test_hbm_gate_blocks_on_intermediates(self, sess):
        """The gate end: with a budget sized between one and two
        statements' FULL estimates (base + intermediates), a second
        concurrent dual-repartition statement must wait — under the
        old base-only charge both fit and oversubscribed the device."""
        import threading as _threading
        import time as _time

        from citus_tpu.sql import parse
        from citus_tpu.wlm import (
            AdmissionRequest,
            WorkloadManager,
            planned_feed_bytes,
        )

        dual = parse("SELECT count(*) FROM kv x, kv y "
                     "WHERE x.v = y.v")[0]
        full = planned_feed_bytes(dual, sess.catalog, sess.store, 2,
                                  sess.settings)
        mgr = WorkloadManager()
        budget = int(full * 1.5)
        first = mgr.admit(AdmissionRequest(
            feed_bytes=full, max_slots=8, max_feed_bytes=budget))
        got = []
        th = _threading.Thread(target=lambda: got.append(mgr.admit(
            AdmissionRequest(feed_bytes=full, max_slots=8,
                             max_feed_bytes=budget))))
        th.start()
        _time.sleep(0.1)
        assert not got, ("second dual-repartition statement must wait "
                         "for the HBM budget")
        mgr.release(first)
        th.join(timeout=5)
        assert len(got) == 1
        mgr.release(got[0])

    def test_gate_consults_measured_pressure(self, sess):
        """The manager admits against max(planned, measured): a
        measured live-byte spike the plans never declared (capacity
        regrow, overlapping passes) blocks further admissions."""
        from citus_tpu.wlm import AdmissionRequest, WorkloadManager

        mgr = WorkloadManager()
        measured = {"v": 0}
        mgr.attach_measured(lambda: measured["v"])
        a = mgr.admit(AdmissionRequest(feed_bytes=10, max_slots=8,
                                       max_feed_bytes=100))
        measured["v"] = 95  # regrow blew past the declared 10
        assert not mgr._fits(AdmissionRequest(
            feed_bytes=10, max_slots=8, max_feed_bytes=100))
        measured["v"] = 0
        assert mgr._fits(AdmissionRequest(
            feed_bytes=10, max_slots=8, max_feed_bytes=100))
        mgr.release(a)

    def test_background_job_admits_at_background_priority(self, sess):
        ran = []
        job = sess.jobs.submit_job("unit", [(lambda: ran.append(1),
                                             "task", [])])
        assert sess.jobs.wait(job).value == "done"
        assert ran == [1]
        snap = sess.wlm.snapshot()
        rows = {(r["priority"], r["tenant"]): r for r in snap["tenants"]}
        assert rows[("background", "background")]["admitted_total"] >= 1


# ---------------------------------------------------------------------------
# acceptance: 8 concurrent sessions, mixed tenants/classes, one gate


def test_eight_concurrent_sessions_mixed_tenants(tmp_path):
    data_dir = str(tmp_path / "d")
    setup = citus_tpu.connect(data_dir=data_dir, n_devices=2,
                              compute_dtype="float64")
    setup.execute("CREATE TABLE kv (id INT, v INT)")
    setup.execute("SELECT create_distributed_table('kv', 'id', 4)")
    rows = [(i, i * 3) for i in range(120)]
    setup.execute("INSERT INTO kv VALUES " + ", ".join(
        f"({i}, {v})" for i, v in rows))
    setup.execute("SELECT count(*), sum(v) FROM kv")  # warm stripes
    expected_sum = sum(v for _, v in rows)

    sessions = []
    for i in range(8):
        sessions.append(citus_tpu.connect(
            data_dir=data_dir, n_devices=2, compute_dtype="float64",
            max_concurrent_statements=2,
            wlm_tenant=f"tenant{i % 4}",
            wlm_default_priority="interactive" if i % 2 else "batch",
            wlm_tenant_weights="tenant0:3,tenant1:1"))

    errors: list = []
    bad: list = []

    def worker(s, idx):
        try:
            for it in range(3):
                r = s.execute("SELECT count(*), sum(v) FROM kv")
                c, sm = r.rows()[0]
                if int(c) != 120 or int(sm) != expected_sum:
                    bad.append((idx, it, c, sm))
                r2 = s.execute(
                    f"SELECT v FROM kv WHERE id = {(idx * 7 + it) % 120}")
                if int(r2.rows()[0][0]) != ((idx * 7 + it) % 120) * 3:
                    bad.append((idx, it, "point"))
        except (AdmissionRejected, StatementTimeout) as e:
            errors.append(e)  # clean outcomes are acceptable
        except Exception as e:  # pragma: no cover - surfaced below
            bad.append((idx, type(e).__name__, str(e)))

    threads = [threading.Thread(target=worker, args=(s, i))
               for i, s in enumerate(sessions)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not bad, f"incorrect results or unclean failures: {bad[:3]}"

    mgr = sessions[0].wlm
    snap = mgr.snapshot()
    # every statement resolved: admitted XOR shed XOR timedout/canceled
    assert _ledger_ok(snap), snap
    assert snap["slots_in_use"] == 0
    assert snap["admitted_total"] >= 8  # the gate actually carried load
    tenants = {r["tenant"] for r in snap["tenants"]}
    assert {"tenant0", "tenant1", "tenant2", "tenant3"} <= tenants
    counters = dict(sessions[0].execute(
        "SELECT citus_stat_counters()").rows())
    assert counters["wlm_admitted_total"] >= 1
    for s in sessions:
        s.close()
    setup.close()
