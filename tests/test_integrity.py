"""End-to-end storage integrity: CRC detection, transparent read
repair from replica copies, scrubber quarantine + re-replication,
restore-point validation, and the observability surface.

Acceptance (ISSUE 7): a bit-flipped stripe under
shard_replication_factor=2 is transparently read-repaired (correct
rows, read_repairs_total increments, the corrupt placement is
quarantined and re-replicated by the scrubber); under factor 1 the
same query fails with a clean CorruptStripe — never wrong rows.
"""

import os

import numpy as np
import pytest

import citus_tpu
from citus_tpu.catalog import Catalog
from citus_tpu.errors import CorruptStripe, StorageError
from citus_tpu.storage import StripeReader, TableStore, write_stripe
from citus_tpu.storage import integrity
from citus_tpu.types import ColumnDef, DataType, TableSchema
from citus_tpu.utils import faultinjection as fi
from citus_tpu.utils import io as dio

SCHEMA_COLS = [("k", DataType.INT64), ("v", DataType.FLOAT64)]


def make_cols(n, rng):
    return {"k": rng.integers(0, 1 << 20, size=n).astype(np.int64),
            "v": rng.normal(size=n)}


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.reset()
    yield
    fi.reset()


# ---------------------------------------------------------------------------
# format-level CRC behavior
# ---------------------------------------------------------------------------
class TestStripeCrc:
    def test_v2_footer_and_chunk_crcs_written(self, tmp_path, rng):
        path = str(tmp_path / "s.ctps")
        footer = write_stripe(path, SCHEMA_COLS, make_cols(1000, rng))
        ch = footer["columns"][0]["chunks"][0]
        assert isinstance(ch["crc"], int)
        StripeReader(path).verify_all_chunks()  # round-trips clean

    def test_bitflip_detected_on_read(self, tmp_path, rng):
        path = str(tmp_path / "s.ctps")
        write_stripe(path, SCHEMA_COLS, make_cols(5000, rng),
                     codec="zlib")
        integrity.flip_one_bit(path)
        with pytest.raises(CorruptStripe):
            r = StripeReader(path)
            r.read()
            r.verify_all_chunks()  # flip may land footer-side or data-side

    def test_verify_flag_off_skips_crc(self, tmp_path, rng):
        # structural checks still run; chunk CRCs don't — measurement
        # lever for the PERF_NOTES scan-overhead A/B
        path = str(tmp_path / "s.ctps")
        cols = make_cols(1000, rng)
        write_stripe(path, SCHEMA_COLS, cols, codec="none")
        # flip a byte INSIDE a value buffer of the uncompressed stripe
        with open(path, "r+b") as f:
            f.seek(16)
            b = f.read(1)
            f.seek(16)
            f.write(bytes([b[0] ^ 0x01]))
        with pytest.raises(CorruptStripe):
            StripeReader(path, verify=True).read()
        vals, _, n = StripeReader(path, verify=False).read()
        assert n == 1000  # unverified read returns (wrong) bytes

    def test_truncated_stripe_is_corrupt_stripe(self, tmp_path, rng):
        path = str(tmp_path / "s.ctps")
        write_stripe(path, SCHEMA_COLS, make_cols(1000, rng))
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        with pytest.raises(CorruptStripe):
            StripeReader(path)

    def test_corrupt_stripe_is_storage_error(self):
        assert issubclass(CorruptStripe, StorageError)


class TestCheckedJson:
    def test_roundtrip_and_detection(self, tmp_path):
        p = str(tmp_path / "m.json")
        dio.atomic_write_json_checked(p, {"a": 1, "b": [2, 3]})
        assert dio.read_json_checked(p) == {"a": 1, "b": [2, 3]}
        raw = open(p).read().replace('"a": 1', '"a": 7')
        with open(p, "w") as f:
            f.write(raw)
        with pytest.raises(CorruptStripe, match="checksum"):
            dio.read_json_checked(p)

    def test_legacy_file_without_crc_loads(self, tmp_path):
        p = str(tmp_path / "m.json")
        dio.atomic_write_json(p, {"a": 1})
        assert dio.read_json_checked(p) == {"a": 1}


# ---------------------------------------------------------------------------
# store-level read repair
# ---------------------------------------------------------------------------
def _store_with_replicas(tmp_path, rng, factor=2):
    cat = Catalog()
    cat.add_node("tpu:0")
    cat.add_node("tpu:1")
    schema = TableSchema(tuple(ColumnDef(n, t) for n, t in SCHEMA_COLS))
    cat.create_distributed_table("t", schema, "k", 2,
                                 replication_factor=factor)
    store = TableStore(str(tmp_path / "data"), cat)
    sid = cat.table_shards("t")[0].shard_id
    cols = make_cols(3000, rng)
    store.append_stripe("t", sid, cols)
    return cat, store, sid, cols


class TestReadRepair:
    def test_mirror_written_under_factor_2(self, tmp_path, rng):
        cat, store, sid, _ = _store_with_replicas(tmp_path, rng)
        ps = cat.shard_placements(sid)
        assert len(ps) == 2
        mirror = store.replica_dir("t", sid, ps[1].node_id)
        assert os.path.isdir(mirror) and len(os.listdir(mirror)) == 1

    def test_factor2_bitflip_transparent_repair(self, tmp_path, rng):
        cat, store, sid, cols = _store_with_replicas(tmp_path, rng)
        rec = store.manifest("t")["shards"][str(sid)][0]
        primary = os.path.join(store.shard_dir("t", sid), rec["file"])
        integrity.flip_one_bit(primary)
        base = integrity.snapshot()
        vals, _, n = store.read_shard("t", sid)  # all columns: the flip
        # may land in either column's buffers (or the footer)
        assert n == 3000  # correct rows, not an error
        d = integrity.delta(base)
        assert d["corruption_detected"] >= 1
        assert d["read_repairs"] >= 1
        # the read also healed the corrupt copy in place from the
        # verified mirror, so the placement is trusted again (a corrupt
        # copy left in place until the next scrub + a second flip on
        # the survivor would be permanent data loss)
        integrity.verify_stripe_file(primary)
        owner = store._primary_owner(sid)
        assert owner.placement_id not in cat._suspect_placements

    def test_factor1_bitflip_clean_corrupt_stripe(self, tmp_path, rng):
        cat, store, sid, _ = _store_with_replicas(tmp_path, rng,
                                                  factor=1)
        rec = store.manifest("t")["shards"][str(sid)][0]
        primary = os.path.join(store.shard_dir("t", sid), rec["file"])
        integrity.flip_one_bit(primary)
        with pytest.raises(CorruptStripe):
            store.read_shard("t", sid)  # all columns: catch the flip
            # wherever it landed

    def test_scrubber_quarantines_and_rereplicates(self, tmp_path, rng):
        from citus_tpu.operations.scrubber import ScrubReport, scrub_store

        cat, store, sid, _ = _store_with_replicas(tmp_path, rng)
        rec = store.manifest("t")["shards"][str(sid)][0]
        primary = os.path.join(store.shard_dir("t", sid), rec["file"])
        integrity.flip_one_bit(primary)
        rep = scrub_store(cat, store, ScrubReport())
        assert rep.corrupt_copies == 1
        assert rep.quarantined == 1
        assert rep.repaired == 1
        assert rep.unrepairable == 0
        # repaired in place from the verified mirror: primary verifies
        integrity.verify_stripe_file(primary)
        # placement restored to active + unsuspected
        owner = store._primary_owner(sid)
        assert owner.shard_state == "active"
        assert owner.placement_id not in cat._suspect_placements
        # second pass is clean
        rep2 = scrub_store(cat, store, ScrubReport())
        assert rep2.corrupt_copies == 0 and rep2.repaired == 0

    def test_scrubber_factor1_reports_unrepairable(self, tmp_path, rng):
        from citus_tpu.operations.scrubber import ScrubReport, scrub_store

        cat, store, sid, _ = _store_with_replicas(tmp_path, rng,
                                                  factor=1)
        rec = store.manifest("t")["shards"][str(sid)][0]
        integrity.flip_one_bit(
            os.path.join(store.shard_dir("t", sid), rec["file"]))
        rep = scrub_store(cat, store, ScrubReport())
        assert rep.corrupt_copies == 1
        assert rep.unrepairable == 1 and rep.repaired == 0
        assert rep.quarantined == 0  # last copy stays routable


# ---------------------------------------------------------------------------
# session-level acceptance + observability
# ---------------------------------------------------------------------------
class TestSessionIntegration:
    def test_end_to_end_repair_counters_quarantine(self, tmp_path):
        from citus_tpu.stats import counters as sc

        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"),
                                 n_devices=2,
                                 shard_replication_factor=2,
                                 retry_backoff_base_ms=1)
        sess.execute("CREATE TABLE kv (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('kv', 'id', 2)")
        sess.execute("INSERT INTO kv VALUES " + ", ".join(
            f"({i}, {i * 10})" for i in range(64)))
        # flip a bit in one committed primary stripe
        man = sess.store.manifest("kv")
        sid = next(s for s in man["shards"] if man["shards"][s])
        rec = man["shards"][sid][0]
        primary = os.path.join(sess.store.shard_dir("kv", int(sid)),
                               rec["file"])
        integrity.flip_one_bit(primary)
        sess.store.refresh("kv")  # drop any warm feed/manifest cache
        got = {int(i): int(v) for i, v in
               sess.execute("SELECT id, v FROM kv").rows()}
        assert got == {i: i * 10 for i in range(64)}  # correct rows
        snap = sess.stats.counters.snapshot()
        assert snap[sc.READ_REPAIRS_TOTAL] >= 1
        assert snap[sc.CORRUPTION_DETECTED_TOTAL] >= 1
        integrity.verify_stripe_file(primary)  # healed in place too
        # corruption found AT REST (no read touched it): the scrubber
        # (citus_check_cluster UDF → background job) quarantines the
        # placement and re-replicates from the verified mirror
        integrity.flip_one_bit(primary)
        row = sess.execute("SELECT citus_check_cluster(0)").rows()[0]
        cols = dict(zip(
            ["stripes_verified", "masks_verified", "corrupt_copies",
             "quarantined", "repaired", "unrepairable",
             "temps_removed", "replica_dirs_removed"], row))
        assert cols["corrupt_copies"] >= 1
        assert cols["repaired"] >= 1
        integrity.verify_stripe_file(primary)
        snap = sess.stats.counters.snapshot()
        assert snap[sc.SCRUB_RUNS_TOTAL] == 1
        assert snap[sc.SCRUB_REPAIRS_TOTAL] >= 1
        # post-repair scrub is clean
        row2 = sess.execute("SELECT citus_check_cluster(0)").rows()[0]
        assert int(row2[2]) == 0  # corrupt_copies
        sess.close()

    def test_factor1_query_fails_cleanly(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"),
                                 n_devices=2, retry_backoff_base_ms=1)
        sess.execute("CREATE TABLE kv (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('kv', 'id', 2)")
        sess.execute("INSERT INTO kv VALUES " + ", ".join(
            f"({i}, {i})" for i in range(64)))
        man = sess.store.manifest("kv")
        sid = next(s for s in man["shards"] if man["shards"][s])
        rec = man["shards"][sid][0]
        integrity.flip_one_bit(os.path.join(
            sess.store.shard_dir("kv", int(sid)), rec["file"]))
        sess.store.refresh("kv")
        with pytest.raises(CorruptStripe):
            sess.execute("SELECT sum(v) FROM kv")
        sess.close()

    def test_explain_analyze_integrity_line(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"),
                                 n_devices=2)
        sess.execute("CREATE TABLE kv (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('kv', 'id', 2)")
        sess.execute("INSERT INTO kv VALUES (1, 1), (2, 2)")
        plan = "\n".join(r[0] for r in sess.execute(
            "EXPLAIN ANALYZE SELECT sum(v) FROM kv").rows())
        assert "Integrity:" in plan
        assert "stripes verified=" in plan
        sess.close()

    def test_stat_activity_has_read_repairs_column(self, tmp_path):
        sess = citus_tpu.connect(data_dir=str(tmp_path / "d"),
                                 n_devices=2)
        r = sess.execute("SELECT citus_stat_activity()")
        assert "read_repairs" in r.column_names
        sess.close()


# ---------------------------------------------------------------------------
# restore-point validation (satellite: no wipe before verify)
# ---------------------------------------------------------------------------
class TestRestorePointValidation:
    def test_damaged_snapshot_refuses_and_preserves_live(self, tmp_path):
        from citus_tpu.operations.restore_point import restore_cluster

        d = str(tmp_path / "d")
        sess = citus_tpu.connect(data_dir=d, n_devices=2)
        sess.execute("CREATE TABLE kv (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('kv', 'id', 2)")
        sess.execute("INSERT INTO kv VALUES (1, 10), (2, 20)")
        sess.execute("SELECT citus_create_restore_point('rp1')")
        sess.execute("INSERT INTO kv VALUES (3, 30)")
        sess.close()
        # damage the snapshot: flip a bit in a snapshotted stripe.
        # Hardlinked snapshots share bytes with live files, so corrupt
        # a COPY-free way: find the snapshot stripe and rewrite it torn
        snap_tables = os.path.join(d, "restore_points", "rp1", "tables",
                                   "kv")
        stripe = None
        for dp, _dirs, files in os.walk(snap_tables):
            for f in files:
                if f.endswith(".ctps"):
                    stripe = os.path.join(dp, f)
                    break
        payload = open(stripe, "rb").read()
        os.unlink(stripe)  # break the hardlink before corrupting
        with open(stripe, "wb") as f:
            f.write(payload[: len(payload) // 2])
        with pytest.raises(CorruptStripe):
            restore_cluster(d, "rp1")
        # live data untouched: all three rows still readable
        sess2 = citus_tpu.connect(data_dir=d, n_devices=2)
        got = {int(i): int(v) for i, v in
               sess2.execute("SELECT id, v FROM kv").rows()}
        assert got == {1: 10, 2: 20, 3: 30}
        sess2.close()

    def test_intact_snapshot_still_restores(self, tmp_path):
        from citus_tpu.operations.restore_point import restore_cluster

        d = str(tmp_path / "d")
        sess = citus_tpu.connect(data_dir=d, n_devices=2)
        sess.execute("CREATE TABLE kv (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('kv', 'id', 2)")
        sess.execute("INSERT INTO kv VALUES (1, 10)")
        sess.execute("SELECT citus_create_restore_point('rp1')")
        sess.execute("INSERT INTO kv VALUES (2, 20)")
        sess.close()
        restore_cluster(d, "rp1")
        sess2 = citus_tpu.connect(data_dir=d, n_devices=2)
        got = {int(i): int(v) for i, v in
               sess2.execute("SELECT id, v FROM kv").rows()}
        assert got == {1: 10}
        sess2.close()


# ---------------------------------------------------------------------------
# directed fault-point tests (registry: every point armed by >=1 test)
# ---------------------------------------------------------------------------
class TestStorageFaultPoints:
    def _sess(self, tmp_path, **kw):
        kw.setdefault("retry_backoff_base_ms", 1)
        kw.setdefault("n_devices", 2)
        return citus_tpu.connect(data_dir=str(tmp_path / "d"), **kw)

    def test_stripe_torn_write_retries_clean(self, tmp_path):
        sess = self._sess(tmp_path, max_statement_retries=2)
        sess.execute("CREATE TABLE kv (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('kv', 'id', 2)")
        with fi.inject("storage.stripe_torn_write",
                       require_fired=True):
            sess.execute("INSERT INTO kv VALUES (1, 1)")  # retried
        assert int(sess.execute(
            "SELECT count(*) FROM kv").rows()[0][0]) == 1
        sess.close()

    def test_manifest_flip_fault_keeps_write_invisible(self, tmp_path):
        sess = self._sess(tmp_path, max_statement_retries=0)
        sess.execute("CREATE TABLE kv (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('kv', 'id', 2)")
        sess.execute("INSERT INTO kv VALUES (1, 1)")
        with fi.inject("storage.manifest_flip"):
            with pytest.raises(Exception):
                sess.execute("INSERT INTO kv VALUES (2, 2)")
        assert int(sess.execute(
            "SELECT count(*) FROM kv").rows()[0][0]) == 1
        sess.close()

    def test_stripe_bitflip_fault_detected(self, tmp_path):
        sess = self._sess(tmp_path, max_statement_retries=2,
                          shard_replication_factor=2)
        sess.execute("CREATE TABLE kv (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('kv', 'id', 2)")
        sess.execute("INSERT INTO kv VALUES " + ", ".join(
            f"({i}, {i})" for i in range(32)))
        sess.store.refresh("kv")
        # the bitflip is injected CORRUPTION (not an exception), so
        # nothing raises — require_fired is the only proof the armed
        # seam was reached and the CRC path actually got tested
        with fi.inject("storage.stripe_bitflip", require_fired=True):
            got = {int(i) for i, in
                   sess.execute("SELECT id FROM kv").rows()}
        assert got == set(range(32))  # repaired or untouched, never wrong
        sess.close()
