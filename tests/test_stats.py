"""Observability tests — stat counters, query stats, tenant stats,
activity, progress (reference: stats/stat_counters.c, query_stats.c,
stat_tenants.c, progress/multi_progress.c)."""

import citus_tpu
import pytest

from citus_tpu.stats import fingerprint
from citus_tpu.stats.counters import StatCounters


@pytest.fixture
def sess(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "stats"), n_devices=4)
    s.execute("""
        create table events (tenant int, kind text, n int);
        select create_distributed_table('events', 'tenant', 8);
        insert into events values (1, 'a', 10), (1, 'b', 20), (2, 'a', 5),
                                  (3, 'c', 1);
    """)
    return s


def _counters(sess):
    r = sess.execute("select citus_stat_counters()")
    return dict(zip(r.columns["name"], r.columns["value"]))


def test_counters_track_queries_and_dml(sess):
    sess.execute("select count(*) from events")            # multi-shard
    sess.execute("select n from events where tenant = 1")  # single-shard
    sess.execute("update events set n = n + 1 where tenant = 2")
    sess.execute("delete from events where tenant = 3")
    c = _counters(sess)
    assert c["queries_multi_shard"] >= 1
    assert c["queries_single_shard"] >= 1
    assert c["dml_update_count"] == 1
    assert c["dml_delete_count"] == 1
    assert c["rows_ingested"] == 4
    assert c["rows_returned"] >= 2
    assert c["ddl_commands"] >= 1


def test_counters_track_repartition(sess):
    sess.execute("select count(*) from events a, events b "
                 "where a.n = b.tenant")
    assert _counters(sess)["queries_repartition"] >= 1


def test_counters_reset(sess):
    sess.execute("select count(*) from events")
    sess.execute("select citus_stat_counters_reset()")
    c = _counters(sess)
    assert all(v == 0 for k, v in c.items())


def test_query_stats_fingerprint_groups_literals():
    assert fingerprint("select * from t where a = 42") == \
        fingerprint("SELECT * FROM t WHERE a = 99")
    assert fingerprint("select * from t where s = 'x'") == \
        fingerprint("select * from t where s = 'other'")
    assert fingerprint("select * from t1") != fingerprint("select * from t2")


def test_stat_statements_records_calls(sess):
    for k in (1, 2, 3):
        sess.execute(f"select sum(n) from events where tenant = {k}")
    r = sess.execute("select citus_stat_statements()")
    by_q = dict(zip(r.columns["query"], r.columns["calls"]))
    hit = [q for q in by_q if "sum ( n )" in q or "sum(n)" in q.replace(" ", "")]
    assert hit and by_q[hit[0]] == 3
    sess.execute("select citus_stat_statements_reset()")
    r = sess.execute("select citus_stat_statements()")
    assert r.row_count <= 1  # only the reset/view calls themselves


def test_stat_tenants_attribution(sess):
    sess.execute("select n from events where tenant = 1")
    sess.execute("select n from events where tenant = 1")
    sess.execute("select n from events where tenant = 2")
    r = sess.execute("select citus_stat_tenants()")
    rows = {(t, a): c for t, a, c, _ in r.rows()}
    assert rows[("events", "1")] == 2
    assert rows[("events", "2")] == 1


def test_stat_tenants_eviction_is_deterministic():
    """Overflowing the bounded tenant table evicts the COLDEST tenant
    (fewest queries, then least-recently seen, then key order) — not
    whichever minimal-count entry happened to be inserted first."""
    from citus_tpu.stats.tenants import TenantStats

    ts = TenantStats(limit=3)
    ts.record("t", "a", 1.0)   # a: 1 query, seen @1
    ts.record("t", "b", 1.0)   # b: 1 query, seen @2
    ts.record("t", "b", 1.0)   # b: 2 queries
    ts.record("t", "c", 1.0)   # c: 1 query, seen @4
    ts.record("t", "a", 1.0)   # a: 2 queries, seen @5 — c is now coldest
    # table full (a, b, c); a new tenant evicts c (1 query) even though
    # a was inserted first
    ts.record("t", "d", 1.0)
    tenants = {s.tenant for s in ts.entries()}
    assert tenants == {"a", "b", "d"}
    # fewest-queries outranks recency
    ts2 = TenantStats(limit=2)
    ts2.record("t", "x", 1.0)
    ts2.record("t", "y", 1.0)
    ts2.record("t", "x", 1.0)  # x:2 queries, y:1
    ts2.record("t", "z", 1.0)  # y evicts on count despite being newer
    assert {s.tenant for s in ts2.entries()} == {"x", "z"}
    # equal counts: the least-recently-seen tenant evicts
    ts3 = TenantStats(limit=2)
    ts3.record("t", "p", 1.0)
    ts3.record("t", "q", 1.0)
    ts3.record("t", "p", 1.0)
    ts3.record("t", "q", 1.0)  # p:2 (seen @3), q:2 (seen @4)
    ts3.record("t", "r", 1.0)  # p is least-recent → evicted
    assert {s.tenant for s in ts3.entries()} == {"q", "r"}


def test_stat_counters_thread_slots():
    import threading

    c = StatCounters()

    def work():
        for _ in range(1000):
            c.increment("x")

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # "x" is not a registered counter name; snapshot only reports known
    # ones — check the raw aggregation instead
    total = sum(slot.get("x", 0) for slot in c._slots)
    assert total == 4000


def test_rebalance_reports_progress(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "rb"), n_devices=2)
    s.execute("""
        create table big (k int, v int);
        select create_distributed_table('big', 'k', 8);
    """)
    s.execute("insert into big values " + ", ".join(
        f"({i}, {i})" for i in range(200)))
    s.execute("select citus_add_node('device:extra')")
    s.execute("select rebalance_table_shards('big')")
    r = s.execute("select get_rebalance_progress()")
    if r.row_count:  # moves happened: every monitor completed
        assert all(p == t for p, t in
                   zip(r.columns["progress"], r.columns["total"]))


def test_explain_analyze_reports_device_rows(sess):
    r = sess.execute("explain analyze select sum(n) from events")
    text = "\n".join(r.columns["QUERY PLAN"])
    assert "Execution Time" in text
