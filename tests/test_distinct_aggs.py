"""DISTINCT aggregates: the two-level dedupe/re-aggregate split, checked
against the sqlite oracle over colocated, broadcast, and repartition
inputs (the reference's count(distinct) worker/master rewrite,
planner/multi_logical_optimizer.c:286 — VERDICT round-2 item 2)."""

import pytest

import citus_tpu
from citus_tpu.errors import PlanningError
from citus_tpu.ingest import tpch
from oracle import compare_results, make_oracle, run_oracle

DATE_COLUMNS = {
    "orders": ["o_orderdate"],
    "lineitem": ["l_shipdate", "l_commitdate", "l_receiptdate"],
}


@pytest.fixture(scope="module")
def sess(tmp_path_factory):
    s = citus_tpu.connect(
        data_dir=str(tmp_path_factory.mktemp("dtpch")),
        n_devices=8, compute_dtype="float64")
    tpch.load_into_session(s, sf=0.002, seed=11, shard_count=8)
    return s


@pytest.fixture(scope="module")
def conn():
    return make_oracle(tpch.generate_tables(0.002, seed=11), DATE_COLUMNS)


def check(sess, conn, sql, tol=1e-6):
    result = sess.execute(sql)
    want = run_oracle(conn, sql)
    ordered = "order by" in sql.lower()
    compare_results(result.rows(), want, ordered, tol)
    return result


def test_global_count_distinct(sess, conn):
    # dist-column arg (dedupe is device-local)
    check(sess, conn, "select count(distinct l_orderkey) from lineitem")
    # non-dist arg (dedupe needs the repartition shuffle)
    check(sess, conn, "select count(distinct l_suppkey) from lineitem")


def test_count_distinct_grouped(sess, conn):
    # group by non-dist column: inner shuffle routes by the group key
    check(sess, conn,
          "select l_returnflag, count(distinct l_suppkey), count(*) "
          "from lineitem group by l_returnflag order by l_returnflag")
    # group by dist column: fully device-local
    check(sess, conn,
          "select l_orderkey, count(distinct l_suppkey) from lineitem "
          "group by l_orderkey order by l_orderkey limit 20")


def test_sum_avg_distinct_and_mixed(sess, conn):
    check(sess, conn,
          "select sum(distinct l_quantity), avg(distinct l_quantity), "
          "min(distinct l_quantity), sum(l_quantity), count(*) "
          "from lineitem")
    check(sess, conn,
          "select o_orderpriority, count(distinct o_custkey), "
          "sum(o_totalprice), max(o_totalprice) from orders "
          "group by o_orderpriority order by o_orderpriority", tol=1e-4)


def test_count_distinct_over_join(sess, conn):
    # repartitioned join input + distinct (Q16 shape: joined dedupe)
    check(sess, conn,
          "select count(distinct o_custkey) from orders, lineitem "
          "where o_orderkey = l_orderkey and l_quantity < 10")
    check(sess, conn,
          "select l_returnflag, count(distinct c_nationkey) "
          "from customer, orders, lineitem "
          "where c_custkey = o_custkey and o_orderkey = l_orderkey "
          "group by l_returnflag order by l_returnflag")


def test_count_distinct_broadcast_input(sess, conn):
    # nation is a reference (broadcast) table
    check(sess, conn, "select count(distinct n_regionkey) from nation")


def test_count_distinct_nulls(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64")
    s.execute("create table t (k int, v int)")
    s.create_distributed_table("t", "k", shard_count=4)
    s.execute("insert into t values (1, 10), (2, 10), (3, null), "
              "(4, 20), (5, null), (6, 20), (7, 30)")
    r = s.execute("select count(distinct v), count(v), count(*) from t")
    assert [int(x) for x in r.rows()[0]] == [3, 5, 7]
    r2 = s.execute("select sum(distinct v) from t")
    assert int(r2.rows()[0][0]) == 60


def test_multiple_distinct_args_supported(sess):
    # lifted in round 4: additional distinct arguments source from
    # same-FROM derived tables / scalar subqueries (decorrelate.py
    # rewrite_multi_distinct); deeper coverage in test_approx_aggs.py
    r = sess.execute("select count(distinct l_suppkey), "
                     "count(distinct l_partkey) from lineitem")
    a = sess.execute(
        "select count(distinct l_suppkey) from lineitem").rows()[0][0]
    b = sess.execute(
        "select count(distinct l_partkey) from lineitem").rows()[0][0]
    assert r.rows() == [(a, b)]


def test_multi_distinct_over_empty_input(tmp_path):
    # fuzz seed 505 #57: count(distinct) must be 0 over zero rows even
    # for the rewritten (non-first) distinct argument — the max() wrap
    # alone turns it into NULL
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64")
    s.execute("create table me (a bigint, b bigint)")
    s.create_distributed_table("me", "a", shard_count=4)
    s.execute("insert into me values (1, 2), (3, 4)")
    r = s.execute("select count(distinct a), count(distinct b), "
                  "sum(distinct b) from me where a >= 900").rows()[0]
    assert r == (0, 0, None)
    s.close()


def test_subqueries_in_every_expression_position(tmp_path):
    # the expression rewriter previously hand-copied node kinds and
    # skipped IsNull/Cast/Extract/Substring, leaving nested subqueries
    # unplanned; it now maps through the shared structural rebuilder
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64")
    s.execute("create table sx (k bigint, v bigint)")
    s.create_distributed_table("sx", "k", shard_count=4)
    s.execute("insert into sx values (1, 10), (2, 20), (3, 30)")
    r = s.execute("select cast((select max(v) from sx) as bigint) "
                  "from sx where k = 1")
    assert r.rows() == [(30,)]
    r = s.execute("select k from sx where ((select max(v) from sx) "
                  "is null) = false order by k")
    assert [x for (x,) in r.rows()] == [1, 2, 3]
    s.close()
