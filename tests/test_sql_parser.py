"""SQL frontend tests, including full TPC-H query texts (the target SQL
surface per BASELINE.md configs)."""

import pytest

from citus_tpu.errors import ParseError
from citus_tpu.sql import ast, parse, parse_one

TPCH_Q1 = """
select
    l_returnflag,
    l_linestatus,
    sum(l_quantity) as sum_qty,
    sum(l_extendedprice) as sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
    avg(l_quantity) as avg_qty,
    avg(l_extendedprice) as avg_price,
    avg(l_discount) as avg_disc,
    count(*) as count_order
from
    lineitem
where
    l_shipdate <= date '1998-12-01' - interval '90' day
group by
    l_returnflag,
    l_linestatus
order by
    l_returnflag,
    l_linestatus
"""

TPCH_Q3 = """
select
    l_orderkey,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    o_orderdate,
    o_shippriority
from
    customer,
    orders,
    lineitem
where
    c_mktsegment = 'BUILDING'
    and c_custkey = o_custkey
    and l_orderkey = o_orderkey
    and o_orderdate < date '1995-03-15'
    and l_shipdate > date '1995-03-15'
group by
    l_orderkey,
    o_orderdate,
    o_shippriority
order by
    revenue desc,
    o_orderdate
limit 10
"""

TPCH_Q5 = """
select
    n_name,
    sum(l_extendedprice * (1 - l_discount)) as revenue
from
    customer,
    orders,
    lineitem,
    supplier,
    nation,
    region
where
    c_custkey = o_custkey
    and l_orderkey = o_orderkey
    and l_suppkey = s_suppkey
    and c_nationkey = s_nationkey
    and s_nationkey = n_nationkey
    and n_regionkey = r_regionkey
    and r_name = 'ASIA'
    and o_orderdate >= date '1994-01-01'
    and o_orderdate < date '1994-01-01' + interval '1' year
group by
    n_name
order by
    revenue desc
"""


class TestLexer:
    def test_comments_and_strings(self):
        stmts = parse("select 'it''s' -- trailing\n /* block */ as x")
        item = stmts[0].items[0]
        assert item.expr.value == "it's"
        assert item.alias == "x"

    def test_position_in_errors(self):
        with pytest.raises(ParseError, match="line 2"):
            parse("select\n  @ from t")


class TestExpressions:
    def q(self, expr_sql):
        return parse_one(f"select {expr_sql} from t").items[0].expr

    def test_precedence_arith_over_comparison(self):
        e = self.q("a + b * 2 > c - 1")
        assert isinstance(e, ast.BinaryOp) and e.op == ">"
        assert e.left.op == "+"
        assert e.left.right.op == "*"

    def test_and_or_precedence(self):
        e = self.q("a = 1 or b = 2 and c = 3")
        assert e.op == "OR"
        assert e.right.op == "AND"

    def test_not_binds_tighter_than_and(self):
        e = self.q("not a = 1 and b = 2")
        assert e.op == "AND"
        assert isinstance(e.left, ast.UnaryOp) and e.left.op == "NOT"

    def test_between_and_in(self):
        e = self.q("x between 1 and 10")
        assert isinstance(e, ast.Between)
        e = self.q("x not in (1, 2, 3)")
        assert isinstance(e, ast.InList) and e.negated
        assert len(e.items) == 3

    def test_like(self):
        e = self.q("p_type like '%BRASS'")
        assert isinstance(e, ast.Like)
        assert e.pattern.value == "%BRASS"

    def test_case_when(self):
        e = self.q("case when a = 1 then 'one' else 'other' end")
        assert isinstance(e, ast.CaseWhen)
        assert len(e.whens) == 1
        assert e.else_result.value == "other"

    def test_date_and_interval_literals(self):
        e = self.q("date '1998-12-01' - interval '90' day")
        assert e.op == "-"
        assert e.left.type_hint == "date"
        assert e.right.type_hint == "interval"
        assert e.right.value == 90 and e.right.interval_unit == "day"

    def test_interval_unit_inside_string(self):
        e = self.q("d + interval '3 month'")
        assert e.right.interval_unit == "month"

    def test_qualified_refs_and_star(self):
        e = self.q("t1.col")
        assert e == ast.ColumnRef("col", "t1")
        sel = parse_one("select t.* from t")
        assert sel.items[0].expr == ast.Star("t")

    def test_agg_calls(self):
        e = self.q("count(*)")
        assert e.star
        e = self.q("count(distinct x)")
        assert e.distinct
        e = self.q("sum(a * b)")
        assert ast.is_aggregate_call(e)

    def test_cast_both_syntaxes(self):
        assert isinstance(self.q("cast(x as bigint)"), ast.Cast)
        e = self.q("x::decimal(15,2)")
        assert isinstance(e, ast.Cast) and e.type_name == "decimal(15,2)"

    def test_extract_and_substring(self):
        e = self.q("extract(year from o_orderdate)")
        assert isinstance(e, ast.Extract) and e.part == "year"
        e = self.q("substring(c_phone from 1 for 2)")
        assert isinstance(e, ast.Substring)

    def test_scalar_and_in_subquery(self):
        sel = parse_one(
            "select * from t where x > (select avg(y) from u) "
            "and k in (select k from v)")
        w = sel.where
        assert isinstance(w.left.right, ast.ScalarSubquery)
        assert isinstance(w.right, ast.InSubquery)

    def test_exists(self):
        sel = parse_one("select * from t where exists (select 1 from u)")
        assert isinstance(sel.where, ast.Exists)

    def test_unary_minus_folds_literal(self):
        assert self.q("-5") == ast.Literal(-5)


class TestSelectShape:
    def test_joins_explicit(self):
        sel = parse_one(
            "select * from a join b on a.k = b.k "
            "left join c on b.j = c.j")
        j = sel.from_items[0]
        assert isinstance(j, ast.Join) and j.join_type == "left"
        assert j.left.join_type == "inner"

    def test_join_using(self):
        sel = parse_one("select * from a join b using (k)")
        j = sel.from_items[0]
        assert j.condition is None and j.using_cols == ("k",)

    def test_implicit_cross_join_list(self):
        sel = parse_one("select * from a, b, c where a.x = b.x")
        assert len(sel.from_items) == 3

    def test_subquery_in_from(self):
        sel = parse_one("select s.x from (select x from t) s")
        assert isinstance(sel.from_items[0], ast.SubqueryRef)

    def test_cte(self):
        sel = parse_one(
            "with r as (select x from t), s as (select y from u) "
            "select * from r, s")
        assert [c.name for c in sel.ctes] == ["r", "s"]

    def test_group_having_order_limit(self):
        sel = parse_one(
            "select k, count(*) c from t group by k having count(*) > 5 "
            "order by c desc nulls last limit 3 offset 1")
        assert sel.group_by and sel.having is not None
        assert sel.order_by[0].descending
        assert sel.order_by[0].nulls_first is False
        assert sel.limit == 3 and sel.offset == 1

    def test_distinct(self):
        assert parse_one("select distinct x from t").distinct


class TestTPCH:
    def test_q1_full_shape(self):
        sel = parse_one(TPCH_Q1)
        assert len(sel.items) == 10
        assert sel.items[4].alias == "sum_disc_price"
        assert len(sel.group_by) == 2
        assert len(sel.order_by) == 2

    def test_q3_full_shape(self):
        sel = parse_one(TPCH_Q3)
        assert len(sel.from_items) == 3
        assert sel.limit == 10
        assert sel.order_by[0].descending

    def test_q5_full_shape(self):
        sel = parse_one(TPCH_Q5)
        assert len(sel.from_items) == 6
        # date + interval '1' year arithmetic parsed
        conds = str(sel.where)
        assert "INTERVAL '1' YEAR" in conds


class TestOtherStatements:
    def test_create_table(self):
        st = parse_one(
            "create table if not exists t (a int not null, b varchar(10), "
            "c decimal(15,2), d date)")
        assert isinstance(st, ast.CreateTable) and st.if_not_exists
        assert st.columns[0].not_null
        assert st.columns[1].type_name == "varchar(10)"

    def test_drop_table(self):
        st = parse_one("drop table if exists t")
        assert isinstance(st, ast.DropTable) and st.if_exists

    def test_insert_values(self):
        st = parse_one("insert into t (a, b) values (1, 'x'), (2, 'y')")
        assert isinstance(st, ast.InsertValues)
        assert len(st.rows) == 2 and st.columns == ("a", "b")

    def test_insert_select(self):
        st = parse_one("insert into t select * from u where x > 0")
        assert isinstance(st, ast.InsertSelect)

    def test_copy(self):
        st = parse_one(
            "copy lineitem from '/tmp/l.tbl' with (format text, "
            "delimiter '|', header)")
        assert isinstance(st, ast.CopyFrom)
        assert st.format == "text" and st.delimiter == "|" and st.header

    def test_explain_analyze(self):
        st = parse_one("explain analyze select * from t")
        assert isinstance(st, ast.Explain) and st.analyze
        assert isinstance(st.statement, ast.Select)

    def test_set_show(self):
        st = parse_one("set citus.shard_count = 32")
        assert st.name == "shard_count" and st.value == 32
        st = parse_one("show shard_count")
        assert st.name == "shard_count"

    def test_script_multi_statement(self):
        stmts = parse("create table t (a int); select * from t;")
        assert len(stmts) == 2

    def test_error_messages_name_position(self):
        with pytest.raises(ParseError, match="expected"):
            parse_one("select from where")

    def test_syntax_errors_never_leak_valueerror(self):
        # regression: int()/float() on malformed tokens must surface as
        # ParseError with position, not bare ValueError
        for bad in ("select x from t limit 1.5",
                    "select x from t offset 1e3",
                    "select d + interval '1.5' month from t",
                    "select d + interval 'abc' day from t"):
            with pytest.raises(ParseError):
                parse_one(bad)

    def test_multiline_string_keeps_positions(self):
        with pytest.raises(ParseError, match="line 3"):
            parse("select 'a\nb',\n @")

    def test_quoted_ident_doubled_quote_escape(self):
        sel = parse_one('select "a""b" from t')
        assert sel.items[0].expr == ast.ColumnRef('a"b')
