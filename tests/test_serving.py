"""Serving layer: cross-session micro-batched point reads + the
CDC-invalidated result cache (citus_tpu/serving/).

Covers the PR-8 acceptance surface:

* ONE fast-path shape classifier shared by WLM admission exemption and
  the serving layer (a corpus both call sites must classify identically);
* micro-batcher: single-flight, coalescing, the answered-XOR-cleanly-
  errored-XOR-fallback ledger under `serving.batch_dispatch` faults;
* batched index reader (`pkindex.read_rows_multi`) ≡ the solo path;
* result cache: CDC-driven cross-session invalidation (DML / COPY /
  txn commit — never a TTL), the manifest-identity backstop for
  journal-missed writes, LRU byte bound, epoch fill races;
* `ChangeFeedCursor` incremental journal consumption;
* FeedCache per-table invalidation index (satellite regression);
* observability: counters, citus_stat_serving(), EXPLAIN "Serving:";
* serving fuzz: cache-on ≡ cache-off under interleaved writes
  (deterministic tier-1 slice; the full run is `slow`).
"""

import json
import os
import random
import threading

import numpy as np
import pytest

import citus_tpu
from citus_tpu.cdc.feed import ChangeFeedCursor
from citus_tpu.errors import CitusTpuError
from citus_tpu.executor.cache import CachedFeed, FeedCache
from citus_tpu.executor.runner import ResultSet
from citus_tpu.serving import batcher_for, classify_point_read
from citus_tpu.serving.result_cache import ResultCache, cache_key
from citus_tpu.sql import parse
from citus_tpu.stats import counters as sc
from citus_tpu.storage import pkindex
from citus_tpu.utils import faultinjection as fi
from citus_tpu.utils.faultinjection import InjectedFault
from citus_tpu.wlm import fastpath_exempt_shape
from citus_tpu.session import _UDFS


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.reset()
    yield
    fi.reset()


@pytest.fixture()
def sess(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=2)
    s.execute("create table kv (k bigint, v bigint, s text)")
    s.create_distributed_table("kv", "k", shard_count=4)
    s.execute("insert into kv values " + ", ".join(
        f"({i}, {i * 10}, 'n{i % 5}')" for i in range(200)))
    s.execute("create table ref (v bigint)")
    s.execute("select create_reference_table('ref')")
    s.execute("insert into ref values (10), (20)")
    yield s
    s.close()


def _second(sess, tmp_path, **kw):
    return citus_tpu.connect(data_dir=sess.data_dir, n_devices=2, **kw)


def _serving_counter(s, name):
    return s.stats.counters.snapshot().get(name, 0)


def _stat_serving(s) -> dict:
    r = s.execute("select citus_stat_serving()")
    return dict(zip(r.column_names, r.rows()[0]))


# ---------------------------------------------------------------------------
# ONE shape classifier, two call sites


CLASSIFIER_CORPUS = [
    # (sql, is_point_read)
    ("select v from kv where k = 5", True),
    ("select v, s from kv where k = 5 and v > 2", True),
    ("select v from kv where 5 = k", True),
    ("select v from kv as t where t.k = 7", True),
    ("select * from kv", False),
    ("select v from kv where v = 5", False),          # non-distcol pin
    ("select count(*) from kv where k = 5", False),   # aggregate
    ("select v from kv where k = 5 or v = 1", False),  # disjunction
    ("select v from kv, ref where k = 1", False),     # join
    ("select v from kv where k = 5 group by v", False),
    ("select distinct v from kv where k = 5", False),
    ("select v from ref where v = 10", False),        # reference table
    ("select v from nope where k = 1", False),        # unknown table
    ("select v from kv where k in (1, 2)", False),
    ("select v from kv where k = 1 limit 1", True),
    ("with c as (select 1) select v from kv where k = 1", False),
]


class TestSharedClassifier:
    def test_corpus_classified_identically_by_both_call_sites(self, sess):
        for sql, want in CLASSIFIER_CORPUS:
            stmt = parse(sql)[0]
            via_serving = classify_point_read(
                stmt, sess.catalog, sess.settings) is not None
            via_wlm = fastpath_exempt_shape(
                stmt, sess.catalog, sess.settings)
            assert via_serving == via_wlm == want, sql

    def test_classifier_agrees_with_bound_plan_router(self, sess):
        """The parse-tree classifier is a conservative mirror of the
        executor's bound-plan matcher (fast_path_shape +
        point_lookup_const) — different representations, one behavior.
        Pin the direction that matters over the corpus: everything the
        classifier exempts from admission, the executor genuinely
        routes fast-path (a fastpath.py change that narrows routing
        without narrowing the exemption fails HERE, not silently).  The
        reverse direction is allowed slack by design — the reference
        accepts the same between FastPathRouterQuery and the real
        router plan."""
        from citus_tpu.executor.fastpath import (fast_path_shape,
                                                 point_lookup_const)
        from citus_tpu.executor.feed import walk_plan
        from citus_tpu.planner.plan import ScanNode

        for sql, want in CLASSIFIER_CORPUS:
            if not want:
                continue
            stmt = parse(sql)[0]
            plan, cleanup = sess._plan_select(stmt, ())
            for t in cleanup:
                sess._drop_temp(t)
            assert fast_path_shape(plan, sess.catalog), sql
            consts = [point_lookup_const(n, sess.catalog, sess.settings)
                      for n in walk_plan(plan.root)
                      if isinstance(n, ScanNode)]
            assert consts and all(c is not None for c in consts), sql

    def test_classification_pins_table_column_value(self, sess):
        pr = classify_point_read(
            parse("select v from kv where s = 'x' and k = 42")[0],
            sess.catalog, sess.settings)
        assert (pr.table, pr.column, pr.value) == ("kv", "k", 42)

    def test_router_disabled_classifies_nothing(self, sess):
        stmt = parse("select v from kv where k = 5")[0]
        with sess.settings.override(enable_fast_path_router=False):
            assert classify_point_read(
                stmt, sess.catalog, sess.settings) is None
            assert not fastpath_exempt_shape(
                stmt, sess.catalog, sess.settings)

    def test_point_reads_exempt_from_admission(self, sess):
        before = sess.wlm.snapshot()["requests_total"]
        sess.execute("select v from kv where k = 11")
        assert sess.wlm.snapshot()["requests_total"] == before


# ---------------------------------------------------------------------------
# micro-batcher


class TestMicroBatcher:
    def test_single_flight_no_added_latency_path(self, sess):
        b = batcher_for(sess.data_dir)
        before = b.snapshot()
        r = sess.execute("select v from kv where k = 17")
        assert r.rows() == [(170,)]
        snap = b.snapshot()
        assert snap["requests_total"] == before["requests_total"] + 1
        assert snap["answered_total"] == before["answered_total"] + 1
        assert snap["queue_depth"] == 0 and not snap["leader_active"]

    def test_concurrent_lookups_coalesce(self, sess, tmp_path,
                                         monkeypatch):
        """8 threads across 2 sessions probing concurrently: every
        answer exact, and (with the batch window held open by a slowed
        reader) at least one dispatch carried more than one lookup."""
        # cache off: the repeats must reach the BATCHER, not the cache
        sess.execute("set serving_result_cache_bytes = 0")
        s2 = _second(sess, tmp_path, serving_result_cache_bytes=0)
        real = pkindex.read_rows_multi

        def slowed(*a, **kw):
            import time

            time.sleep(0.02)  # arrivals pile up behind the leader
            return real(*a, **kw)

        monkeypatch.setattr(pkindex, "read_rows_multi", slowed)
        b = batcher_for(sess.data_dir)
        base = b.snapshot()
        barrier = threading.Barrier(8)
        errors: list = []

        def worker(s, key):
            try:
                barrier.wait()
                for _ in range(3):
                    r = s.execute(f"select v from kv where k = {key}")
                    assert r.rows() == [(key * 10,)], key
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=worker,
                                    args=((sess, s2)[i % 2], 20 + i))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        snap = b.snapshot()
        try:
            assert not errors, errors[0]
            assert snap["requests_total"] - base["requests_total"] == 24
            assert snap["answered_total"] - base["answered_total"] == 24
            assert snap["max_batch_seen"] >= 2
            assert snap["queue_depth"] == 0 and not snap["leader_active"]
        finally:
            s2.close()

    def test_batch_dispatch_fault_errors_whole_batch_cleanly(
            self, sess, tmp_path):
        """Ledger invariant: a fault at dispatch resolves EVERY queued
        lookup as a clean error — none lost in the dead batch — and the
        next lookup finds a working batcher (no leaked leader slot)."""
        s2 = _second(sess, tmp_path,
                     max_statement_retries=0)  # surface, don't retry
        sess.execute("set max_statement_retries = 0")
        b = batcher_for(sess.data_dir)
        base = b.snapshot()
        fi.arm("serving.batch_dispatch", times=2)
        outcomes: list = []
        lock = threading.Lock()

        def worker(s, key):
            try:
                r = s.execute(f"select v from kv where k = {key}")
                with lock:
                    outcomes.append(("ok", r.rows()))
            except Exception as e:
                with lock:
                    outcomes.append(("err", e))

        threads = [threading.Thread(target=worker,
                                    args=((sess, s2)[i % 2], 30 + i))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        fi.reset()
        try:
            assert len(outcomes) == 6
            for kind, payload in outcomes:
                if kind == "err":  # clean framework error, classified
                    assert isinstance(payload, CitusTpuError), payload
            assert any(k == "err" for k, _ in outcomes)
            snap = b.snapshot()
            assert snap["requests_total"] - base["requests_total"] == \
                (snap["answered_total"] - base["answered_total"]) + \
                (snap["errored_total"] - base["errored_total"]) + \
                (snap["fallback_total"] - base["fallback_total"])
            assert snap["queue_depth"] == 0 and not snap["leader_active"]
            # the batcher still works after the dead batch
            assert sess.execute(
                "select v from kv where k = 3").rows() == [(30,)]
        finally:
            s2.close()

    def test_batch_dispatch_fault_is_retried_transparently(self, sess):
        fi.arm("serving.batch_dispatch", times=1)
        r = sess.execute("select v from kv where k = 77")
        assert r.rows() == [(770,)]
        snap = sess.stats.counters.snapshot()
        assert snap[sc.RETRIES_TOTAL] >= 1
        assert snap[sc.FAULTS_INJECTED_TOTAL] >= 1

    def test_index_miss_falls_back_to_scan(self, sess, monkeypatch):
        """lookup() returning None (overlay materialized between
        eligibility and dispatch) resolves as fallback: the statement
        still answers via the ordinary scan path."""
        monkeypatch.setattr(pkindex, "lookup",
                            lambda *a, **kw: None)
        b = batcher_for(sess.data_dir)
        base = b.snapshot()["fallback_total"]
        r = sess.execute("select v from kv where k = 19")
        assert r.rows() == [(190,)]
        assert b.snapshot()["fallback_total"] == base + 1

    def test_open_overlay_session_goes_solo(self, sess, tmp_path):
        """A session with an open transaction overlay — even a
        delete-only one, which the index's records-only guard cannot
        see — must not ride the batcher: staged state is private to its
        own store.  Riding another session's probe store would un-see
        its own staged DELETE (read-your-writes), and leading a batch
        would leak the uncommitted delete to other sessions (dirty
        read)."""
        s2 = _second(sess, tmp_path)
        try:
            b = batcher_for(sess.data_dir)
            sess.execute("begin")
            sess.execute("delete from kv where k = 33")
            base = b.snapshot()["requests_total"]
            # read-your-writes: the staged delete is visible, solo
            assert sess.execute(
                "select v from kv where k = 33").rows() == []
            assert b.snapshot()["requests_total"] == base
            # no dirty read: the other session sees the committed row
            assert s2.execute(
                "select v from kv where k = 33").rows() == [(330,)]
            sess.execute("rollback")
            assert sess.execute(
                "select v from kv where k = 33").rows() == [(330,)]
        finally:
            s2.close()

    def test_serving_disabled_solo_path_identical(self, sess):
        with sess.settings.override(serving_enabled=False):
            b = batcher_for(sess.data_dir)
            base = b.snapshot()["requests_total"]
            r = sess.execute("select v from kv where k = 21")
            assert r.rows() == [(210,)]
            assert b.snapshot()["requests_total"] == base

    def test_requester_side_counters_fold(self, sess):
        before = _serving_counter(s=sess,
                                  name=sc.SERVING_BATCHED_LOOKUPS_TOTAL)
        sess.execute("select v from kv where k = 23")
        assert _serving_counter(
            sess, sc.SERVING_BATCHED_LOOKUPS_TOTAL) == before + 1
        assert _serving_counter(
            sess, sc.SERVING_BATCH_DISPATCH_TOTAL) >= 1


# ---------------------------------------------------------------------------
# batched index reader


class TestReadRowsMulti:
    def _hits_by_shard(self, sess, keys):
        """(shard_id → [(key, hits)]) over `keys` that have index hits."""
        out: dict[int, list] = {}
        for shard in sess.catalog.table_shards("kv"):
            for k in keys:
                hits = pkindex.lookup(sess.store, "kv", shard.shard_id,
                                      "k", k)
                if hits:
                    out.setdefault(shard.shard_id, []).append((k, hits))
        return out

    def test_multi_matches_solo(self, sess):
        by_shard = self._hits_by_shard(sess, list(range(1, 40)))
        sid, pairs = max(by_shard.items(), key=lambda kv: len(kv[1]))
        assert len(pairs) >= 3
        pairs = pairs[:5]
        cols = ["v", "s", "k"]
        multi = pkindex.read_rows_multi(
            sess.store, "kv", sid, cols, [h for _k, h in pairs])
        for (k, hits), (mv, mm, mn) in zip(pairs, multi):
            sv, sm, sn = pkindex.read_rows(sess.store, "kv", sid, cols,
                                           hits)
            assert mn == sn
            for c in cols:
                np.testing.assert_array_equal(mv[c], sv[c])
                np.testing.assert_array_equal(mm[c], sm[c])

    def test_multi_honors_delete_masks(self, sess):
        by_shard = self._hits_by_shard(sess, list(range(1, 40)))
        sid, pairs = max(by_shard.items(), key=lambda kv: len(kv[1]))
        dead_key = pairs[0][0]
        sess.execute(f"delete from kv where k = {dead_key}")
        hits = pkindex.lookup(sess.store, "kv", sid, "k", dead_key)
        assert hits  # index keeps the entry; the mask kills the row
        (vals, mask, n), = pkindex.read_rows_multi(
            sess.store, "kv", sid, ["v"], [hits])
        assert n == 0 and vals["v"].size == 0


# ---------------------------------------------------------------------------
# result cache: CDC invalidation, backstop, bounds


class TestResultCache:
    def test_repeat_hits_and_stat_serving(self, sess):
        q = "select v, s from kv where k = 9"
        sess.execute(q)
        h0 = _serving_counter(sess, sc.SERVING_CACHE_HITS_TOTAL)
        r = sess.execute(q)
        assert r.rows() == [(90, "n4")]
        assert _serving_counter(
            sess, sc.SERVING_CACHE_HITS_TOTAL) == h0 + 1
        stat = _stat_serving(sess)
        assert stat["cache_hits_total"] >= 1
        assert stat["cache_entries"] >= 1

    def test_cross_session_dml_invalidates_exactly(self, sess, tmp_path):
        s2 = _second(sess, tmp_path)
        try:
            q_kv = "select v from kv where k = 12"
            q_ref = "select count(*) from ref"
            assert sess.execute(q_kv).rows() == [(120,)]
            sess.execute(q_ref)
            inv0 = _serving_counter(
                sess, sc.SERVING_CACHE_INVALIDATIONS_TOTAL)
            s2.execute("update kv set v = 1 where k = 12")
            # the touched table's entry drops; the repeat re-executes
            assert sess.execute(q_kv).rows() == [(1,)]
            assert _serving_counter(
                sess, sc.SERVING_CACHE_INVALIDATIONS_TOTAL) > inv0
            # the untouched table's entry survived and still hits
            h0 = _serving_counter(sess, sc.SERVING_CACHE_HITS_TOTAL)
            sess.execute(q_ref)
            assert _serving_counter(
                sess, sc.SERVING_CACHE_HITS_TOTAL) == h0 + 1
        finally:
            s2.close()

    def test_copy_and_txn_commit_invalidate(self, sess, tmp_path):
        s2 = _second(sess, tmp_path)
        try:
            q = "select count(*) from kv"
            n0 = int(sess.execute(q).rows()[0][0])
            csv = str(tmp_path / "more.csv")
            with open(csv, "w") as f:
                f.write("9001,1,x\n9002,2,y\n")
            s2.execute(f"COPY kv FROM '{csv}' WITH (FORMAT csv)")
            assert int(sess.execute(q).rows()[0][0]) == n0 + 2
            s2.execute("begin")
            s2.execute("delete from kv where k = 9001")
            # not committed yet: the cached count must NOT see it
            assert int(sess.execute(q).rows()[0][0]) == n0 + 2
            s2.execute("commit")
            assert int(sess.execute(q).rows()[0][0]) == n0 + 1
        finally:
            s2.close()

    def test_open_transaction_bypasses_cache(self, sess):
        q = "select v from kv where k = 31"
        assert sess.execute(q).rows() == [(310,)]
        sess.execute("begin")
        sess.execute("update kv set v = 7 where k = 31")
        m0 = _serving_counter(sess, sc.SERVING_CACHE_MISSES_TOTAL)
        h0 = _serving_counter(sess, sc.SERVING_CACHE_HITS_TOTAL)
        assert sess.execute(q).rows() == [(7,)]  # staged row visible
        # neither a hit nor a fill happened inside the txn
        assert _serving_counter(
            sess, sc.SERVING_CACHE_MISSES_TOTAL) == m0
        assert _serving_counter(sess, sc.SERVING_CACHE_HITS_TOTAL) == h0
        sess.execute("rollback")
        assert sess.execute(q).rows() == [(310,)]

    def test_manifest_backstop_catches_journal_missed_write(
            self, sess, tmp_path):
        """cdc.append is post-visibility: a committed write whose
        journal append never landed must STILL invalidate — via the
        manifest-identity re-check on hit."""
        s2 = _second(sess, tmp_path)
        try:
            q = "select v from kv where k = 44"
            assert sess.execute(q).rows() == [(440,)]
            with s2.store.change_log.suppress():  # journal sees nothing
                s2.execute("update kv set v = 4 where k = 44")
            assert sess.execute(q).rows() == [(4,)]
        finally:
            s2.close()

    def test_no_ttl_entry_valid_until_a_write(self, sess):
        import time

        q = "select count(*) from kv where v >= 0"
        sess.execute(q)
        time.sleep(0.05)  # a TTL-based design would be racy here
        h0 = _serving_counter(sess, sc.SERVING_CACHE_HITS_TOTAL)
        sess.execute(q)
        assert _serving_counter(
            sess, sc.SERVING_CACHE_HITS_TOTAL) == h0 + 1

    def test_lru_byte_bound_and_oversized_refusal(self, sess):
        from citus_tpu.serving.result_cache import result_cache_for

        cache = result_cache_for(sess.data_dir)
        cache.clear()
        sess.execute("set serving_result_cache_bytes = 4096")
        for k in range(60, 90):
            sess.execute(f"select v from kv where k = {k}")
        assert 0 < cache.total_bytes <= 4096
        assert 0 < len(cache) < 30
        # an entry bigger than a quarter of the budget is refused —
        # one answer must not evict the whole working set
        sess.execute("set serving_result_cache_bytes = 1000")
        cache.clear()
        sess.execute("select k, v, s from kv where v >= 0")
        assert len(cache) == 0

    def test_cache_fill_fault_is_clean_and_retried(self, sess):
        q = "select count(*) from kv where v >= -5"
        fi.arm("serving.cache_fill", times=1)
        r = sess.execute(q)  # fill faulted → clean retry re-executed
        assert int(r.rows()[0][0]) == 200
        assert sess.stats.counters.snapshot()[sc.RETRIES_TOTAL] >= 1
        sess.execute("set max_statement_retries = 0")
        fi.arm("serving.cache_fill", times=1)
        with pytest.raises(InjectedFault):
            sess.execute("select count(*) from kv where v >= -6")

    def test_uncacheable_statements_skip_the_cache(self, sess):
        m0 = _serving_counter(sess, sc.SERVING_CACHE_MISSES_TOTAL)
        sess.execute("select nextval('does_not_exist')") \
            if False else None
        # volatile UDF call shapes are rejected by cache_key directly
        stmt = parse("select nextval('s1')")[0]
        assert cache_key(stmt, (), sess.catalog, sess.settings,
                         _UDFS) is None
        assert _serving_counter(
            sess, sc.SERVING_CACHE_MISSES_TOTAL) == m0

    def test_view_reads_subscribe_to_base_tables(self, sess):
        sess.execute("create view big as select k, v from kv "
                     "where v >= 1000")
        q = "select count(*) from big"
        n0 = int(sess.execute(q).rows()[0][0])
        sess.execute("update kv set v = v + 10000 where k = 5")
        assert int(sess.execute(q).rows()[0][0]) == n0 + 1


class TestResultCacheUnit:
    def _mk(self, tmp_path):
        d = str(tmp_path / "rc")
        os.makedirs(d, exist_ok=True)
        return d, ResultCache(d)

    def _emit(self, d, lsn, table):
        with open(os.path.join(d, "cdc_changes.jsonl"), "a") as f:
            f.write(json.dumps({"lsn": lsn, "table": table,
                                "kind": "insert", "shard_id": 1,
                                "file": "x", "rows": 1}) + "\n")

    def _res(self, n=3):
        return ResultSet(["a"], {"a": np.arange(n)}, n)

    def test_fill_token_refuses_mid_execution_write(self, tmp_path):
        d, c = self._mk(tmp_path)
        token = c.fill_token()
        self._emit(d, 1, "t")  # a write lands while "executing"
        assert not c.put(("k",), self._res(), ["t"], {}, token, 1 << 20)
        # a fresh token fills fine
        assert c.put(("k",), self._res(), ["t"], {}, c.fill_token(),
                     1 << 20)

    def test_table_indexed_invalidation(self, tmp_path):
        d, c = self._mk(tmp_path)
        t = c.fill_token()
        c.put(("ka",), self._res(), ["a"], {}, t, 1 << 20)
        c.put(("kb",), self._res(), ["b"], {}, t, 1 << 20)
        c.put(("kab",), self._res(), ["a", "b"], {}, t, 1 << 20)
        self._emit(d, 1, "a")
        assert c.get(("kb",)) is not None
        assert c.get(("ka",)) is None
        assert c.get(("kab",)) is None
        assert c.invalidations == 2

    def test_journal_regression_drops_everything(self, tmp_path):
        d, c = self._mk(tmp_path)
        self._emit(d, 1, "a")
        c.fill_token()  # consume to the tail
        c.put(("ka",), self._res(), ["a"], {}, c.fill_token(), 1 << 20)
        path = os.path.join(d, "cdc_changes.jsonl")
        with open(path, "w"):
            pass  # restore_cluster replaced the journal
        assert c.get(("ka",)) is None
        assert len(c) == 0


class TestChangeFeedCursor:
    def test_incremental_poll_and_torn_tail(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        cur = ChangeFeedCursor(path)
        with open(path, "a") as f:
            f.write(json.dumps({"lsn": 1, "table": "a"}) + "\n")
            f.write(json.dumps({"lsn": 2, "table": "b"}) + "\n")
        evs = cur.poll()
        assert [e["lsn"] for e in evs] == [1, 2]
        assert cur.poll() == []
        with open(path, "a") as f:
            f.write('{"lsn": 3, "tab')  # torn mid-append
        assert cur.poll() == []  # unterminated line stays unconsumed
        with open(path, "a") as f:
            f.write('le": "c"}\n')
        assert [e["lsn"] for e in cur.poll()] == [3]
        assert cur.last_lsn == 3

    def test_journal_replacement_returns_none(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps({"lsn": 1, "table": "a"}) + "\n")
            f.write(json.dumps({"lsn": 2, "table": "a"}) + "\n")
        cur = ChangeFeedCursor(path)  # attaches at the tail
        assert cur.poll() == []
        with open(path, "w") as f:
            f.write(json.dumps({"lsn": 1, "table": "a"}) + "\n")
        assert cur.poll() is None  # regressed: resubscribe
        assert cur.poll() == []


# ---------------------------------------------------------------------------
# FeedCache per-table index (satellite regression)


class TestFeedCacheIndex:
    def _feed(self, nbytes=100):
        return CachedFeed(sharded=True, arrays={}, nulls={}, valid=None,
                          capacity=0, nbytes=nbytes)

    def test_invalidation_is_table_indexed(self):
        fc = FeedCache(max_bytes=1 << 20)
        fc.put(("a", 1, "x"), self._feed())
        fc.put(("a", 2, "x"), self._feed())
        fc.put(("b", 1, "x"), self._feed())
        fc.invalidate_table("a", keep_version=2)
        assert fc.get(("a", 1, "x")) is None
        assert fc.get(("a", 2, "x")) is not None
        assert fc.get(("b", 1, "x")) is not None
        assert fc.invalidations == 1
        fc.invalidate_table("b")
        assert fc.get(("b", 1, "x")) is None
        assert fc.invalidations == 2
        assert fc.total_bytes == 100

    def test_eviction_maintains_index(self):
        fc = FeedCache(max_bytes=250)
        fc.put(("a", 1, "x"), self._feed(100))
        fc.put(("a", 1, "y"), self._feed(100))
        fc.put(("a", 1, "z"), self._feed(100))  # evicts the oldest
        assert len(fc) == 2 and fc.total_bytes == 200
        fc.invalidate_table("a")  # the evicted key must not resurface
        assert len(fc) == 0 and fc.total_bytes == 0

    def test_invalidation_hammer_thread_safe(self, sess, tmp_path):
        """Cached-plan-hammer style: point reads + repeated aggregates
        from two sessions while a third hammers DML (every write runs
        the indexed invalidation) — torn-free, exact answers after
        quiescence."""
        s2 = _second(sess, tmp_path)
        w = _second(sess, tmp_path)
        errors: list = []

        def reader(s):
            try:
                for i in range(10):
                    r = s.execute("select v from kv where k = 101")
                    assert len(r.rows()) <= 1
                    s.execute("select count(*), sum(v) from kv")
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def writer():
            try:
                for i in range(10):
                    w.execute(f"update kv set v = {i} where k = 101")
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=reader, args=(s,))
                   for s in (sess, s2) for _ in range(2)]
        threads.append(threading.Thread(target=writer))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        try:
            # a straggler must fail HERE, not corrupt the quiescence
            # asserts below with still-racing reads
            assert not any(t.is_alive() for t in threads), \
                "hammer thread still running after join timeout"
            assert not errors, errors[0]
            final = [s.execute("select v from kv where k = 101").rows()
                     for s in (sess, s2, w)]
            assert final[0] == final[1] == final[2] == [(9,)]
            b = batcher_for(sess.data_dir).snapshot()
            assert b["requests_total"] == (
                b["answered_total"] + b["errored_total"]
                + b["fallback_total"])
        finally:
            s2.close()
            w.close()


# ---------------------------------------------------------------------------
# observability


class TestObservability:
    def test_stat_serving_columns(self, sess):
        sess.execute("select v from kv where k = 2")
        stat = _stat_serving(sess)
        for col in ("requests_total", "answered_total", "errored_total",
                    "fallback_total", "batch_dispatch_total",
                    "batched_lookups_total", "max_batch_seen",
                    "avg_batch_occupancy", "queue_depth",
                    "cache_entries", "cache_bytes", "cache_hits_total",
                    "cache_misses_total", "cache_invalidations_total",
                    "cache_last_lsn"):
            assert col in stat
        assert stat["requests_total"] >= 1
        assert stat["answered_total"] >= 1

    def test_explain_analyze_serving_line(self, sess):
        sess.execute("select v from kv where k = 8")  # fill the cache
        r = sess.execute("explain analyze select v from kv where k = 8")
        text = "\n".join(r.columns["QUERY PLAN"])
        assert "Serving:" in text
        assert "result-cache=cached" in text
        assert "batched lookups=1" in text
        with sess.settings.override(serving_enabled=False):
            r = sess.execute(
                "explain analyze select v from kv where k = 8")
            text = "\n".join(r.columns["QUERY PLAN"])
            assert "Serving: off" in text

    def test_counters_registered_in_snapshot(self, sess):
        snap = sess.stats.counters.snapshot()
        for name in (sc.SERVING_BATCHED_LOOKUPS_TOTAL,
                     sc.SERVING_BATCH_DISPATCH_TOTAL,
                     sc.SERVING_CACHE_HITS_TOTAL,
                     sc.SERVING_CACHE_MISSES_TOTAL,
                     sc.SERVING_CACHE_INVALIDATIONS_TOTAL):
            assert name in snap


# ---------------------------------------------------------------------------
# serving fuzz: cache-on ≡ cache-off under interleaved writes


def _run_serving_fuzz(tmp_path, n_ops: int, seed: int) -> dict:
    from fuzzer import generate_serving

    data_dir = str(tmp_path / "srvfuzz")
    writer = citus_tpu.connect(data_dir=data_dir, n_devices=2)
    writer.execute("CREATE TABLE kv (id INT, v INT)")
    writer.execute("SELECT create_distributed_table('kv', 'id', 4)")
    writer.execute("INSERT INTO kv VALUES " + ", ".join(
        f"({i}, {i * 3})" for i in range(60)))
    on_s = citus_tpu.connect(data_dir=data_dir, n_devices=2)
    off_s = citus_tpu.connect(data_dir=data_dir, n_devices=2,
                              serving_result_cache_bytes=0)
    rng = random.Random(seed)
    state = {"next_id": 60}
    stats = {"reads": 0, "writes": 0}
    try:
        for op in range(n_ops):
            kind, sql, rows = generate_serving(rng, state)
            if kind == "copy":
                csv = str(tmp_path / f"srv_{op}.csv")
                with open(csv, "w") as f:
                    for i, v in rows:
                        f.write(f"{i},{v}\n")
                sql = f"COPY kv FROM '{csv}' WITH (FORMAT csv)"
                kind = "write"
            if kind == "txn_write":
                writer.execute("BEGIN")
                writer.execute(sql)
                writer.execute("COMMIT")
                stats["writes"] += 1
                continue
            if kind == "write":
                writer.execute(sql)
                stats["writes"] += 1
                continue
            stats["reads"] += 1
            got = sorted(on_s.execute(sql).rows())
            want = sorted(off_s.execute(sql).rows())
            assert got == want, (
                f"cache-on diverged from cache-off on {sql!r} "
                f"(step {op}): {got} != {want}")
        hits = on_s.stats.counters.snapshot()[
            sc.SERVING_CACHE_HITS_TOTAL]
        assert hits > 0, "fuzz run never hit the cache — no coverage"
        stats["cache_hits"] = hits
        return stats
    finally:
        writer.close()
        on_s.close()
        off_s.close()


def test_serving_fuzz_smoke_slice(tmp_path):
    """Deterministic tier-1 slice: repeated reads with the result cache
    on vs off return identical rows under interleaved DML/COPY/txn
    writes from a second session (CDC-driven invalidation, no TTLs)."""
    stats = _run_serving_fuzz(tmp_path, n_ops=50, seed=814)
    assert stats["reads"] >= 20 and stats["writes"] >= 5


@pytest.mark.slow
def test_serving_fuzz_full(tmp_path):
    stats = _run_serving_fuzz(tmp_path, n_ops=350, seed=20260803)
    assert stats["reads"] >= 150 and stats["writes"] >= 50
