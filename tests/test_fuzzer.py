"""Randomized query fuzzing vs the sqlite oracle (VERDICT round-2 item 9;
reference: src/test/regress/citus_tests/query_generator/).

Two entry points: `test_fuzz_smoke` is the deterministic 12-query
tier-1 slice; the full `test_fuzz_against_oracle` is `slow` (FUZZ_N
env overrides its query count, default 60 — each unique query pays an
XLA compile, ~930 s measured on the 1-core tier-1 sandbox;
FUZZ_N=500 is the long validation run; FUZZ_SEED pins the run).  A
mismatch shrinks to the smallest failing query and reports its SQL —
add that SQL to test_regressions.py when fixing.
"""

import os
import random

import pytest

import citus_tpu
from citus_tpu.errors import PlanningError
from citus_tpu.ingest import tpch
from fuzzer import Fuzz, generate, shrink
from oracle import compare_results, make_oracle, run_oracle

DATE_COLUMNS = {
    "orders": ["o_orderdate"],
    "lineitem": ["l_shipdate", "l_commitdate", "l_receiptdate"],
}


@pytest.fixture(scope="module")
def fuzz_env(tmp_path_factory):
    # feedback recompiles each unique query once (tightened buffers) —
    # doubling this module's XLA compile count would cross the
    # per-process jaxlib crash threshold pytest.ini documents.  The
    # feedback path has its own coverage (test_prepared, isolation);
    # fuzzing targets planner/executor SEMANTICS, so run it off here.
    sess = citus_tpu.connect(
        data_dir=str(tmp_path_factory.mktemp("fuzz_tpch")),
        n_devices=4, compute_dtype="float64",
        enable_capacity_feedback=False)
    tpch.load_into_session(sess, sf=0.002, seed=23, shard_count=8)
    conn = make_oracle(tpch.generate_tables(0.002, seed=23), DATE_COLUMNS)
    return sess, conn


def _run_both(sess, conn, q: Fuzz) -> str | None:
    """None = agree; a string = mismatch description."""
    sql = q.sql()
    try:
        got = sess.execute(sql)
    except PlanningError:
        # unsupported shape is a clean refusal, not a wrong answer
        return None
    want = run_oracle(conn, sql)
    ordered = q.order_limit is not None
    try:
        compare_results(got.rows(), want, ordered, 1e-6)
    except AssertionError as e:
        return str(e)
    return None


@pytest.mark.slow
def test_fuzz_against_oracle(fuzz_env):
    """The full fuzz run.  Marked `slow` (wlm round): tools/t1_times.py
    measured it at ~930 s on the 1-core tier-1 sandbox — alone larger
    than the whole 870 s gate, so the timed run died inside it and
    every alphabetically-later file lost its dots.  Tier-1 fuzz
    coverage rides test_fuzz_smoke below; FUZZ_N=500 stays the long
    validation run."""
    _fuzz_run(fuzz_env, int(os.environ.get("FUZZ_N", "60")),
              int(os.environ.get("FUZZ_SEED", "20260730")))


def test_fuzz_smoke(fuzz_env):
    """Deterministic tier-1 slice: same generator/oracle/shrinker,
    bounded query count (the chaos-soak smoke-slice pattern)."""
    _fuzz_run(fuzz_env, 12, 20260731, sanity=False)


def _fuzz_run(fuzz_env, n: int, seed: int, sanity: bool = True):
    sess, conn = fuzz_env
    log_path = os.environ.get("FUZZ_LOG")  # crash forensics: last line =
    rng = random.Random(seed)              # the query that was executing
    planning_rejects = 0
    for i in range(n):
        q = generate(rng)
        sql = q.sql()
        if log_path:
            with open(log_path, "a") as f:
                f.write(f"{i}\t{sql}\n")
                f.flush()
        try:
            mismatch = _run_both(sess, conn, q)
        except Exception as e:  # engine crash — shrink it too
            mismatch = f"exception: {type(e).__name__}: {e}"
        if mismatch is None:
            continue

        def still_fails(cand: Fuzz) -> bool:
            try:
                return _run_both(sess, conn, cand) is not None
            except Exception:
                return True

        small = shrink(q, still_fails)
        pytest.fail(
            f"fuzz query #{i} (seed {seed}) disagrees with oracle.\n"
            f"original: {sql}\n"
            f"shrunk:   {small.sql()}\n"
            f"mismatch: {mismatch}")
    if not sanity:
        return
    # sanity: the generator must mostly produce supported queries
    sanity_rng = random.Random(seed + 1)
    for _ in range(50):
        q = generate(sanity_rng)
        try:
            sess.execute(q.sql())
        except PlanningError:
            planning_rejects += 1
        except Exception:
            pass
    assert planning_rejects < 40, \
        "generator emits mostly-unsupported queries; tighten the grammar"
