"""Outer joins (LEFT/RIGHT/FULL) cross-checked against the sqlite oracle,
over every distribution strategy (colocated, broadcast/reference,
repartition) on the 8-device virtual mesh.

Reference semantics: planner/multi_router_planner.c:187 and pushdown
planning handle LEFT/RIGHT/FULL; Q13 is the canonical outer-join TPC-H
shape (customer LEFT JOIN orders with an ON-side filter).
"""

import pytest

import citus_tpu
from citus_tpu.errors import PlanningError
from citus_tpu.ingest import tpch
from oracle import compare_results, make_oracle, run_oracle

DATE_COLUMNS = {
    "orders": ["o_orderdate"],
    "lineitem": ["l_shipdate", "l_commitdate", "l_receiptdate"],
}


@pytest.fixture(scope="module")
def sess(tmp_path_factory):
    s = citus_tpu.connect(
        data_dir=str(tmp_path_factory.mktemp("outer")),
        n_devices=8, compute_dtype="float64")
    tpch.load_into_session(s, sf=0.002, seed=11, shard_count=8)
    return s


@pytest.fixture(scope="module")
def conn():
    data = tpch.generate_tables(0.002, seed=11)
    return make_oracle(data, DATE_COLUMNS)


def check(sess, conn, sql, tol=1e-6):
    result = sess.execute(sql)
    want = run_oracle(conn, sql)
    ordered = "order by" in sql.lower()
    compare_results(result.rows(), want, ordered, tol)
    return result


class TestLeftJoin:
    def test_colocated_left(self, sess, conn):
        # orders ⋈ lineitem share the orderkey sharding: local strategy
        check(sess, conn, """
            select o_orderkey, count(l_orderkey)
            from orders left join lineitem on o_orderkey = l_orderkey
            group by o_orderkey order by o_orderkey limit 50""")

    def test_broadcast_left(self, sess, conn):
        # nation is a reference table (replicated build side)
        check(sess, conn, """
            select c_custkey, n_name
            from customer left join nation
              on c_nationkey = n_nationkey and n_nationkey < 5
            order by c_custkey limit 40""")

    def test_repartition_left(self, sess, conn):
        # customer joined on a non-distribution column of orders
        check(sess, conn, """
            select c_custkey, count(o_orderkey)
            from customer left join orders on c_custkey = o_custkey
            group by c_custkey order by c_custkey limit 60""")

    def test_left_where_is_null_anti_join(self, sess, conn):
        check(sess, conn, """
            select count(*)
            from customer left join orders on c_custkey = o_custkey
            where o_orderkey is null""")

    def test_q13_shape(self, sess, conn):
        # TPC-H Q13: ON-side filter on the nullable side + grouped counts
        check(sess, conn, """
            select c_count, count(*) as custdist from (
              select c_custkey, count(o_orderkey) as c_count
              from customer left join orders
                on c_custkey = o_custkey
                and o_comment not like '%special%requests%'
              group by c_custkey
            ) as c_orders
            group by c_count
            order by custdist desc, c_count desc""")

    def test_left_preserves_where_on_preserved_side(self, sess, conn):
        check(sess, conn, """
            select c_custkey, o_orderkey
            from customer left join orders on c_custkey = o_custkey
            where c_custkey < 20
            order by c_custkey, o_orderkey""")


class TestRightFullJoin:
    def test_right_join(self, sess, conn):
        check(sess, conn, """
            select o_custkey, c_name
            from orders right join customer on o_custkey = c_custkey
            order by c_name limit 50""")

    def test_right_join_broadcast_build(self, sess, conn):
        # replicated build side must not duplicate unmatched rows per device
        check(sess, conn, """
            select count(*)
            from customer right join nation on c_nationkey = n_nationkey""")

    def test_full_join(self, sess, conn):
        check(sess, conn, """
            select count(*)
            from customer full join orders on c_custkey = o_custkey""")

    def test_full_join_counts_unmatched_both(self, sess, conn):
        check(sess, conn, """
            select count(*) from (
              select c_custkey, o_orderkey
              from customer full join orders on c_custkey = o_custkey
              where c_custkey is null or o_orderkey is null
            ) as unmatched""")


class TestOuterJoinEdgeCases:
    def test_null_keys_never_match_but_emit(self, sess):
        s2 = citus_tpu.connect(n_devices=4)
        s2.execute("CREATE TABLE l (id INT, k INT)")
        s2.execute("SELECT create_distributed_table('l', 'id', 4)")
        s2.execute("CREATE TABLE r (id INT, k INT)")
        s2.execute("SELECT create_distributed_table('r', 'id', 4)")
        s2.execute("INSERT INTO l VALUES (1, 1), (2, NULL), (3, 3)")
        s2.execute("INSERT INTO r VALUES (10, 1), (11, NULL)")
        rows = s2.execute("""
            SELECT l.id, r.id FROM l
            LEFT JOIN r ON l.k = r.k ORDER BY l.id""").rows()
        # NULL keys match nothing, but rows 2 (left NULL) still emits
        assert rows == [(1, 10), (2, None), (3, None)]
        full = s2.execute("""
            SELECT count(*) FROM l FULL JOIN r ON l.k = r.k""").rows()
        # 1 match + l(2,3 unmatched) + r(11 unmatched) = 4
        assert int(full[0][0]) == 4

    def test_outer_join_requires_equality(self, sess):
        with pytest.raises(PlanningError):
            sess.execute("""
                select count(*) from customer
                left join orders on c_custkey < o_custkey""")

    def test_cross_side_residual_rejected(self, sess):
        with pytest.raises(PlanningError):
            sess.execute("""
                select count(*) from customer
                left join orders
                on c_custkey = o_custkey and c_acctbal > o_totalprice""")

    def test_aggregate_over_nullable_group_key(self, sess, conn):
        # grouping by the nullable side's column: NULL group must appear
        check(sess, conn, """
            select o_orderpriority, count(*)
            from customer left join orders on c_custkey = o_custkey
            group by o_orderpriority order by o_orderpriority""")
