"""Background services: job runner DAG execution, maintenance daemon
duties, background rebalance with live progress.

Reference: utils/background_jobs.c (dependency-ordered parallel tasks,
citus_job_wait/cancel), utils/maintenanced.c:460 (periodic 2PC recovery +
deferred cleanup), shard_rebalancer.c:1165 (citus_rebalance_start).
"""

import threading
import time

import pytest

import citus_tpu
from citus_tpu.background import BackgroundJobRunner, JobStatus


class TestJobRunner:
    def test_dependency_order(self):
        runner = BackgroundJobRunner(max_executors=4)
        order = []
        lock = threading.Lock()

        def step(n):
            def run():
                with lock:
                    order.append(n)
            return run

        job = runner.submit_job("chain", [
            (step(1), "a", []),
            (step(2), "b", [0]),
            (step(3), "c", [1]),
        ])
        assert runner.wait(job, timeout=10) is JobStatus.DONE
        assert order == [1, 2, 3]
        runner.shutdown()

    def test_parallel_fanout(self):
        runner = BackgroundJobRunner(max_executors=4)
        started = []
        gate = threading.Barrier(3, timeout=10)

        def fan(n):
            def run():
                started.append(n)
                gate.wait()  # requires ≥3 concurrent workers to pass
            return run

        job = runner.submit_job("fan", [(fan(i), f"t{i}", [])
                                        for i in range(3)])
        assert runner.wait(job, timeout=10) is JobStatus.DONE
        assert sorted(started) == [0, 1, 2]
        runner.shutdown()

    def test_failure_cancels_dependents(self):
        runner = BackgroundJobRunner(max_executors=2)

        def boom():
            raise ValueError("nope")

        ran = []
        job = runner.submit_job("fail", [
            (boom, "boom", []),
            (lambda: ran.append(1), "dependent", [0]),
        ])
        assert runner.wait(job, timeout=10) is JobStatus.FAILED
        tasks = list(runner.job_status(job).tasks.values())
        assert tasks[0].status is JobStatus.FAILED
        assert "nope" in tasks[0].error
        assert tasks[1].status is JobStatus.CANCELLED
        assert ran == []
        runner.shutdown()

    def test_cancel_scheduled(self):
        runner = BackgroundJobRunner(max_executors=1)
        block = threading.Event()
        job = runner.submit_job("cancellable", [
            (block.wait, "block", []),
            (lambda: None, "later", [0]),
        ])
        runner.cancel(job)
        block.set()
        status = runner.wait(job, timeout=10)
        assert status is JobStatus.CANCELLED
        runner.shutdown()


class TestMaintenanceDaemon:
    def test_periodic_recovery_and_cleanup(self, tmp_data_dir):
        sess = citus_tpu.connect(data_dir=tmp_data_dir,
                                 recover_2pc_interval_ms=50,
                                 defer_shard_delete_interval_ms=50)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and (
                sess.maintenance.recover_runs < 2
                or sess.maintenance.cleanup_runs < 2):
            time.sleep(0.05)
        assert sess.maintenance.recover_runs >= 2
        assert sess.maintenance.cleanup_runs >= 2
        sess.close()
        runs = sess.maintenance.recover_runs
        time.sleep(0.3)
        assert sess.maintenance.recover_runs == runs  # stopped

    def test_disabled_by_negative_interval(self, tmp_data_dir):
        sess = citus_tpu.connect(data_dir=tmp_data_dir,
                                 recover_2pc_interval_ms=-1)
        time.sleep(0.3)
        assert sess.maintenance.recover_runs == 0
        sess.close()


class TestBackgroundRebalance:
    def test_rebalance_runs_in_background_with_progress(self, tmp_data_dir):
        # 1-device mesh but 3 catalog nodes: shards land round-robin, then
        # removing capacity... instead: create skew by adding nodes AFTER
        # table creation so everything sits on the first nodes
        sess = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=1,
                                 rebalance_improvement_threshold=0.05)
        sess.execute("CREATE TABLE t (id INT, v INT)")
        sess.execute("SELECT create_distributed_table('t', 'id', 8)")
        sess.execute("INSERT INTO t VALUES " + ", ".join(
            f"({i}, {i})" for i in range(400)))
        sess.execute("SELECT citus_add_node('extra:1')")
        sess.execute("SELECT citus_add_node('extra:2')")
        r = sess.execute("SELECT citus_rebalance_start()")
        job_id = int(r.rows()[0][0])
        assert job_id > 0
        # queries keep running while the job executes
        total = sess.execute("SELECT sum(v) FROM t").rows()[0][0]
        assert int(total) == sum(range(400))
        status = sess.execute(
            f"SELECT citus_job_wait({job_id})").rows()[0][0]
        assert status == "done"
        prog = sess.execute("SELECT get_rebalance_progress()")
        assert prog.row_count >= 1
        # placements actually spread across nodes now
        nodes_used = {sess.catalog.active_placement(s.shard_id).node_id
                      for s in sess.catalog.table_shards("t")}
        assert len(nodes_used) >= 2
        # data intact after the background moves
        total2 = sess.execute("SELECT sum(v) FROM t").rows()[0][0]
        assert int(total2) == sum(range(400))
        sess.close()

    def test_rebalance_start_noop_when_balanced(self, tmp_data_dir):
        sess = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=1)
        sess.execute("CREATE TABLE t (id INT)")
        sess.execute("SELECT create_distributed_table('t', 'id', 4)")
        r = sess.execute("SELECT citus_rebalance_start()")
        assert int(r.rows()[0][0]) == 0
        sess.close()
