"""Bench-artifact honesty check (VERDICT r5): the README headline table
must quote EXACTLY the newest driver-captured BENCH_r*.json numbers —
never a hotter hand-picked sample.  Tier-1: runs on every commit, skips
cleanly when no bench artifact is present (fresh clones, CI without
driver captures)."""

import glob
import json
import os
import re

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# README table row label → (SF1 metric, SF10 metric)
TABLE_METRICS = {
    "TPC-H Q1": ("tpch_q1_rows_per_sec", None),
    "TPC-H Q3": ("tpch_q3_rows_per_sec", "tpch_q3_sf10_rows_per_sec"),
    "dual-repartition join": ("dual_repartition_join_rows_per_sec",
                              "dual_repartition_join_sf10_rows_per_sec"),
    "single-repartition join": (
        "single_repartition_join_rows_per_sec",
        "single_repartition_join_sf10_rows_per_sec"),
    "co-located join": ("colocated_join_rows_per_sec", None),
}


def _newest_artifact():
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    return paths[-1] if paths else None


def _newest_multichip():
    paths = sorted(glob.glob(os.path.join(ROOT, "MULTICHIP_r*.json")))
    return paths[-1] if paths else None


def _artifact_metrics(path):
    """metric → line dict, parsed from the driver capture's JSON-lines
    tail (the artifact wraps the run's stdout)."""
    with open(path) as f:
        doc = json.load(f)
    out = {}
    for line in doc.get("tail", "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" in obj:
            out[obj["metric"]] = obj
    return out


def _readme_table_rows():
    """label → (sf1 cell, sf10 cell) from the README headline table."""
    with open(os.path.join(ROOT, "README.md")) as f:
        text = f.read()
    m = re.search(r"\| config \| SF1 \| SF10 \|\n\|[-| ]+\|\n"
                  r"((?:\|.*\|\n)+)", text)
    assert m, "README headline table (| config | SF1 | SF10 |) missing"
    rows = {}
    for line in m.group(1).strip().splitlines():
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        assert len(cells) == 3, f"malformed README bench row: {line!r}"
        rows[cells[0]] = (cells[1], cells[2])
    return rows


def _quoted_multiplier(cell):
    """First N.N× multiplier quoted in a table cell (None for '—')."""
    m = re.search(r"(\d+(?:\.\d+)?)×", cell)
    return None if m is None else m.group(1)


def _quoted_cpu_multiplier(cell):
    m = re.search(r"(\d+)× measured CPU", cell)
    return None if m is None else int(m.group(1))


@pytest.fixture(scope="module")
def artifact():
    path = _newest_artifact()
    if path is None:
        pytest.skip("no BENCH_r*.json driver capture present")
    return path


def test_readme_names_the_newest_artifact(artifact):
    with open(os.path.join(ROOT, "README.md")) as f:
        text = f.read()
    name = os.path.basename(artifact)
    assert name.replace(".json", "") in text, (
        f"README bench section must cite the newest driver capture "
        f"({name}), not an older one")


def test_readme_table_matches_newest_artifact(artifact):
    metrics = _artifact_metrics(artifact)
    assert metrics, f"{artifact} has no parseable JSON metric lines"
    rows = _readme_table_rows()
    assert set(rows) == set(TABLE_METRICS), (
        "README table rows drifted from the audited metric map; "
        "update TABLE_METRICS with the new row")
    mismatches = []
    for label, (m1, m10) in TABLE_METRICS.items():
        for cell, metric in zip(rows[label], (m1, m10)):
            quoted = _quoted_multiplier(cell)
            if metric is None:
                if quoted is not None:
                    mismatches.append(
                        f"{label}: quotes {quoted}× but no artifact "
                        "metric is mapped for that column")
                continue
            line = metrics.get(metric)
            if line is None:
                mismatches.append(
                    f"{label}: artifact lacks {metric} but the README "
                    f"quotes {quoted}×")
                continue
            want = f"{line['vs_baseline']:.1f}"
            if quoted != want:
                mismatches.append(
                    f"{label}: README quotes {quoted}× but "
                    f"{os.path.basename(artifact)} says "
                    f"{want}× ({metric})")
            cpu_quoted = _quoted_cpu_multiplier(cell)
            if cpu_quoted is not None:
                vs_cpu = line.get("vs_cpu")
                if vs_cpu is None or round(vs_cpu) != cpu_quoted:
                    mismatches.append(
                        f"{label}: README quotes {cpu_quoted}× "
                        f"measured CPU but the artifact says "
                        f"{vs_cpu} ({metric})")
    assert not mismatches, "README bench table is stale:\n  " + \
        "\n  ".join(mismatches)


def test_readme_memory_pressure_shares_match_artifact(artifact):
    """The memory-pressure section may only quote driver-stamped
    governed/ungoverned completion shares when the newest artifact
    actually contains the memory_pressure lines — and then it must
    quote THOSE shares (the degradation ladder's honesty contract:
    no hand-picked runs)."""
    with open(os.path.join(ROOT, "README.md")) as f:
        text = f.read()
    quoted = re.search(
        r"(\d+(?:\.\d+)?)% governed vs (\d+(?:\.\d+)?)% ungoverned "
        r"\(driver", text)
    metrics = _artifact_metrics(artifact)
    gov = metrics.get("memory_pressure_completed_share_governed")
    ungov = metrics.get("memory_pressure_completed_share_ungoverned")
    if gov is None or ungov is None:
        assert quoted is None, (
            "README quotes driver-stamped memory-pressure shares but "
            f"{os.path.basename(artifact)} has no memory_pressure "
            "capture")
        return
    want = (f"{gov['value'] * 100:g}", f"{ungov['value'] * 100:g}")
    assert quoted is not None, (
        f"{os.path.basename(artifact)} captures memory_pressure "
        f"shares ({want[0]}%/{want[1]}%) but the README quotes no "
        "driver-stamped numbers")
    assert (quoted.group(1), quoted.group(2)) == want, (
        f"README quotes {quoted.group(1)}%/{quoted.group(2)}% but the "
        f"artifact says {want[0]}%/{want[1]}%")


def test_readme_serving_multiplier_matches_artifact(artifact):
    """The serving section may only quote a driver-stamped batched-vs-
    per-statement multiplier when the newest artifact actually contains
    the point_lookup_qps lines — and then it must quote THAT ratio."""
    with open(os.path.join(ROOT, "README.md")) as f:
        text = f.read()
    quoted = re.search(
        r"(\d+(?:\.\d+)?)× the per-statement baseline \(driver", text)
    metrics = _artifact_metrics(artifact)
    full = metrics.get("point_lookup_qps")
    base = metrics.get("point_lookup_qps_baseline")
    if full is None or base is None:
        assert quoted is None, (
            "README quotes a driver-stamped serving multiplier but "
            f"{os.path.basename(artifact)} has no point_lookup_qps "
            "capture")
        return
    want = f"{full['value'] / base['value']:.1f}"
    assert quoted is not None, (
        f"{os.path.basename(artifact)} captures point_lookup_qps "
        f"({want}× baseline) but the README serving section quotes no "
        "driver-stamped multiplier")
    assert quoted.group(1) == want, (
        f"README quotes {quoted.group(1)}× but the artifact says "
        f"{want}×")


@pytest.fixture(scope="module")
def multichip():
    path = _newest_multichip()
    if path is None:
        pytest.skip("no MULTICHIP_r*.json driver capture present")
    return path


def test_multichip_silent_success_shell_impossible(multichip):
    """The r05 failure mode: rc 0, ok true, skipped false — and an
    EMPTY tail, indistinguishable from a run that measured nothing.
    The newest MULTICHIP artifact must either carry evidence (a
    non-empty tail with at least one parseable line) or say WHY it
    was skipped."""
    with open(multichip) as f:
        doc = json.load(f)
    if doc.get("skipped"):
        assert doc.get("skip_reason") or doc.get("reason"), (
            f"{os.path.basename(multichip)} is skipped without a "
            "reason — silent skips are as uninformative as the old "
            "empty-tail shells")
        return
    if doc.get("rc", 1) == 0:
        assert str(doc.get("tail", "")).strip(), (
            f"{os.path.basename(multichip)} claims success (rc 0, not "
            "skipped) with an EMPTY tail — the silent-success shell; "
            "run bench_multichip.py (or dryrun_multichip, which now "
            "prints per-scenario lines) so the artifact carries "
            "evidence")


def test_multichip_artifact_carries_measured_scaling(multichip):
    """bench_multichip.py artifacts must carry rows/s per device count
    AND the derived speedup/efficiency keys — the acceptance shape for
    the scale axis (host fake devices acceptable, stamped as such)."""
    with open(multichip) as f:
        doc = json.load(f)
    if doc.get("skipped"):
        pytest.skip("newest MULTICHIP artifact records a skipped run")
    results = doc.get("results")
    assert results, (
        f"{os.path.basename(multichip)} has no 'results' — regenerate "
        "with bench_multichip.py")
    assert "host_fake_devices" in doc, "fake-device honesty stamp missing"
    for metric, by_n in results.items():
        if metric != "multichip_device_loss_recovery_seconds":
            # the device-loss scenario needs >=2 devices (there is
            # nothing to fail over to on one) — no 1-device baseline
            assert "1" in by_n, f"{metric}: no 1-device baseline row"
        for nd, obj in by_n.items():
            assert obj.get("value"), f"{metric}@{nd}dev: no rows/s"
            assert "host_fake_devices" in obj
    assert doc.get("speedup_vs_1dev"), "speedup_vs_1dev keys missing"
    assert doc.get("scaling_efficiency"), "scaling_efficiency keys missing"


def test_readme_multichip_claims_match_artifact(multichip):
    """The README multi-chip section may only quote driver-stamped
    8-device speedups, and must quote exactly the newest artifact's
    values (same honesty contract as every other bench section)."""
    with open(os.path.join(ROOT, "README.md")) as f:
        text = f.read()
    quoted = re.search(
        r"Q3\s+\*\*(\d+(?:\.\d+)?)×\*\*\s+and\s+the\s+dual-repartition"
        r"\s+shape\s+\*\*(\d+(?:\.\d+)?)×\*\*\s+at\s+8\s+devices", text)
    with open(multichip) as f:
        doc = json.load(f)
    sp = doc.get("speedup_vs_1dev", {})
    q3 = sp.get("multichip_q3_rows_per_sec", {}).get("8")
    dual = sp.get("multichip_dual_repartition_rows_per_sec", {}).get("8")
    if q3 is None or dual is None or doc.get("skipped"):
        assert quoted is None, (
            "README quotes 8-device speedups but "
            f"{os.path.basename(multichip)} has no measured scaling")
        return
    assert quoted is not None, (
        f"{os.path.basename(multichip)} measures Q3 {q3}× / "
        f"dual {dual}× at 8 devices but the README multi-chip section "
        "quotes no driver-stamped numbers")
    assert quoted.group(1) == f"{q3:.2f}" and \
        quoted.group(2) == f"{dual:.2f}", (
        f"README quotes {quoted.group(1)}×/{quoted.group(2)}× but "
        f"{os.path.basename(multichip)} says {q3:.2f}×/{dual:.2f}×")
    assert os.path.basename(multichip).replace(".json", "") in text, (
        "README multi-chip section must cite the newest MULTICHIP "
        "artifact by name")


def test_readme_device_loss_claims_match_artifact(multichip):
    """Any README device-loss/recovery claim is pinned to the newest
    MULTICHIP artifact's device_loss scenario keys — and a scenario
    the README can cite must prove a REAL rescue: an oracle-identical
    answer with queries_rescued_total > 0 (the acceptance bar for the
    mesh fault-tolerance work)."""
    with open(os.path.join(ROOT, "README.md")) as f:
        text = f.read()
    quoted = re.search(
        r"kill-to-first-answer\s+recovery\s+of\s+"
        r"\*\*(\d+(?:\.\d+)?)\s*s\*\*", text)
    with open(multichip) as f:
        doc = json.load(f)
    by_n = doc.get("results", {}).get(
        "multichip_device_loss_recovery_seconds", {})
    if not by_n or doc.get("skipped"):
        assert quoted is None, (
            "README quotes a device-loss recovery time but "
            f"{os.path.basename(multichip)} carries no device_loss "
            "scenario — regenerate with bench_multichip.py")
        return
    for nd, obj in by_n.items():
        assert obj.get("queries_rescued_total", 0) > 0, (
            f"device_loss@{nd}dev: recovery time without a rescued "
            "query is not a failover measurement")
        assert obj.get("oracle_identical") is True, (
            f"device_loss@{nd}dev: the post-kill answer differed from "
            "the pre-kill oracle — wrong rows, not a recovery")
    if quoted is None:
        return  # measuring without quoting is honest
    top = max(by_n, key=int)
    want = f"{by_n[top]['value']:.2f}"
    assert quoted.group(1) == want, (
        f"README quotes {quoted.group(1)} s recovery but "
        f"{os.path.basename(multichip)} measures {want} s at "
        f"{top} devices")


def test_readme_pipelined_scan_claims_match_artifact(artifact):
    """The pipelined-scan section may only quote driver-stamped numbers
    (the pipelined-vs-eager multiplier, the transfer wall share, the
    bytes-on-wire ratio) when the newest artifact actually carries the
    new scan keys — and then it must quote THOSE values (same honesty
    contract as the serving/memory-pressure sections)."""
    with open(os.path.join(ROOT, "README.md")) as f:
        text = f.read()
    q_ab = re.search(
        r"(\d+(?:\.\d+)?)× the eager cold scan \(driver", text)
    q_share = re.search(
        r"transfer wall share (\d+(?:\.\d+)?)% \(driver", text)
    q_wire = re.search(
        r"(\d+(?:\.\d+)?)% of the decoded bytes cross the wire "
        r"\(driver", text)
    metrics = _artifact_metrics(artifact)
    line = metrics.get("columnar_scan_gb_per_sec")
    eager = metrics.get("columnar_scan_gb_per_sec_eager")
    has_pipeline_keys = (line is not None and eager is not None
                         and "wire_ratio" in line
                         and line.get("scan_pipeline") not in (None,
                                                               "off"))
    if not has_pipeline_keys:
        assert q_ab is None and q_share is None and q_wire is None, (
            "README quotes driver-stamped pipelined-scan numbers but "
            f"{os.path.basename(artifact)} has no pipelined scan "
            "capture (phase keys missing)")
        return
    want_ab = f"{line['value'] / eager['value']:.1f}"
    assert q_ab is not None and q_ab.group(1) == want_ab, (
        f"README pipelined-vs-eager multiplier must quote {want_ab}× "
        f"from {os.path.basename(artifact)} (got "
        f"{q_ab.group(1) if q_ab else None})")
    want_share = f"{line['transfer_wall_share'] * 100:g}"
    assert q_share is not None and q_share.group(1) == want_share, (
        f"README transfer wall share must quote {want_share}% from "
        f"{os.path.basename(artifact)}")
    if q_wire is not None and line.get("wire_ratio") is not None:
        assert q_wire.group(1) == f"{line['wire_ratio'] * 100:g}", (
            f"README wire ratio must quote "
            f"{line['wire_ratio'] * 100:g}% from "
            f"{os.path.basename(artifact)}")


def test_readme_cold_start_claims_match_artifact(artifact):
    """The zero-cold-start section may only quote driver-stamped
    restart/storm speedups (and the zero-redundant-compiles claim)
    when the newest artifact actually carries the cold_start_* keys —
    and then it must quote THOSE values (same honesty contract as the
    serving/memory-pressure/pipelined-scan sections)."""
    with open(os.path.join(ROOT, "README.md")) as f:
        text = f.read()
    q_fa = re.search(
        r"restart-to-first-answer (\d+(?:\.\d+)?)× faster \(driver",
        text)
    q_storm = re.search(
        r"compile-storm p99 (\d+(?:\.\d+)?)× better \(driver", text)
    q_zero = re.search(r"zero redundant compiles \(driver", text)
    metrics = _artifact_metrics(artifact)
    fa = metrics.get("cold_start_first_answer_speedup")
    storm = metrics.get("cold_start_storm_speedup")
    redundant = metrics.get("cold_start_redundant_compiles")
    if fa is None or storm is None:
        assert q_fa is None and q_storm is None and q_zero is None, (
            "README quotes driver-stamped cold-start numbers but "
            f"{os.path.basename(artifact)} has no cold_start capture")
        return
    assert q_fa is not None and \
        q_fa.group(1) == f"{fa['value']:.1f}", (
            f"README restart-to-first-answer speedup must quote "
            f"{fa['value']:.1f}× from {os.path.basename(artifact)}")
    assert q_storm is not None and \
        q_storm.group(1) == f"{storm['value']:.1f}", (
            f"README compile-storm speedup must quote "
            f"{storm['value']:.1f}× from {os.path.basename(artifact)}")
    if q_zero is not None:
        assert redundant is not None and redundant["value"] == 0, (
            "README claims zero redundant compiles but the artifact "
            f"stamps cold_start_redundant_compiles="
            f"{redundant and redundant['value']}")


def test_readme_phase_attribution_requires_trace_derived_keys(artifact):
    """PR-14 honesty gate: phase-attribution numbers (transfer wall
    share, phase_* walls) may be quoted in the README only when the
    newest artifact derived them FROM THE SPAN TRACE (the driver
    stamps `phase_source: "trace"`) — hand-rolled timers and EXPLAIN
    must agree by construction, so a quote not backed by the trace is
    a quote the flight recorder cannot corroborate."""
    with open(os.path.join(ROOT, "README.md")) as f:
        text = f.read()
    q_share = re.search(
        r"transfer wall share (\d+(?:\.\d+)?)% \(driver", text)
    metrics = _artifact_metrics(artifact)
    line = metrics.get("columnar_scan_gb_per_sec") or {}
    trace_derived = line.get("phase_source") == "trace"
    if q_share is not None:
        assert trace_derived, (
            "README quotes a phase-attribution number (transfer wall "
            f"share) but {os.path.basename(artifact)}'s scan line is "
            f"not trace-derived (phase_source="
            f"{line.get('phase_source')!r}); re-run bench.py so the "
            "phase keys come from the span flight recorder")
    # and a trace-derived artifact must carry coherent phase keys
    if trace_derived:
        for key in ("phase_prefetch_decode_seconds",
                    "phase_transfer_dispatch_seconds"):
            assert key in line, f"phase_source=trace without {key}"
