"""TPC-H Q11/Q13/Q15/Q16 vs the sqlite oracle — its own module (and so,
under --dist loadfile, its own worker process) because each query's XLA
compile counts against the per-process jaxlib CPU-backend crash
threshold pytest.ini documents.

These run at SF0.01: at SF0.002 Q11's GERMANY filter can match zero
suppliers, making the oracle comparison vacuous (r4 VERDICT weak #8).
Reference coverage: multi_mx_tpch_query*.sql.
"""

import pytest

import citus_tpu
from citus_tpu.ingest import tpch
from oracle import compare_results, make_oracle, run_oracle

DATE_COLUMNS = {
    "orders": ["o_orderdate"],
    "lineitem": ["l_shipdate", "l_commitdate", "l_receiptdate"],
}


@pytest.fixture(scope="module")
def sf01(tmp_path_factory):
    sess = citus_tpu.connect(
        data_dir=str(tmp_path_factory.mktemp("tpch01")),
        n_devices=8, compute_dtype="float64")
    tpch.load_into_session(sess, sf=0.01, seed=7, shard_count=8)
    conn = make_oracle(tpch.generate_tables(0.01, seed=7), DATE_COLUMNS)
    yield sess, conn
    sess.close()


def check(sess, conn, sql):
    result = sess.execute(sql)
    want = run_oracle(conn, sql)
    compare_results(result.rows(), want,
                    "order by" in sql.lower(), 1e-6)
    return result


class TestTPCHExtra:
    def test_q11(self, sf01):
        r = check(*sf01, tpch.Q11)
        assert r.row_count > 0

    def test_q13(self, sf01):
        r = check(*sf01, tpch.Q13)
        assert r.row_count > 0

    def test_q15(self, sf01):
        r = check(*sf01, tpch.Q15)
        assert r.row_count > 0

    def test_q16(self, sf01):
        r = check(*sf01, tpch.Q16)
        assert r.row_count > 0

    def test_all_22_shapes_in_tree(self):
        # the reference ships TPC-H regress coverage for every query
        # (multi_mx_tpch_query*.sql); 22/22 are registered here
        assert len(tpch.QUERIES) == 22
        assert set(tpch.QUERIES) == {f"Q{i}" for i in range(1, 23)}
