"""CREATE/DROP VIEW: persisted definitions expanded as derived tables.

Reference: the view propagation command layer
(/root/reference/src/backend/distributed/commands/view.c:1-832); here a
single controller persists the definition in the catalog and references
expand at planning time.  Includes TPC-H Q15's standard (view) form.
"""

import pytest

import citus_tpu
from citus_tpu.errors import CatalogError, PlanningError
from citus_tpu.ingest import tpch
from oracle import compare_results, make_oracle, run_oracle

DATE_COLUMNS = {
    "orders": ["o_orderdate"],
    "lineitem": ["l_shipdate", "l_commitdate", "l_receiptdate"],
}


@pytest.fixture(scope="module")
def sess(tmp_path_factory):
    s = citus_tpu.connect(data_dir=str(tmp_path_factory.mktemp("views")),
                          n_devices=4, compute_dtype="float64")
    s.execute("create table vt (k bigint, g bigint, v double precision)")
    s.create_distributed_table("vt", "k", shard_count=4)
    s.execute("insert into vt values (1, 0, 1.5), (2, 0, 2.5), "
              "(3, 1, 10.0), (4, 1, 20.0), (5, 2, 7.0)")
    yield s
    s.close()


class TestViewBasics:
    def test_create_and_select(self, sess):
        sess.execute("create view small as select k, v from vt "
                     "where v < 8.0")
        r = sess.execute("select k from small order by k")
        assert [x for (x,) in r.rows()] == [1, 2, 5]

    def test_view_with_column_aliases(self, sess):
        sess.execute("create view gsum (grp, total) as "
                     "select g, sum(v) from vt group by g")
        r = sess.execute("select grp, total from gsum order by grp")
        assert [(int(g), float(t)) for g, t in r.rows()] == [
            (0, 4.0), (1, 30.0), (2, 7.0)]

    def test_view_joins_base_table(self, sess):
        r = sess.execute(
            "select vt.k, gsum.total from vt, gsum "
            "where vt.g = gsum.grp and vt.k <= 2 order by vt.k")
        assert [(int(k), float(t)) for k, t in r.rows()] == [
            (1, 4.0), (2, 4.0)]

    def test_or_replace(self, sess):
        sess.execute("create or replace view small as "
                     "select k, v from vt where v < 3.0")
        r = sess.execute("select k from small order by k")
        assert [x for (x,) in r.rows()] == [1, 2]

    def test_duplicate_without_replace_raises(self, sess):
        with pytest.raises(CatalogError):
            sess.execute("create view small as select k from vt")

    def test_name_collision_with_table_raises(self, sess):
        with pytest.raises(CatalogError):
            sess.execute("create view vt as select 1 from vt")

    def test_column_count_mismatch_raises(self, sess):
        with pytest.raises(PlanningError):
            sess.execute("create view bad (a, b, c) as select k, v from vt")

    def test_drop(self, sess):
        sess.execute("create view dropme as select k from vt")
        sess.execute("drop view dropme")
        with pytest.raises(Exception):
            sess.execute("select * from dropme")
        with pytest.raises(CatalogError):
            sess.execute("drop view dropme")
        sess.execute("drop view if exists dropme")  # no error

    def test_recursive_view_clean_error(self, sess):
        # CREATE only parses the body, so a self-reference is creatable;
        # use must fail with a clean error, not a RecursionError
        sess.execute("create view rec1 as select k from vt")
        sess.execute("create or replace view rec1 as "
                     "select k from rec1")
        with pytest.raises(PlanningError, match="recursion"):
            sess.execute("select * from rec1")
        sess.execute("drop view rec1")

    def test_table_cannot_shadow_view(self, sess):
        # tables, sequences and views share one relation namespace:
        # a table named like a view would be unreachable (FROM
        # resolution prefers the view)
        sess.execute("create view shadowed as select k from vt")
        with pytest.raises(CatalogError, match="already exists"):
            sess.execute("create table shadowed (x bigint)")
        with pytest.raises(CatalogError, match="already exists"):
            sess.execute("create sequence shadowed")
        sess.execute("drop view shadowed")

    def test_view_in_scalar_subquery(self, sess):
        r = sess.execute("select count(*) from vt where v < "
                         "(select max(total) from gsum)").rows()[0][0]
        assert r == 5


def test_view_persists_across_sessions(tmp_path):
    d = str(tmp_path / "persist")
    s = citus_tpu.connect(data_dir=d, n_devices=2)
    s.execute("create table pt (a bigint)")
    s.create_distributed_table("pt", "a", shard_count=2)
    s.execute("insert into pt values (1), (2), (3)")
    s.execute("create view pv as select a from pt where a > 1")
    s.close()
    s2 = citus_tpu.connect(data_dir=d, n_devices=2)
    r = s2.execute("select a from pv order by a")
    assert [x for (x,) in r.rows()] == [2, 3]
    s2.close()


def test_q15_standard_view_form(tmp_path_factory):
    """TPC-H Q15 exactly as the spec writes it: CREATE VIEW revenue0,
    query, DROP VIEW — cross-checked against sqlite."""
    sess = citus_tpu.connect(
        data_dir=str(tmp_path_factory.mktemp("q15")),
        n_devices=8, compute_dtype="float64")
    tpch.load_into_session(sess, sf=0.01, seed=7, shard_count=8)
    conn = make_oracle(tpch.generate_tables(0.01, seed=7), DATE_COLUMNS)

    view_ddl = """
create view revenue0 (supplier_no, total_revenue) as
  select l_suppkey, sum(l_extendedprice * (1 - l_discount))
  from lineitem
  where l_shipdate >= date '1996-01-01'
    and l_shipdate < date '1996-01-01' + interval '3' month
  group by l_suppkey
"""
    q15 = """
select s_suppkey, s_name, s_address, s_phone, total_revenue
from supplier, revenue0
where s_suppkey = supplier_no
  and total_revenue = (select max(total_revenue) from revenue0)
order by s_suppkey
"""
    sess.execute(view_ddl)
    conn.executescript("""
create view revenue0 (supplier_no, total_revenue) as
  select l_suppkey, sum(l_extendedprice * (1 - l_discount))
  from lineitem
  where l_shipdate >= '1996-01-01' and l_shipdate < '1996-04-01'
  group by l_suppkey;
""")
    result = sess.execute(q15)
    want = run_oracle(conn, q15)
    assert result.row_count > 0
    compare_results(result.rows(), want, True, 1e-6)
    sess.execute("drop view revenue0")
    sess.close()
