"""Shard split, tenant isolation, and deferred cleanup.

Reference: operations/shard_split.c + citus_split_shard_by_split_points.c
(online split), operations/isolate_shards.c (tenant isolation),
operations/shard_cleaner.c (pg_dist_cleanup deferred cleanup).
"""

import glob
import os

import numpy as np
import pytest

import citus_tpu
from citus_tpu.catalog.distribution import (
    INT32_MAX,
    INT32_MIN,
    hash_token,
)
from citus_tpu.errors import CatalogError
from citus_tpu.operations.cleanup import CleanupRegistry


def make_data(sess, rows=400, shards=4):
    sess.execute("CREATE TABLE t (id INT, grp INT, v FLOAT8)")
    sess.execute(f"SELECT create_distributed_table('t', 'id', {shards})")
    sess.execute("CREATE TABLE s (id INT, w INT)")
    sess.execute(
        "SELECT create_distributed_table('s', 'id', 4, 't')"
        .replace(", 4,", f", {shards},"))
    vals = ", ".join(f"({i}, {i % 10}, {i}.5)" for i in range(rows))
    sess.execute(f"INSERT INTO t VALUES {vals}")
    svals = ", ".join(f"({i}, {i * 2})" for i in range(0, rows, 2))
    sess.execute(f"INSERT INTO s VALUES {svals}")


def table_state(sess):
    r1 = sess.execute("SELECT count(*), sum(v) FROM t").rows()[0]
    r2 = sess.execute(
        "SELECT count(*) FROM t, s WHERE t.id = s.id").rows()[0]
    return int(r1[0]), round(float(r1[1]), 2), int(r2[0])


class TestShardSplit:
    def test_split_preserves_data_and_colocation(self, tmp_data_dir):
        sess = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=4)
        make_data(sess)
        before = table_state(sess)
        shard = sess.catalog.table_shards("t")[1]
        mid = (shard.min_value + shard.max_value) // 2
        r = sess.execute(
            f"SELECT citus_split_shard_by_split_points({shard.shard_id}, "
            f"'{mid}')")
        children = [int(x) for x in r.rows()[0][0].split(",")]
        assert len(children) == 2
        # the colocation group grew together
        assert len(sess.catalog.table_shards("t")) == 5
        assert len(sess.catalog.table_shards("s")) == 5
        # bounds are contiguous and renumbered
        mins = sess.catalog.shard_mins("t")
        assert mins[0] == INT32_MIN
        assert list(mins) == sorted(mins)
        shards = sess.catalog.table_shards("t")
        for a, b in zip(shards, shards[1:]):
            assert a.max_value + 1 == b.min_value
        assert shards[-1].max_value == INT32_MAX
        # data intact, colocated join still correct
        assert table_state(sess) == before
        # queries route correctly post-split (pruning by dist col)
        one = sess.execute("SELECT v FROM t WHERE id = 123").rows()
        assert one == [(123.5,)]

    def test_split_survives_reopen(self, tmp_data_dir):
        sess = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=4)
        make_data(sess, rows=200)
        before = table_state(sess)
        shard = sess.catalog.table_shards("t")[0]
        mid = (shard.min_value + shard.max_value) // 2
        sess.execute(
            f"SELECT citus_split_shard_by_split_points({shard.shard_id}, "
            f"'{mid}')")
        sess.close()
        sess2 = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=4)
        assert table_state(sess2) == before
        assert len(sess2.catalog.table_shards("t")) == 5

    def test_parent_dir_cleaned_after_split(self, tmp_data_dir):
        sess = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=4)
        make_data(sess, rows=100)
        shard = sess.catalog.table_shards("t")[2]
        parent_dir = os.path.join(tmp_data_dir, "tables", "t",
                                  f"shard_{shard.shard_id}")
        assert os.path.isdir(parent_dir)
        mid = (shard.min_value + shard.max_value) // 2
        sess.execute(
            f"SELECT citus_split_shard_by_split_points({shard.shard_id}, "
            f"'{mid}')")
        # inline sweep removed the superseded parent dir + manifest rows
        assert not os.path.isdir(parent_dir)
        man = sess.store.manifest("t")
        assert str(shard.shard_id) not in man["shards"]

    def test_invalid_split_points(self, tmp_data_dir):
        sess = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=4)
        make_data(sess, rows=50)
        shard = sess.catalog.table_shards("t")[0]
        with pytest.raises(CatalogError):
            sess.execute(
                f"SELECT citus_split_shard_by_split_points("
                f"{shard.shard_id}, '{shard.max_value}')")
        with pytest.raises(CatalogError):
            sess.execute(
                "SELECT citus_split_shard_by_split_points(999999, '0')")

    def test_crash_mid_split_recovers(self, tmp_data_dir, monkeypatch):
        sess = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=4)
        make_data(sess, rows=100)
        before = table_state(sess)
        shard = sess.catalog.table_shards("t")[1]
        mid = (shard.min_value + shard.max_value) // 2

        import citus_tpu.operations.shard_split as split_mod

        calls = {"n": 0}
        orig = split_mod._rewrite_shard

        def crash_on_second(session, table, parent, child_ids, los, his):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("simulated crash mid-split")
            return orig(session, table, parent, child_ids, los, his)

        monkeypatch.setattr(split_mod, "_rewrite_shard", crash_on_second)
        with pytest.raises(RuntimeError):
            split_mod.split_shard_by_split_points(sess, shard.shard_id,
                                                  [mid])
        monkeypatch.undo()
        # catalog untouched; children cleaned; data consistent
        assert len(sess.catalog.table_shards("t")) == 4
        assert table_state(sess) == before
        assert CleanupRegistry(tmp_data_dir).pending() == []
        # a fresh session also sees a consistent state
        sess2 = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=4)
        assert table_state(sess2) == before


class TestTenantIsolation:
    def test_isolate_tenant(self, tmp_data_dir):
        sess = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=4)
        make_data(sess, rows=300)
        before = table_state(sess)
        r = sess.execute("SELECT isolate_tenant_to_node('t', 42)")
        tenant_shard = int(r.rows()[0][0])
        # the tenant's shard covers exactly its token (up to space edges)
        tok = int(hash_token(np.asarray([42], dtype=np.int32))[0])
        s = sess.catalog.shards[tenant_shard]
        assert s.contains_token(tok)
        assert (s.min_value == tok or s.min_value == INT32_MIN)
        assert (s.max_value == tok or s.max_value == INT32_MAX)
        # all data survives; tenant rows still query correctly
        assert table_state(sess) == before
        rows = sess.execute("SELECT v FROM t WHERE id = 42").rows()
        assert rows == [(42.5,)]
        # only tenant-token rows live in the tenant shard
        vals, _valid, n = sess.store.read_shard("t", tenant_shard, ["id"])
        toks = hash_token(vals["id"])
        assert all(s.contains_token(int(x)) for x in toks)

    def test_isolate_in_string_distributed_table(self, tmp_data_dir):
        sess = citus_tpu.connect(data_dir=tmp_data_dir, n_devices=4)
        sess.execute("CREATE TABLE logs (tenant TEXT, n INT)")
        sess.execute("SELECT create_distributed_table('logs', 'tenant', 4)")
        sess.execute("INSERT INTO logs VALUES " + ", ".join(
            f"('tenant{i % 7}', {i})" for i in range(100)))
        before = sess.execute(
            "SELECT count(*), sum(n) FROM logs").rows()[0]
        sess.execute("SELECT isolate_tenant_to_node('logs', 'tenant3')")
        after = sess.execute(
            "SELECT count(*), sum(n) FROM logs").rows()[0]
        assert before == after
        per_tenant = sess.execute(
            "SELECT count(*) FROM logs WHERE tenant = 'tenant3'").rows()
        assert int(per_tenant[0][0]) == 100 // 7 + (1 if 3 < 100 % 7 else 0)
