"""Multi-query concurrency: overlapping execute() calls from several
threads, concurrent with a background rebalance (VERDICT round-2 item 7;
the reference's adaptive executor runs many tasks concurrently,
executor/adaptive_executor.c:962)."""

import threading

import pytest

import citus_tpu


@pytest.fixture()
def sess(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64")
    s.execute("create table cq (k bigint, g bigint, v bigint)")
    s.create_distributed_table("cq", "k", shard_count=8)
    rows = [(i, i % 7, i * 3) for i in range(1, 1201)]
    s.execute("insert into cq values "
              + ",".join(str(t) for t in rows))
    yield s
    s.close()


EXPECTED_SUM = sum(i * 3 for i in range(1, 1201))


def _worker(sess, errors, n_iters):
    try:
        for i in range(n_iters):
            r = sess.execute("select sum(v), count(*) from cq")
            row = r.rows()[0]
            assert int(row[0]) == EXPECTED_SUM and int(row[1]) == 1200
            r2 = sess.execute(
                f"select v from cq where k = {(i % 1200) + 1}")
            assert int(r2.rows()[0][0]) == ((i % 1200) + 1) * 3
            r3 = sess.execute(
                "select g, count(*) from cq group by g order by g")
            assert sum(int(x[1]) for x in r3.rows()) == 1200
    except Exception as e:  # pragma: no cover - surfaced below
        errors.append(e)


def test_four_threads_with_background_rebalance(sess):
    # skew placements so the rebalancer has real moves to make
    nodes = sess.catalog.active_nodes()
    for shard in sess.catalog.table_shards("cq")[:4]:
        p = sess.catalog.active_placement(shard.shard_id)
        p.node_id = nodes[0].node_id
    sess.catalog._bump()

    errors: list = []
    threads = [threading.Thread(target=_worker,
                                args=(sess, errors, 6))
               for _ in range(4)]
    for t in threads:
        t.start()
    job = sess.execute("select citus_rebalance_start()")
    for t in threads:
        t.join()
    sess.execute("select citus_rebalance_wait()")
    assert not errors, errors[0]
    # post-rebalance correctness
    r = sess.execute("select sum(v) from cq")
    assert int(r.rows()[0][0]) == EXPECTED_SUM


def test_cached_plan_hits_thread_safe_across_sessions(sess, tmp_path):
    """Thread-safety audit regression (wlm round): hammer cached-plan
    hits from two sessions sharing the data_dir AND two threads inside
    each — the executor's capacity memo used to be iterated while
    written (dict-changed-size crash), and the plan/feed caches must
    serve torn-free entries under concurrent get/put."""
    sess2 = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                              compute_dtype="float64",
                              serving_result_cache_bytes=0)
    # serving result cache off (both sessions): this test hammers the
    # PLAN cache — a result-cache hit would short-circuit before it
    sess.execute("set serving_result_cache_bytes = 0")
    # warm both plan caches so the loop runs on the cached-hit path
    for s in (sess, sess2):
        s.execute("select sum(v), count(*) from cq")
        s.execute("select g, count(*) from cq group by g")

    errors: list = []

    def hammer(s):
        try:
            for _ in range(8):
                r = s.execute("select sum(v), count(*) from cq")
                assert int(r.rows()[0][0]) == EXPECTED_SUM
                r2 = s.execute("select g, count(*) from cq group by g")
                assert sum(int(x[1]) for x in r2.rows()) == 1200
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(s,))
               for s in (sess, sess2) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        assert not errors, errors[0]
        for s in (sess, sess2):
            assert s.executor.plan_cache.hits > 0
    finally:
        sess2.close()


def test_parallel_rebalance_moves_not_fully_chained(sess):
    """Moves touching disjoint node pairs must not depend on each other
    (the reference parallelizes across nodes under per-node caps)."""
    nodes = sess.catalog.active_nodes()
    shards = sess.catalog.table_shards("cq")
    # force all shards onto nodes 0 and 1 → moves target nodes 2 and 3
    for i, shard in enumerate(shards):
        p = sess.catalog.active_placement(shard.shard_id)
        p.node_id = nodes[i % 2].node_id
    sess.catalog._bump()
    job_id = sess._start_background_rebalance()
    assert job_id
    sess.jobs.wait(job_id)
    job = next(j for j in sess.jobs.jobs() if j.job_id == job_id)
    move_tasks = sorted(job.tasks.values(),
                        key=lambda t: t.task_id)[:-1]  # drop finalize
    task_ids = [t.task_id for t in move_tasks]
    # a pure chain means task i depends exactly on task i-1; the
    # per-node scheduling must leave at least one move independent
    chained = all(
        t.depends_on == ((task_ids[i - 1],) if i else ())
        for i, t in enumerate(move_tasks))
    assert not chained or len(move_tasks) <= 1

def test_lock_orders_clean_under_sanitizer(tmp_path):
    """The graftlint runtime half: two sessions sharing one data_dir
    (shared WLM/2PL/store managers) run overlapping reads, DML and a
    transaction with the lock-order sanitizer armed — every lock
    created in this scope is order-tracked, and any ABBA inversion
    between the managers raises LockOrderViolation immediately."""
    from citus_tpu.analysis import sanitizer

    sanitizer.reset()
    sanitizer.enable()
    try:
        d = str(tmp_path / "tsan")
        s1 = citus_tpu.connect(data_dir=d, n_devices=4,
                               compute_dtype="float64")
        s1.execute("create table tz (k bigint, v bigint)")
        s1.create_distributed_table("tz", "k", shard_count=4)
        s1.execute("insert into tz values "
                   + ",".join(f"({i}, {i})" for i in range(1, 301)))
        s2 = citus_tpu.connect(data_dir=d, n_devices=4,
                               compute_dtype="float64")
        errors: list = []

        def worker(s, base):
            try:
                for i in range(6):
                    s.execute(f"select sum(v) from tz where k > {base}")
                    s.execute(f"update tz set v = v + 1 "
                              f"where k = {base + i + 1}")
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(s, b))
                   for s, b in ((s1, 0), (s2, 100), (s1, 200))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        s1.execute("begin")
        s1.execute("update tz set v = 0 where k = 1")
        s1.execute("commit")
        assert not errors, errors[0]
        stats = sanitizer.stats()
        assert stats["locks_created"] > 0
        assert stats["acquisitions"] > 100
        s1.close()
        s2.close()
    finally:
        sanitizer.disable()
    assert sanitizer.violations() == [], \
        [str(v) for v in sanitizer.violations()]
