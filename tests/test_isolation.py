"""Pairwise isolation matrix: concurrent-OPERATION semantics.

The reference pins these behaviors with 124 isolation specs under
/root/reference/src/test/regress/spec/ (permutations of steps across
concurrent sessions, e.g. isolation_concurrent_dml.spec's
"in-progress insert blocks concurrent updates").  The fault-injection
matrix (test_fault_injection.py) covers crash seams; this file covers
the other axis: two live operations interleaving.  Every scenario
asserts a semantic invariant — visibility, atomicity, ordering, or
conservation — not just "no exception".

Scenario families:
  A. two-writer 2PL conflicts        (blocking, serialization)
  B. deadlock cycles                 (youngest-victim, retry)
  C. shard split × DML / reads       (conservation, routing)
  D. shard move / rebalance × reads  (consistency, failover)
  E. CDC × concurrent DML            (ordering, replay equivalence)
  F. restore point × concurrent txn  (atomicity of the cut)
  G. health sweep × queries          (no false positives, stability)
  H. background jobs × DDL           (cleanup, idempotence)
  I. 2PC recovery × concurrent reads (roll-forward visibility)
"""

import threading
import time

import pytest

import citus_tpu
from citus_tpu.session import Session
from citus_tpu.transaction.locks import DeadlockDetectedError


def make_session(data_dir, **kw):
    return Session(data_dir=str(data_dir), **kw)


def setup(sess, name="t", rows=24, shards=4):
    sess.execute(f"CREATE TABLE {name} (id INT, v INT)")
    sess.execute(f"SELECT create_distributed_table('{name}', 'id', "
                 f"{shards})")
    if rows:
        vals = ", ".join(f"({i}, {i * 10})" for i in range(rows))
        sess.execute(f"INSERT INTO {name} VALUES {vals}")
    return rows, sum(i * 10 for i in range(rows))


def totals(sess, name="t"):
    row = sess.execute(f"SELECT count(*), sum(v) FROM {name}").rows()[0]
    return int(row[0]), int(row[1])


def run_thread(fn):
    out = {}

    def wrap():
        try:
            out["result"] = fn()
        except Exception as e:  # surfaced by join_thread
            out["error"] = e

    t = threading.Thread(target=wrap)
    t.start()
    return t, out


def join_thread(t, out, timeout=60):
    t.join(timeout=timeout)
    assert not t.is_alive(), "isolation step hung"
    if "error" in out:
        raise out["error"]
    return out.get("result")


# -- A. two-writer 2PL conflicts ----------------------------------------
class TestTwoWriters:
    def test_txn_write_blocks_second_writer(self, tmp_path):
        # isolation_concurrent_dml.spec permutation 1: an in-progress
        # write blocks a concurrent update to the same rows until COMMIT
        s1 = make_session(tmp_path)
        setup(s1)
        s2 = make_session(tmp_path)
        s1.execute("BEGIN")
        s1.execute("UPDATE t SET v = 1 WHERE id = 3")
        started = threading.Event()

        def blocked():
            started.set()
            s2.execute("UPDATE t SET v = 2 WHERE id = 3")

        t, out = run_thread(blocked)
        started.wait(10)
        time.sleep(0.3)
        assert t.is_alive(), "second writer should block on the 2PL lock"
        s1.execute("COMMIT")
        join_thread(t, out)
        # s2 applied AFTER s1: last-committed value wins
        assert int(s1.execute(
            "SELECT v FROM t WHERE id = 3").rows()[0][0]) == 2

    def test_serialized_increments_conserve_both(self, tmp_path):
        # two read-modify-write transactions on one row serialize: both
        # increments survive (lost-update prevention via 2PL)
        s1 = make_session(tmp_path)
        setup(s1)
        s2 = make_session(tmp_path)

        def inc(s):
            def go():
                s.execute("BEGIN")
                s.execute("UPDATE t SET v = v + 1 WHERE id = 5")
                time.sleep(0.1)
                s.execute("COMMIT")
            return go

        t1, o1 = run_thread(inc(s1))
        t2, o2 = run_thread(inc(s2))
        join_thread(t1, o1)
        join_thread(t2, o2)
        assert int(s1.execute(
            "SELECT v FROM t WHERE id = 5").rows()[0][0]) == 52

    def test_disjoint_shard_writers_do_not_block(self, tmp_path):
        # writes to different shards must proceed concurrently (the
        # reference's per-shard lock granularity, not a table lock)
        s1 = make_session(tmp_path)
        setup(s1)
        s2 = make_session(tmp_path)
        ids = [s.shard_id for s in s1.catalog.table_shards("t")]
        assert len(ids) >= 2
        # find two ids routed to different shards
        import numpy as np

        from citus_tpu.catalog.distribution import hash_token
        by_shard = {}
        for i in range(24):
            tok = int(hash_token(np.asarray([i], dtype=np.int64))[0])
            for sh in s1.catalog.table_shards("t"):
                if sh.contains_token(tok):
                    by_shard.setdefault(sh.shard_id, i)
        a, b = list(by_shard.values())[:2]
        s1.execute("BEGIN")
        s1.execute(f"UPDATE t SET v = 1 WHERE id = {a}")
        done = threading.Event()

        def other():
            s2.execute(f"UPDATE t SET v = 2 WHERE id = {b}")
            done.set()

        t, out = run_thread(other)
        assert done.wait(20), \
            "disjoint-shard writer must not wait on s1's lock"
        join_thread(t, out)
        s1.execute("COMMIT")

    def test_insert_vs_update_conservation(self, tmp_path):
        # concurrent INSERT txn + UPDATE autocommit: whatever the
        # interleaving, committed state shows both effects exactly once
        s1 = make_session(tmp_path)
        n, sm = setup(s1)
        s2 = make_session(tmp_path)
        s1.execute("BEGIN")
        s1.execute("INSERT INTO t VALUES (100, 1000)")

        def upd():
            s2.execute("UPDATE t SET v = v + 5 WHERE id = 1")

        t, out = run_thread(upd)
        time.sleep(0.2)
        s1.execute("COMMIT")
        join_thread(t, out)
        assert totals(s1) == (n + 1, sm + 1000 + 5)


# -- B. deadlock cycles --------------------------------------------------
class TestDeadlocks:
    def test_three_session_cycle_one_victim(self, tmp_path):
        # a 3-cycle in the wait graph: exactly one youngest victim is
        # cancelled, the other two commit (lock_graph.c:142 +
        # distributed_deadlock_detection.c youngest-victim rule)
        s = [make_session(tmp_path) for _ in range(3)]
        for i in range(3):
            setup(s[0] if i == 0 else s[i], name=f"d{i}", rows=2)
        barrier = threading.Barrier(3, timeout=30)
        outcome = {}

        def worker(i):
            def go():
                si = s[i]
                si.execute("BEGIN")
                si.execute(f"UPDATE d{i} SET v = {i}")
                barrier.wait()
                try:
                    si.execute(f"UPDATE d{(i + 1) % 3} SET v = {i}")
                    si.execute("COMMIT")
                    outcome[i] = "ok"
                except DeadlockDetectedError:
                    outcome[i] = "victim"
            return go

        threads = [run_thread(worker(i)) for i in range(3)]
        for t, out in threads:
            join_thread(t, out, timeout=90)
        assert sorted(outcome.values()) == ["ok", "ok", "victim"], outcome

    def test_victim_retry_commits(self, tmp_path):
        # after cancellation the victim's retry must succeed and both
        # transactions' effects land (the reference expects clients to
        # retry serialization failures)
        s1 = make_session(tmp_path)
        setup(s1, name="a", rows=2)
        setup(s1, name="b", rows=2)
        s2 = make_session(tmp_path)
        barrier = threading.Barrier(2, timeout=30)

        def w(s, first, second, tag, outcome):
            def go():
                for attempt in range(6):
                    s.execute("BEGIN")
                    try:
                        s.execute(f"UPDATE {first} SET v = v + 1")
                        if attempt == 0:
                            barrier.wait()
                        s.execute(f"UPDATE {second} SET v = v + 1")
                        s.execute("COMMIT")
                        outcome[tag] = "ok"
                        return
                    except DeadlockDetectedError:
                        outcome[tag] = "retrying"
                        # rolled back automatically; back off like a
                        # real client (an instant retry can re-enter
                        # the same cycle and lose again)
                        time.sleep(0.05 * (attempt + 1))
            return go

        outcome = {}
        t1, o1 = run_thread(w(s1, "a", "b", "s1", outcome))
        t2, o2 = run_thread(w(s2, "b", "a", "s2", outcome))
        join_thread(t1, o1, 90)
        join_thread(t2, o2, 90)
        assert outcome == {"s1": "ok", "s2": "ok"}
        # both increments applied to both tables
        assert int(s1.execute(
            "SELECT sum(v) FROM a").rows()[0][0]) == 10 + 2 * 2
        assert int(s1.execute(
            "SELECT sum(v) FROM b").rows()[0][0]) == 10 + 2 * 2


# -- C. shard split × DML / reads ---------------------------------------
class TestSplitInterleavings:
    def test_split_with_concurrent_inserts_conserves_rows(self, tmp_path):
        # isolation_blocking_shard_split.spec: rows inserted while a
        # split runs are present exactly once afterwards
        s1 = make_session(tmp_path)
        setup(s1, rows=40)
        s2 = make_session(tmp_path)
        stop = threading.Event()
        inserted = []

        def inserter():
            k = 1000
            while not stop.is_set():
                s2.execute(f"INSERT INTO t VALUES ({k}, {k})")
                inserted.append(k)
                k += 1
            return inserted

        t, out = run_thread(inserter)
        time.sleep(0.1)
        for shard in list(s1.catalog.table_shards("t"))[:2]:
            mid = (shard.min_value + shard.max_value) // 2
            s1.execute("SELECT citus_split_shard_by_split_points("
                       f"{shard.shard_id}, '{mid}')")
        time.sleep(0.2)
        stop.set()
        join_thread(t, out)
        n, sm = totals(s1)
        assert n == 40 + len(inserted)
        assert sm == sum(i * 10 for i in range(40)) + sum(inserted)
        # every inserted row routes correctly post-split
        for k in inserted[:3] + inserted[-3:]:
            assert int(s1.execute(
                f"SELECT v FROM t WHERE id = {k}").rows()[0][0]) == k

    def test_split_waits_for_inflight_txn(self, tmp_path):
        # a split of a shard with an uncommitted write must not lose the
        # write: it either blocks until COMMIT or sees the committed row
        s1 = make_session(tmp_path)
        setup(s1, rows=16)
        s2 = make_session(tmp_path)
        s1.execute("BEGIN")
        s1.execute("UPDATE t SET v = 7777 WHERE id = 2")
        import numpy as np

        from citus_tpu.catalog.distribution import hash_token
        tok = int(hash_token(np.asarray([2], dtype=np.int64))[0])
        shard = next(sh for sh in s1.catalog.table_shards("t")
                     if sh.contains_token(tok))
        mid = (shard.min_value + shard.max_value) // 2

        def splitter():
            s2.execute("SELECT citus_split_shard_by_split_points("
                       f"{shard.shard_id}, '{mid}')")

        t, out = run_thread(splitter)
        time.sleep(0.3)
        s1.execute("COMMIT")
        try:
            join_thread(t, out, 60)
        except Exception:
            pass  # a clean refusal is acceptable; losing the write is not
        assert int(s1.execute(
            "SELECT v FROM t WHERE id = 2").rows()[0][0]) == 7777

    def test_reads_stable_during_split(self, tmp_path):
        s1 = make_session(tmp_path)
        n, sm = setup(s1, rows=60)
        s2 = make_session(tmp_path)
        stop = threading.Event()

        def reader():
            checks = 0
            while not stop.is_set():
                assert totals(s2) == (n, sm)
                checks += 1
            return checks

        t, out = run_thread(reader)
        for shard in list(s1.catalog.table_shards("t"))[:3]:
            mid = (shard.min_value + shard.max_value) // 2
            s1.execute("SELECT citus_split_shard_by_split_points("
                       f"{shard.shard_id}, '{mid}')")
        stop.set()
        checks = join_thread(t, out)
        assert checks > 0
        assert totals(s1) == (n, sm)


# -- D. shard move / rebalance × reads ----------------------------------
class TestMoveInterleavings:
    def test_reads_consistent_during_move(self, tmp_path):
        s1 = make_session(tmp_path)
        n, sm = setup(s1, rows=50)
        s2 = make_session(tmp_path)
        nodes = s1.catalog.active_nodes()
        shard = s1.catalog.table_shards("t")[0]
        cur = s1.catalog.active_placement(shard.shard_id).node_id
        target = next(x for x in nodes if x.node_id != cur)
        stop = threading.Event()

        def reader():
            checks = 0
            while not stop.is_set():
                assert totals(s2) == (n, sm)
                checks += 1
            return checks

        t, out = run_thread(reader)
        s1.execute(f"SELECT citus_move_shard_placement({shard.shard_id}, "
                   f"'{target.name}')")
        stop.set()
        assert join_thread(t, out) > 0
        assert s1.catalog.active_placement(shard.shard_id).node_id \
            == target.node_id
        assert totals(s1) == (n, sm)

    def test_insert_during_rebalance_conserved(self, tmp_path):
        s1 = make_session(tmp_path)
        setup(s1, rows=30)
        s2 = make_session(tmp_path)
        # skew placements so the rebalancer makes real moves
        nodes = s1.catalog.active_nodes()
        for shard in s1.catalog.table_shards("t"):
            s1.catalog.active_placement(shard.shard_id).node_id = \
                nodes[0].node_id
        s1.catalog._bump()
        stop = threading.Event()
        inserted = []

        def inserter():
            k = 500
            while not stop.is_set():
                s2.execute(f"INSERT INTO t VALUES ({k}, 1)")
                inserted.append(k)
                k += 1

        t, out = run_thread(inserter)
        s1.execute("SELECT citus_rebalance_start()")
        s1.execute("SELECT citus_rebalance_wait()")
        stop.set()
        join_thread(t, out)
        n, _sm = totals(s1)
        assert n == 30 + len(inserted)

    def test_failover_read_after_node_death(self, tmp_path):
        # replication factor 2: killing one node's placements mid-loop
        # must not break reads (catalog failover to the replica)
        s1 = make_session(tmp_path, shard_replication_factor=2)
        n, sm = setup(s1, rows=30)
        assert totals(s1) == (n, sm)
        victim = s1.catalog.active_nodes()[0]
        s1.catalog.disable_node(victim.name)
        assert totals(s1) == (n, sm)  # replicas answer


# -- E. CDC × concurrent DML --------------------------------------------
class TestCdcInterleavings:
    def test_concurrent_writers_lsn_order_and_replay(self, tmp_path):
        # two writers race; the change feed must still be a total order
        # (strictly increasing LSNs) whose replay reproduces final state
        s1 = make_session(tmp_path)
        setup(s1, rows=0)
        s2 = make_session(tmp_path)

        def writer(s, base):
            def go():
                for i in range(8):
                    s.execute(f"INSERT INTO t VALUES ({base + i}, "
                              f"{(base + i) * 10})")
            return go

        t1, o1 = run_thread(writer(s1, 0))
        t2, o2 = run_thread(writer(s2, 100))
        join_thread(t1, o1)
        join_thread(t2, o2)
        events = s1.change_events("t")
        lsns = [e["lsn"] for e in events]
        assert lsns == sorted(lsns) and len(set(lsns)) == len(lsns)
        inserted = set()
        for e in events:
            if e["kind"] == "insert":
                values, _valid = s1.change_rows(e)
                for v in values["id"]:
                    inserted.add(int(v))
        assert inserted == set(range(8)) | set(range(100, 108))

    def test_feed_cutoff_is_prefix_consistent(self, tmp_path):
        # reading the feed WHILE a writer commits: events up to any lsn
        # form a prefix (no torn suffix, no out-of-order late arrivals)
        s1 = make_session(tmp_path)
        setup(s1, rows=0)
        s2 = make_session(tmp_path)
        stop = threading.Event()

        def writer():
            k = 0
            while not stop.is_set():
                s2.execute(f"INSERT INTO t VALUES ({k}, 1)")
                k += 1
            return k

        t, out = run_thread(writer)
        seen_max = 0
        for _ in range(10):
            events = s1.change_events("t")
            lsns = [e["lsn"] for e in events]
            assert lsns == sorted(lsns)
            assert not lsns or lsns[-1] >= seen_max
            seen_max = max(seen_max, lsns[-1] if lsns else 0)
        stop.set()
        total = join_thread(t, out)
        assert len(s1.change_events("t")) == total


# -- F. restore point × concurrent txn ----------------------------------
class TestRestoreInterleavings:
    def test_restore_point_excludes_inflight_txn(self, tmp_path):
        from citus_tpu.operations.restore_point import restore_cluster

        s1 = make_session(tmp_path / "d")
        n, sm = setup(s1, rows=10)
        s2 = make_session(tmp_path / "d")
        s2.execute("BEGIN")
        s2.execute("INSERT INTO t VALUES (999, 9990)")
        s1.execute("SELECT citus_create_restore_point('cut')")
        s2.execute("COMMIT")
        assert totals(s1) == (n + 1, sm + 9990)
        s1.close()
        s2.close()
        restore_cluster(str(tmp_path / "d"), "cut")
        s3 = make_session(tmp_path / "d")
        # the uncommitted-at-cut transaction is absent after restore
        assert totals(s3) == (n, sm)

    def test_restore_cut_is_atomic_under_concurrent_inserts(self,
                                                            tmp_path):
        from citus_tpu.operations.restore_point import restore_cluster

        s1 = make_session(tmp_path / "d")
        setup(s1, rows=0)
        s2 = make_session(tmp_path / "d")
        stop = threading.Event()

        def inserter():
            k = 0
            while not stop.is_set():
                s2.execute(f"INSERT INTO t VALUES ({k}, {k * 3})")
                k += 1
            return k

        t, out = run_thread(inserter)
        time.sleep(0.3)
        s1.execute("SELECT citus_create_restore_point('mid')")
        time.sleep(0.2)
        stop.set()
        total = join_thread(t, out)
        s1.close()
        s2.close()
        restore_cluster(str(tmp_path / "d"), "mid")
        s3 = make_session(tmp_path / "d")
        n, sm = totals(s3)
        # whole prefix of inserts: count k rows ⇒ ids 0..k-1 exactly
        assert 0 <= n <= total
        assert sm == sum(i * 3 for i in range(n)), \
            "restored state is not a clean prefix of the insert stream"


# -- G. health sweep × queries ------------------------------------------
class TestHealthInterleavings:
    def test_sweep_during_queries_no_false_positives(self, tmp_path):
        from citus_tpu.operations import health

        s1 = make_session(tmp_path)
        n, sm = setup(s1, rows=20)
        stop = threading.Event()

        def reader():
            checks = 0
            while not stop.is_set():
                assert totals(s1) == (n, sm)
                checks += 1
            return checks

        t, out = run_thread(reader)
        for _ in range(3):
            assert health.health_sweep(s1) == []  # all nodes healthy
        stop.set()
        assert join_thread(t, out) > 0
        assert all(node.is_active
                   for node in s1.catalog.nodes.values())

    def test_sweep_disables_dead_spare_while_queries_run(self, tmp_path):
        from citus_tpu.operations import health

        s1 = make_session(tmp_path)
        n, sm = setup(s1, rows=20)
        s1.catalog.add_node("device:99")  # beyond the mesh: dead
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                assert totals(s1) == (n, sm)

        t, out = run_thread(reader)
        disabled = health.health_sweep(s1)
        stop.set()
        join_thread(t, out)
        assert "device:99" in disabled
        assert totals(s1) == (n, sm)


# -- H. background jobs × DDL -------------------------------------------
class TestBackgroundInterleavings:
    def test_double_rebalance_start_is_safe(self, tmp_path):
        s1 = make_session(tmp_path)
        setup(s1, rows=20)
        nodes = s1.catalog.active_nodes()
        for shard in s1.catalog.table_shards("t"):
            s1.catalog.active_placement(shard.shard_id).node_id = \
                nodes[0].node_id
        s1.catalog._bump()
        s1.execute("SELECT citus_rebalance_start()")
        s1.execute("SELECT citus_rebalance_start()")  # concurrent second
        s1.execute("SELECT citus_rebalance_wait()")
        # every shard still has exactly one active placement
        for shard in s1.catalog.table_shards("t"):
            active = [p for p in s1.catalog.placements.values()
                      if p.shard_id == shard.shard_id
                      and p.shard_state == "active"]
            assert len(active) == 1, \
                f"shard {shard.shard_id} has {len(active)} placements"

    def test_drop_table_during_reads_clean_error(self, tmp_path):
        # concurrent DROP: readers either answer from the pre-drop state
        # or fail with a clean catalog error — never a crash/garbage
        s1 = make_session(tmp_path)
        n, sm = setup(s1, rows=20)
        s2 = make_session(tmp_path)
        stop = threading.Event()
        clean = {"errors": 0, "ok": 0}

        def reader():
            while not stop.is_set():
                try:
                    assert totals(s2) == (n, sm)
                    clean["ok"] += 1
                except AssertionError:
                    raise
                except Exception:
                    clean["errors"] += 1  # clean engine error is fine
            return clean

        t, out = run_thread(reader)
        time.sleep(0.2)
        s1.execute("DROP TABLE t")
        stop.set()
        join_thread(t, out)
        assert clean["ok"] > 0


# -- I. 2PC recovery × concurrent reads ---------------------------------
class TestRecoveryInterleavings:
    def test_recovery_rolls_forward_while_new_session_reads(self,
                                                            tmp_path):
        # crash between commit-record and apply: the NEXT session must
        # roll the transaction forward; concurrent readers on that
        # session see the rolled-forward state exactly once
        from citus_tpu.utils import faultinjection as fi

        # retries off: the resilient layer would otherwise resolve the
        # died commit in-place (roll-forward) — this test wants the
        # crash handed to the NEXT session's recovery pass
        s1 = make_session(tmp_path, max_statement_retries=0)
        n, sm = setup(s1, rows=10)
        s1.execute("BEGIN")
        s1.execute("UPDATE t SET v = v + 1")
        with fi.inject("txn.apply"):
            with pytest.raises(Exception):
                s1.execute("COMMIT")
        s2 = make_session(tmp_path)  # triggers recovery

        def reader():
            return totals(s2)

        threads = [run_thread(reader) for _ in range(3)]
        results = [join_thread(t, o) for t, o in threads]
        assert all(r == (n, sm + n) for r in results), results
