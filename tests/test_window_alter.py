"""Window functions + ALTER TABLE (VERDICT round-2 item 8).

Window results cross-check against sqlite (which implements the same
default frame: RANGE UNBOUNDED PRECEDING .. CURRENT ROW with peers)."""

import sqlite3

import pytest

import citus_tpu
from citus_tpu.errors import CatalogError, PlanningError


@pytest.fixture()
def sess(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d"), n_devices=4,
                          compute_dtype="float64")
    s.execute("create table w (k bigint, g bigint, v bigint, "
              "f double precision)")
    s.create_distributed_table("w", "k", shard_count=8)
    rows = [(i, i % 5, (i * 37) % 100, float((i * 13) % 50))
            for i in range(1, 301)]
    rows[10] = (11, 1, rows[11][2], rows[11][3])  # duplicate order values
    s.execute("insert into w values "
              + ",".join(f"({a},{b},{c},{d})" for a, b, c, d in rows))
    con = sqlite3.connect(":memory:")
    con.execute("create table w (k, g, v, f)")
    con.executemany("insert into w values (?,?,?,?)", rows)
    yield s, con
    s.close()


def _check(sess_con, sql, tol=1e-9):
    s, con = sess_con
    got = sorted(tuple(None if x is None else round(float(x), 6)
                       for x in r) for r in s.execute(sql).rows())
    want = sorted(tuple(None if x is None else round(float(x), 6)
                        for x in r) for r in con.execute(sql).fetchall())
    assert got == want, f"{sql}\n{got[:5]} vs {want[:5]}"


def test_row_number_rank_dense_rank(sess):
    _check(sess, "select k, row_number() over (partition by g order by v, k) "
                 "from w")
    _check(sess, "select k, rank() over (partition by g order by v) from w")
    _check(sess, "select k, dense_rank() over (partition by g order by v) "
                 "from w")


def test_running_and_whole_partition_aggregates(sess):
    _check(sess, "select k, sum(v) over (partition by g order by k) from w")
    _check(sess, "select k, sum(v) over (partition by g) from w")
    _check(sess, "select k, count(*) over (partition by g order by v) "
                 "from w")
    _check(sess, "select k, min(f) over (partition by g order by k), "
                 "max(v) over (partition by g order by k) from w")
    _check(sess, "select k, avg(v) over (partition by g) from w", tol=1e-6)


def test_window_desc_and_global_partition(sess):
    _check(sess, "select k, row_number() over (order by v desc, k) from w")
    _check(sess, "select k, sum(v) over (order by k) from w")


def test_window_over_dist_column_partition(sess):
    # partition by the distribution column: device-local, no shuffle
    _check(sess, "select k, count(*) over (partition by k) from w")


def test_window_with_join_and_mixed_select(sess):
    s, con = sess
    s.execute("create table d (g bigint, name bigint)")
    s.execute("select create_reference_table('d')")
    s.execute("insert into d values (0,100),(1,101),(2,102),(3,103),(4,104)")
    con.execute("create table d (g, name)")
    con.executemany("insert into d values (?,?)",
                    [(0, 100), (1, 101), (2, 102), (3, 103), (4, 104)])
    _check(sess, "select k, name, v + row_number() over "
                 "(partition by w.g order by k) from w, d where w.g = d.g")


def test_window_restrictions(sess):
    s, _ = sess
    with pytest.raises(PlanningError, match="PARTITION BY"):
        s.execute("select row_number() over (partition by g order by k), "
                  "row_number() over (partition by v order by k) from w")
    with pytest.raises(PlanningError, match="OVER"):
        s.execute("select row_number() from w")
    with pytest.raises(PlanningError, match="aggregate|GROUP BY"):
        s.execute("select g, sum(count(*)) over (partition by g) "
                  "from w group by g")


def test_window_string_keys_rejected(tmp_path):
    """Dictionary codes are insertion-ordered, not lexicographic: ranking
    or min/max over a string column must be a planning error, not a
    silently wrong answer.  PARTITION BY strings (equality only) works."""
    s = citus_tpu.connect(data_dir=str(tmp_path / "ws"), n_devices=4,
                          compute_dtype="float64")
    try:
        s.execute("create table ws (k bigint, name text, v bigint)")
        s.create_distributed_table("ws", "k", shard_count=4)
        s.execute("insert into ws values (1,'zeta',10),(2,'alpha',20),"
                  "(3,'zeta',30),(4,'beta',40)")
        with pytest.raises(PlanningError, match="string"):
            s.execute("select rank() over (order by name) from ws")
        with pytest.raises(PlanningError, match="string"):
            s.execute("select min(name) over (partition by k) from ws")
        # equality-only use of strings is fine
        r = s.execute("select name, sum(v) over (partition by name) "
                      "from ws order by name, sum")
        assert [tuple(x) for x in r.rows()] == [
            ("alpha", 20), ("beta", 40), ("zeta", 40), ("zeta", 40)]
        # count over strings is order-insensitive → allowed
        r = s.execute("select count(name) over (partition by k) from ws")
        assert r.row_count == 4
    finally:
        s.close()


def test_alter_table_add_drop_rename(sess):
    s, _ = sess
    s.execute("alter table w add column extra bigint")
    r = s.execute("select count(*), count(extra) from w")
    assert [int(x) for x in r.rows()[0]] == [300, 0]  # backfilled NULL
    s.execute("insert into w (k, g, v, f, extra) values (1000, 0, 5, 1.0, 7)")
    r2 = s.execute("select count(extra), sum(extra) from w")
    assert [int(x) for x in r2.rows()[0]] == [1, 7]
    # filters over the mixed old/new stripes
    r3 = s.execute("select k from w where extra = 7")
    assert [int(x[0]) for x in r3.rows()] == [1000]

    s.execute("alter table w drop column extra")
    with pytest.raises(Exception):
        s.execute("select extra from w")
    with pytest.raises(CatalogError, match="distribution column"):
        s.execute("alter table w drop column k")

    s.execute("alter table w rename column v to val")
    r4 = s.execute("select sum(val) from w")
    assert int(r4.rows()[0][0]) > 0
    s.execute("insert into w (k, g, val, f) values (1001, 0, 9, 1.0)")
    r5 = s.execute("select sum(val) from w where k = 1001")
    assert int(r5.rows()[0][0]) == 9


def test_alter_rename_distribution_column(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d2"), n_devices=4,
                          compute_dtype="float64")
    s.execute("create table rn (a bigint, b bigint)")
    s.create_distributed_table("rn", "a", shard_count=4)
    s.execute("insert into rn values (1, 10), (2, 20)")
    s.execute("alter table rn rename column a to aa")
    assert s.catalog.table("rn").distribution_column == "aa"
    r = s.execute("select b from rn where aa = 2")
    assert int(r.rows()[0][0]) == 20
    s.execute("insert into rn values (3, 30)")
    assert int(s.execute("select sum(b) from rn").rows()[0][0]) == 60
    s.close()


def test_rename_add_collision_and_null_partitions(tmp_path):
    s = citus_tpu.connect(data_dir=str(tmp_path / "d3"), n_devices=4,
                          compute_dtype="float64")
    s.execute("create table c (k bigint, a bigint, b bigint)")
    s.create_distributed_table("c", "k", shard_count=4)
    s.execute("insert into c values (1, 100, 5), (2, 200, 7), "
              "(3, null, 5), (4, null, 7)")
    # rename a -> b2, then re-add a: must read NULL, not the old data
    s.execute("alter table c rename column a to a2")
    s.execute("alter table c add column a bigint")
    r = s.execute("select k, a, a2 from c order by k limit 2")
    assert [tuple(x) for x in r.rows()] == [(1, None, 100), (2, None, 200)]
    # drop then re-add: old values must not resurrect either
    s.execute("alter table c drop column a2")
    s.execute("alter table c add column a2 bigint")
    r2 = s.execute("select count(a2) from c")
    assert int(r2.rows()[0][0]) == 0
    # NULL expression partitions: all-NULL rows form ONE partition / peer
    r3 = s.execute("select k, count(*) over (partition by a + b) from c "
                   "where k >= 3")
    assert sorted(int(x[1]) for x in r3.rows()) == [2, 2]
    r4 = s.execute("select k, rank() over (order by a + b) from c "
                   "where k >= 3")
    assert sorted(int(x[1]) for x in r4.rows()) == [1, 1]
    # column named 'over' / 'partition' still parses
    s.execute("create table soft (over bigint, partition bigint)")
    s.create_distributed_table("soft", "over", shard_count=2)
    s.execute("insert into soft values (1, 2)")
    assert int(s.execute("select partition from soft where over = 1")
               .rows()[0][0]) == 2
    s.close()
